"""Headline benchmark: classifier online-train throughput (AROW) on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no benchmark figures (BASELINE.md: "published": {});
its hot path is the per-datum C++ driver update under a write lock
(classifier_serv.cpp:127-146, SURVEY.md §3.2). As the baseline we time a
faithful per-example C++ (-O3) implementation of the same sequential AROW
update on this host (native/arow_baseline.cpp — the honest stand-in for
the reference's single-core C++ serving thread; round 1 compared against
numpy, which undersold it), falling back to the numpy loop when no
toolchain is present, and report vs_baseline as the speedup of the TPU
microbatched kernel over it. "extra.baseline_impl" records which ran.

Workload: AROW binary classifier (Criteo-CTR-shaped: L=2, D=2^20 hashed
features, 64 non-zeros/example), the BASELINE.json primary config.
"""

import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from jubatus_tpu.ops import classifier as C

DIM_BITS = 20
D = 1 << DIM_BITS
L = 2
K = 64
# microbatch = bounded-staleness window (SURVEY.md §7 hard part b): all
# examples in a batch score against the batch-start snapshot. Measured on
# v5e (same process, median of trials): 4096→8192 +12%, 8192→32768 +20%
# (269k→322k samples/s) — gather/scatter launch overhead amortizes with
# batch; beyond 32768 gains flatten (65536: +1.5%). Deployments trading
# staleness for throughput should scale --interval-count with batch size.
BATCH = 32768
WARMUP_STEPS = 2
STEPS = 8
#: the C++ baseline needs enough examples to amortize its cold-cache
#: warm-up (measured: 2k reads ~340k/s, >=20k reads the steady ~600k/s);
#: the numpy fallback stays small (it is ~26x slower per example)
BASELINE_EXAMPLES = 100000
NUMPY_BASELINE_EXAMPLES = 2000


def make_data(rng, n):
    idx = rng.integers(1, D, size=(n, K), dtype=np.int32)
    val = rng.normal(size=(n, K)).astype(np.float32)
    labels = rng.integers(0, L, size=n).astype(np.int32)
    return idx, val, labels


def numpy_arow_per_example(idx, val, labels, r=1.0):
    """Reference-semantics sequential AROW on CPU (the baseline stand-in)."""
    w = np.zeros((L, D), np.float32)
    sigma = np.ones((L, D), np.float32)
    n = len(labels)
    t0 = time.perf_counter()
    for i in range(n):
        ii, vv, y = idx[i], val[i], labels[i]
        s = (w[:, ii] * vv).sum(axis=1)
        other = 1 - y
        margin = s[y] - s[other]
        loss = max(0.0, 1.0 - margin)
        if loss > 0.0:
            x2 = vv * vv
            v = ((sigma[y, ii] + sigma[other, ii]) * x2).sum()
            beta = 1.0 / (v + r)
            alpha = loss * beta
            w[y, ii] += alpha * sigma[y, ii] * vv
            w[other, ii] -= alpha * sigma[other, ii] * vv
            prec_inc = x2 / r
            sigma[y, ii] = 1.0 / (1.0 / sigma[y, ii] + prec_inc)
            sigma[other, ii] = 1.0 / (1.0 / sigma[other, ii] + prec_inc)
    return n / (time.perf_counter() - t0)


def cpp_arow_baseline(idx, val, labels, r=1.0, dim=None):
    """Sequential C++ AROW examples/s (native/arow_baseline.cpp), or
    (None, reason) when the library can't build."""
    import ctypes

    from jubatus_tpu import native as nb

    src = f"{nb.NATIVE_DIR}/arow_baseline.cpp"
    out = f"{nb.BUILD_DIR}/libarow_baseline.so"
    try:
        if nb._stale(src, out) and not nb._compile(src, out):
            return None, "compile failed"
        lib = ctypes.CDLL(out)
    except OSError as e:
        return None, f"load failed: {e}"
    lib.jt_arow_baseline.restype = ctypes.c_double
    lib.jt_arow_baseline.argtypes = [
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_int, ctypes.c_int64, ctypes.c_float,
    ]
    idx = np.ascontiguousarray(idx, np.int32)
    val = np.ascontiguousarray(val, np.float32)
    labels = np.ascontiguousarray(labels, np.int32)
    sps = float(lib.jt_arow_baseline(idx, val, labels, len(labels),
                                     idx.shape[1], dim or D, r))
    return (sps, "cpp -O3") if sps > 0 else (None, "zero result")


# probe program, liveness verdict, round numbering and the durable/
# compact output path live in the jax-free benchlib so the re-probe
# daemon (tools/tunnel_reprobe.py) and the unit tests share them without
# importing the device stack
from benchlib import emit, probe_tunnel, tunnel_is_alive  # noqa: E402


def _tunnel_alive(probe_timeout_s: float = None) -> bool:  # type: ignore[assignment]
    return tunnel_is_alive(probe_tunnel(probe_timeout_s))


#: set by the SIGTERM handler (see __main__); checked between phases
_TERM = {"req": False}


def _term_checkpoint(where: str) -> None:
    """Exit at a phase boundary if SIGTERM arrived mid-phase. Boundaries
    are the only safe exits: within a phase, device ops may be in flight
    on worker threads (serving) or in children (d24/mix)."""
    if _TERM["req"]:
        import sys

        print(f"SIGTERM received; exiting at phase boundary: {where}",
              file=sys.stderr)
        os._exit(143)


def _probe_device(timeout_s: float = None):  # type: ignore[assignment]
    """Backend init under a watchdog: the axon tunnel can hang
    indefinitely, and a bench that never prints its JSON line is worse
    than a degraded one. On a hang, retry with backoff via fresh
    subprocess probes (the wedge is often transient between processes);
    only when the tunnel stays dead re-exec on CPU (sitecustomize pins
    JAX_PLATFORMS at interpreter start, so a fresh process + config
    update is the reliable switch)."""
    import os
    import sys
    import threading

    if timeout_s is None:
        timeout_s = float(os.environ.get("JUBATUS_BENCH_PROBE_TIMEOUT", "240"))
    from jubatus_tpu.cmd import apply_platform_override

    apply_platform_override()  # honors JUBATUS_TPU_PLATFORM
    result = {}

    def probe():
        try:
            result["dev"] = jax.devices()[0]
        except Exception as e:  # noqa: BLE001
            result["err"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "dev" in result:
        return result["dev"]
    if os.environ.get("JUBATUS_TPU_PLATFORM") == "cpu":
        # CPU probe failed too: exit loudly, never exec-loop
        print(f"device init failed even on CPU: {result.get('err', 'hung')}",
              file=sys.stderr)
        sys.exit(1)
    # this process is lost (init hung holds the backend lock); decide the
    # NEXT process's platform by probing the tunnel with backoff
    # 2, not more: each attempt costs up to ~2.5 min (90 s probe + up to
    # 60 s backoff) on a wedged tunnel, and the whole capture must stay
    # inside the driver's window — the cron-style re-probe across the
    # round is the real second chance, not a longer ladder here
    attempts = int(os.environ.get("JUBATUS_BENCH_PROBE_ATTEMPTS", "2"))
    reexecs = int(os.environ.get("_JUBATUS_BENCH_CHIP_REEXECS", "0"))
    revived = False
    if reexecs < 2:  # bounded: never exec-loop on a flapping tunnel
        for i in range(attempts):
            if i:
                time.sleep(min(60.0 * i, 180.0))
            print(f"probe attempt {i + 1}/{attempts} (subprocess)...",
                  file=sys.stderr)
            if _tunnel_alive():
                revived = True
                break
    if revived:
        print("tunnel answered a fresh process; re-running on the chip",
              file=sys.stderr)
        os.environ["_JUBATUS_BENCH_CHIP_REEXECS"] = str(reexecs + 1)
    else:
        print(f"device init did not complete in {timeout_s:.0f}s and "
              f"{attempts} fresh-process probes failed "
              f"({result.get('err', 'hung')}); re-running on CPU",
              file=sys.stderr)
        os.environ["JUBATUS_TPU_PLATFORM"] = "cpu"
    # keep argv: a --d24-probe child that falls back to CPU must remain
    # the probe, not re-exec into the full benchmark
    os.execv(sys.executable,
             [sys.executable, os.path.abspath(__file__)] + sys.argv[1:])


def d24_probe() -> None:
    """Subprocess entry: the D=2^24 kernel throughput, fresh compile.

    Inputs stay UNCOMMITTED (jnp.asarray, not device_put-with-device):
    committing the index arrays pins a layout that makes the 2^24 gather
    program ~20x slower (measured 12k vs 238k samples/s; the 2^20
    program is insensitive). Letting XLA pick input layouts is the
    production shape — the serving path feeds jnp.asarray too."""
    rng = np.random.default_rng(0)
    dev = _probe_device()
    big_d = 1 << 24
    val = jnp.asarray(rng.normal(size=(BATCH, K)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, L, size=BATCH).astype(np.int32))
    mask = jnp.ones(L, dtype=bool)
    st = C.init_state(L, big_d, confidence=True)
    idxs = [jnp.asarray(rng.integers(1, big_d, size=(BATCH, K),
                                     dtype=np.int32))
            for _ in range(4)]
    st = C.train_batch(st, idxs[0], val, labels, mask, 1.0, method="AROW")
    float(jnp.sum(st.dw))
    t0 = time.perf_counter()
    for i in range(1, 4):
        st = C.train_batch(st, idxs[i], val, labels, mask, 1.0, method="AROW")
    float(jnp.sum(st.dw))
    # the parent keys the result by THIS platform — a CPU-fallback child
    # must never surface under a tpu_* key (VERDICT r3)
    print(f"D24={3 * BATCH / (time.perf_counter() - t0):.1f} "
          f"PLAT={dev.platform}")


def main():
    rng = np.random.default_rng(0)
    dev = _probe_device()

    # --- TPU path ---
    state = C.init_state(L, D, confidence=True)
    mask = jnp.array([True, True])
    batches = [make_data(rng, BATCH) for _ in range(STEPS + WARMUP_STEPS)]
    dev_batches = [
        (jax.device_put(i, dev), jax.device_put(v, dev), jax.device_put(l, dev))
        for i, v, l in batches
    ]
    for i in range(WARMUP_STEPS):
        bi, bv, bl = dev_batches[i]
        state = C.train_batch(state, bi, bv, bl, mask, 1.0, method="AROW")
    # NB: block_until_ready under the axon tunnel can return before remote
    # execution finishes; a scalar device->host fetch is the reliable barrier.
    float(jnp.sum(state.dw))
    t0 = time.perf_counter()
    for i in range(WARMUP_STEPS, WARMUP_STEPS + STEPS):
        bi, bv, bl = dev_batches[i]
        state = C.train_batch(state, bi, bv, bl, mask, 1.0, method="AROW")
    float(jnp.sum(state.dw))
    tpu_sps = STEPS * BATCH / (time.perf_counter() - t0)

    extra = {"bench_platform": dev.platform}  # "cpu" = tunnel-down fallback
    # crossover scale: the same kernel at Criteo-shaped D=2^24, where the
    # tables (512 MB with covariance) fit no CPU cache. Measured in a
    # SUBPROCESS with uncommitted inputs: committed (device_put) index
    # arrays pin a layout that makes THIS program ~20x slower
    # (docs/PERF_NOTES.md "Input layout"), and a fresh process keeps the
    # probe's compile and buffers fully isolated from the headline run.
    try:
        import subprocess
        import sys

        # the child inherits the PARENT's platform verdict: a CPU-fallback
        # parent pins the child to CPU (no 240 s re-probe of a wedged
        # tunnel), and either way the child gets NO subprocess-probe
        # retries — its worst-case budget must stay far inside the 900 s
        # watchdog, because a timeout-SIGKILL mid-backend-init is exactly
        # the wedge trigger (memory: axon-tunnel-wedge)
        child_env = dict(os.environ)
        child_env["JUBATUS_BENCH_PROBE_ATTEMPTS"] = "0"
        if dev.platform == "cpu":
            child_env["JUBATUS_TPU_PLATFORM"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--d24-probe"],
            capture_output=True, text=True, timeout=900, env=child_env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        got = False
        for line in proc.stdout.splitlines():
            if line.startswith("D24="):
                sps_s, _, plat_s = line[4:].partition(" PLAT=")
                plat = plat_s.strip() or "unknown"
                # key carries the platform that produced the number: only
                # a run on the real chip (the axon tunnel device) may mint
                # the tpu_ key; cpu is the tunnel-down fallback; anything
                # else is recorded under its own name, never as tpu
                if plat in ("tpu", "axon"):
                    extra["tpu_d2^24_samples_per_sec"] = float(sps_s)
                elif plat == "cpu":
                    extra["cpu_jax_d2^24_samples_per_sec"] = float(sps_s)
                    extra["tpu_d2^24_error"] = \
                        "tunnel down; child fell back to cpu"
                else:
                    extra[f"{plat}_jax_d2^24_samples_per_sec"] = \
                        float(sps_s)
                    extra["tpu_d2^24_error"] = \
                        f"unexpected platform {plat!r}; chip key withheld"
                got = True
        if not got:
            extra["tpu_d2^24_error"] = (proc.stderr or "no output")[-160:]
    except Exception as e:  # noqa: BLE001
        extra["tpu_d2^24_error"] = repr(e)[:160]
    _term_checkpoint("after d24 probe")
    # --- baseline: faithful sequential C++ AROW, numpy fallback ---
    bi, bv, bl = make_data(rng, BASELINE_EXAMPLES)
    base_sps, base_impl = cpp_arow_baseline(bi, bv, bl)
    if base_sps is None:
        n = NUMPY_BASELINE_EXAMPLES
        base_sps, base_impl = \
            numpy_arow_per_example(bi[:n], bv[:n], bl[:n]), "numpy"
    else:
        # context for the honest number (docs/PERF_NOTES.md "single chip
        # vs single core"): at D=2^20 the C++ loop's 8 MB tables live in
        # host CPU cache — the regime the reference was designed for. At
        # Criteo-shaped D=2^24 (512 MB with covariance) the cache spills
        # and the comparison inverts; record that scale too.
        big_bi = rng.integers(1, 1 << 24, size=(BASELINE_EXAMPLES, K),
                              dtype=np.int32)
        big_sps, _ = cpp_arow_baseline(big_bi, bv, bl, dim=1 << 24)
        extra["baseline_cpp_d2^24_samples_per_sec"] = round(big_sps or 0.0, 1)

    # --- mix plane (VERDICT r1 item 4: round time + bytes vs the <=1 s
    # --- north star, like linear_mixer.cpp:553-558 logs) ---
    try:
        import bench_mix

        extra.update(bench_mix.collect(dev))
    except Exception as e:  # noqa: BLE001 — headline must still print
        extra["mix_error"] = repr(e)[:200]
    _term_checkpoint("after mix plane")

    # --- chip-advantage axes (VERDICT r2 item 7): L-scaling flat-vs-linear
    # --- and the CPU lock-contention row, captured by the driver itself ---
    try:
        import sys as _sys

        _tools = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools")
        if _tools not in _sys.path:
            _sys.path.insert(0, _tools)
        import bench_chip_axes
    except Exception as e:  # noqa: BLE001
        extra["chip_axes_error"] = repr(e)[:200]
        bench_chip_axes = None
    if bench_chip_axes is not None:
        # independent trys: a toolchain-less host loses only the CPU rows,
        # never the device-side sweep (and vice versa)
        try:
            extra.update(bench_chip_axes.cpu_axes())
        except Exception as e:  # noqa: BLE001
            extra["cpu_axes_error"] = repr(e)[:200]
        try:
            extra.update(bench_chip_axes.chip_l_sweep())
        except Exception as e:  # noqa: BLE001
            extra["chip_l_error"] = repr(e)[:200]
    _term_checkpoint("after chip axes")

    # --- end-to-end serving path (VERDICT r1 item 2: the product, not the
    # --- kernel: RPC decode -> datum -> fv convert -> device) ---
    try:
        import bench_serving

        extra.update(bench_serving.collect())
    except Exception as e:  # noqa: BLE001
        extra["e2e_error"] = repr(e)[:200]

    extra["baseline_impl"] = base_impl
    extra["baseline_samples_per_sec"] = round(base_sps, 1)
    payload = {
        "metric": "classifier_train_samples_per_sec_arow_d2^20",
        "value": round(tpu_sps, 1),
        "unit": "samples/s",
        "vs_baseline": round(tpu_sps / base_sps, 2),
        "extra": extra,
    }
    emit(payload)


if __name__ == "__main__":
    import signal
    import sys

    # SIGTERM (e.g. tools/tunnel_reprobe.py's budget overrun) must never
    # cut an in-flight device op — that wedges the axon tunnel for
    # hours. The default disposition would; an immediate os._exit would
    # too, because it kills WORKER THREADS (the serving phase runs an
    # in-process EngineServer whose flushes dispatch on RPC threads).
    # So the handler only sets a flag; _term_checkpoint() exits at phase
    # BOUNDARIES, where no in-process device work is in flight (each
    # phase joins its servers/children before returning). A bench hung
    # inside one phase simply never exits — the sender abandons us,
    # which is the designed-for outcome. Other processes in the capture
    # group are safe by construction: the d24 child runs this same
    # handler, bench_mix collective children and serving load
    # generators are CPU-only (scrub_child_env strips the axon site).
    if "--d24-probe" in sys.argv:
        # IGNORE SIGTERM outright in the child: even a bytecode-boundary
        # exit could land between an async dispatch and its device->host
        # barrier (jax dispatch returns while the op still runs through
        # the tunnel), and dying with a remote op in flight is the wedge
        # trigger. The child's lifetime is already bounded (one probe,
        # parent-side timeout abandons it); finishing on its own is the
        # safe outcome.
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        d24_probe()
    else:
        signal.signal(signal.SIGTERM,
                      lambda s, f: _TERM.__setitem__("req", True))
        main()
