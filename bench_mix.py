"""Mix-plane benchmark: one mix round on Criteo-shaped diffs vs the
BASELINE.md north star (mix round <= 1 s).

The reference logs per-round time + bytes (linear_mixer.cpp:553-558); this
does the same for the TPU mix plane on two paths:

- ``device_round``: the single-host production path (LocalMixGroup shape):
  per-replica host diffs [L, D] f32 -> device_put -> jitted reduce + apply
  into the master weights -> scalar fetch barrier. Run on whatever device
  bench.py runs on (the real chip under the driver).
- ``allreduce8``: the multi-replica collective path (`allreduce_diffs`,
  psum over the mesh's replica axis), executed on an 8-device virtual CPU
  mesh in a subprocess — the same path `dryrun_multichip` validates. Wall
  time on virtual CPU devices is NOT an ICI number; it proves the
  collective compiles + executes and bounds the host-side orchestration.

Both paths report the f32 and bf16-compressed (half wire bytes) variants.

Usage: python bench_mix.py        — prints one JSON dict of mix metrics.
Also importable: bench.py folds `collect(...)` into its "extra" field.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

L = 2
DIM_BITS = 20
D = 1 << DIM_BITS
N_REPLICAS = 2          # device_round: reference's smallest real cluster
TRIALS = 5


def _median(xs):
    return float(np.median(np.asarray(xs)))


def device_round(dev=None) -> dict:
    """One full mix round, single-device reduce (replicas co-hosted)."""
    import jax
    import jax.numpy as jnp

    if dev is None:
        dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    diffs_host = [rng.normal(size=(L, D)).astype(np.float32)
                  for _ in range(N_REPLICAS)]
    master = jax.device_put(jnp.zeros((L, D), jnp.float32), dev)

    @jax.jit
    def reduce_apply(master, stacked):
        return master + jnp.sum(stacked, axis=0)

    @jax.jit
    def reduce_apply_bf16(master, stacked):
        # wire-compressed variant: replicas ship bf16 diffs (half the
        # host->device and inter-replica bytes); master stays f32
        return master + jnp.sum(stacked.astype(jnp.float32), axis=0)

    out = {}
    for name, fn, cast in (("f32", reduce_apply, np.float32),
                           ("bf16", reduce_apply_bf16, None)):
        if cast is None:
            import ml_dtypes

            ship = [d.astype(ml_dtypes.bfloat16) for d in diffs_host]
        else:
            ship = diffs_host
        # warmup (compile)
        stacked = jax.device_put(np.stack(ship), dev)
        master = fn(master, stacked)
        float(jnp.sum(master))
        times = []
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            stacked = jax.device_put(np.stack(ship), dev)  # get_diff arrival
            master = fn(master, stacked)
            float(jnp.sum(master))                         # put_diff barrier
            times.append(time.perf_counter() - t0)
        bytes_moved = sum(x.nbytes for x in ship)
        out[f"device_round_ms_{name}"] = round(_median(times) * 1e3, 2)
        out[f"device_round_mb_{name}"] = round(bytes_moved / 2**20, 2)
    return out


def allreduce8() -> dict:
    """allreduce_diffs on an 8-replica virtual CPU mesh (subprocess)."""
    import jax
    import jax.numpy as jnp

    from jubatus_tpu.parallel.mesh import replica_mesh
    from jubatus_tpu.parallel.mix import _psum_stacked
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = replica_mesh(8)
    rng = np.random.default_rng(0)
    stacked_host = {"w": rng.normal(size=(8, L, D)).astype(np.float32)}
    sharding = NamedSharding(mesh, P("replica"))
    stacked = jax.tree_util.tree_map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), stacked_host)

    out = {}
    for name, compress in (("f32", False), ("bf16", True)):
        total = _psum_stacked(stacked, mesh=mesh, axis="replica",
                              compress=compress)
        jax.block_until_ready(total)
        times = []
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            total = _psum_stacked(stacked, mesh=mesh, axis="replica",
                                  compress=compress)
            jax.block_until_ready(total)
            times.append(time.perf_counter() - t0)
        # ring allreduce wire bytes per replica: 2*(n-1)/n of the payload
        payload = L * D * (2 if compress else 4)
        out[f"allreduce8_ms_{name}"] = round(_median(times) * 1e3, 2)
        out[f"allreduce8_wire_mb_per_replica_{name}"] = round(
            payload * 2 * 7 / 8 / 2**20, 2)
    return out


def _allreduce8_subprocess() -> dict:
    """Run allreduce8 with 8 virtual CPU devices regardless of parent env."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env["JUBATUS_TPU_PLATFORM"] = "cpu"
    path = env.get("PYTHONPATH", "")
    if repo not in path.split(os.pathsep):
        env["PYTHONPATH"] = repo + (os.pathsep + path if path else "")
    prog = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import json, bench_mix\n"
        "print('MIXBENCH=' + json.dumps(bench_mix.allreduce8()))\n"
    )
    proc = subprocess.run([sys.executable, "-c", prog], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=600)
    for line in proc.stdout.splitlines():
        if line.startswith("MIXBENCH="):
            return json.loads(line[len("MIXBENCH="):])
    return {"allreduce8_error": (proc.stderr or proc.stdout)[-300:]}


def collect(dev=None) -> dict:
    out = device_round(dev)
    out.update(_allreduce8_subprocess())
    # the north-star comparison: worst measured round vs the 1 s target
    rounds = [v for k, v in out.items() if k.endswith("_ms_f32")
              or k.endswith("_ms_bf16")]
    if rounds:
        out["mix_round_worst_ms"] = max(rounds)
        out["mix_under_1s_target"] = bool(max(rounds) < 1000.0)
    return out


if __name__ == "__main__":
    print(json.dumps(collect(), indent=1))
