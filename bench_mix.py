"""Mix-plane benchmark: mix rounds at Criteo scale and at the BASELINE.md
north star (mix round <= 1 s at D=2^24), plus a REAL multi-process
collective round.

The reference logs per-round time + bytes (linear_mixer.cpp:553-558); this
does the same for the TPU mix plane on three paths:

- ``device_round`` at D=2^20 AND D=2^24: the single-host production path
  (LocalMixGroup shape): per-replica host diffs [L, D] f32 ->
  host-to-device -> jitted reduce + apply into the master weights ->
  scalar fetch barrier. Run on whatever device bench.py runs on (the real
  chip under the driver). Transfers use uncommitted ``jnp.asarray`` — a
  committed device_put pins layouts and measured ~1.4x slower.
- ``allreduce8``: the multi-replica collective path (`allreduce_diffs`,
  psum over the mesh's replica axis), executed on an 8-device virtual CPU
  mesh in a subprocess — the same path `dryrun_multichip` validates. Wall
  time on virtual CPU devices is NOT an ICI number; it proves the
  collective compiles + executes and bounds the host-side orchestration.
- ``collective_nproc4``: a FULL production collective_mixer round across
  4 jax.distributed processes (prepare RPC fan-out, schema sync, GO via
  the coordinator, psum_pytree, acks) — the complete orchestration stack,
  timed on the master. Virtual CPU world: the number bounds protocol +
  host cost, not interconnect bandwidth (labeled as such).

Every path reports f32 and, where applicable, the compressed wire
variants — bf16 (half the bytes) and block-quantized int8 (~4x fewer
bytes, --mix-compress int8) — plus a multi-round drift probe proving the
int8 error-feedback residual keeps averaged weights unbiased
(``collective_round_drift_vs_f32`` vs the stateless ``_noef`` control).

Usage: python bench_mix.py        — prints one JSON dict of mix metrics.
Also importable: bench.py folds `collect(...)` into its "extra" field.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

L = 2
N_REPLICAS = 2          # device_round: reference's smallest real cluster
TRIALS = 5
NORTH_STAR_BITS = 24    # BASELINE.md: Criteo-shaped 2^24 model, round <= 1 s


def _median(xs):
    return float(np.median(np.asarray(xs)))


def scrub_child_env(env: dict) -> dict:
    """Make a child-process env safe for CPU-only work: pin the platform
    and drop the axon plugin from PYTHONPATH — its registration hook
    initializes the device tunnel regardless of JAX_PLATFORMS, and a
    wedged tunnel hangs the child forever. ONE owner for this scrub
    (bench_serving and the tests import it) so the next plugin quirk is
    fixed in one place."""
    env = dict(env)
    env["JAX_PLATFORMS"] = "cpu"
    env["JUBATUS_TPU_PLATFORM"] = "cpu"
    repo = os.path.dirname(os.path.abspath(__file__))
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    if repo not in parts:
        parts.insert(0, repo)
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env


def device_round(dim_bits: int, dev=None, trials: int = TRIALS,
                 tag: str = "") -> dict:
    """One full mix round, single-device reduce (replicas co-hosted).
    ``dev`` pins the default device for the round (uncommitted arrays —
    committing pins layouts, measured ~1.4x slower)."""
    import contextlib

    import jax
    import jax.numpy as jnp

    ctx = jax.default_device(dev) if dev is not None else \
        contextlib.nullcontext()
    with ctx:
        return _device_round_impl(dim_bits, trials, tag)


def _device_round_impl(dim_bits: int, trials: int, tag: str) -> dict:
    import jax
    import jax.numpy as jnp

    d = 1 << dim_bits
    rng = np.random.default_rng(0)
    diffs_host = [rng.normal(size=(L, d)).astype(np.float32)
                  for _ in range(N_REPLICAS)]
    master = jnp.zeros((L, d), jnp.float32)

    @jax.jit
    def reduce_apply(master, stacked):
        return master + jnp.sum(stacked, axis=0)

    @jax.jit
    def reduce_apply_bf16(master, stacked):
        # wire-compressed variant: replicas ship bf16 diffs (half the
        # host->device and inter-replica bytes); master stays f32
        return master + jnp.sum(stacked.astype(jnp.float32), axis=0)

    out = {}
    suffix = tag or f"d{dim_bits}"
    for name, fn, cast in (("f32", reduce_apply, np.float32),
                           ("bf16", reduce_apply_bf16, None)):
        if cast is None:
            import ml_dtypes

            ship = [x.astype(ml_dtypes.bfloat16) for x in diffs_host]
        else:
            ship = diffs_host
        # warmup (compile)
        stacked = jnp.asarray(np.stack(ship))
        master = fn(master, stacked)
        float(jnp.sum(master))
        times = []
        for _ in range(trials):
            t0 = time.perf_counter()
            stacked = jnp.asarray(np.stack(ship))  # get_diff arrival
            master = fn(master, stacked)
            float(jnp.sum(master))                 # put_diff barrier
            times.append(time.perf_counter() - t0)
            del stacked
        bytes_moved = sum(x.nbytes for x in ship)
        out[f"mix_round_ms_{suffix}_{name}"] = round(_median(times) * 1e3, 2)
        out[f"mix_round_mb_{suffix}_{name}"] = round(bytes_moved / 2**20, 2)
    return out


def allreduce8() -> dict:
    """allreduce_diffs on an 8-replica virtual CPU mesh (subprocess)."""
    import jax
    import jax.numpy as jnp

    from jubatus_tpu.parallel.mesh import replica_mesh
    from jubatus_tpu.parallel.mix import _psum_stacked
    from jax.sharding import NamedSharding, PartitionSpec as P

    D = 1 << 20
    mesh = replica_mesh(8)
    rng = np.random.default_rng(0)
    stacked_host = {"w": rng.normal(size=(8, L, D)).astype(np.float32)}
    sharding = NamedSharding(mesh, P("replica"))
    stacked = jax.tree_util.tree_map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), stacked_host)

    out = {}
    for name, compress in (("f32", False), ("bf16", True)):
        total = _psum_stacked(stacked, mesh=mesh, axis="replica",
                              compress=compress)
        jax.block_until_ready(total)
        times = []
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            total = _psum_stacked(stacked, mesh=mesh, axis="replica",
                                  compress=compress)
            jax.block_until_ready(total)
            times.append(time.perf_counter() - t0)
        # ring allreduce wire bytes per replica: 2*(n-1)/n of the payload
        payload = L * D * (2 if compress else 4)
        out[f"allreduce8_ms_{name}"] = round(_median(times) * 1e3, 2)
        out[f"allreduce8_wire_mb_per_replica_{name}"] = round(
            payload * 2 * 7 / 8 / 2**20, 2)
    return out


def _allreduce8_subprocess() -> dict:
    """Run allreduce8 with 8 virtual CPU devices regardless of parent env."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env["JUBATUS_TPU_PLATFORM"] = "cpu"
    path = env.get("PYTHONPATH", "")
    if repo not in path.split(os.pathsep):
        env["PYTHONPATH"] = repo + (os.pathsep + path if path else "")
    # CPU-only children must not import the axon plugin: a wedged
    # device tunnel hangs its registration hook at jax backend init
    # regardless of JAX_PLATFORMS
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    prog = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import json, bench_mix\n"
        "print('MIXBENCH=' + json.dumps(bench_mix.allreduce8()))\n"
    )
    proc = subprocess.run([sys.executable, "-c", prog], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=600)
    for line in proc.stdout.splitlines():
        if line.startswith("MIXBENCH="):
            return json.loads(line[len("MIXBENCH="):])
    return {"allreduce8_error": (proc.stderr or proc.stdout)[-300:]}


_COLLECTIVE_CHILD = r"""
import os, sys, time, json
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); n = int(sys.argv[2])
jax_port, coord_dir = sys.argv[3], sys.argv[4]
dim_bits = int(sys.argv[5]) if len(sys.argv) > 5 else 0
mode = sys.argv[6] if len(sys.argv) > 6 else "off"  # off|bf16|int8
# CPU worlds need the gloo collectives backend or every psum raises
# ("Multiprocess computations aren't implemented on the CPU backend")
from jubatus_tpu.parallel.multihost import enable_cpu_collectives
enable_cpu_collectives()
jax.distributed.initialize(f"127.0.0.1:{jax_port}", num_processes=n,
                           process_id=pid)
from jubatus_tpu.client import ClassifierClient, Datum
from jubatus_tpu.coord import membership
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs

# dim_bits > 0: the north-star-scale round — AROW (w + sigma diffs, the
# reference's confidence-weighted shape) at hash_max_size-pinned dim
if dim_bits:
    CONF = {"method": "AROW", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}],
                          "hash_max_size": 1 << dim_bits}}
else:
    CONF = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
args = ServerArgs(engine="classifier", coordinator=coord_dir, name="mb",
                  listen_addr="127.0.0.1", mixer="collective_mixer",
                  interval_sec=1e9, interval_count=1 << 30,
                  mix_compress=mode,
                  # north-star payloads (256 MB diffs) need a mixer-plane
                  # timeout matched to the transfer, like the reference's
                  # --interconnect_timeout knob for big models
                  interconnect_timeout=180.0 if dim_bits else 10.0,
                  timeout=180.0 if dim_bits else 10.0)
srv = EngineServer("classifier", CONF, args)
srv.start(0)
c = ClassifierClient("127.0.0.1", srv.args.rpc_port, "mb", timeout=300)
for _ in range(4):
    c.train([["pos", Datum({f"x{pid}": 1.0})],
             ["neg", Datum({f"x{pid}": -1.0})]])
# budget starts AFTER training: at north-star dims the d2^24 train
# compiles eat minutes of one time-sliced core, and a peer whose wait
# expires calls srv.stop() — tearing its listener down right under the
# master's mix fan-out (connection refused on every peer). The d24
# budget matches the parent's 1200 s timeout: a peer deadline SHORTER
# than the parent's lets a slow master outlive its peers and fan out
# into torn-down listeners instead of timing out cleanly at the parent
deadline = time.time() + (120 if not dim_bits else 1800)
while time.time() < deadline:
    if len(membership.get_all_nodes(srv.coord, "classifier", "mb")) == n:
        break
    time.sleep(0.2)
# the d24 world measures f32, bf16 AND int8 back to back in ONE world
# (flip compress in place between rounds — the prepare signature
# re-reads it, so all members flipping keeps the cluster matched); a
# second world boot would pay membership + d24 train compiles twice
variants = ["bf16", "int8"] if (dim_bits and mode == "off") else []
if pid == 0:
    time.sleep(1.5 if not dim_bits else 5.0)  # peers finish training
    def warmed_round():
        # warmup until the COLLECTIVE path engages (compiles the psum):
        # big models boot slowly on a time-sliced host and a transient
        # prepare failure routes one round to the RPC fallback — retry
        for attempt in range(4):
            out = srv.mixer.mix_now()
            if out and out.get("collective"):
                break
            print(f"warmup attempt {attempt}: {out!r}", flush=True)
            time.sleep(3.0)
        assert out and out.get("collective"), out
        # registry hygiene: drop the warmup rounds (compile-heavy) so the
        # mix.round histogram embedded below covers steady state only
        srv.rpc.trace.reset()
        # median of 3 measured rounds: the round is dominated by the
        # device-queue drain at the chunk-0 barrier on a time-sliced
        # host, which is noisy run to run — one sample flips mode
        # comparisons, three stabilize them
        times = []
        for _ in range(3 if dim_bits else 1):
            t0 = time.perf_counter()
            out = srv.mixer.mix_now()      # measured round
            times.append((time.perf_counter() - t0) * 1e3)
            assert out and out.get("collective"), out
        times.sort()
        return times[len(times) // 2]
    rec = {}
    plat = jax.devices()[0].platform
    def measure(tag):
        # per-phase breakdown of the measured round (VERDICT r4 #5):
        # makes the wire-bandwidth claim arithmetic from measured terms
        # instead of an assertion — cast (~0, on-device by design), ship
        # (host->device + wire prep), reduce (wire+fold as ONE fused
        # collective), readback, plus the wire bytes and quant mode the
        # flight recorder stamps per round
        ms = warmed_round()
        rec[f"collective_round_ms_nproc{n}{tag}"] = round(ms, 2)
        rec[f"collective_round{tag}_platform"] = plat
        phases = dict(getattr(srv.mixer, "last_phases", {}))
        for k, v in phases.items():
            rec[f"collective_phase_{k}{tag}"] = v
        if "wire_mb" in phases:
            rec[f"collective_wire_mb_per_round{tag}"] = phases["wire_mb"]
        # steady-state mix.round quantiles from the span histograms
        # (warmup rounds were reset away inside warmed_round)
        tr = srv.rpc.trace.trace_status()
        for q in ("p50_ms", "p99_ms", "max_ms"):
            k = f"trace.mix.round.{q}"
            if k in tr:
                rec[f"collective_mix_round_{q}{tag}"] = tr[k]
    tag = (f"_d{dim_bits}" if dim_bits else "") + \
        (f"_{mode}" if mode != "off" else "")
    measure(tag)
    diffs = {k: m.get_diff() for k, m in srv.driver.get_mixables().items()}
    import numpy as np
    nbytes = 0
    for d in diffs.values():
        leaves, _ = jax.tree_util.tree_flatten(d)
        nbytes += sum(np.asarray(x).nbytes for x in leaves)
    rec[f"collective_round{tag}_payload_mb_per_replica"] = \
        round(nbytes / 2**20, 2)
    rec[f"collective_round{tag}_note"] = (
        f"{n} jax.distributed {plat} processes; orchestration+psum "
        "cost, not interconnect bandwidth")
    flight = srv.mixer.flight.snapshot(last=1)
    if flight:
        rec[f"collective_flight_last{tag}"] = flight[-1]
    for v in variants:
        srv.mixer.compress = v
        open(coord_dir.rstrip("/") + f".flip_{v}", "w").close()
        fdeadline = time.time() + 120
        while time.time() < fdeadline:
            if all(os.path.exists(f"{coord_dir.rstrip('/')}.flipped_{v}_{p}")
                   for p in range(1, n)):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"peers never acked the {v} flip")
        measure(f"_d{dim_bits}_{v}")
    print("COLLECTIVE=" + json.dumps(rec), flush=True)
    # explicit completion marker (SIBLING of the coordinator dir — the
    # file coordinator owns everything inside): peers must NOT key off
    # model_version — failed warmup attempts still run RPC-fallback
    # rounds that bump it, and a peer leaving early tears its listener
    # down under the master's next fan-out
    open(coord_dir.rstrip("/") + ".done", "w").close()
else:
    done = coord_dir.rstrip("/") + ".done"
    pending = list(variants)
    while time.time() < deadline:
        if os.path.exists(done):
            break
        if pending and os.path.exists(
                f"{coord_dir.rstrip('/')}.flip_{pending[0]}"):
            v = pending.pop(0)
            srv.mixer.compress = v
            open(f"{coord_dir.rstrip('/')}.flipped_{v}_{pid}", "w").close()
        time.sleep(0.2)
c.close()
srv.stop()
print(f"CHILD-{pid}-DONE", flush=True)
"""


def run_jax_world(child_src: str, n: int, timeout: float = 300.0,
                  extra_args: tuple = ()):
    """Spawn ``n`` jax.distributed CPU child processes (argv: pid, n,
    jax_port, coord_dir, *extra); returns (outputs, returncodes).
    Shared by this bench and tests/test_collective_mixer.py — one
    harness owns the port pick, env scrub, CONCURRENT pipe draining
    (a child blocked writing into a full pipe while the parent reads
    siblings sequentially would deadlock a collective), kill-and-reap
    on timeout, and coordinator-dir cleanup."""
    import shutil
    import tempfile
    import threading

    repo = os.path.dirname(os.path.abspath(__file__))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    jax_port = s.getsockname()[1]
    s.close()
    coord_dir = tempfile.mkdtemp(prefix="mixbench_coord_")
    env = scrub_child_env(
        {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"})
    procs = []
    outs = [""] * n
    threads = []
    try:
        for i in range(n):
            p = subprocess.Popen(
                [sys.executable, "-c", child_src, str(i), str(n),
                 str(jax_port), coord_dir, *map(str, extra_args)],
                env=env, cwd=repo, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            procs.append(p)

            def drain(idx=i, proc=p):
                outs[idx] = proc.stdout.read()

            t = threading.Thread(target=drain, daemon=True)
            t.start()
            threads.append(t)
        deadline = time.monotonic() + timeout
        for p in procs:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        for t in threads:
            t.join(timeout=10)
        return outs, [p.returncode for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        shutil.rmtree(coord_dir, ignore_errors=True)
        import glob as _glob

        for marker in _glob.glob(coord_dir.rstrip("/") + ".*"):
            try:  # the children's sibling marker files (.done, .flip_*)
                os.unlink(marker)
            except OSError:
                pass


def collective_nproc(n: int = 4, dim_bits: int = 0,
                     timeout: float = 300.0, mode: str = "off") -> dict:
    """Timed production collective round across ``n`` OS processes.
    ``dim_bits`` > 0 runs the north-star-scale variant (AROW diffs at
    D=2^dim_bits — w + sigma, 2^dim_bits * L * 2 * 4 bytes f32 per
    replica) and measures ALL THREE wire modes back to back in one
    world when ``mode`` starts at "off" (f32 → flip bf16 → flip int8);
    ``mode`` pins a single --mix-compress variant otherwise."""
    out: dict = {}
    tag = (f"_d{dim_bits}" if dim_bits else "") + \
        (f"_{mode}" if mode != "off" else "")
    err_key = f"collective_round{tag}_error"
    extra = ((str(dim_bits), mode)
             if (dim_bits or mode != "off") else ())
    try:
        outs, rcs = run_jax_world(_COLLECTIVE_CHILD, n, timeout=timeout,
                                  extra_args=extra)
    except subprocess.TimeoutExpired:
        return {err_key: "timeout"}
    if any(rc != 0 for rc in rcs):
        return {err_key: f"child exits {rcs}: {(''.join(outs))[-200:]}"}
    for text in outs:
        for line in text.splitlines():
            if line.startswith("COLLECTIVE="):
                out.update(json.loads(line[len("COLLECTIVE="):]))
    if not out:
        out[err_key] = "no master output"
    return out


_DRIFT_CHILD = r"""
import sys, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); n = int(sys.argv[2])
jax_port = sys.argv[3]
dim_bits = int(sys.argv[5]); rounds = int(sys.argv[6])
from jubatus_tpu.parallel.multihost import enable_cpu_collectives
enable_cpu_collectives()
jax.distributed.initialize(f"127.0.0.1:{jax_port}", num_processes=n,
                           process_id=pid)
from jubatus_tpu.parallel.collective import ErrorFeedback, psum_pytree

# every process contributes fresh per-round diffs; all processes run the
# SAME sequence of collectives (f32, int8+EF, int8 stateless) so the
# streams stay in lockstep — no mixer protocol needed for a raw probe
rng = np.random.default_rng(100 + pid)
shape = (2, 1 << dim_bits)
# force the chunked (= quantized) path even at probe dims below the
# default 8 MiB chunk: ~4 chunks per leaf at any dim_bits
chunk_mb = min(8.0, max(0.25, shape[0] * shape[1] * 4 / 2**20 / 4))
ef = ErrorFeedback()
S32 = np.zeros(shape, np.float32)
S8 = np.zeros(shape, np.float32)
S8n = np.zeros(shape, np.float32)
ph = {}
d1 = None
for r in range(rounds):
    x = {"w": rng.normal(size=shape).astype(np.float32)}
    S32 += psum_pytree(x, compress="off", chunk_mb=chunk_mb)["w"]
    S8 += psum_pytree(x, compress="int8", chunk_mb=chunk_mb, phases=ph,
                      feedback=ef)["w"]
    S8n += psum_pytree(x, compress="int8", chunk_mb=chunk_mb)["w"]
    if d1 is None:
        d1 = float(np.linalg.norm(S8 - S32))
if pid == 0:
    ref = float(np.linalg.norm(S32))
    print("DRIFT=" + json.dumps({
        "collective_round_drift_vs_f32":
            float(np.linalg.norm(S8 - S32)) / ref,
        "collective_round_drift_vs_f32_noef":
            float(np.linalg.norm(S8n - S32)) / ref,
        "collective_round_drift_rounds": rounds,
        "collective_round_drift_first_round_l2": d1,
        "collective_round_drift_ef_rounds": ef.rounds,
        "collective_wire_mb_per_round": ph.get("wire_mb"),
        "collective_round_drift_note": (
            f"cumulative {rounds}-round averaged-weight drift of the "
            "int8 transport at D=2^%d across %d processes; error "
            "feedback telescopes it to ONE round's quantization error, "
            "stateless int8 random-walks" % (dim_bits, n)),
    }), flush=True)
print(f"CHILD-{pid}-DONE", flush=True)
"""


def drift_probe(n: int = 4, dim_bits: int = 22, rounds: int = 6,
                timeout: float = 600.0) -> dict:
    """Multi-round averaged-weight drift of the int8 quantized transport
    vs the exact f32 collective, measured on a REAL n-process world:
    ``collective_round_drift_vs_f32`` (error feedback carried between
    rounds — bounded, non-compounding) against the ``_noef`` control
    (stateless quantization — sqrt(rounds) random walk). The test
    suite's world-of-1 gate proves the telescoping algebra; this probe
    proves it survives the scatter/gather ring."""
    try:
        outs, rcs = run_jax_world(_DRIFT_CHILD, n, timeout=timeout,
                                  extra_args=(str(dim_bits), str(rounds)))
    except subprocess.TimeoutExpired:
        return {"collective_round_drift_error": "timeout"}
    if any(rc != 0 for rc in rcs):
        return {"collective_round_drift_error":
                f"child exits {rcs}: {(''.join(outs))[-300:]}"}
    for text in outs:
        for line in text.splitlines():
            if line.startswith("DRIFT="):
                return json.loads(line[len("DRIFT="):])
    return {"collective_round_drift_error": "no master output"}


_SCALING_CHILD = r"""
import os, sys, time, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); n = int(sys.argv[2])
jax_port = sys.argv[3]
dim_bits = int(sys.argv[5]); topo = sys.argv[6]
from jubatus_tpu.parallel.multihost import enable_cpu_collectives
enable_cpu_collectives()
jax.distributed.initialize(f"127.0.0.1:{jax_port}", num_processes=n,
                           process_id=pid)
from jubatus_tpu.parallel.collective import psum_pytree

# raw transport probe, no servers: one f32 leaf of 2^dim_bits elements
# (the north-star model dim) through the chunked pipeline, flat vs
# hierarchical, IN THE SAME WORLD — same processes, same gloo sockets,
# and the parity check compares the exact same inputs through both
rng = np.random.default_rng(41 + pid)
x = {"w": rng.normal(size=(1 << dim_bits,)).astype(np.float32)}
rec = {}
totals = {}
trials = 2 if n >= 16 else 3
for variant, kw in (("flat", {}), ("hier", {"topology": topo})):
    ph = {}
    out = psum_pytree(x, phases=ph, **kw)   # warmup: compiles
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        out = psum_pytree(x, phases=ph, **kw)
        times.append((time.perf_counter() - t0) * 1e3)
    totals[variant] = out["w"]
    times.sort()
    rec[variant] = {"ms": times[len(times) // 2], "phases": dict(ph)}
# parity: the two paths reduce in different association orders (ring
# scatter vs two-tier tree), so multi-process totals agree to float32
# rounding, not bitwise — world-1 bitwise parity is the unit suite's
# gate (tests/test_collective_pipeline.py). Gate here on relative error
# at the noise floor of an n-way f32 sum.
scale = float(np.max(np.abs(totals["flat"]))) or 1.0
rel = float(np.max(np.abs(totals["flat"] - totals["hier"]))) / scale
parity = bool(rel < 1e-5)
if pid == 0:
    h, m = (int(s) for s in topo.split("x"))
    sfx = f"nproc{n}_d{dim_bits}"
    fp, hp = rec["flat"]["phases"], rec["hier"]["phases"]
    # flat fleets co-locate the same M processes per physical host the
    # hierarchical grouping names: a flat HOST ships M ring shares
    flat_per_host = m * fp["wire_bytes_per_host"]
    out = {
        f"collective_round_ms_{sfx}": round(rec["flat"]["ms"], 2),
        f"collective_round_ms_{sfx}_hier": round(rec["hier"]["ms"], 2),
        f"collective_scaling_topo_nproc{n}": topo,
        f"collective_wire_bytes_per_host_{sfx}": flat_per_host,
        f"collective_wire_bytes_per_host_{sfx}_hier":
            hp["wire_bytes_per_host"],
        f"collective_wire_per_host_reduction_nproc{n}": round(
            flat_per_host / max(1, hp["wire_bytes_per_host"]), 2),
        f"collective_hier_parity_nproc{n}": parity,
        f"collective_hier_max_rel_err_nproc{n}": rel,
        f"collective_phase_intra_ms_{sfx}_hier": hp["intra_ms"],
        f"collective_phase_inter_ms_{sfx}_hier": hp["inter_ms"],
        f"collective_scaling_note_nproc{n}": (
            f"{n} gloo CPU processes grouped {topo} time-slicing one "
            "core: ms bounds orchestration, wire bytes are the model"),
    }
    print("SCALING=" + json.dumps(out), flush=True)
print(f"CHILD-{pid}-DONE", flush=True)
"""

#: nproc -> the HxM grouping the scaling sweep exercises (hosts on the
#: wire x processes co-located per host)
SCALING_TOPOLOGIES = {4: "2x2", 8: "2x4", 16: "4x4"}


def scaling_sweep(nprocs=(4, 8, 16), dim_bits: int = NORTH_STAR_BITS,
                  timeout: float = 900.0) -> dict:
    """Round time + wire bytes vs nproc, flat vs hierarchical (ISSUE 9).

    The scaling gate: the flat ring's wire bytes per host grow with the
    DEVICE count (every process ships the payload's ring share; M
    co-located processes multiply it), the hierarchical reduce's stay
    proportional to HOSTS on the wire — one chunk copy per host,
    whatever M is. Each world also asserts bit-parity between the two
    paths on identical inputs. On this box the gloo 'intra' tier is
    loopback TCP, not ICI, so round-time wins only appear at nproc>=8
    where the flat ring's hop count dominates; the wire-byte keys are
    the portable claim."""
    out: dict = {}
    for n in nprocs:
        topo = SCALING_TOPOLOGIES.get(n)
        if topo is None:
            h = max(1, n // 4)
            topo = f"{h}x{n // h}"
        err_key = f"collective_scaling_error_nproc{n}"
        try:
            outs, rcs = run_jax_world(
                _SCALING_CHILD, n, timeout=timeout,
                extra_args=(str(dim_bits), topo))
        except subprocess.TimeoutExpired:
            out[err_key] = "timeout"
            continue
        if any(rc != 0 for rc in rcs):
            out[err_key] = f"child exits {rcs}: {(''.join(outs))[-300:]}"
            continue
        got = False
        for text in outs:
            for line in text.splitlines():
                if line.startswith("SCALING="):
                    out.update(json.loads(line[len("SCALING="):]))
                    got = True
        if not got:
            out[err_key] = "no master output"
    return out


def async_fold_probe(dim_bits: int = 20, members: int = 4,
                     trials: int = 5) -> dict:
    """Fold-phase cost of the async plane's bounded-staleness weights
    (ISSUE 11): a weighted host fold of ``members`` dense 2^dim_bits
    f32 diffs vs the sync plane's plain tree_sum over the same
    payloads. The weighting is one extra multiply per stale
    contribution — the probe records the measured overhead ratio so
    "staleness weights are ~free at fold time" stays a number, not a
    claim. (The round-BARRIER comparison — sync gather stalled by a
    straggler vs async cadence — is bench_serving's
    ``e2e_async_mix_straggler_cadence_x``.)"""
    import numpy as np

    from jubatus_tpu.framework.async_mixer import fold_weight, scale_tree
    from jubatus_tpu.parallel.mix import tree_sum

    rng = np.random.default_rng(11)
    d = 1 << dim_bits
    diffs = [{"w": rng.normal(size=d).astype(np.float32),
              "b": rng.normal(size=16).astype(np.float32)}
             for _ in range(members)]
    # half the members one round stale, one at the bound — the shape a
    # mildly-degraded fleet folds every tick
    stal = [0, 1] * (members // 2) + [0] * (members % 2)
    weights = [fold_weight(s, 8) for s in stal]

    def timed(fn):
        best = float("inf")
        for _ in range(trials):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    plain_ms = timed(lambda: tree_sum(diffs))
    weighted_ms = timed(lambda: tree_sum(
        [scale_tree(df, w) for df, w in zip(diffs, weights)]))
    tag = f"d{dim_bits}_m{members}"
    out = {f"mix_async_fold_ms_{tag}": round(weighted_ms, 3),
           f"mix_sync_fold_ms_{tag}": round(plain_ms, 3)}
    if plain_ms > 0:
        out[f"mix_async_fold_weighted_overhead_ratio_{tag}"] = round(
            weighted_ms / plain_ms, 3)
    return out


def collect(dev=None) -> dict:
    import jax

    out = device_round(20, dev, tag="d20")
    out.update(device_round(NORTH_STAR_BITS, dev, trials=3, tag="d24"))
    # the platform the single-device rounds ran on (a cpu here means the
    # tunnel was down and every mix_round_ms_* above is host CPU)
    out["mix_platform"] = (dev.platform if dev is not None
                           else jax.devices()[0].platform)
    out.update(_allreduce8_subprocess())
    out.update(collective_nproc(4))
    # multi-round drift of the quantized transport vs f32 on a real
    # 4-process world: error feedback bounded vs stateless random walk
    out.update(drift_probe())
    # the d24 world measures f32, bf16 AND int8 rounds back to back (one
    # boot, one membership, flip-in-place): per-phase keys for all three
    # variants let the --mix-compress tradeoff be audited per term
    # (on-device cast/quant cost vs 2x/4x fewer wire bytes) instead of
    # as one opaque total (VERDICT r4 #5)
    out.update(collective_nproc(4, dim_bits=NORTH_STAR_BITS, timeout=1800))
    # nproc scaling curve, flat vs hierarchical (ISSUE 9): wire bytes
    # per host must track hosts-on-the-wire, not total processes
    out.update(scaling_sweep())
    # async mix (ISSUE 11): staleness-weighted fold cost vs plain sum
    out.update(async_fold_probe())
    # wire-reduction ratio the int8 mode actually achieved at d24, and
    # the round-time comparison against the bf16 baseline (on CPU
    # loopback the quantization compute competes with the saved memcpy
    # on the SAME starved core — the wire win is the ICI story, see
    # docs/PERF_NOTES.md "Quantized mix")
    w_f32 = out.get(f"collective_wire_mb_per_round_d{NORTH_STAR_BITS}")
    w_int8 = out.get(f"collective_wire_mb_per_round_d{NORTH_STAR_BITS}_int8")
    if w_f32 and w_int8:
        out["collective_wire_reduction_int8_vs_f32"] = round(
            w_f32 / w_int8, 2)
    ms_bf16 = out.get(f"collective_round_ms_nproc4_d{NORTH_STAR_BITS}_bf16")
    ms_int8 = out.get(f"collective_round_ms_nproc4_d{NORTH_STAR_BITS}_int8")
    if ms_bf16 and ms_int8:
        out["collective_round_int8_vs_bf16_ratio"] = round(
            ms_int8 / ms_bf16, 3)
    gates = [v for k, v in out.items() if k.startswith("mix_round_ms_d24_")]
    if gates:
        out["mix_round_worst_ms"] = max(gates)
    # the north-star flag (BASELINE.md: mix round <= 1 s at D=2^24) is
    # computed ONLY from the measurement that includes BOTH the scale and
    # the multi-process transport: the nproc4 collective round shipping
    # d24 AROW diffs, labeled with the platform that ran it (VERDICT r3:
    # a single-device psum on the CPU fallback checks no box).
    ns_key = f"collective_round_ms_nproc4_d{NORTH_STAR_BITS}"
    if ns_key in out:
        ms = out[ns_key]
        plat = out.get(f"collective_round_d{NORTH_STAR_BITS}_platform",
                       "cpu")
        out["mix_under_1s_target"] = bool(ms < 1000.0)
        out["mix_under_1s_platform"] = plat
        if plat == "cpu" and ms >= 1000.0:
            payload = out.get(
                f"collective_round_d{NORTH_STAR_BITS}"
                "_payload_mb_per_replica", 0.0)
            wire = payload * 2 * 3 / 4  # ring allreduce, n=4
            out["mix_under_1s_note"] = (
                f"fails on cpu orchestration (4 processes time-slicing one "
                f"core, loopback transport); passing needs real chips: "
                f"~{wire:.0f} MB/replica on the wire per round, i.e. ICI "
                f"must sustain >= {wire / 1000:.1f} GB/s per link with "
                f"host orchestration off the critical path")
    return out


_SHARDED_CHILD = r"""
import json, os, sys, time
import numpy as np
import jax, jax.numpy as jnp

dim_bits = int(sys.argv[1]); shards = int(sys.argv[2])
method = sys.argv[3] if len(sys.argv) > 3 else "AROW"
B, K, L = 2048, 32, 2
D = 1 << dim_bits
from jubatus_tpu.ops import classifier as ops
from jubatus_tpu.parallel import sharded_model as sm

conf = method in ops.CONFIDENCE_METHODS
rng = np.random.default_rng(0)
idx = jnp.asarray(rng.integers(0, D, (B, K)).astype(np.int32))
val = jnp.asarray(rng.normal(size=(B, K)).astype(np.float32))
labels = jnp.asarray(rng.integers(0, L, B).astype(np.int32))
mask = jnp.asarray(np.ones(L, bool))
qi = jnp.asarray(rng.integers(0, D, (256, K)).astype(np.int32))
qv = jnp.asarray(rng.normal(size=(256, K)).astype(np.float32))

if shards > 1:
    mesh = sm.feature_shard_mesh(shards)
    st = sm.place_state(mesh, ops.init_state(L, D, conf), D)
    train = lambda s: sm.train_batch(mesh, s, idx, val, labels, mask,
                                     1.0, method=method)
    classify = lambda s: sm.scores(mesh, s, qi, qv, mask)
else:
    st = ops.init_state(L, D, conf)
    train = lambda s: ops.train_batch(s, idx, val, labels, mask, 1.0,
                                      method=method)
    classify = lambda s: ops.scores(s, qi, qv, mask)

# per-device weight-state footprint: the acceptance criterion's shape
per_dev = {}
for leaf in st:
    for sh in leaf.addressable_shards:
        per_dev[sh.device.id] = per_dev.get(sh.device.id, 0) + \
            int(np.prod(sh.data.shape)) * leaf.dtype.itemsize
total_bytes = sum(int(leaf.nbytes) for leaf in st)

st = train(st); jax.block_until_ready(st)         # compile
t_train = []
for _ in range(5):
    t0 = time.perf_counter()
    st = train(st); jax.block_until_ready(st)
    t_train.append(time.perf_counter() - t0)
sc = classify(st); jax.block_until_ready(sc)      # compile
t_cls = []
for _ in range(15):
    t0 = time.perf_counter()
    jax.block_until_ready(classify(st))
    t_cls.append(time.perf_counter() - t0)
out = {
    "samples_per_sec": round(B / float(np.median(t_train)), 1),
    "classify_p99_ms": round(
        float(np.percentile(np.asarray(t_cls) * 1e3, 99)), 2),
    "state_bytes_total": total_bytes,
    "state_bytes_per_device_max": max(per_dev.values()),
    "devices": len(per_dev),
}
print(json.dumps(out))
"""


def run_sharded_model(dim_bits: int = 26, shard_counts=(1, 8),
                      method: str = "AROW",
                      timeout: float = 1800.0) -> dict:
    """Feature-sharded linear model bench (ISSUE 13): train samples/s
    and classify p99 at D=2^dim_bits, single- vs multi-shard, each in a
    subprocess with that many virtual devices. Emits
    ``sharded_train_samples_per_sec_d{bits}_{s}shard`` (up-good) and
    ``sharded_classify_p99_ms_d{bits}_{s}shard`` (down-good), plus the
    per-device weight-state footprint that IS the HBM-capacity win —
    virtual CPU devices share one core, so multi-shard WALL numbers
    bound orchestration + psum cost, not chip throughput (same caveat
    as allreduce8)."""
    import jax

    out: dict = {"sharded_model_platform": jax.devices()[0].platform}
    for s in shard_counts:
        env = scrub_child_env(dict(os.environ))
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={max(s, 1)}"])
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _SHARDED_CHILD, str(dim_bits),
                 str(s), method],
                capture_output=True, text=True, timeout=timeout, env=env)
            doc = json.loads(proc.stdout.strip().splitlines()[-1])
        except Exception as e:  # noqa: BLE001 — partial results beat a dead bench
            out[f"sharded_model_error_{s}shard"] = repr(e)[:200]
            continue
        tag = f"d{dim_bits}_{s}shard"
        out[f"sharded_train_samples_per_sec_{tag}"] = doc["samples_per_sec"]
        out[f"sharded_classify_p99_ms_{tag}"] = doc["classify_p99_ms"]
        out[f"sharded_state_mb_per_device_{tag}"] = round(
            doc["state_bytes_per_device_max"] / 2 ** 20, 1)
        out[f"sharded_state_mb_total_{tag}"] = round(
            doc["state_bytes_total"] / 2 ** 20, 1)
    # the acceptance shape: per-device footprint <= total / n_shards
    # (+ O(1) replicated leaves) — recorded as a boolean gate
    for s in shard_counts:
        if s <= 1:
            continue
        tag = f"d{dim_bits}_{s}shard"
        per = out.get(f"sharded_state_mb_per_device_{tag}")
        tot = out.get(f"sharded_state_mb_total_{tag}")
        if per is not None and tot is not None:
            out[f"sharded_footprint_sliced_{tag}_ok"] = \
                bool(per <= tot / s + 1.0)
    return out


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "sharded":
        # the ISSUE 13 slice on its own: feature-sharded train/classify
        # at D=2^bits (default 26), single- vs N-shard
        bits = int(sys.argv[2]) if len(sys.argv) > 2 else 26
        shards = int(sys.argv[3]) if len(sys.argv) > 3 else 8
        print(json.dumps(run_sharded_model(bits, (1, shards)), indent=1))
    else:
        print(json.dumps(collect(), indent=1))
