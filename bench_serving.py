"""End-to-end serving-plane benchmark: RPC train samples/s through a real
EngineServer (VERDICT r1 item 2 — measure the product, not the kernel).

Path measured: client msgpack encode -> TCP loopback -> transport framing ->
native ingest parse (C++: datum decode + fv convert + feature hashing,
native/fast_ingest.cpp) -> microbatch coalesce -> jitted AROW update on the
bench device; the Python-converter path serves as the fallback A/B. This is
the reference's hot ingest path (classifier_serv.cpp:127-146) reshaped for
TPU (SURVEY.md §3.2).

Clients are separate PROCESSES (their encode work must not share the
server's GIL — in-process client threads understate the server by ~2x),
and they PRE-ENCODE their request frames once, then pump raw bytes: this
host gives the whole bench ONE CPU core (client processes, server, and the
C++ baseline all share it), and a Python client's msgpack encode costs
~20 us/sample — 16 Python clients alone cannot generate 200k samples/s of
traffic on that core. The reference's clients are C++ (encode ~ns-scale);
pre-encoding emulates C++-speed clients so the metric measures the SERVER
plane (framing, C++ ingest parse, coalescing, device step, response), which
does full per-request work either way. A warmup phase triggers every
bucket-shape compile before timing starts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

N_CLIENTS = 16
CALL_BATCH = 500
K = 32                  # numeric features per datum
WARMUP_SECONDS = 12.0
MEASURE_SECONDS = 20.0
TEXT_MEASURE_SECONDS = 12.0
#: base seed for every client worker's rng (ISSUE 12 satellite): each
#: client derives its stream from [SEED, client_idx], so a whole run's
#: traffic trace is reproducible across runs — the pid-seeded rngs the
#: clients used before made no two runs comparable. --seed overrides.
SEED = 1729

CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}

#: text workload (VERDICT r2 item 6): space splitter + tf sample weight —
#: the reference's canonical text shape (≙ config/classifier/pa.json's
#: string_rules, tokenized) — native-expressible since round 2/3
TEXT_CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "tf",
         "global_weight": "bin"}]},
}

#: idf global weight: since round 3 the native parser takes the
#: WeightManager's dense df tables and replays observe+scale in C++
#: (fraction 1.0); before that this metric measured the Python-converter
#: fallback at ~6.5k samples/s
TEXT_IDF_CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "tf",
         "global_weight": "idf"}]},
}

#: combination rules (≙ config/classifier/arow_combinational_feature.json):
#: native-expressible since round 4 — the C++ parser runs the named cross
#: product (K numeric features -> K*(K-1)/2 extra pairs per datum)
COMBO_CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {
        "num_rules": [{"key": "*", "type": "num"}],
        "combination_rules": [
            {"key_left": "*", "key_right": "*", "type": "mul"}],
    },
}

#: string filters ride the HYBRID fast path since round 5: the regex
#: itself runs in Python (std::regex vs `re` divergence risk — round-3
#: finding), memoized per distinct input, via a request rewrite; the
#: datum walk/tokenize/tf/hash stay in C++ (fraction 1.0; the mode is
#: recorded in e2e_text_filter_mode)
TEXT_FILTER_CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {
        "string_filter_types": {
            "strip_digits": {"method": "regexp", "pattern": "[0-9]+",
                             "replace": ""}},
        "string_filter_rules": [
            {"key": "*", "type": "strip_digits", "suffix": "-nodigit"}],
        "string_rules": [
            {"key": "*", "type": "space", "sample_weight": "tf",
             "global_weight": "bin"}]},
}

_CLIENT_PROG = r"""
import os, socket, sys, time
import numpy as np
import msgpack
port, call_batch, k, warmup, measure, workload = (
    int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
    float(sys.argv[4]), float(sys.argv[5]), sys.argv[6])
from jubatus_tpu.client import Datum
# replayable traffic (ISSUE 12): per-client stream derived from the
# run's base seed + this client's index ("pid" keeps the old behavior)
seed, idx = sys.argv[7], int(sys.argv[8])
rng = (np.random.default_rng(os.getpid()) if seed == "pid"
       else np.random.default_rng([int(seed), idx]))
VOCAB = [f"w{i:03d}" for i in range(400)]

def mk_datum():
    if workload.startswith("text"):
        words = rng.choice(len(VOCAB), size=k)
        return Datum({"body": " ".join(VOCAB[w] for w in words)})
    return Datum({f"f{j}": float(v)
                  for j, v in enumerate(rng.normal(size=k))})

frames = []
train_frames = []
for _ in range(8):
    batch = []
    for _ in range(call_batch):
        label = "a" if rng.random() < 0.5 else "b"
        batch.append([label, mk_datum().to_msgpack()])
    train_frames.append(msgpack.packb([0, 1, "train", ["bench", batch]],
                                      use_bin_type=True))
if workload == "classify":
    # query plane: read-mostly traffic against a model the warmup trains
    for _ in range(8):
        batch = [mk_datum().to_msgpack() for _ in range(call_batch)]
        frames.append(msgpack.packb([0, 1, "classify", ["bench", batch]],
                                    use_bin_type=True))
else:
    frames = train_frames
sock = socket.create_connection(("127.0.0.1", port), timeout=120.0)
sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
unp = msgpack.Unpacker()
PIPELINE = 4  # msgpack-rpc pipelining: keep the server core saturated

def read_reply():
    while True:
        try:
            msg = unp.unpack()
            if msg[2] is not None:  # msgpack-rpc error slot: a failing
                raise RuntimeError(msg[2])  # server must fail the bench
            return
        except msgpack.OutOfData:
            pass
        data = sock.recv(65536)
        if not data:
            raise ConnectionError("server closed")
        unp.feed(data)

in_flight = 0
def call(frame):
    global in_flight
    sock.sendall(frame)
    in_flight += 1
    if in_flight >= PIPELINE:
        read_reply()
        in_flight -= 1

if workload == "classify":
    # give the model labels/weights before querying it
    call(train_frames[0])
    while in_flight:
        read_reply(); in_flight -= 1
deadline_warm = time.perf_counter() + warmup
i = 0
while time.perf_counter() < deadline_warm:
    call(frames[i % len(frames)]); i += 1
count = 0
t0 = time.perf_counter()
deadline = t0 + measure
while time.perf_counter() < deadline:
    call(frames[i % len(frames)]); i += 1; count += call_batch
while in_flight:  # completed-work accounting: drain before the clock stops
    read_reply(); in_flight -= 1
elapsed = time.perf_counter() - t0
print(f"CLIENT {count} {elapsed:.4f}")
"""


def _latency_keys(trace_snapshot: dict, suffix: str) -> dict:
    """Steady-state per-RPC latency quantiles from the server's span
    histograms (utils/tracing.py), keyed for the BENCH json. mean_ms
    rides along because it is CONTINUOUS (total/count) where the
    quantiles are bucket-quantized (~19% steps) — the overhead A/Bs'
    <2% budgets are only resolvable against the mean."""
    out = {}
    for m in ("train", "classify"):
        for q in ("p50_ms", "p99_ms", "mean_ms"):
            k = f"trace.rpc.{m}.{q}"
            if k in trace_snapshot:
                out[f"e2e_rpc_{m}_{q}_{suffix}"] = trace_snapshot[k]
    return out


def _default_microbatch() -> int:
    """Flush-size cap by platform: on a real chip big flushes amortize
    the tunnel round trip (the kernel's sweet spot is 32k,
    docs/PERF_NOTES.md); on the CPU fallback the device step runs ON the
    single bench core, so a big flush starves the loadgen (measured:
    cap 32k = 113k samples/s vs cap 8k = 145k, same shape otherwise)."""
    import jax

    return 32768 if jax.default_backend() != "cpu" else 8192


def run(transport: str = "python", workload: str = "numeric",
        conf: dict = CONF, measure: float = MEASURE_SECONDS,
        tag: str = "", microbatch: int = 0, native_ingest: bool = True,
        forensics: bool = True, model_health=None,
        profile_hz=None, events_enabled=None, quality=None,
        usage=None, seed=None) -> dict:
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    prev = os.environ.get("JUBATUS_TPU_NATIVE_RPC")
    prev_ing = os.environ.get("JUBATUS_TPU_NATIVE_INGEST")
    # native is the DEFAULT transport now; "0" forces the Python one
    os.environ["JUBATUS_TPU_NATIVE_RPC"] = \
        "1" if transport == "native" else "0"
    # set BOTH ways (like NATIVE_RPC above): an inherited =0 from an
    # operator shell must not silently turn the native rows into
    # Python-ingest runs and flatten the A/B to ~1.0
    os.environ["JUBATUS_TPU_NATIVE_INGEST"] = "1" if native_ingest else "0"
    # model_health (ISSUE 7): None keeps the stock server (the other
    # benches' behavior); True arms the FULL observability load —
    # 1 s telemetry ticks driving time-series ring sampling + SLO
    # burn-rate evaluation against live SLOs; False strips the plane
    # entirely (no ring, no SLO engine, no sampler thread) — the
    # honest "off" side of the overhead A/B
    health_args: dict = {}
    if model_health is True:
        health_args = dict(
            telemetry_interval=1.0,
            slo=["latency:rpc.classify:p99:50", "error_rate:*:0.01"],
            slo_fast_window=5.0, slo_slow_window=30.0)
    elif model_health is False:
        health_args = dict(telemetry_interval=0.0, timeseries_capacity=0)
    # profile_hz (ISSUE 8): None keeps the stock server (the always-on
    # sampler at its default rate); a number pins the sampling rate for
    # the profiling-overhead A/B (0 = sampler thread fully off)
    if profile_hz is not None:
        health_args["profile_hz"] = float(profile_hz)
    # events_enabled (ISSUE 14): None keeps the stock server (journal at
    # its default depth + incident triggers armed); False strips the
    # event plane entirely (capacity 0 = emit() no-ops, auto-capture
    # off) — the honest "off" side of the event-plane overhead A/B
    if events_enabled is False:
        health_args["event_capacity"] = 0
        health_args["incident_window"] = 0.0
    # quality (ISSUE 17): None keeps the stock server (data-quality
    # plane at its default sampling); True arms it at the documented
    # production rate (5% of train/score rows feed the sketches);
    # False disarms it entirely (sample 0.0 = admit() never fires,
    # recorder calls are a single float compare) — the honest "off"
    # side of the quality-overhead A/B
    if quality is True:
        health_args["quality_sample"] = 0.05
    elif quality is False:
        health_args["quality_sample"] = 0.0
    # usage (ISSUE 19): None keeps the stock server (usage ledger armed
    # at its default top-64 table); True pins the documented default
    # explicitly; False disarms the attribution plane entirely (top 0 =
    # no ledger object, the span sink is never installed, recorder
    # hooks stay None) — the honest "off" side of the usage-overhead A/B
    if usage is True:
        health_args["usage_top"] = 64
    elif usage is False:
        health_args["usage_top"] = 0
    try:
        srv = EngineServer(
            "classifier", conf,
            args=ServerArgs(engine="classifier", thread=N_CLIENTS,
                            listen_addr="127.0.0.1",
                            microbatch_max=microbatch
                            or _default_microbatch(), **health_args))
        # forensics=False: histograms stay on (the p50/p99 keys below need
        # them) but the span store + slow log are disabled — the A/B for
        # ISSUE 4's <2% overhead budget
        if not forensics:
            srv.rpc.trace.set_forensics(False)
        port = srv.start(0)
    finally:
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev
        if prev_ing is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_INGEST", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_INGEST"] = prev_ing

    repo = os.path.dirname(os.path.abspath(__file__))
    from bench_mix import scrub_child_env  # one owner for the env scrub

    env = scrub_child_env(os.environ)
    procs = []
    total, elapsed_max = 0, 0.0
    # "mixed": half the clients write (train), half read (classify),
    # concurrently against one server — the snapshot-read-under-write-load
    # story the reference settles with a process-wide rw lock
    # (server_helper.hpp:296-303); here reads coalesce against model
    # snapshots while writes flush (VERDICT r4 #6)
    wl_list = (["numeric" if i % 2 == 0 else "classify"
                for i in range(N_CLIENTS)]
               if workload == "mixed" else [workload] * N_CLIENTS)
    per_wl = {wl: 0 for wl in wl_list}
    stats = {}
    trace_snapshot: dict = {}
    # quantile hygiene: reset the server's span registry once the clients'
    # warmup window closes, so the histograms embedded in the BENCH json
    # cover steady state only (warmup includes every bucket-shape compile)
    reset_timer = threading.Timer(WARMUP_SECONDS + 1.0, srv.rpc.trace.reset)
    reset_timer.daemon = True
    reset_timer.start()
    # try/finally like run_proxy: a communicate() timeout or client crash
    # must not leak the server + up to N_CLIENTS load generators into the
    # next trial's measurement window (they'd share the single bench core)
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _CLIENT_PROG, str(port),
                 str(CALL_BATCH), str(K), str(WARMUP_SECONDS), str(measure),
                 wl, str(SEED if seed is None else seed), str(idx)],
                env=env, cwd=repo, stdout=subprocess.PIPE, text=True)
            for idx, wl in enumerate(wl_list)
        ]
        dead: list = []
        for idx, (p, wl) in enumerate(zip(procs, wl_list)):
            out, _ = p.communicate(timeout=WARMUP_SECONDS + measure + 240)
            reported = False
            for line in out.splitlines():
                if line.startswith("CLIENT "):
                    _, cnt, el = line.split()
                    total += int(cnt)
                    per_wl[wl] += int(cnt)
                    elapsed_max = max(elapsed_max, float(el))
                    reported = True
            # a client that died without a CLIENT line would otherwise
            # contribute a silent 0 and the run would report a
            # plausible-but-low number as if every client were counted
            if p.returncode != 0 or not reported:
                dead.append(f"client {idx} ({wl}): rc={p.returncode}, "
                            f"tail={out[-120:]!r}")
        for nm, co in srv.coalescers.items():
            stats[nm] = co.stats()
        # steady-state latency quantiles off the server's own registry
        # (reset at warmup end above) — the per-request tail the
        # throughput number hides
        trace_snapshot = srv.rpc.trace.trace_status()
    finally:
        reset_timer.cancel()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        srv.stop()
    sps = total / elapsed_max if elapsed_max else 0.0
    if dead:
        err = "; ".join(dead)
        if workload == "mixed":
            return {"e2e_mixed_error": err}
        return {f"e2e_rpc_{workload}_error_{tag or transport}": err}
    if workload == "mixed":
        out = {
            "e2e_mixed_train_classify_samples_per_sec": round(sps, 1),
            "e2e_mixed_train_samples_per_sec": round(
                per_wl.get("numeric", 0) / elapsed_max, 1)
            if elapsed_max else 0.0,
            "e2e_mixed_classify_samples_per_sec": round(
                per_wl.get("classify", 0) / elapsed_max, 1)
            if elapsed_max else 0.0,
        }
        out.update(_latency_keys(trace_snapshot, "mixed"))
        return out
    fast_items = stats.get("train_raw", {}).get("item_count", 0)
    slow_items = stats.get("train", {}).get("item_count", 0)
    avg_batch = 0.0
    for s in stats.values():
        if s.get("item_count"):
            avg_batch = max(avg_batch, s.get("avg_batch", 0.0))
    suffix = tag or transport
    verb = "classify" if workload == "classify" else "train"
    out = {f"e2e_rpc_{verb}_samples_per_sec_{suffix}": round(sps, 1)}
    out.update(_latency_keys(trace_snapshot, suffix))
    ing = getattr(srv, "ingest_stats", None) or {}
    if verb == "train":  # coalescer stats are train-plane only
        out[f"e2e_avg_device_batch_{suffix}"] = round(avg_batch, 1)
        out[f"e2e_fast_path_fraction_{suffix}"] = round(
            fast_items / max(fast_items + slow_items, 1), 3)
        # host/device overlap (ISSUE 5): fraction of stage-1 featurize
        # time hidden under an active device flush, from whichever train
        # coalescer carried the traffic (PipelinedCoalescer stats)
        ov = max((s.get("overlap_fraction", 0.0)
                  for s in stats.values() if s.get("prep_seconds", 0.0) > 0),
                 default=None)
        if ov is not None:
            out[f"e2e_fv_overlap_fraction_{suffix}"] = round(ov, 4)
        nf = (ing.get("schema_flushes", 0) + ing.get("sparse_flushes", 0)
              + ing.get("combo_flushes", 0))
        if nf:  # dense-submatrix plan engagement (uniform key schema)
            out[f"e2e_schema_flush_fraction_{suffix}"] = round(
                ing.get("schema_flushes", 0) / nf, 3)
            # device-side combo expansion engagement (base-width wire)
            if ing.get("combo_flushes", 0):
                out[f"e2e_combo_flush_fraction_{suffix}"] = round(
                    ing.get("combo_flushes", 0) / nf, 3)
    else:
        # the query-plane claim is LAUNCH collapse (VERDICT r4 weak #3):
        # dispatches/s and avg coalesced batch are the numbers of record
        qs = stats.get("classify_raw", {}) or stats.get("estimate_raw", {})
        if qs.get("flush_count") and elapsed_max:
            # flush_count covers warmup+measure; scale by the measured
            # fraction of traffic for an honest per-second figure
            frac = total / max(qs.get("item_count", total), 1)
            out[f"e2e_{verb}_dispatches_per_sec_{suffix}"] = round(
                qs["flush_count"] * frac / elapsed_max, 1)
            out[f"e2e_{verb}_avg_coalesced_batch_{suffix}"] = round(
                qs.get("avg_batch", 0.0), 1)
        nq = (ing.get("schema_query_flushes", 0)
              + ing.get("sparse_query_flushes", 0))
        if nq:
            out[f"e2e_schema_query_flush_fraction_{suffix}"] = round(
                ing.get("schema_query_flushes", 0) / nq, 3)
    return out


def run_fv_convert(seconds: float = 2.0) -> dict:
    """Pure host-featurization throughput for the two shapes ISSUE 5
    targets (no server, no device): ``convert_batch`` over 2048-datum
    batches, K=32 features/datum — the featurize-plane numbers the e2e
    keys decompose against. tools/bench_fv_sweep.py is the full
    batch-size x config sweep; this embeds the two keys of record."""
    import numpy as np

    from jubatus_tpu.core import Datum
    from jubatus_tpu.core.fv import make_fv_converter

    rng = np.random.default_rng(0)
    vocab = [f"w{i:03d}" for i in range(400)]
    out = {}
    for tag, conf in (("combo", COMBO_CONF), ("text_idf", TEXT_IDF_CONF)):
        if tag == "combo":
            data = [Datum({f"f{j}": float(v)
                           for j, v in enumerate(rng.normal(size=K))})
                    for _ in range(2048)]
        else:
            data = [Datum({"body": " ".join(
                vocab[w] for w in rng.choice(len(vocab), size=K))})
                for _ in range(2048)]
        conv = make_fv_converter(conf["converter"], dim_bits=18)
        conv.convert_batch(data[:64], update_weights=True)  # warm plans
        n = 0
        t0 = time.perf_counter()
        deadline = t0 + seconds
        while True:
            conv.convert_batch(data, update_weights=True)
            n += 1
            now = time.perf_counter()
            if now >= deadline:
                break
        out[f"e2e_fv_convert_samples_per_sec_{tag}"] = round(
            n * len(data) / (now - t0), 1)
    return out


def run_tracing_overhead(transport: str = "python",
                         measure: float = TEXT_MEASURE_SECONDS) -> dict:
    """ISSUE 4 satellite: the forensics layer ships with its cost
    measured. Adjacent A/B on the classify (query) plane — span store +
    slow log ENABLED vs DISABLED (histograms on both sides, so the
    steady-state p50/p99 keys come from the same machinery) — and the
    p50 ratio of record, budgeted at <2% regression. One bench core
    swings ~±10% run to run, so the ok-flag uses the MEDIAN-free single
    adjacent pair plus slack only in the honest direction: a ratio a
    hair over 1.02 on a noisy host is reported as-is."""
    out: dict = {}
    sides = {}
    for tag, forensics in (("forensics_on", True), ("forensics_off", False)):
        try:
            r = run(transport, workload="classify", measure=measure,
                    tag=tag, forensics=forensics)
        except Exception as e:  # noqa: BLE001 — partial results beat none
            out[f"e2e_{tag}_error"] = repr(e)[:200]
            continue
        out.update(r)
        sides[tag] = r
    p50_on = sides.get("forensics_on", {}).get(
        "e2e_rpc_classify_p50_ms_forensics_on")
    p50_off = sides.get("forensics_off", {}).get(
        "e2e_rpc_classify_p50_ms_forensics_off")
    if p50_on and p50_off:
        ratio = p50_on / p50_off
        out["e2e_tracing_overhead_p50_ratio"] = round(ratio, 4)
        out["e2e_tracing_overhead_ok"] = bool(ratio <= 1.02)
    p99_on = sides.get("forensics_on", {}).get(
        "e2e_rpc_classify_p99_ms_forensics_on")
    p99_off = sides.get("forensics_off", {}).get(
        "e2e_rpc_classify_p99_ms_forensics_off")
    if p99_on and p99_off:
        out["e2e_tracing_overhead_p99_ratio"] = round(p99_on / p99_off, 4)
    return out


def run_observability_overhead(transport: str = "python",
                               measure: float = TEXT_MEASURE_SECONDS
                               ) -> dict:
    """ISSUE 7 satellite: the FULL observability plane's cost, measured
    the same adjacent-A/B way as the ISSUE 4 tracing overhead — but the
    "on" side now also carries time-series ring sampling + live SLO
    burn-rate evaluation on a 1 s telemetry tick, and the "off" side
    strips forensics AND the model-health plane entirely. Same classify
    workload, same <2% p50 budget
    (``e2e_observability_overhead_p50_ratio``)."""
    out: dict = {}
    sides = {}
    for tag, forensics, health in (("obs_on", True, True),
                                   ("obs_off", False, False)):
        try:
            r = run(transport, workload="classify", measure=measure,
                    tag=tag, forensics=forensics, model_health=health)
        except Exception as e:  # noqa: BLE001 — partial results beat none
            out[f"e2e_{tag}_error"] = repr(e)[:200]
            continue
        out.update(r)
        sides[tag] = r
    p50_on = sides.get("obs_on", {}).get("e2e_rpc_classify_p50_ms_obs_on")
    p50_off = sides.get("obs_off", {}).get("e2e_rpc_classify_p50_ms_obs_off")
    if p50_on and p50_off:
        ratio = p50_on / p50_off
        out["e2e_observability_overhead_p50_ratio"] = round(ratio, 4)
        out["e2e_observability_overhead_ok"] = bool(ratio <= 1.02)
    p99_on = sides.get("obs_on", {}).get("e2e_rpc_classify_p99_ms_obs_on")
    p99_off = sides.get("obs_off", {}).get("e2e_rpc_classify_p99_ms_obs_off")
    if p99_on and p99_off:
        out["e2e_observability_overhead_p99_ratio"] = round(
            p99_on / p99_off, 4)
    return out


def run_event_plane_overhead(transport: str = "python",
                             measure: float = TEXT_MEASURE_SECONDS
                             ) -> dict:
    """ISSUE 14 satellite: the event plane ships with its serving cost
    measured. The plane is OFF the request hot path by design (events
    fire on state transitions, not per request), so the A/B — journal
    at default depth + incident triggers armed vs capacity 0 + triggers
    off — measures the residual hook cost under the same classify
    workload and <2% p50 budget as the other observability planes.
    A per-emit microbench (``e2e_event_emit_us``) pins the cost one
    transition pays when it DOES fire."""
    out: dict = {}
    sides = {}
    for tag, enabled in (("events_on", None), ("events_off", False)):
        try:
            r = run(transport, workload="classify", measure=measure,
                    tag=tag, events_enabled=enabled)
        except Exception as e:  # noqa: BLE001 — partial results beat none
            out[f"e2e_{tag}_error"] = repr(e)[:200]
            continue
        out.update(r)
        sides[tag] = r
    p50_on = sides.get("events_on", {}).get(
        "e2e_rpc_classify_p50_ms_events_on")
    p50_off = sides.get("events_off", {}).get(
        "e2e_rpc_classify_p50_ms_events_off")
    if p50_on and p50_off:
        ratio = p50_on / p50_off
        out["e2e_event_plane_overhead_p50_ratio"] = round(ratio, 4)
        out["e2e_event_plane_overhead_ok"] = bool(ratio <= 1.02)
    mean_on = sides.get("events_on", {}).get(
        "e2e_rpc_classify_mean_ms_events_on")
    mean_off = sides.get("events_off", {}).get(
        "e2e_rpc_classify_mean_ms_events_off")
    if mean_on and mean_off:
        out["e2e_event_plane_overhead_mean_ratio"] = round(
            mean_on / mean_off, 4)
    # per-emit cost: what one state transition pays to land on the
    # timeline (journal append + HLC tick + trace-context probe)
    from jubatus_tpu.utils.events import EventJournal

    j = EventJournal(capacity=2048)
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        j.emit("bench", "tick", seq=i)
    out["e2e_event_emit_us"] = round(
        (time.perf_counter() - t0) / n * 1e6, 3)
    return out


def run_profiling_overhead(transport: str = "python",
                           measure: float = TEXT_MEASURE_SECONDS,
                           pairs: int = 3) -> dict:
    """ISSUE 8 satellite: the always-on stack sampler ships with its
    cost measured. Adjacent A/B PAIRS on the classify plane — sampler
    ON at the default ~67 Hz vs fully OFF (no thread) — with
    median-of-pairs ratios: the histogram quantiles move in ~19%
    bucket steps, so a single pair's p50 ratio reads either 1.0 or a
    full bucket (dry runs: 1.0, 1.0, 1.1892 from identical code). The
    <2% budget (``e2e_profiling_overhead_ok``) therefore gates on the
    CONTINUOUS mean-latency ratio, with the median p50 ratio required
    to stay within one bucket step."""
    out: dict = {}
    r_p50, r_p99, r_mean = [], [], []
    for i in range(max(1, pairs)):
        sides = {}
        for tag, hz in (("prof_on", 67.0), ("prof_off", 0.0)):
            try:
                r = run(transport, workload="classify", measure=measure,
                        tag=tag, profile_hz=hz)
            except Exception as e:  # noqa: BLE001 — partial beats none
                out[f"e2e_{tag}_error"] = repr(e)[:200]
                continue
            if i == 0:
                out.update(r)  # per-side keys of record: first pair
            sides[tag] = r
        for key, acc in (("p50_ms", r_p50), ("p99_ms", r_p99),
                         ("mean_ms", r_mean)):
            on = sides.get("prof_on", {}).get(
                f"e2e_rpc_classify_{key}_prof_on")
            off = sides.get("prof_off", {}).get(
                f"e2e_rpc_classify_{key}_prof_off")
            if on and off:
                acc.append(on / off)
    import numpy as _np

    if r_p50:
        med_p50 = float(_np.median(r_p50))
        out["e2e_profiling_overhead_p50_ratio"] = round(med_p50, 4)
        if r_mean:
            med_mean = float(_np.median(r_mean))
            out["e2e_profiling_overhead_mean_ratio"] = round(med_mean, 4)
            # mean resolves the 2%; p50 can only prove "same bucket"
            out["e2e_profiling_overhead_ok"] = bool(
                med_mean <= 1.02 and med_p50 <= 1.19)
        out["e2e_profiling_overhead_note"] = (
            f"median of {len(r_p50)} adjacent on/off pairs; p50/p99 are "
            "bucket-quantized (~19% steps), the mean ratio carries the "
            "<2% verdict")
    if r_p99:
        out["e2e_profiling_overhead_p99_ratio"] = round(
            float(_np.median(r_p99)), 4)
    return out


def run_quality_overhead(transport: str = "python",
                         measure: float = TEXT_MEASURE_SECONDS,
                         pairs: int = 3) -> dict:
    """ISSUE 17: the data-quality plane ships with its serving cost
    measured. Adjacent A/B PAIRS on the classify plane — recorder
    armed at the documented 5% sample vs ``--quality-sample 0`` (the
    off side's recorder calls collapse to one float compare in
    ``admit``) — through the Python converter so the ``convert_batch``
    recording hook sits ON the measured path. Same protocol and <2%
    budget as run_profiling_overhead: a single pair swings ~±10% on
    the shared core, so the verdict is the MEDIAN-of-pairs mean ratio,
    with the median p50 ratio held to one histogram bucket step
    (~19%)."""
    out: dict = {}
    r_p50, r_mean = [], []
    for i in range(max(1, pairs)):
        sides = {}
        for tag, armed in (("quality_on", True), ("quality_off", False)):
            try:
                r = run(transport, workload="classify", measure=measure,
                        tag=tag, native_ingest=False, quality=armed)
            except Exception as e:  # noqa: BLE001 — partial beats none
                out[f"e2e_{tag}_error"] = repr(e)[:200]
                continue
            if i == 0:
                out.update(r)  # per-side keys of record: first pair
            sides[tag] = r
        for key, acc in (("p50_ms", r_p50), ("mean_ms", r_mean)):
            on = sides.get("quality_on", {}).get(
                f"e2e_rpc_classify_{key}_quality_on")
            off = sides.get("quality_off", {}).get(
                f"e2e_rpc_classify_{key}_quality_off")
            if on and off:
                acc.append(on / off)
    import numpy as _np

    if r_p50 and r_mean:
        med_p50 = float(_np.median(r_p50))
        med_mean = float(_np.median(r_mean))
        out["e2e_quality_overhead_p50_ratio"] = round(med_p50, 4)
        out["e2e_quality_overhead_mean_ratio"] = round(med_mean, 4)
        out["e2e_quality_overhead_ok"] = bool(
            med_mean <= 1.02 and med_p50 <= 1.19)
        out["e2e_quality_overhead_note"] = (
            f"median of {len(r_mean)} adjacent on/off pairs; the mean "
            "ratio carries the <2% verdict, p50 is bucket-quantized "
            "(~19% steps)")
    return out


def run_usage_overhead(transport: str = "python",
                       measure: float = TEXT_MEASURE_SECONDS,
                       pairs: int = 3) -> dict:
    """ISSUE 19: the usage-attribution plane ships with its serving
    cost measured. Adjacent A/B PAIRS on the classify plane — ledger
    armed at the documented top-64 table vs ``--usage-top 0`` (the off
    side never constructs a ledger: no span sink, no recorder hooks,
    no per-request principal swap billing) — same protocol and <2%
    budget as run_quality_overhead: a single pair swings ~±10% on the
    shared core, so the verdict is the MEDIAN-of-pairs mean ratio,
    with the median p50 ratio held to one histogram bucket step
    (~19%)."""
    out: dict = {}
    r_p50, r_mean = [], []
    for i in range(max(1, pairs)):
        sides = {}
        for tag, armed in (("usage_on", True), ("usage_off", False)):
            try:
                r = run(transport, workload="classify", measure=measure,
                        tag=tag, native_ingest=False, usage=armed)
            except Exception as e:  # noqa: BLE001 — partial beats none
                out[f"e2e_{tag}_error"] = repr(e)[:200]
                continue
            if i == 0:
                out.update(r)  # per-side keys of record: first pair
            sides[tag] = r
        for key, acc in (("p50_ms", r_p50), ("mean_ms", r_mean)):
            on = sides.get("usage_on", {}).get(
                f"e2e_rpc_classify_{key}_usage_on")
            off = sides.get("usage_off", {}).get(
                f"e2e_rpc_classify_{key}_usage_off")
            if on and off:
                acc.append(on / off)
    import numpy as _np

    if r_p50 and r_mean:
        med_p50 = float(_np.median(r_p50))
        med_mean = float(_np.median(r_mean))
        out["e2e_usage_overhead_p50_ratio"] = round(med_p50, 4)
        out["e2e_usage_overhead_mean_ratio"] = round(med_mean, 4)
        out["e2e_usage_overhead_ok"] = bool(
            med_mean <= 1.02 and med_p50 <= 1.19)
        out["e2e_usage_overhead_note"] = (
            f"median of {len(r_mean)} adjacent on/off pairs; the mean "
            "ratio carries the <2% verdict, p50 is bucket-quantized "
            "(~19% steps)")
    return out


def run_usage_attribution(nproc: int = 4, seconds: float = 18.0,
                          base_rate: float = 40.0, seed=None) -> dict:
    """ISSUE 19: the usage ledger's books must BALANCE. A mixed
    3-tenant fleet_sim profile (checkout/search/ads, tenant id on the
    envelope's 7th element) drives proxy + two backends; afterwards the
    conservation gate compares, per node, the ledger's accounted
    CPU-thread-seconds against the span plane's process totals (sum of
    ``rpc.*`` dispatch-histogram ``total_s``, client spans excluded) and
    the accounted device-seconds against the coalescers' measured device
    time. Both sides observe the SAME work through different pipes — a
    gap means requests are escaping attribution.

    Keys of record:

    - ``e2e_usage_attribution_err_frac`` — worst per-node relative gap
      across both planes; gated ≤ 0.10 (``..._ok``).
    - ``e2e_usage_tenants_distinct_ok`` — the fleet-merged doc (live
      ``get_usage`` through the proxy, folded with
      ``usage.merge_usage``) shows ≥ 2 tenants with distinct nonzero
      CPU cost — attribution, not just accounting.
    - ``e2e_capacity_headroom`` — a backend's published headroom gauge
      after a forced capacity tick.
    """
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs
    from jubatus_tpu.utils import usage as usage_mod
    from bench_mix import scrub_child_env

    fleet_sim = _fleet_sim()
    seed = SEED if seed is None else int(seed)
    # flat rate, no flash: the gate is about books, not elasticity
    model = fleet_sim.TrafficModel(seed=seed, base_rate=base_rate,
                                   diurnal_amplitude=0.0)
    prev = os.environ.get("JUBATUS_TPU_NATIVE_RPC")
    os.environ["JUBATUS_TPU_NATIVE_RPC"] = "0"
    servers: list = []
    proxy = None
    out: dict = {}
    try:
        store = _Store()
        for _ in range(2):
            srv = EngineServer(
                "classifier", CONF,
                args=ServerArgs(engine="classifier",
                                coordinator="(shared)", name="usage",
                                listen_addr="127.0.0.1", thread=32,
                                interval_sec=1e9, interval_count=1 << 30,
                                telemetry_interval=1.0),
                coord=MemoryCoordinator(store))
            srv.start(0)
            servers.append(srv)
        proxy = Proxy(ProxyArgs(engine="classifier",
                                listen_addr="127.0.0.1", thread=64,
                                interconnect_timeout=120.0),
                      coord=MemoryCoordinator(store))
        pport = proxy.start(0)
        res = fleet_sim.drive(
            pport, model, nproc, seconds, cluster="usage",
            workload="train", call_batch=4, lat_slo_ms=1000.0,
            inflight_cap=16, env=scrub_child_env(os.environ))
        out["e2e_usage_driven_done"] = int(res.get("done", 0))

        # -- conservation: ledger vs span plane, per node ---------------
        errs = []
        for node in servers + [proxy]:
            hists = node.rpc.trace.snapshot()["hists"]
            span_s = sum(
                h["total_s"] for n, h in hists.items()
                if n.startswith("rpc.") and
                not n.startswith("rpc.client."))
            tot = node.usage.totals()
            if span_s > 1e-3:
                errs.append(abs(tot["cpu_seconds"] - span_s) / span_s)
        # device plane: billed device shares vs the coalescers' clock
        dev_led = sum(s.usage.totals()["device_seconds"]
                      for s in servers)
        dev_clock = sum(
            co.stats().get("device_seconds", 0.0)
            for s in servers for co in s.coalescers.values())
        if dev_clock > 1e-3:
            errs.append(abs(dev_led - dev_clock) / dev_clock)
        err = max(errs) if errs else 1.0
        out["e2e_usage_attribution_err_frac"] = round(err, 4)
        out["e2e_usage_attribution_ok"] = bool(err <= 0.10)

        # -- distinct per-tenant cost via the LIVE fold path ------------
        # (the same pipe jubactl -c usage reads: get_usage through the
        # proxy broadcasts to members; merge is sketch/table fold,
        # never gauge averaging)
        with RpcClient("127.0.0.1", pport, timeout=30.0) as c:
            docs = c.call("get_usage", "usage")
        fleet = usage_mod.merge_usage(
            [d for d in docs.values() if d])
        rows = usage_mod.principal_rows(fleet)
        tenant_cpu = {p: agg["cpu_seconds"] for p, agg in rows
                      if not p.startswith("(") and
                      agg["cpu_seconds"] > 0.0}
        out["e2e_usage_tenants_seen"] = len(tenant_cpu)
        out["e2e_usage_tenants_distinct_ok"] = bool(
            len(tenant_cpu) >= 2 and
            len(set(round(v, 6) for v in tenant_cpu.values())) >= 2)
        for p, v in sorted(tenant_cpu.items()):
            out[f"e2e_usage_cpu_s_{p}"] = round(v, 4)

        # -- capacity headroom gauge ------------------------------------
        srv0 = servers[0]
        srv0.usage.tick(srv0._capacity_rows_per_sec())
        st = srv0.usage.stats()
        if "headroom" in st:
            out["e2e_capacity_headroom"] = round(
                float(st["headroom"]), 4)
    finally:
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev
        if proxy is not None:
            proxy.stop()
        for s in servers:
            s.stop()
    return out


def run_usage(transport: str = "python",
              measure: float = TEXT_MEASURE_SECONDS) -> dict:
    """ISSUE 19 aggregate: attribution conservation + overhead A/B."""
    out: dict = {}
    try:
        out.update(run_usage_attribution())
    except Exception as e:  # noqa: BLE001 — partial beats none
        out["e2e_usage_attribution_error"] = repr(e)[:200]
    try:
        out.update(run_usage_overhead(transport, measure=measure))
    except Exception as e:  # noqa: BLE001 — partial beats none
        out["e2e_usage_overhead_error"] = repr(e)[:200]
    return out


def run_quality_prequential(batches: int = 80, batch: int = 40,
                            holdout: int = 400) -> dict:
    """ISSUE 17: the prequential (test-then-train) estimate must TRACK
    reality. Margin-separated linear labels (PA converges within the
    first batches), microbatch OFF so the train handler's current-model
    scoring is synchronous and deterministic, one quality window that
    never rolls. After training, a FRESH holdout is classified with the
    final model; the streaming estimate must sit within one point of
    that held-out accuracy (``e2e_prequential_tracks_holdout_ok``)."""
    import numpy as np
    from jubatus_tpu.client import Datum
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    rng = np.random.default_rng(SEED)
    w = rng.standard_normal(8)
    w /= float(np.linalg.norm(w))

    def draw(n):
        rows = []
        while len(rows) < n:
            x = rng.uniform(-1.0, 1.0, size=8)
            m = float(x @ w)
            if abs(m) < 0.3:  # margin: PA separates this in one pass
                continue
            rows.append(("pos" if m > 0 else "neg",
                         Datum({f"f{j}": float(x[j]) for j in range(8)})))
        return rows

    prev = os.environ.get("JUBATUS_TPU_NATIVE_RPC")
    os.environ["JUBATUS_TPU_NATIVE_RPC"] = "0"
    srv = None
    out: dict = {}
    try:
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", listen_addr="127.0.0.1",
                            thread=4, microbatch_max=0,
                            telemetry_interval=0.0, quality_sample=1.0,
                            quality_window=1e6))
        port = srv.start(0)
        with RpcClient("127.0.0.1", port, timeout=120.0) as c:
            for _ in range(batches):
                c.call("train", "quality",
                       [[lab, d.to_msgpack()] for lab, d in draw(batch)])
            ok = n = 0
            rows = draw(holdout)
            for i in range(0, len(rows), 50):
                chunk = rows[i:i + 50]
                ranked = c.call("classify", "quality",
                                [d.to_msgpack() for _lab, d in chunk])
                for (lab, _d), r in zip(chunk, ranked):
                    n += 1
                    if not r:
                        continue
                    top = max(r, key=lambda kv: float(kv[1]))[0]
                    if isinstance(top, bytes):
                        top = top.decode()
                    ok += int(top == lab)
        st = srv.quality.stats()
    finally:
        if srv is not None:
            srv.stop()
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev
    preq = st.get("prequential_accuracy")
    hold = round(ok / max(n, 1), 4)
    out["e2e_prequential_accuracy"] = preq
    out["e2e_holdout_accuracy"] = hold
    out["e2e_prequential_scored_rows"] = st.get("scored_rows", 0)
    if preq is not None:
        out["e2e_prequential_tracks_holdout_ok"] = bool(
            abs(preq - hold) <= 0.01 + 1e-9)
    return out


def run_quality_drift_drill(nproc: int = 4, shift_at: float = 15.0,
                            magnitude: float = 1.5, window_s: float = 6.0,
                            base_rate: float = 80.0,
                            threshold: float = 0.2) -> dict:
    """ISSUE 17 drill: a seeded mid-run covariate+concept shift
    (fleet_sim ``--shift-at``) must light the whole reporting chain:
    ``quality.drift.<group>`` crosses the threshold within two windows
    of the shift, the drift SLO (plain ``gauge:`` grammar — zero new
    SLO machinery) fires, and exactly ONE incident bundle captures the
    offending feature group's reference/live sketch pair.

    ``e2e_drift_baseline_psi`` is the pre-shift false-alarm level
    (down-good: a rising baseline means the detector is noisy);
    ``e2e_shift_peak_score`` records the drill's magnitude for context
    (its absolute value tracks the injected shift, not code quality).

    Sizing: clean-window PSI noise rides the number of DISTINCT user
    draws per group-window (``call_batch`` duplicates the same datum,
    adding no information). 80 req/s over 6 s windows gives the
    smallest tenant (ads, weight 0.2) ~96 draws/window — enough to
    hold the clean-phase level under the 0.2 operating point."""
    import tempfile

    from jubatus_tpu.client import Datum
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from bench_mix import scrub_child_env

    fleet_sim = _fleet_sim()
    seconds = 2.0 * shift_at  # symmetric clean/shifted phases
    model = fleet_sim.TrafficModel(
        seed=SEED, base_rate=base_rate, diurnal_amplitude=0.0,
        shift_at=shift_at, shift_magnitude=magnitude)
    feature_groups = {t[:2] for t, _w in model.tenants}
    prev = os.environ.get("JUBATUS_TPU_NATIVE_RPC")
    prev_ing = os.environ.get("JUBATUS_TPU_NATIVE_INGEST")
    os.environ["JUBATUS_TPU_NATIVE_RPC"] = "0"
    # Python ingest: feature NAMES must reach the recorder so drift
    # lands in the per-tenant groups the incident is meant to name
    # (the native raw path records under the one "hashed" group)
    os.environ["JUBATUS_TPU_NATIVE_INGEST"] = "0"
    inc_dir = tempfile.mkdtemp(prefix="jubatus_quality_drill_")
    srv = None
    res: dict = {}
    records: list = []
    stop = threading.Event()
    out: dict = {
        "e2e_shift_at_s": shift_at, "e2e_shift_magnitude": magnitude,
        "e2e_quality_window_s": window_s}
    try:
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(
                engine="classifier", name="fleet",
                listen_addr="127.0.0.1", thread=32,
                interval_sec=1e9, interval_count=1 << 30,
                telemetry_interval=1.0,
                quality_sample=1.0, quality_window=window_s,
                quality_ref_windows=1,
                slo=[f"drift=gauge:quality.drift.max:{threshold:g}"],
                slo_fast_window=window_s, slo_slow_window=2 * window_s,
                incident_dir=inc_dir))
        port = srv.start(0)
        # warm the jit caches before the clock starts (the first train
        # compiles ~seconds and would eat the clean phase) WITHOUT
        # letting the constant warm-up rows pollute the reference
        # window the clean traffic pins
        srv.quality.arm(sample=0.0)
        warm = [["a", Datum({f"{t[:2]}{j}": 0.5 for j in range(8)}
                            ).to_msgpack()] for t, _w in model.tenants]
        with RpcClient("127.0.0.1", port, timeout=120.0) as c:
            c.call("train", "fleet", warm * 4)
        srv.rpc.trace.reset()

        from jubatus_tpu.utils.quality import OUTPUT_DRIFT_KEYS

        def monitor():
            while not stop.wait(0.5):
                try:
                    scores = {g: v for g, v in
                              srv.quality.drift_scores().items()
                              if g not in OUTPUT_DRIFT_KEYS}
                    records.append({
                        "ts": time.time(),
                        "drift_max": max(scores.values())
                        if scores else 0.0,
                        "alerts": [a["name"] for a in
                                   (srv.slo.alerts() if srv.slo
                                    else [])]})
                except Exception:  # noqa: BLE001 — bench monitor
                    pass

        mon = threading.Thread(target=monitor, daemon=True,
                               name="quality-drill-monitor")
        mon.start()
        # re-arm just after the workers' start barrier falls, so the
        # first live window (-> the pinned reference) covers exactly
        # one window of real traffic, not the idle warm-up stretch
        rearm = threading.Timer(5.3, srv.quality.arm, kwargs={
            "sample": 1.0})
        rearm.daemon = True
        rearm.start()
        res = fleet_sim.drive(
            port, model, nproc, seconds, cluster="fleet",
            workload="train", call_batch=4, lat_slo_ms=1000.0,
            inflight_cap=16, start_delay_s=5.0,
            env=scrub_child_env(os.environ))
        # grace: the final window's drift + the SLO's slow-burn window
        # may settle a few ticks after the trace ends
        deadline = time.monotonic() + 3.0 * window_s
        while time.monotonic() < deadline:
            if records and records[-1]["alerts"]:
                break
            time.sleep(0.5)
        stop.set()
        mon.join(timeout=5.0)
        scores = srv.quality.drift_scores()
        inc = srv.incidents.list()
        bundles = inc.get("incidents", [])
        inc_doc = (srv.incidents.get(bundles[0]["id"])
                   if len(bundles) == 1 else {})
    finally:
        stop.set()
        if srv is not None:
            srv.stop()
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev
        if prev_ing is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_INGEST", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_INGEST"] = prev_ing
    if res.get("dead"):
        out["e2e_drift_drill_dead_clients"] = "; ".join(res["dead"])
    shift_wall = res.get("t0_wall", 0.0) + shift_at
    clean = [r["drift_max"] for r in records if r["ts"] < shift_wall]
    out["e2e_drift_baseline_psi"] = round(max(clean), 4) if clean else 0.0
    out["e2e_shift_peak_score"] = round(
        max((r["drift_max"] for r in records), default=0.0), 4)
    first = next((r for r in records if r["ts"] >= shift_wall
                  and r["drift_max"] > threshold), None)
    lag = round(first["ts"] - shift_wall, 1) if first else -1.0
    out["e2e_drift_detection_lag_s"] = lag
    # "within two windows" with one tick of slack: the live window only
    # crosses min-count ~a second into the shifted regime
    out["e2e_drift_detected_ok"] = bool(
        first is not None and lag <= 2.0 * window_s + 1.5)
    out["e2e_drift_slo_fired_ok"] = any(
        "drift" in r["alerts"] for r in records)
    feat = {g: v for g, v in scores.items() if g in feature_groups}
    if feat:
        out["e2e_shift_group"] = max(feat.items(),
                                     key=lambda kv: kv[1])[0]
    out["e2e_drift_incident_count"] = len(bundles)
    top = (inc_doc.get("quality") or {}).get("top_drift_group", "") \
        if inc_doc else ""
    out["e2e_drift_incident_ok"] = bool(
        len(bundles) == 1 and top in feature_groups)
    if top:
        out["e2e_drift_incident_group"] = top
    return out


def run_quality(transport: str = "python",
                measure: float = TEXT_MEASURE_SECONDS) -> dict:
    """ISSUE 17 slice: quality-plane overhead A/B + prequential-vs-
    holdout tracking + the seeded concept-shift drill."""
    out: dict = {}
    try:
        out.update(run_quality_overhead(transport, measure))
    except Exception as e:  # noqa: BLE001 — partial results beat none
        out["e2e_quality_overhead_error"] = repr(e)[:200]
    try:
        out.update(run_quality_prequential())
    except Exception as e:  # noqa: BLE001
        out["e2e_prequential_error"] = repr(e)[:200]
    try:
        out.update(run_quality_drift_drill())
    except Exception as e:  # noqa: BLE001
        out["e2e_drift_drill_error"] = repr(e)[:200]
    return out


def run_proxy(transport: str = "python",
              measure: float = MEASURE_SECONDS) -> dict:
    """Proxy-tier path (VERDICT r2 item 8): clients -> Proxy (random
    routing, session pool) -> EngineServer, numeric workload. Proxy and
    server share this process (the host has ONE core, so separate
    processes would interleave on it exactly like threads do); the proxy
    hop's real cost — decode, route, re-encode, second socket — is all
    here. Reference shape: juba*_proxy, proxy.hpp:502-593."""
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    prev = os.environ.get("JUBATUS_TPU_NATIVE_RPC")
    # native is the DEFAULT transport now; "0" forces the Python one
    os.environ["JUBATUS_TPU_NATIVE_RPC"] = \
        "1" if transport == "native" else "0"
    srv = proxy = None
    procs = []
    try:
        store = _Store()
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator="(shared)",
                            name="bench", listen_addr="127.0.0.1",
                            thread=N_CLIENTS, interval_sec=1e9,
                            interval_count=1 << 30),
            coord=MemoryCoordinator(store))
        srv.start(0)
        # interconnect timeout must cover the backend's coalescer wait
        # (train blocks until its flush; the server grants timeout*6):
        # the default 10 s intermittently fires under full pipelining on
        # the one-core host, failing the whole trial with a timeout the
        # raw relay correctly refuses to retry (double-apply risk)
        proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1",
                                thread=N_CLIENTS,
                                interconnect_timeout=120.0),
                      coord=MemoryCoordinator(store))
        pport = proxy.start(0)
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev

        repo = os.path.dirname(os.path.abspath(__file__))
        from bench_mix import scrub_child_env

        env = scrub_child_env(os.environ)
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _CLIENT_PROG, str(pport),
                 str(CALL_BATCH), str(K), str(WARMUP_SECONDS), str(measure),
                 "numeric", str(SEED), str(idx)],
                env=env, cwd=repo, stdout=subprocess.PIPE, text=True)
            for idx in range(N_CLIENTS)
        ]
        total, elapsed_max = 0, 0.0
        for p in procs:
            out, _ = p.communicate(
                timeout=WARMUP_SECONDS + measure + 240)
            for line in out.splitlines():
                if line.startswith("CLIENT "):
                    _, cnt, el = line.split()
                    total += int(cnt)
                    elapsed_max = max(elapsed_max, float(el))
    finally:
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev
        for p in procs:
            if p.poll() is None:
                p.kill()
        if proxy is not None:
            proxy.stop()
        if srv is not None:
            srv.stop()
    sps = total / elapsed_max if elapsed_max else 0.0
    out = {f"e2e_rpc_train_samples_per_sec_proxy_{transport}":
           round(sps, 1)}
    # self-healing plane quiescence proof (ISSUE 3): on the happy path
    # the retry/failover budget must not be spent and no breaker may
    # trip — a nonzero rate here means the plane is misfiring under
    # normal load, not healing anything
    counters = proxy.rpc.trace.counters() if proxy is not None else {}
    forwards = max(1, proxy.forward_count) if proxy is not None else 1
    out["e2e_retry_rate"] = round(
        counters.get("rpc.retries", 0) / forwards, 6)
    out["e2e_breaker_open_total"] = sum(
        b.get("opened_total", 0)
        for b in (proxy.breakers.snapshot().values()
                  if proxy is not None else []))
    out["e2e_fanout_timeouts_total"] = counters.get(
        "proxy.fanout_timeouts", 0)
    return out


#: churn-tolerant load generator (elastic membership, ISSUE 10): counts
#: per-call errors instead of dying on the first one, and reconnects
#: when the proxy drops the connection — the churn bench measures the
#: CLUSTER's error behavior, so the client must survive to report it
_CHURN_CLIENT_PROG = r"""
import os, socket, sys, time
import numpy as np
import msgpack
port, call_batch, k, warmup, measure, workload = (
    int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
    float(sys.argv[4]), float(sys.argv[5]), sys.argv[6])
from jubatus_tpu.client import Datum
# replayable traffic (ISSUE 12): same per-client stream derivation as
# the main client program — churn traces replay across runs too
seed, idx = sys.argv[7], int(sys.argv[8])
rng = (np.random.default_rng(os.getpid()) if seed == "pid"
       else np.random.default_rng([int(seed), idx]))

def mk_datum():
    return Datum({f"f{j}": float(v)
                  for j, v in enumerate(rng.normal(size=k))})

frames = []
for _ in range(8):
    batch = []
    for _ in range(call_batch):
        label = "a" if rng.random() < 0.5 else "b"
        batch.append([label, mk_datum().to_msgpack()])
    if workload == "classify":
        frames.append(msgpack.packb(
            [0, 1, "classify", ["bench", [d for _l, d in batch]]],
            use_bin_type=True))
    else:
        frames.append(msgpack.packb([0, 1, "train", ["bench", batch]],
                                    use_bin_type=True))

sock = None
unp = msgpack.Unpacker()
def connect():
    global sock, unp
    if sock is not None:
        try: sock.close()
        except OSError: pass
    sock = socket.create_connection(("127.0.0.1", port), timeout=30.0)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    unp = msgpack.Unpacker()
connect()

errors = 0
def call(frame):
    # one call in flight (no pipelining: per-call error accounting)
    global errors
    try:
        sock.sendall(frame)
        while True:
            try:
                msg = unp.unpack()
                break
            except msgpack.OutOfData:
                pass
            data = sock.recv(65536)
            if not data:
                raise ConnectionError("closed")
            unp.feed(data)
        if msg[2] is not None:
            errors += 1
        return True
    except (OSError, ConnectionError):
        errors += 1
        for _ in range(20):
            try:
                connect()
                return False
            except OSError:
                time.sleep(0.25)
        raise

deadline_warm = time.perf_counter() + warmup
i = 0
while time.perf_counter() < deadline_warm:
    call(frames[i % len(frames)]); i += 1
count = 0
errors = 0  # steady-state accounting only
t0 = time.perf_counter()
deadline = t0 + measure
while time.perf_counter() < deadline:
    if call(frames[i % len(frames)]):
        count += call_batch
    i += 1
elapsed = time.perf_counter() - t0
print(f"CHURNCLIENT {workload} {count} {errors} {elapsed:.4f}")
"""


def run_churn(transport: str = "python", measure: float = 60.0,
              churn_period: float = 30.0, backends: int = 3) -> dict:
    """Churn chaos bench (elastic membership, ISSUE 10): 16 mixed
    clients (8 train / 8 classify) against a proxy over ``backends``
    classifier servers while a churn thread KILLS one backend and boots
    a replacement every ``churn_period`` seconds.

    Keys of record:

    - ``e2e_churn_mixed_error``  — error fraction of IDEMPOTENT
      (classify) traffic during churn; the breaker/failover/ring-refresh
      planes must hold it ~0.
    - ``e2e_churn_train_error``  — error fraction of effectful traffic
      (bounded, not zero: a train in flight on the killed socket cannot
      be blindly re-forwarded).
    - ``e2e_churn_p99_inflation_ratio`` — churn-window p99 over the
      quiescent p99 measured first on the same topology (max over
      train/classify at the proxy hop).
    - ``e2e_churn_epoch`` — final membership epoch (join/leave count).
    """
    import numpy as _np

    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    prev = os.environ.get("JUBATUS_TPU_NATIVE_RPC")
    os.environ["JUBATUS_TPU_NATIVE_RPC"] = \
        "1" if transport == "native" else "0"
    store = _Store()

    def boot():
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator="(shared)",
                            name="bench", listen_addr="127.0.0.1",
                            thread=8, interval_sec=1e9,
                            interval_count=1 << 30),
            coord=MemoryCoordinator(store))
        srv.start(0)
        return srv

    servers = []
    proxy = None
    procs = []
    stop_churn = threading.Event()
    churn_events = [0]
    try:
        servers = [boot() for _ in range(backends)]
        proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1",
                                thread=N_CLIENTS,
                                interconnect_timeout=120.0),
                      coord=MemoryCoordinator(store))
        pport = proxy.start(0)
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev

        def churn_loop():
            rng = _np.random.default_rng(0)
            while not stop_churn.wait(churn_period):
                victim_i = int(rng.integers(len(servers)))
                victim = servers[victim_i]
                victim.stop()  # hard kill: ephemeral regs vanish
                churn_events[0] += 1
                if stop_churn.wait(2.0):  # let breakers/refresh react
                    return
                servers[victim_i] = boot()
                churn_events[0] += 1

        repo = os.path.dirname(os.path.abspath(__file__))
        from bench_mix import scrub_child_env

        env = scrub_child_env(os.environ)
        # phase 1 (quiescent): same topology, no churn — the p99
        # baseline the inflation ratio divides by
        quiet_measure = max(measure / 3.0, 10.0)
        wl_list = ["numeric" if i % 2 == 0 else "classify"
                   for i in range(N_CLIENTS)]

        def load(seconds):
            ps = [subprocess.Popen(
                [sys.executable, "-c", _CHURN_CLIENT_PROG, str(pport),
                 str(CALL_BATCH), str(K), str(WARMUP_SECONDS / 2),
                 str(seconds), wl, str(SEED), str(idx)],
                env=env, cwd=repo, stdout=subprocess.PIPE, text=True)
                for idx, wl in enumerate(wl_list)]
            procs.extend(ps)
            # quantile hygiene (same stance as run()): drop the clients'
            # warmup window (compiles, cold sockets) from the phase's
            # histograms so quiet-vs-churn p99 compares steady states
            rt = threading.Timer(WARMUP_SECONDS / 2 + 1.0,
                                 proxy.rpc.trace.reset)
            rt.daemon = True
            rt.start()
            counts = {"numeric": 0, "classify": 0}
            errs = {"numeric": 0, "classify": 0}
            calls = {"numeric": 0, "classify": 0}
            elapsed = 0.0
            for p in ps:
                out, _ = p.communicate(timeout=seconds + 300)
                for line in out.splitlines():
                    if line.startswith("CHURNCLIENT "):
                        _, wl, cnt, er, el = line.split()
                        counts[wl] += int(cnt)
                        errs[wl] += int(er)
                        calls[wl] += int(cnt) // CALL_BATCH + int(er)
                        elapsed = max(elapsed, float(el))
            return counts, errs, calls, elapsed

        proxy.rpc.trace.reset()
        load(quiet_measure)
        quiet = proxy.rpc.trace.trace_status()
        # phase 2 (churn): kill/boot cycle under the same load
        proxy.rpc.trace.reset()
        churner = threading.Thread(target=churn_loop, daemon=True,
                                   name="churn")
        churner.start()
        counts, errs, calls, elapsed = load(measure)
        stop_churn.set()
        churner.join(timeout=10.0)
        churned = proxy.rpc.trace.trace_status()
    finally:
        stop_churn.set()
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev
        for p in procs:
            if p.poll() is None:
                p.kill()
        if proxy is not None:
            proxy.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown
                pass
    out = {
        "e2e_churn_events": churn_events[0],
        "e2e_churn_mixed_error": round(
            errs["classify"] / max(calls["classify"], 1), 6),
        "e2e_churn_train_error": round(
            errs["numeric"] / max(calls["numeric"], 1), 6),
        "e2e_churn_mixed_samples_per_sec": round(
            (counts["numeric"] + counts["classify"]) / elapsed, 1)
        if elapsed else 0.0,
    }
    from jubatus_tpu.coord.memory import MemoryCoordinator as _MC

    from jubatus_tpu.coord import membership as _membership

    out["e2e_churn_epoch"] = _membership.get_epoch(
        _MC(store), "classifier", "bench")
    ratios = []
    for m in ("train", "classify"):
        q = quiet.get(f"trace.rpc.{m}.p99_ms")
        c = churned.get(f"trace.rpc.{m}.p99_ms")
        if q and c:
            out[f"e2e_churn_rpc_{m}_p99_ms"] = c
            ratios.append(c / q)
    if ratios:
        out["e2e_churn_p99_inflation_ratio"] = round(max(ratios), 3)
        out["e2e_churn_p99_inflation_ok"] = bool(max(ratios) <= 3.0)
    return out


def run_killall_drill(nodes: int = 3, train_seconds: float = 10.0,
                      store_interval: float = 0.4) -> dict:
    """Kill-everything chaos drill (durable model plane, ISSUE 18): a
    fleet uploading to a shared snapshot store is hard-killed in its
    entirety — no drain, no save, every process gone at once — then
    rebooted from the store alone.

    Keys of record:

    - ``e2e_fleet_coldstart_to_serving_s`` — boot an EMPTY fleet and
      train it to its working model: the price of losing the model.
    - ``e2e_warmboot_recovery_s`` — boot the SAME fleet from the store
      after the massacre: snapshot download + chain replay, no
      retraining.
    - ``e2e_warmboot_beats_cold_ok`` — the whole point: recovery must
      beat retraining.
    - ``e2e_killall_model_loss_rows`` — acked training rows lost BEYOND
      the diff-chain tail. The store's contract is bounded loss: rows
      trained after the last uploaded record (the tail window, at most
      one ``--store-interval``) may die with the fleet; anything the
      chain acknowledged must replay. This key must be 0.
    - ``e2e_killall_tail_window_rows`` — rows in the allowed tail
      window (informational: bounded by interval x ingest rate).
    """
    import shutil as _shutil
    import tempfile as _tempfile

    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.framework.model_store import LocalDirBackend, ModelStore
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    store_dir = _tempfile.mkdtemp(prefix="jubatus_killall_store_")
    coord_store = _Store()

    def boot():
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator="(shared)",
                            name="bench", listen_addr="127.0.0.1",
                            thread=4, interval_sec=1e9,
                            interval_count=1 << 30,
                            telemetry_interval=0.1,
                            store_dir=store_dir,
                            store_interval=store_interval,
                            store_compact_every=6),
            coord=MemoryCoordinator(coord_store))
        srv.start(0)
        return srv

    def boot_fleet():
        """All processes restart concurrently after a massacre — boot
        in parallel, exactly like init respawning the whole host."""
        slots: list = [None] * nodes
        def one(i):
            slots[i] = boot()
        ts = [threading.Thread(target=one, args=(i,)) for i in range(nodes)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if any(s is None for s in slots):
            raise RuntimeError("fleet boot failed")
        return slots

    def first_classify(srv):
        """Serving = the node answers a query. Returns the client."""
        c = ClassifierClient("127.0.0.1", srv.rpc.port, "bench",
                             timeout=10.0)
        c.classify([Datum({f"f{j}": 0.0 for j in range(4)})])
        return c

    def datum(rng):
        return Datum({f"f{j}": float(v)
                      for j, v in enumerate(rng.normal(size=4))})

    rng = __import__("numpy").random.default_rng(SEED)
    servers: list = []
    out: dict = {}
    try:
        # ---- phase 1: cold start — empty store, boot + train to the
        # working model. This is what dying WITHOUT a store costs.
        t0 = time.monotonic()
        servers = boot_fleet()
        clients = [first_classify(s) for s in servers]
        acked = [0] * nodes
        deadline = time.monotonic() + train_seconds
        while time.monotonic() < deadline:
            for i, c in enumerate(clients):
                batch = [("pos" if rng.random() < 0.5 else "neg",
                          datum(rng)) for _ in range(50)]
                acked[i] += c.train(batch)
        for c in clients:
            c.classify([datum(rng)])
        cold_s = time.monotonic() - t0
        out["e2e_fleet_coldstart_to_serving_s"] = round(cold_s, 3)
        # let the last diff land, then freeze the per-node chain tails:
        # everything at/under these versions MUST survive the massacre
        time.sleep(store_interval + 0.5)
        reader = ModelStore(LocalDirBackend(store_dir), cluster="bench",
                            engine="classifier")
        tails = {}
        for rec in reader.records():
            tails[rec.node] = max(tails.get(rec.node, 0), rec.version)
        acked_by_node = {s._store_node_name(): acked[i]
                        for i, s in enumerate(servers)}
        # ---- phase 2: the massacre — every process hard-killed at
        # once (stop() drops ephemeral regs and persists NOTHING)
        for s in servers:
            s.stop()
        servers = []
        # ---- phase 3: warm reboot from the store alone
        t0 = time.monotonic()
        servers = boot_fleet()
        clients = [first_classify(s) for s in servers]
        warm_s = time.monotonic() - t0
        out["e2e_warmboot_recovery_s"] = round(warm_s, 3)
        out["e2e_warmboot_beats_cold_ok"] = bool(warm_s < cold_s)
        outcomes = [s.warmboot.get("outcome") for s in servers]
        out["e2e_killall_warm_nodes"] = outcomes.count("warm")
        out["e2e_warmboot_load_s"] = round(max(
            float(s.warmboot.get("seconds", 0.0)) for s in servers), 3)
        out["e2e_warmboot_chain_len"] = max(
            int(s.warmboot.get("chain_len", 0)) for s in servers)
        # ---- verdict: replay every pre-kill chain and count rows lost
        # beyond each tail (must be 0 — the chain acked them), plus the
        # allowed tail window (acked but never uploaded before death)
        loss_beyond_tail = 0
        tail_window = 0
        for node, tail_version in tails.items():
            _blob, meta = reader.materialize(node=node)
            loss_beyond_tail += max(0, tail_version
                                    - int(meta["model_version"]))
            tail_window += max(0, acked_by_node.get(node, 0)
                               - tail_version)
        out["e2e_killall_model_loss_rows"] = loss_beyond_tail
        out["e2e_killall_tail_window_rows"] = tail_window
        out["e2e_killall_acked_rows"] = sum(acked)
    finally:
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown
                pass
        _shutil.rmtree(store_dir, ignore_errors=True)
    return out


def run_migration_cycle(rows: int = 2000) -> dict:
    """Join -> migrate -> drain cycle on a nearest_neighbor cluster
    (elastic membership, ISSUE 10): measures the state-migration data
    plane's throughput and proves row parity across a full membership
    cycle.

    - ``e2e_migration_mb_per_sec`` — chunked double-buffered pull rate
      (framework/migration.py RangePuller) for a fresh joiner.
    - ``e2e_churn_rows_lost`` — rows missing from the union of
      survivors after join + drain (MUST be 0).
    """
    import numpy as _np

    from jubatus_tpu.client import Datum as _Datum
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    conf = {"method": "lsh", "parameter": {"hash_num": 64},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    store = _Store()

    def boot(auto=True):
        srv = EngineServer(
            "nearest_neighbor", conf,
            args=ServerArgs(engine="nearest_neighbor",
                            coordinator="(shared)", name="nn",
                            listen_addr="127.0.0.1", thread=4,
                            interval_sec=1e9, interval_count=1 << 30,
                            auto_rebalance=auto),
            coord=MemoryCoordinator(store))
        srv.start(0)
        return srv

    servers = [boot(), boot()]
    out: dict = {}
    try:
        rng = _np.random.default_rng(7)
        clients = [RpcClient("127.0.0.1", s.args.rpc_port)
                   for s in servers]
        for i in range(rows):
            d = _Datum({f"f{j}": float(v)
                        for j, v in enumerate(rng.normal(size=16))})
            clients[i % 2].call("set_row", "nn", f"row{i:06d}",
                                d.to_msgpack())
        # join cold, then a measured explicit rebalance = the migration
        # data plane's number of record
        joiner = boot(auto=False)
        servers.append(joiner)
        jc = RpcClient("127.0.0.1", joiner.args.rpc_port)
        pull = jc.call("rebalance", "nn")
        out["e2e_migration_mb_per_sec"] = float(pull.get("mb_per_sec", 0.0))
        out["e2e_migration_rows_pulled"] = int(pull.get("rows", 0))
        # drain the first server; every row must survive on the union
        clients[0].call("drain", "nn", False)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            st = clients[0].call("drain_status", "nn")
            state = st.get("state")
            state = state.decode() if isinstance(state, bytes) else state
            if state == "drained":
                break
            time.sleep(0.2)
        survivors = set()
        for s in servers[1:]:
            c = RpcClient("127.0.0.1", s.args.rpc_port)
            for rid in c.call("get_all_rows", "nn"):
                survivors.add(rid.decode()
                              if isinstance(rid, bytes) else rid)
            c.close()
        expect = {f"row{i:06d}" for i in range(rows)}
        out["e2e_churn_rows_total"] = rows
        out["e2e_churn_rows_lost"] = len(expect - survivors)
        for c in clients:
            c.close()
        jc.close()
    finally:
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown
                pass
    return out


def run_async_mix(rounds: int = 12, storm_seconds: float = 4.0) -> dict:
    """Asynchronous staleness-bounded mix bench (ISSUE 11): the round
    barrier off the serving path, measured.

    Phase 1 — drift-parity gate on matched fresh 3-member clusters
    (sync linear vs --mix-async) fed IDENTICAL training: the async
    fold's convergence telemetry and folded model must match the sync
    plane's (``e2e_async_mix_drift_parity_ok``).

    Phase 2 — cadence/stall storm on the async cluster: train/classify
    clients hammer every member while rounds stream back to back.

    - ``e2e_train_stall_during_mix_ms`` — worst measured model-lock
      hold attributable to the mix plane (snapshot + apply gauges)
      while rounds streamed: the "train never waits on a round" claim
      as a number.
    - ``e2e_async_mix_rounds_per_sec`` vs ``e2e_sync_mix_rounds_per_sec``
      — fold cadence under identical load; the async/sync ratio is the
      cadence headroom (``e2e_async_mix_cadence_x``).
    - ``e2e_async_classify_p99_during_mix_ms`` — serving tail while
      rounds stream (and the sync twin for comparison).
    """
    import threading as _threading

    import numpy as _np

    from jubatus_tpu.client import Datum as _Datum
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    conf = {"method": "PA",
            "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}

    def boot_cluster(mix_async: bool):
        store = _Store()
        servers = []
        for _ in range(3):
            srv = EngineServer(
                "classifier", conf,
                args=ServerArgs(engine="classifier",
                                coordinator="(shared)", name="asyncmix",
                                listen_addr="127.0.0.1", thread=4,
                                interval_sec=1e9,
                                interval_count=1 << 30,
                                telemetry_interval=0,
                                mix_async=mix_async),
                coord=MemoryCoordinator(store))
            srv.start(0)
            servers.append(srv)
        return servers

    def train(srv, rows):
        with RpcClient("127.0.0.1", srv.args.rpc_port) as c:
            c.call("train", "asyncmix",
                   [[label, _Datum(d).to_msgpack()] for label, d in rows])

    out: dict = {}
    # -- phase 1: drift parity on identical, quiesced traffic ---------------
    sync_cluster = boot_cluster(False)
    async_cluster = boot_cluster(True)
    try:
        assert async_cluster[0].mixer.mix_now() is not None  # master+hint
        rows_by_member = [
            [("l0", {"x": 1.0, "y": -0.5}), ("l1", {"x": -1.0, "y": 2.0})],
            [("l0", {"x": 0.5, "y": -2.0}), ("l1", {"x": -0.25, "y": 1.0})],
            [("l1", {"x": -2.0, "y": 0.75}), ("l0", {"x": 2.0, "y": -1.0})],
        ]
        div_sync, div_async = [], []
        for _ in range(3):
            for i in range(3):
                train(sync_cluster[i], rows_by_member[i])
                train(async_cluster[i], rows_by_member[i])
            rs = sync_cluster[0].mixer.mix_now()
            for s in async_cluster[1:]:
                s.mixer.submit_now()
            ra = async_cluster[0].mixer.mix_now()
            div_sync.append((rs or {}).get("health", {}).get(
                "premix_divergence_mean", 0.0))
            div_async.append((ra or {}).get("health", {}).get(
                "premix_divergence_mean", 0.0))
            rows_by_member = rows_by_member[1:] + rows_by_member[:1]
        out["e2e_async_mix_divergence_sync"] = round(
            float(_np.mean(div_sync)), 6)
        out["e2e_async_mix_divergence_async"] = round(
            float(_np.mean(div_async)), 6)
        # identical contributions + all-fresh weights must agree to
        # float noise; 5% absolute headroom keeps the gate honest
        # without riding rounding
        out["e2e_async_mix_drift_parity_ok"] = bool(
            _np.allclose(div_async, div_sync, rtol=1e-3, atol=0.05))

        # -- phase 2: cadence/stall storm under live traffic ----------------
        def storm(servers, is_async, window=storm_seconds):
            stop = _threading.Event()
            p99_lat: list = []

            def writer(idx):
                rng = _np.random.default_rng(idx)
                with RpcClient("127.0.0.1",
                               servers[idx].args.rpc_port) as c:
                    k = 0
                    while not stop.is_set():
                        d = _Datum({"x": float(rng.normal()),
                                    "y": float(rng.normal())})
                        try:
                            c.call("train", "asyncmix",
                                   [[f"l{k % 2}", d.to_msgpack()]])
                        except Exception:  # noqa: BLE001 — bench load
                            return
                        k += 1

            def reader():
                with RpcClient("127.0.0.1",
                               servers[0].args.rpc_port) as c:
                    while not stop.is_set():
                        t0 = time.perf_counter()
                        try:
                            c.call("classify", "asyncmix",
                                   [_Datum({"x": 1.0, "y": 0.0})
                                    .to_msgpack()])
                        except Exception:  # noqa: BLE001
                            return
                        p99_lat.append(
                            (time.perf_counter() - t0) * 1e3)

            threads = [_threading.Thread(target=writer, args=(i,))
                       for i in range(3)]
            threads.append(_threading.Thread(target=reader))
            if is_async:
                # each member pushes on its own background cadence —
                # the production shape: a delayed submitter blocks only
                # its own thread, never the fold
                def submitter(idx):
                    while not stop.is_set():
                        try:
                            servers[idx].mixer.submit_now()
                        except Exception:  # noqa: BLE001 — bench load
                            return
                        time.sleep(0.02)

                threads += [_threading.Thread(target=submitter, args=(i,))
                            for i in (1, 2)]
            for t in threads:
                t.start()
            time.sleep(0.3)  # traffic flowing before rounds start
            done_rounds = 0
            t0 = time.perf_counter()
            deadline = t0 + window
            while time.perf_counter() < deadline and \
                    done_rounds < rounds:
                if servers[0].mixer.mix_now() is not None:
                    done_rounds += 1
            wall = time.perf_counter() - t0
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            stall = 0.0
            for s in servers:
                g = s.rpc.trace.gauges()
                stall = max(stall,
                            g.get("mix.apply_stall_ms", 0.0),
                            g.get("mix.snapshot_stall_ms", 0.0))
            p99 = float(_np.percentile(p99_lat, 99)) if p99_lat else 0.0
            return done_rounds / wall if wall > 0 else 0.0, stall, p99

        sync_rps, sync_stall, sync_p99 = storm(sync_cluster, False)
        async_rps, async_stall, async_p99 = storm(async_cluster, True)
        out["e2e_sync_mix_rounds_per_sec"] = round(sync_rps, 2)
        out["e2e_async_mix_rounds_per_sec"] = round(async_rps, 2)
        if sync_rps > 0:
            out["e2e_async_mix_cadence_x"] = round(async_rps / sync_rps, 2)
        out["e2e_train_stall_during_mix_ms"] = round(async_stall, 3)
        out["e2e_sync_train_stall_during_mix_ms"] = round(sync_stall, 3)
        out["e2e_async_classify_p99_during_mix_ms"] = round(async_p99, 2)
        out["e2e_sync_classify_p99_during_mix_ms"] = round(sync_p99, 2)
        lag = max(getattr(s.mixer, "async_lag_rounds", 0)
                  for s in async_cluster)
        out["e2e_async_mix_lag_rounds"] = int(lag)
        out["e2e_async_mix_dropped_stale"] = int(sum(
            getattr(s.mixer, "async_dropped_stale", 0)
            for s in async_cluster))

        # -- phase 3: straggler cadence — the round-barrier number ----------
        # One member delayed ~10x the round cadence. The sync gather
        # WAITS for it every round; the async fold never does — the
        # cadence ratio under the same fault is the headline of record
        # (ISSUE 11: "mix cadence raisable 10x at the same serving
        # p99"), and the async p99 must stay flat while it happens.
        from jubatus_tpu.utils import faults as _faults

        delay = 2.5
        sync_victim = sync_cluster[2]
        sync_rule = (f"rpc.call.mix_get_diff."
                     f"127.0.0.1:{sync_victim.args.rpc_port}"
                     f":delay:{delay}")
        async_victim = async_cluster[2]
        async_rule = (f"mix.async.submit."
                      f"{async_victim.self_nodeinfo().name}"
                      f":delay:{delay}")
        rules = _faults.arm(sync_rule)
        try:
            s_rps, _s_stall, s_p99 = storm(sync_cluster, False,
                                           window=2.5 * delay)
        finally:
            _faults.disarm(rules)
        rules = _faults.arm(async_rule)
        try:
            a_rps, a_stall, a_p99 = storm(async_cluster, True,
                                          window=2.5 * delay)
        finally:
            _faults.disarm(rules)
        out["e2e_sync_mix_straggler_rounds_per_sec"] = round(s_rps, 3)
        out["e2e_async_mix_straggler_rounds_per_sec"] = round(a_rps, 3)
        if s_rps > 0:
            out["e2e_async_mix_straggler_cadence_x"] = round(
                a_rps / s_rps, 1)
        out["e2e_async_classify_p99_straggler_ms"] = round(a_p99, 2)
        out["e2e_sync_classify_p99_straggler_ms"] = round(s_p99, 2)
        out["e2e_train_stall_straggler_ms"] = round(a_stall, 3)
    finally:
        for s in sync_cluster + async_cluster:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown
                pass
    return out


def run_poison_drill(rounds: int = 6) -> dict:
    """Model-integrity poison drill (ISSUE 15): the guard, measured as
    load-bearing.

    Phase 1 — guarded fleet vs clean twin: a 3-member cluster under
    ``--mix-guard quarantine`` with member 2 armed as a poisoner
    (``mix.diff.poison.<node>:nan``, then a fresh cluster with
    ``scale:1e6``) runs ``rounds`` mix rounds of fixed traffic. The
    twin runs the same traffic with member 2 simply NOT training —
    which is exactly what a perfect quarantine reduces the poisoner
    to. Keys:

    - ``e2e_poison_quarantined_total`` — contributions the guard kept
      out of folds (must be > 0: the poisoner is caught every round);
    - ``e2e_poison_zero_nonfinite_applied_ok`` — no member's model
      ever carries a non-finite weight;
    - ``e2e_poison_drift_vs_clean`` — relative L2 distance between the
      guarded fleet's folded model and the clean twin's (float noise:
      the quarantine removed the poison and nothing else).

    Phase 2 — rollback recovery: a hand-poisoned put_diff total against
    a snapshotted member must be refused, auto-roll back to last-good,
    and leave the member serving — ``e2e_rollback_recovery_s`` is
    refusal→serving wall time.

    Phase 3 — the control: the SAME nan poisoner against a fleet with
    ``--mix-guard off`` must corrupt the model
    (``e2e_poison_unguarded_corrupted``) — proving the guard is what
    stood between the drill and a poisoned fleet
    (``e2e_poison_guard_load_bearing_ok``)."""
    import jax as _jax
    import numpy as _np

    from jubatus_tpu.client import Datum as _Datum
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.utils import faults as _faults

    conf = {"method": "PA",
            "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}

    def boot(name: str, guard: str, n: int = 3):
        store = _Store()
        servers = []
        for _ in range(n):
            srv = EngineServer(
                "classifier", conf,
                args=ServerArgs(engine="classifier",
                                coordinator="(shared)", name=name,
                                listen_addr="127.0.0.1", thread=2,
                                interval_sec=1e9,
                                interval_count=1 << 30,
                                telemetry_interval=0,
                                mix_guard=guard, mix_norm_bound=8.0),
                coord=MemoryCoordinator(store))
            srv.start(0)
            servers.append(srv)
        return servers

    def train(srv, name, rows):
        with RpcClient("127.0.0.1", srv.args.rpc_port) as c:
            c.call("train", name,
                   [[label, _Datum(d).to_msgpack()] for label, d in rows])

    def float_leaves(srv):
        leaves = _jax.tree_util.tree_flatten(srv.driver.pack())[0]
        out = []
        for x in leaves:
            a = _np.asarray(x)
            if a.dtype != object and _np.issubdtype(a.dtype,
                                                    _np.floating):
                out.append(a.reshape(-1))
        return out

    def model_finite(srv) -> bool:
        return all(bool(_np.isfinite(a).all()) for a in float_leaves(srv))

    def model_vec(srv):
        parts = float_leaves(srv)
        return _np.concatenate(parts) if parts else _np.zeros(1)

    def rows_of(rnd: int, i: int):
        return [("l0", {"x": float(rnd + 1), "y": -0.5 * (i + 1)}),
                ("l1", {"x": -1.0 * (i + 1), "y": float(rnd + 1)})]

    def drive(servers, name, victim_trains=True, rule=""):
        rules = _faults.arm(rule) if rule else []
        try:
            for rnd in range(rounds):
                for i, s in enumerate(servers):
                    if i == 2 and not victim_trains:
                        continue
                    train(s, name, rows_of(rnd, i))
                servers[0].mixer.mix_now()
        finally:
            if rules:
                _faults.disarm(rules)

    def quarantined_total(servers) -> int:
        return int(sum(s.rpc.trace.counters().get("mix.quarantined", 0)
                       for s in servers))

    def rel_drift(a, b) -> float:
        va, vb = model_vec(a), model_vec(b)
        if va.shape != vb.shape:
            return float("inf")
        denom = float(_np.linalg.norm(vb)) + 1e-12
        return float(_np.linalg.norm(va - vb)) / denom

    out: dict = {}
    clusters: list = []
    try:
        # -- phase 1: guarded drill vs clean twin, nan then scale -------
        drifts = []
        quarantined = 0
        finite_ok = True
        for tag, mode_rule in (("nan", "nan"), ("scale", "scale:1e6")):
            drill = boot(f"pd_{tag}", "quarantine")
            # the twin is the fleet a PERFECT quarantine reduces the
            # drill to: the poisoner's whole contribution (count leaf
            # included) absent from every fold — i.e. a 2-member
            # cluster running members 0/1's identical traffic
            twin = boot(f"pt_{tag}", "quarantine", n=2)
            clusters += [drill, twin]
            victim = drill[2].self_nodeinfo().name
            drive(drill, f"pd_{tag}",
                  rule=f"mix.diff.poison.{victim}:{mode_rule}")
            drive(twin, f"pt_{tag}")
            quarantined += quarantined_total(drill)
            finite_ok = finite_ok and all(model_finite(s) for s in drill)
            drifts.append(rel_drift(drill[0], twin[0]))
            out[f"e2e_poison_{tag}_quarantined"] = quarantined_total(drill)
        out["e2e_poison_quarantined_total"] = quarantined
        out["e2e_poison_zero_nonfinite_applied_ok"] = bool(finite_ok)
        out["e2e_poison_drift_vs_clean"] = round(max(drifts), 6)
        out["e2e_poison_drift_ok"] = bool(max(drifts) < 1e-3)

        # -- phase 2: rollback recovery ---------------------------------
        from jubatus_tpu.framework.linear_mixer import PROTOCOL_VERSION

        srv = clusters[0][0]
        srv.take_snapshot()
        m = srv.mixer
        with srv.driver.lock:
            diffs = {n: mx.get_diff()
                     for n, mx in srv.driver.get_mixables().items()}

        def _nanify(x):
            a = _np.asarray(x)
            if a.dtype != object and _np.issubdtype(a.dtype,
                                                    _np.floating):
                return _np.full_like(a, _np.nan)
            return a

        poisoned = {"protocol": PROTOCOL_VERSION,
                    "schema": m.local_get_schema(),
                    "base_version": m.model_version,
                    "diffs": _jax.tree_util.tree_map(_nanify, diffs)}
        t0 = time.perf_counter()
        applied = m.local_put_obj(poisoned)
        with RpcClient("127.0.0.1", srv.args.rpc_port) as c:
            c.call("classify", srv.args.name,
                   [_Datum({"x": 1.0, "y": 0.0}).to_msgpack()])
        recovery = time.perf_counter() - t0
        out["e2e_rollback_recovery_s"] = round(recovery, 3)
        out["e2e_rollback_refused_and_restored_ok"] = bool(
            not applied and srv.rollbacks >= 1 and model_finite(srv))

        # -- phase 3: guard off — the poison lands (the control) --------
        exposed = boot("pd_off", "off")
        clusters.append(exposed)
        victim = exposed[2].self_nodeinfo().name
        drive(exposed, "pd_off",
              rule=f"mix.diff.poison.{victim}:nan")
        corrupted = not all(model_finite(s) for s in exposed)
        out["e2e_poison_unguarded_corrupted"] = float(corrupted)
        out["e2e_poison_guard_load_bearing_ok"] = bool(
            corrupted and finite_ok and quarantined > 0)
    finally:
        for cluster in clusters:
            for s in cluster:
                try:
                    s.stop()
                except Exception:  # noqa: BLE001 — teardown
                    pass
    return out


def _fleet_sim():
    """Import tools/fleet_sim.py (tools/ is not a package)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    tools = os.path.join(repo, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import fleet_sim

    return fleet_sim


def run_fleet(nproc: int = 8, initial: int = 2, max_replicas: int = 6,
              quiet: float = 12.0, flash_len: float = 28.0,
              tail: float = 15.0, seed=None,
              per_flush_s: float = 0.1, flush_examples: int = 12,
              base_rate: float = 8.0, flash_mult: float = 10.0,
              call_batch: int = 4, slo_ms: float = 400.0) -> dict:
    """Autoscale flash-crowd drill (ISSUE 12): a seeded 10x traffic
    step against proxy + classifier fleet, autoscaled vs a static
    control fleet.

    Sizing: per-replica capacity is pinned at ``flush_examples /
    per_flush_s`` = 120 examples/s = 30 req/s. Base load 8 req/s runs
    the initial 2 replicas at ~13%; the 10x step offers 80 req/s —
    1.33x the static fleet's capacity (pinned underwater for the whole
    flash) but 0.44 utilization at the autoscaled max of 6, so
    queueing settles well under the 400 ms SLO (4 flush quanta) after
    scale-out. The whole peak stays beneath the one bench core's REAL
    Python proxy+backend throughput ceiling (~190 req/s measured):
    above it, CPU — which added replicas share — becomes the binding
    constraint and the drill would measure the box, not the control
    loop.

    Each backend's device flush is throttled to a fixed per-flush cost
    (a GIL-releasing sleep) with the flush size capped at
    ``flush_examples``, so per-replica capacity is pinned to
    ``flush_examples / per_flush_s`` examples/s and replica count — not
    the one bench core — bounds fleet capacity: scale-out genuinely
    adds capacity, which is the property under test, and overload
    genuinely backs up in ``microbatch.queue_depth``. Load comes from
    tools/fleet_sim.py (diurnal curve + zipf hot users + tenant mix +
    one flash-crowd step at ``quiet`` seconds), identical traffic on
    both runs (same seed).

    Keys of record:

    - ``e2e_scaleout_recovery_s`` — flash onset to the first 3-second
      violation-free stretch on the autoscaled fleet (client-observed).
    - ``e2e_autoscale_slo_violation_s`` / ``e2e_static_slo_violation_s``
      — violated seconds from flash onset on each fleet;
      ``e2e_autoscale_beats_static_ok`` gates autoscaled < static.
    - ``e2e_capacity_per_replica`` — late-flash completed examples/s
      per serving replica on the autoscaled fleet.
    - ``e2e_autoscale_scaleout_latency_s`` — flash onset to the first
      scale_out journal record (the control loop's reaction time).
    """
    from jubatus_tpu.coord.autoscaler import (AutoscaleConfig, Autoscaler,
                                              HookActuator)
    from jubatus_tpu.coord.base import NodeInfo
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs
    from bench_mix import scrub_child_env

    fleet_sim = _fleet_sim()
    seed = SEED if seed is None else int(seed)
    seconds = quiet + flash_len + tail
    model = fleet_sim.TrafficModel(
        seed=seed, base_rate=base_rate, diurnal_period_s=240.0,
        diurnal_amplitude=0.15, flash=((quiet, flash_len, flash_mult),))

    prev = os.environ.get("JUBATUS_TPU_NATIVE_RPC")
    os.environ["JUBATUS_TPU_NATIVE_RPC"] = "0"

    def throttle(srv):
        # fixed per-flush device cost + capped flush size: capacity
        # rides replica count, not the shared bench core (the sleep
        # releases the GIL; the batch itself is never touched — the
        # pipelined coalescer's device stage receives PREPARED batches
        # whose shape is the flush fn's business, not ours)
        for co in srv.coalescers.values():
            orig = co._flush

            def slowed(batch, _orig=orig):
                time.sleep(per_flush_s)
                return _orig(batch)

            co._flush = slowed

    def run_side(autoscaled: bool) -> dict:
        store = _Store()
        servers = []
        srv_lock = threading.Lock()
        stop = threading.Event()

        def boot():
            srv = EngineServer(
                "classifier", CONF,
                args=ServerArgs(
                    engine="classifier", coordinator="(shared)",
                    name="fleet", listen_addr="127.0.0.1", thread=32,
                    interval_sec=1e9, interval_count=1 << 30,
                    microbatch_max=flush_examples,
                    telemetry_interval=1.0,
                    slo=[f"latency:rpc.train:p99:{slo_ms:g}"],
                    slo_fast_window=5.0, slo_slow_window=15.0),
                coord=MemoryCoordinator(store))
            srv.start(0)
            throttle(srv)
            with srv_lock:
                servers.append(srv)
            return srv

        def spawn(n):
            for _ in range(int(n)):
                boot()

        def drain(target):
            node = NodeInfo.from_name(target)
            with srv_lock:
                victim = next((s for s in servers
                               if s.args.rpc_port == node.port), None)
            if victim is None:
                raise RuntimeError(f"no local server {target}")
            with RpcClient(node.host, node.port, timeout=30.0) as c:
                c.call("drain", "fleet", False)
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    st = c.call("drain_status", "fleet")
                    state = st.get("state")
                    state = state.decode() if isinstance(state, bytes) \
                        else state
                    if state == "drained":
                        break
                    time.sleep(0.2)
            victim.stop()
            with srv_lock:
                servers.remove(victim)

        proxy = scaler = None
        try:
            for _ in range(initial):
                boot()
            # each forwarded train call parks a proxy worker for the
            # backend's full coalesce latency — the pool must cover the
            # clients' aggregate in-flight or the PROXY becomes the
            # capacity ceiling and scale-out can't show
            proxy = Proxy(ProxyArgs(engine="classifier",
                                    listen_addr="127.0.0.1", thread=256,
                                    interconnect_timeout=120.0),
                          coord=MemoryCoordinator(store))
            pport = proxy.start(0)
            # warm the jit caches (first train compiles ~seconds) and
            # drop the compile-era histograms BEFORE the clock starts:
            # the drill measures the control loop, not XLA compilation.
            # In-process replicas share one jit cache, so later spawns
            # boot warm.
            from jubatus_tpu.client import Datum as _Datum

            warm_batches = []
            for tenant, _w in model.tenants:
                d = _Datum({f"{tenant[:2]}{j}": 0.5 for j in range(8)})
                for b in (1, call_batch, flush_examples // call_batch):
                    warm_batches.append([["a", d.to_msgpack()]]
                                        * max(b, 1))
            for s in list(servers):
                with RpcClient("127.0.0.1", s.args.rpc_port,
                               timeout=60.0) as c:
                    for batch in warm_batches:
                        c.call("train", "fleet", batch)
                s.rpc.trace.reset()
            cfg = AutoscaleConfig(
                min_replicas=initial, max_replicas=max_replicas,
                poll_interval_s=1.0, window_s=8.0, burn_hot=2.0,
                queue_hot=100.0, queue_cold_fraction=0.3,
                scale_out_confirm=2, scale_out_step=2,
                # scale-in is proven by run_fleet_scalein; inside the
                # drill it must not shrink the fleet mid-phase
                scale_in_confirm=10_000,
                cooldown_s=3.0, backoff_initial_s=1.0,
                dry_run=not autoscaled)
            scaler = Autoscaler(MemoryCoordinator(store), "classifier",
                                "fleet", HookActuator(spawn, drain),
                                config=cfg)
            sizes = []  # (wall_ts, fleet size) sampled per poll

            def tick_loop():
                while not stop.wait(cfg.poll_interval_s):
                    try:
                        rec = scaler.tick()
                        sizes.append((rec["ts"],
                                      rec["signals"]["replicas"]))
                    except Exception:  # noqa: BLE001 — bench loop
                        pass

            ctl = threading.Thread(target=tick_loop, daemon=True,
                                   name="fleet-autoscaler")
            ctl.start()
            t0_wall = time.time()
            out = fleet_sim.drive(
                pport, model, nproc, seconds, cluster="fleet",
                workload="train", call_batch=call_batch,
                lat_slo_ms=slo_ms, inflight_cap=16,
                env=scrub_child_env(os.environ))
            stop.set()
            ctl.join(timeout=10.0)
            # worker-reported clock anchor beats the pre-spawn wall
            # time (worker imports cost seconds before the trace runs)
            out.setdefault("t0_wall", t0_wall)
            out["journal"] = list(scaler.journal)
            out["sizes"] = sizes
            out["final_replicas"] = len(servers)
            out["counters"] = {
                k: v for k, v in scaler.registry.counters().items()
                if k.startswith("autoscale.")}
            return out
        finally:
            stop.set()
            if scaler is not None:
                scaler.stop()
            if proxy is not None:
                proxy.stop()
            with srv_lock:
                doomed = list(servers)
            for s in doomed:
                try:
                    s.stop()
                except Exception:  # noqa: BLE001 — teardown
                    pass

    out: dict = {"e2e_fleet_nproc": nproc, "e2e_fleet_seed": seed,
                 "e2e_fleet_offered_req_per_sec_base": base_rate,
                 "e2e_fleet_flash_multiplier": flash_mult}
    try:
        auto = run_side(autoscaled=True)
        static = run_side(autoscaled=False)
    finally:
        if prev is None:
            os.environ.pop("JUBATUS_TPU_NATIVE_RPC", None)
        else:
            os.environ["JUBATUS_TPU_NATIVE_RPC"] = prev
    onset = int(quiet)
    for tag, side in (("autoscale", auto), ("static", static)):
        viol = fleet_sim.violation_seconds(
            side["per_sec"], start=onset, end=int(seconds) + 1)
        out[f"e2e_{tag}_slo_violation_s"] = len(viol)
        out[f"e2e_{tag}_done_total"] = side["done"]
        out[f"e2e_{tag}_shed_total"] = side["shed"]
        out[f"e2e_{tag}_error_total"] = side["errors"]
        if side.get("dead"):
            out[f"e2e_{tag}_dead_clients"] = "; ".join(side["dead"])
        if tag == "autoscale":
            rec = fleet_sim.recovery_second(viol, onset,
                                            horizon=int(seconds))
            out["e2e_scaleout_recovery_s"] = (
                round(rec - onset, 1) if rec is not None else -1.0)
    # control-loop reaction time + fleet trajectory (autoscaled side)
    spawns = [j for j in auto["journal"] if j["action"] == "scale_out"]
    if spawns:
        out["e2e_autoscale_scaleout_latency_s"] = round(
            spawns[0]["ts"] - (auto["t0_wall"] + quiet), 1)
    out["e2e_autoscale_spawns"] = auto["counters"].get(
        "autoscale.spawns", 0)
    out["e2e_autoscale_drains"] = auto["counters"].get(
        "autoscale.drains", 0)
    out["e2e_autoscale_blocked"] = auto["counters"].get(
        "autoscale.blocked", 0)
    out["e2e_autoscale_final_replicas"] = auto["final_replicas"]
    # capacity per replica: late-flash completed examples/s over the
    # serving fleet size then (sizes sampled per poll, wall-clock)
    late0, late1 = int(quiet + flash_len - 8), int(quiet + flash_len)
    done = auto["per_sec"]["done"][late0:late1]
    late_sizes = [n for ts, n in auto["sizes"]
                  if auto["t0_wall"] + late0 <= ts
                  <= auto["t0_wall"] + late1]
    if done and late_sizes:
        out["e2e_capacity_per_replica"] = round(
            (sum(done) * call_batch / len(done))
            / max(sum(late_sizes) / len(late_sizes), 1.0), 1)
    both = ("e2e_autoscale_slo_violation_s" in out
            and "e2e_static_slo_violation_s" in out)
    if both:
        out["e2e_autoscale_beats_static_ok"] = bool(
            out["e2e_autoscale_slo_violation_s"]
            < out["e2e_static_slo_violation_s"])
    return out


def run_fleet_scalein(rows: int = 600) -> dict:
    """Scale-in half of the drill: an IDLE 3-member nearest_neighbor
    fleet goes sustained-cold, the autoscaler drains the least-loaded
    member through the ISSUE 10 state machine, and every row survives
    on the remaining members — ``e2e_churn_rows_lost`` must stay 0
    through an autoscaler-initiated drain."""
    import numpy as _np

    from jubatus_tpu.client import Datum as _Datum
    from jubatus_tpu.coord.autoscaler import (AutoscaleConfig, Autoscaler,
                                              HookActuator)
    from jubatus_tpu.coord.base import NodeInfo
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    conf = {"method": "lsh", "parameter": {"hash_num": 64},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    store = _Store()
    servers = []

    def boot():
        srv = EngineServer(
            "nearest_neighbor", conf,
            args=ServerArgs(engine="nearest_neighbor",
                            coordinator="(shared)", name="fleet",
                            listen_addr="127.0.0.1", thread=4,
                            interval_sec=1e9, interval_count=1 << 30,
                            telemetry_interval=1.0),
            coord=MemoryCoordinator(store))
        srv.start(0)
        servers.append(srv)
        return srv

    def drain(target):
        node = NodeInfo.from_name(target)
        victim = next(s for s in servers
                      if s.args.rpc_port == node.port)
        with RpcClient(node.host, node.port, timeout=60.0) as c:
            c.call("drain", "fleet", False)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                st = c.call("drain_status", "fleet")
                state = st.get("state")
                state = state.decode() if isinstance(state, bytes) \
                    else state
                if state == "drained":
                    break
                time.sleep(0.2)
        victim.stop()
        servers.remove(victim)

    out: dict = {}
    scaler = None
    try:
        for _ in range(3):
            boot()
        rng = _np.random.default_rng(SEED)
        clients = [RpcClient("127.0.0.1", s.args.rpc_port, timeout=30.0)
                   for s in servers]
        for i in range(rows):
            d = _Datum({f"f{j}": float(v)
                        for j, v in enumerate(rng.normal(size=16))})
            clients[i % 3].call("set_row", "fleet", f"row{i:06d}",
                                d.to_msgpack())
        for c in clients:
            c.close()
        scaler = Autoscaler(
            MemoryCoordinator(store), "nearest_neighbor", "fleet",
            HookActuator(lambda n: boot(), drain),
            config=AutoscaleConfig(
                min_replicas=2, max_replicas=3, poll_interval_s=0.5,
                scale_in_confirm=3, cooldown_s=0.0))
        deadline = time.monotonic() + 60.0
        drained = 0
        while time.monotonic() < deadline and drained == 0:
            rec = scaler.tick()
            drained = scaler.registry.counters().get(
                "autoscale.drains", 0)
            time.sleep(0.5)
        out["e2e_autoscale_scalein_drains"] = drained
        survivors = set()
        for s in servers:
            with RpcClient("127.0.0.1", s.args.rpc_port,
                           timeout=30.0) as c:
                for rid in c.call("get_all_rows", "fleet"):
                    survivors.add(rid.decode()
                                  if isinstance(rid, bytes) else rid)
        expect = {f"row{i:06d}" for i in range(rows)}
        out["e2e_churn_rows_total"] = rows
        out["e2e_churn_rows_lost"] = len(expect - survivors)
        out["e2e_autoscale_scalein_replicas"] = len(servers)
    finally:
        if scaler is not None:
            scaler.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown
                pass
    return out


_SHARDED_KNN_CHILD = r"""
import json, sys, time
import numpy as np
import jax, jax.numpy as jnp

rows = int(float(sys.argv[1])); shards = int(sys.argv[2])
hash_num, B, k = 64, 4, 10
rng = np.random.default_rng(3)
from jubatus_tpu.ops import knn
words = knn.packed_words(hash_num)
# synthesize the signature table directly: the bench measures the QUERY
# plane (scan + top-k merge), not 1e8 python-side row inserts
sigs_h = rng.integers(0, 2 ** 32, size=(rows, words), dtype=np.uint32)
q = jnp.asarray(rng.integers(0, 2 ** 32, size=(B, words), dtype=np.uint32))

if shards > 1:
    from jax.sharding import Mesh
    from jubatus_tpu.parallel import sharded_knn
    pad = (-rows) % shards
    if pad:
        sigs_h = np.pad(sigs_h, ((0, pad), (0, 0)))
    mesh = Mesh(np.asarray(jax.devices()[:shards]), ("shard",))
    sigs = sharded_knn.shard_table(mesh, jnp.asarray(sigs_h))
    valid = sharded_knn.shard_table(
        mesh, jnp.asarray(np.arange(len(sigs_h)) < rows))
    query = lambda: sharded_knn.sharded_hamming_topk(
        mesh, q, sigs, hash_num=hash_num, k=k, valid=valid)
else:
    sigs = jnp.asarray(sigs_h)

    import functools
    @functools.partial(jax.jit, static_argnames=("k",))
    def dense_topk(q, sigs, k):
        d = knn._hamming_distances_batch_xla(q, sigs, hash_num=hash_num)
        nd, idx = jax.lax.top_k(-d, k)
        return -nd, idx
    query = lambda: dense_topk(q, sigs, k)
per_dev = {}
for sh in sigs.addressable_shards:
    per_dev[sh.device.id] = per_dev.get(sh.device.id, 0) + int(
        np.prod(sh.data.shape)) * 4
jax.block_until_ready(query())          # compile + warm
trials = 12 if rows >= 10 ** 7 else 25
ts = []
for _ in range(trials):
    t0 = time.perf_counter()
    jax.block_until_ready(query())
    ts.append(time.perf_counter() - t0)
ts = np.asarray(ts) * 1e3
print(json.dumps({
    "p99_ms": round(float(np.percentile(ts, 99)), 2),
    "p50_ms": round(float(np.median(ts)), 2),
    "table_mb_per_device_max": round(max(per_dev.values()) / 2 ** 20, 1),
    "trials": trials, "batch": B, "k": k,
}))
"""


def run_sharded_knn(shard_counts=(1, 8), scales=("1e6", "1e8"),
                    timeout: float = 3600.0) -> dict:
    """Sharded row-store query bench (ISSUE 13): global top-k over a
    synthesized LSH signature table at 10⁶ and 10⁸ rows, single- vs
    multi-shard (per-shard partial top-k + log-depth on-device merge),
    each in a subprocess with that many virtual devices. Emits
    ``knn_query_p99_ms_rows{1e6,1e8}_{s}shard`` (down-good). Virtual
    CPU devices share one core: multi-shard wall bounds orchestration +
    merge cost; the per-device table slice is the capacity win."""
    import bench_mix

    out: dict = {}
    for scale in scales:
        for s in shard_counts:
            env = bench_mix.scrub_child_env(dict(os.environ))
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "device_count" not in f]
            env["XLA_FLAGS"] = " ".join(
                flags +
                [f"--xla_force_host_platform_device_count={max(s, 1)}"])
            tag = f"rows{scale}_{s}shard"
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", _SHARDED_KNN_CHILD, scale,
                     str(s)],
                    capture_output=True, text=True, timeout=timeout,
                    env=env)
                doc = json.loads(proc.stdout.strip().splitlines()[-1])
            except Exception as e:  # noqa: BLE001 — partial results
                out[f"knn_query_error_{tag}"] = repr(e)[:200]
                continue
            out[f"knn_query_p99_ms_{tag}"] = doc["p99_ms"]
            out[f"knn_query_p50_ms_{tag}"] = doc["p50_ms"]
            out[f"knn_query_table_mb_per_device_{tag}"] = \
                doc["table_mb_per_device_max"]
    return out


_SHARDED_IVF_CHILD = r"""
import json, sys, time
import numpy as np
import jax, jax.numpy as jnp

rows = int(float(sys.argv[1])); shards = int(sys.argv[2])
n_cells = int(sys.argv[3]); nprobe = int(sys.argv[4])
hash_num, B, k = 64, 4, 10
rng = np.random.default_rng(3)
from jubatus_tpu.ops import ivf, knn
from jax.sharding import Mesh
from jubatus_tpu.parallel import sharded_knn
from jubatus_tpu.parallel.sharded_ivf import sharded_ivf_topk

words = knn.packed_words(hash_num)
assert rows % shards == 0
c_local = rows // shards

# CLUSTERED table — the regime an IVF tier serves (and the regime real
# row stores live in): 4096 planted centers, each row = its center XOR
# sparse bit-noise (AND of 4 random words ~= 2 flipped bits per 64)
n_true = 4096
centers = rng.integers(0, 2 ** 32, size=(n_true, words), dtype=np.uint32)
owner = rng.integers(0, n_true, size=rows)
noise = rng.integers(0, 2 ** 32, size=(rows, words), dtype=np.uint32)
for _ in range(3):
    noise &= rng.integers(0, 2 ** 32, size=(rows, words), dtype=np.uint32)
sigs_h = centers[owner] ^ noise
del noise, owner

# queries: perturbed planted centers (near-data, like live traffic)
qc = centers[rng.integers(0, n_true, size=64)]
qn = rng.integers(0, 2 ** 32, size=(64, words), dtype=np.uint32)
for _ in range(3):
    qn &= rng.integers(0, 2 ** 32, size=(64, words), dtype=np.uint32)
q_all = jnp.asarray(qc ^ qn)
q = q_all[:B]

# ---- build the IVF index (timed: ann_build_rows_per_sec) ----------------
t_build0 = time.perf_counter()
samp = sigs_h[rng.choice(rows, size=min(rows, 65536), replace=False)]
emb_s = np.asarray(ivf.embed_signatures(jnp.asarray(samp),
                                        method="lsh", hash_num=hash_num))
cen = np.array(ivf.train_centroids(emb_s, n_cells, iters=4, seed=0))
n_super = max(8, 2 * int(np.sqrt(n_cells)))
supers, members = ivf.build_super(cen, n_super=n_super, seed=0)
cells = np.empty(rows, np.int32)
CHUNK = 1 << 21
for a in range(0, rows, CHUNK):
    b = min(a + CHUNK, rows)
    e = np.asarray(ivf.embed_signatures(jnp.asarray(sigs_h[a:b]),
                                        method="lsh", hash_num=hash_num))
    cells[a:b] = ivf.assign_cells_grouped(e, cen, supers, members,
                                          top_supers=2)
# split hot cells on TRUE counts (the online tier's resplit, done once
# at build): a cell past 1.5x the mean forces the fixed-shape slot cap
# -- and the rescore gather cost is nprobe*cap -- so k-sub-means each
# hot cell into ~mean-sized children before laying out the table
mean_c = rows / n_cells
T = int(2.0 * mean_c)
cnt0 = np.bincount(cells, minlength=n_cells)
hot = np.nonzero(cnt0 > T)[0]
if hot.size:
    lut = np.full(n_cells, -1, np.int32)
    lut[hot] = np.arange(hot.size, dtype=np.int32)
    idxs = np.nonzero(lut[cells] >= 0)[0]
    hcells = cells[idxs]
    he = np.empty((idxs.size, hash_num), np.float32)
    for a in range(0, idxs.size, CHUNK):
        b = min(a + CHUNK, idxs.size)
        he[a:b] = np.asarray(ivf.embed_signatures(
            jnp.asarray(sigs_h[idxs[a:b]]), method="lsh",
            hash_num=hash_num))
    def np_kmeans(pts, k2, seed):
        # pure-numpy lloyd: the split fit is tiny (<=16384 x E, 3
        # iters) and per-cell shapes all differ -- jitting each would
        # mean hundreds of one-shot XLA compiles
        r2 = np.random.default_rng(seed)
        c0 = pts[r2.choice(pts.shape[0], size=k2, replace=False)].copy()
        for _ in range(3):
            a0 = np.argmin((c0 * c0).sum(1)[None] - 2.0 * (pts @ c0.T), 1)
            for j in range(k2):
                m2 = a0 == j
                if m2.any():
                    c0[j] = pts[m2].mean(0)
        return c0
    extra, next_id = [], n_cells
    fit_rng = np.random.default_rng(7)
    for ci in hot:
        mi = np.nonzero(hcells == ci)[0]
        sub_k = max(2, int(np.ceil(cnt0[ci] / mean_c)))
        fit = mi if mi.size <= 16384 else fit_rng.choice(mi, 16384,
                                                        replace=False)
        sc = np_kmeans(he[fit], sub_k, seed=int(ci))
        a2 = np.argmin((sc * sc).sum(1)[None] - 2.0 * (he[mi] @ sc.T), 1)
        ids = np.concatenate(
            [[ci], next_id + np.arange(sub_k - 1)]).astype(np.int32)
        cells[idxs[mi]] = ids[a2]
        cen[ci] = sc[0]
        extra.append(sc[1:])
        next_id += sub_k - 1
    cen = np.concatenate([cen] + extra).astype(np.float32)
    del he, idxs, hcells, lut
cen_j = jnp.asarray(cen)
n_cells_f = cen.shape[0]
# group rows into per-(shard, cell) slot lists: [S*n_cells, cap] int32,
# -1 padded, LOCAL slots -- and permute each shard's arena CELL-
# CONTIGUOUS (the compacted layout a rebuild converges to) so a probed
# cell's rescore gather is a sequential stream, not C/S-wide random
# cache misses
key = cells.astype(np.int64) + (np.arange(rows) // c_local) * n_cells_f
order = np.argsort(key, kind="stable")
sigs_h = sigs_h[order]
cnt = np.bincount(key, minlength=shards * n_cells_f)
starts = np.concatenate([[0], np.cumsum(cnt)[:-1]])
cap = 1 << int(np.ceil(np.log2(max(int(cnt.max()), 1))))
table = np.full((shards * n_cells_f, cap), -1, np.int32)
ks = key[order]
pos = np.arange(rows) - starts[ks]
table[ks, pos] = (np.arange(rows) % c_local).astype(np.int32)
build_s = time.perf_counter() - t_build0
del key, order, ks, pos

mesh = Mesh(np.asarray(jax.devices()[:shards]), ("shard",))
sigs = sharded_knn.shard_table(mesh, jnp.asarray(sigs_h))
slots = sharded_knn.shard_table(mesh, jnp.asarray(table))
cen_r = sharded_knn.replicate(mesh, cen_j)
del table

def embed(qq):
    return ivf.embed_signatures(qq, method="lsh", hash_num=hash_num)

ivf_query = lambda qq: sharded_ivf_topk(
    mesh, qq, embed(qq), sigs, cen_r, slots,
    method="lsh", hash_num=hash_num, k=k, nprobe=nprobe)
exact_query = lambda qq: sharded_knn.sharded_hamming_topk(
    mesh, qq, sigs, hash_num=hash_num, k=k)

def p99(fn, qq, trials):
    jax.block_until_ready(fn(qq))            # compile + warm
    ts = []
    for _ in range(trials):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(qq))
        ts.append(time.perf_counter() - t0)
    ts = np.asarray(ts) * 1e3
    return (round(float(np.percentile(ts, 99)), 2),
            round(float(np.median(ts)), 2))

trials = 12 if rows >= 10 ** 7 else 25
ivf_p99, ivf_p50 = p99(ivf_query, q, max(trials, 25))
exact_p99, exact_p50 = p99(exact_query, q, trials)

# recall@10 over 64 near-data queries, by distance threshold: an IVF
# answer counts if its distance <= the exact 10th-nearest distance
# (hamming quantizes hard — id-set overlap would punish legal tie
# resolution, not index quality)
hit = tot = 0
for a in range(0, 64, 8):
    qq = q_all[a:a + 8]
    ed, _ = exact_query(qq)
    ad, _ = ivf_query(qq)
    kth = np.sort(np.asarray(ed), axis=1)[:, k - 1:k]
    hit += int((np.asarray(ad)[:, :k] <= kth + 1e-6).sum())
    tot += 8 * k
print(json.dumps({
    "ivf_p99_ms": ivf_p99, "ivf_p50_ms": ivf_p50,
    "exact_p99_ms": exact_p99, "exact_p50_ms": exact_p50,
    "recall_at_10": round(hit / tot, 4),
    "build_rows_per_sec": round(rows / build_s, 1),
    "build_s": round(build_s, 2), "cells": n_cells_f, "nprobe": nprobe,
    "cells_base": n_cells, "hot_split": int(n_cells_f - n_cells),
    "cell_cap": int(cap), "trials": trials, "batch": B, "k": k,
}))
"""


def run_sharded_knn_ivf(scales=("1e6", "1e8"), shards: int = 8,
                        timeout: float = 7200.0) -> dict:
    """IVF ANN-tier bench (ISSUE 16): two-phase probe+rescore vs the
    exact sharded scan over a CLUSTERED signature table (4096 planted
    centers — the exact-scan cliff is identical, but the data has the
    cell structure real row stores do). Emits
    ``knn_query_p99_ms_rows{scale}_{S}shard_ivf`` (down-good),
    ``ann_recall_at_10_rows{scale}`` (up-good, distance-threshold
    recall vs the exact scan on the SAME table) and
    ``ann_build_rows_per_sec`` (up-good, train + assign + group wall).
    Exact p99 is re-measured in the same child so the speedup quote is
    same-process, same-table honest."""
    import math

    import bench_mix

    out: dict = {}
    for scale in scales:
        rows = int(float(scale))
        n_cells = min(8192,
                      max(64, 1 << int(round(math.log2(rows ** 0.5)))))
        nprobe = max(32, n_cells // 256)
        env = bench_mix.scrub_child_env(dict(os.environ))
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={shards}"])
        tag = f"rows{scale}_{shards}shard"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _SHARDED_IVF_CHILD, scale,
                 str(shards), str(n_cells), str(nprobe)],
                capture_output=True, text=True, timeout=timeout, env=env)
            if not proc.stdout.strip():
                raise RuntimeError(
                    f"exit {proc.returncode}: "
                    + (proc.stderr or "")[-250:])
            doc = json.loads(proc.stdout.strip().splitlines()[-1])
        except Exception as e:  # noqa: BLE001 — partial results
            out[f"knn_query_error_{tag}_ivf"] = repr(e)[:200]
            continue
        out[f"knn_query_p99_ms_{tag}_ivf"] = doc["ivf_p99_ms"]
        out[f"knn_query_p50_ms_{tag}_ivf"] = doc["ivf_p50_ms"]
        out[f"knn_query_p99_ms_{tag}"] = doc["exact_p99_ms"]
        out[f"knn_query_p50_ms_{tag}"] = doc["exact_p50_ms"]
        out[f"ann_recall_at_10_rows{scale}"] = doc["recall_at_10"]
        out[f"ann_cells_rows{scale}"] = doc["cells"]
        out[f"ann_nprobe_rows{scale}"] = doc["nprobe"]
        out["ann_build_rows_per_sec"] = doc["build_rows_per_sec"]
    return out


def run_tune_regret(dim_bits: int = 24, regret_band: float = 1.25,
                    round_budget: int = 24, trials: int = 5,
                    observe_rounds: int = 6) -> dict:
    """Self-tuning regret bench (ISSUE 20): does the closed loop find
    what a hand sweep finds, and how fast?

    Phase 1 — oracle: hand-sweep every (wire mode x chunk size) plan on
    the tuner's own ladders over a d24-shaped loopback psum (one
    [2, 2^23] f32 leaf = 2^24 params, the BASELINE.md Criteo shape) —
    the best median round is the hand-tuned optimum the tuner is graded
    against (exactly tools/bench_mix_chunk_sweep.py's recipe, single
    process).

    Phase 2 — the REAL control loop from default knobs: a PerfTuner in
    ``on`` mode (bench-paced: settle_rounds=1/confirm=1/no cooldown —
    the bench ticks once per measured round, so the production pacing
    knobs would only multiply wall clock, not change the search) drives
    the same measured psum through a closure adapter. Each tick feeds
    the tuner the true round ms + phase ratios of the CURRENTLY applied
    plan; its apply_mix actuates the plan the next round measures.

    - ``e2e_tune_regret_ratio`` — oracle round ms of the plan the tuner
      SETTLED on / oracle optimum (1.0 = found the hand-tuned plan;
      the acceptance band is <= 1.25).
    - ``e2e_tune_converge_rounds`` — mix rounds consumed before the
      applied plan first measured inside the regret band (target <= 12).
    - ``e2e_tune_observe_overhead_ratio`` — mean round ms with an
      observe-mode (dry-run) tuner ticking every round vs none (the
      <2% A/B budget).
    """
    import numpy as _np

    from jubatus_tpu.coord.perf_tuner import (TUNER_DEFAULTS, PerfTuner,
                                              TunerConfig)
    from jubatus_tpu.parallel.collective import (DEFAULT_CHUNK_MB,
                                                 ErrorFeedback,
                                                 psum_pytree)

    rng = _np.random.default_rng(SEED)
    diff = {"dw": rng.normal(
        size=(2, 1 << (dim_bits - 1))).astype(_np.float32)}
    ef = ErrorFeedback()
    warmed: set = set()

    def measure(mode: str, chunk: float):
        """Best-of-trials round ms (+ last phases) of the loopback psum
        under one plan; first visit warms the (mode, chunk) compile so
        every scored sample is steady-state. Min, not median: host-
        scheduling noise on a shared CPU is strictly additive and
        swings wider than the chunk-size signal itself."""
        kw = {"feedback": ef} if mode == "int8" else {}
        if (mode, chunk) not in warmed:
            psum_pytree(diff, compress=mode, chunk_mb=chunk, **kw)
            warmed.add((mode, chunk))
        times, ph = [], {}
        for _ in range(trials):
            ph = {}
            t0 = time.perf_counter()
            psum_pytree(diff, compress=mode, chunk_mb=chunk, phases=ph,
                        **kw)
            times.append((time.perf_counter() - t0) * 1e3)
        return float(min(times)), ph

    out: dict = {}
    # -- phase 1: the hand-tuned oracle over the tuner's own ladders --------
    oracle: dict = {}
    for mode in TUNER_DEFAULTS["wire_ladder"]:
        for chunk in TUNER_DEFAULTS["chunk_ladder_mb"]:
            oracle[(mode, float(chunk))] = measure(mode, float(chunk))[0]
    best_plan = min(oracle, key=oracle.get)
    oracle_ms = oracle[best_plan]
    out["e2e_tune_oracle_plan"] = f"{best_plan[0]}/{best_plan[1]}mb"
    out["e2e_tune_oracle_round_ms"] = round(oracle_ms, 2)
    default_plan = ("off", float(DEFAULT_CHUNK_MB))
    out["e2e_tune_default_round_ms"] = round(oracle[default_plan], 2)

    # -- phase 2: the closed loop from default knobs ------------------------
    class _Adapter:
        wire, chunk = default_plan
        rounds = 0
        last_ms = 0.0
        ship_frac = 0.5

        def mix_signals(self):
            return {"rounds": self.rounds, "round_ms": self.last_ms,
                    "wire": self.wire, "chunk_mb": self.chunk,
                    "ef_drift": 0.0, "ship_frac": self.ship_frac}

        def apply_mix(self, wire, chunk_mb):
            self.wire, self.chunk = wire, float(chunk_mb)

        def coalescer_signals(self):
            return []

        def cadence_signals(self):
            return None

    ad = _Adapter()
    tuner = PerfTuner(TunerConfig(mode="on", confirm=1, cooldown_s=0.0,
                                  settle_rounds=1), ad)
    converged_at = None
    now = 0.0
    for r in range(1, round_budget + 1):
        ms, ph = measure(ad.wire, ad.chunk)
        denom = sum(float(ph.get(k, 0.0)) for k in
                    ("ship_ms", "reduce_ms", "readback_ms"))
        ad.ship_frac = float(ph.get("ship_ms", 0.0)) / denom \
            if denom > 0 else 0.5
        ad.rounds, ad.last_ms = r, ms
        # grade the plan this round actually ran (by its oracle score,
        # so measurement noise can't flap the convergence round)
        if converged_at is None and \
                oracle[(ad.wire, ad.chunk)] <= regret_band * oracle_ms:
            converged_at = r
        now += 1.0
        tuner.tick(now)
        if tuner.mix is not None and tuner.mix.converged:
            break
    settled = (ad.wire, float(ad.chunk))
    out["e2e_tune_settled_plan"] = f"{settled[0]}/{settled[1]}mb"
    # regret of record: settled vs oracle plan RE-MEASURED in adjacent
    # alternation (the oracle table's samples are a process-epoch old —
    # on a shared CPU that drift alone can exceed the chunk signal, and
    # cross-epoch ratios would grade the scheduler, not the tuner)
    if settled == best_plan:
        out["e2e_tune_regret_ratio"] = 1.0
    else:
        s_ts, o_ts = [], []
        for _ in range(3):
            s_ts.append(measure(*settled)[0])
            o_ts.append(measure(*best_plan)[0])
        # <1.0 means the re-measure inverted the sweep's pick (a flat
        # surface): that is zero regret, not negative
        out["e2e_tune_regret_ratio"] = round(
            max(1.0, min(s_ts) / min(o_ts)), 3)
    out["e2e_tune_converge_rounds"] = converged_at or round_budget
    out["e2e_tune_rounds_total"] = ad.rounds
    out["e2e_tune_plans_scored"] = len(tuner.mix.scores) \
        if tuner.mix is not None else 0

    # -- phase 3: observe-mode A/B (dry-run tick on the round path) ---------
    # interleaved plain/observed rounds, median vs median: adjacent
    # alternation is the same honesty protocol the transport ratio uses
    # (sequential arms ride ±10% host-scheduling swings that dwarf a
    # microsecond tick)
    obs_ad = _Adapter()
    obs = PerfTuner(TunerConfig(mode="observe"), obs_ad)
    plain_times, observe_times = [], []
    t = 1000.0
    for r in range(observe_rounds):
        for arm in (plain_times, observe_times):
            t0 = time.perf_counter()
            psum_pytree(diff, compress=default_plan[0],
                        chunk_mb=default_plan[1])
            arm.append((time.perf_counter() - t0) * 1e3)
            if arm is observe_times:
                obs_ad.rounds, obs_ad.last_ms = r + 1, arm[-1]
                t += 1.0
                obs.tick(t)
    plain_ms = float(_np.median(plain_times))
    observe_ms = float(_np.median(observe_times))
    out["e2e_tune_observe_overhead_ratio"] = round(
        observe_ms / plain_ms, 3) if plain_ms > 0 else 1.0
    return out


def collect(trials: int = 2) -> dict:
    """Alternate transports and keep each one's best trial: run-to-run
    spread through the device tunnel is ~±10% (host scheduling + tunnel
    latency), so a single-shot A/B regularly inverts. Alternating A/B/A/B
    in one process and comparing per-transport bests keeps the comparison
    honest without tripling the wall clock. The proxy RATIO is computed
    from MEDIANS of both sides (direct's spread on the shared core is
    ±12%; a best-vs-best ratio would be a race between two maxima)."""
    out = {"e2e_clients": N_CLIENTS, "e2e_call_batch": CALL_BATCH,
           "e2e_features_per_datum": K,
           "e2e_microbatch_max": _default_microbatch()}
    transports = ["python"]
    try:
        from jubatus_tpu.rpc import native_server

        if native_server.available():
            transports.append("native")
    except Exception as e:  # noqa: BLE001
        out["e2e_native_error"] = repr(e)[:200]
    best: dict = {}
    runs_by_tr: dict = {tr: [] for tr in transports}
    for t in range(trials):
        for tr in transports:
            try:
                r = run(tr)
            except Exception as e:  # noqa: BLE001 — partial results beat
                out[f"e2e_{tr}_error"] = repr(e)[:200]  # a dead bench
                continue
            key = f"e2e_rpc_train_samples_per_sec_{tr}"
            runs_by_tr[tr].append(r[key])
            if key not in best or r[key] > best[key]:
                best.update(r)
    out.update(best)
    # the native-transport margin, of record (VERDICT r4 #7): median vs
    # median over the SAME adjacent A/B/A/B alternation the runs came
    # from (best-vs-best would race two maxima; early-vs-late would ride
    # the process-age trend). If the margin is genuinely small now that
    # microbatching dominates, this key is the honest record of that.
    import numpy as _np

    if runs_by_tr.get("python") and runs_by_tr.get("native"):
        out["e2e_transport_ratio_native_vs_python"] = round(
            float(_np.median(runs_by_tr["native"]))
            / float(_np.median(runs_by_tr["python"])), 3)
        out["e2e_transport_ratio_note"] = (
            f"median of {len(runs_by_tr['native'])} native vs "
            f"{len(runs_by_tr['python'])} python runs, adjacent alternation")
    # text workloads, once each on the preferred transport: the canonical
    # tokenized shape and the idf variant — BOTH on the native fast path
    # since round 3 (idf rides the C++ parser with the df tables)
    text_tr = "native" if "native" in transports else "python"
    for tag, conf, wl, ning in (
            ("text", TEXT_CONF, "text", True),
            ("text_idf", TEXT_IDF_CONF, "text", True),
            ("combo", COMBO_CONF, "numeric", True),
            # the Python-converter A/B for the combo fast path: same
            # wire traffic, native parser declined (VERDICT r4 #3)
            ("combo_python", COMBO_CONF, "numeric", False),
            ("text_filter", TEXT_FILTER_CONF, "text", True)):
        try:
            out.update(run(text_tr, workload=wl, conf=conf,
                           measure=TEXT_MEASURE_SECONDS, tag=tag,
                           native_ingest=ning))
        except Exception as e:  # noqa: BLE001
            out[f"e2e_{tag}_error"] = repr(e)[:200]
    # honesty: the text_filter fast path is HYBRID — the regex runs in
    # Python (std::regex/`re` divergence risk), memoized per distinct
    # input; the datum walk/tokenize/tf/hash/emit stay in C++
    out["e2e_text_filter_mode"] = "hybrid: python regex (memoized) + C++ parse"
    # featurize-plane throughput of record (ISSUE 5): convert_batch on
    # the combo and idf shapes, no server/device in the loop
    try:
        out.update(run_fv_convert())
    except Exception as e:  # noqa: BLE001
        out["e2e_fv_convert_error"] = repr(e)[:200]
    # headline host/device overlap: the Python-converter combo run rides
    # the pipelined generic train path (featurize||device by design)
    if "e2e_fv_overlap_fraction_combo_python" in out:
        out["e2e_fv_overlap_fraction"] = \
            out["e2e_fv_overlap_fraction_combo_python"]
    ck = "e2e_rpc_train_samples_per_sec_combo"
    if out.get(ck) and out.get(ck + "_python"):
        out["e2e_combo_native_vs_python"] = round(
            out[ck] / out[ck + "_python"], 2)
    # features-per-datum for the combo shape, so throughput normalizes
    # per EMITTED feature (K base keys -> K + K*(K-1)/2 with the
    # wildcard x wildcard mul rule)
    out["e2e_combo_features_per_datum"] = K + K * (K - 1) // 2
    # query plane: classify samples/s against the trained numeric model
    # (snapshot reads through the raw classify handler — no coalescer)
    try:
        out.update(run(text_tr, workload="classify",
                       measure=TEXT_MEASURE_SECONDS))
    except Exception as e:  # noqa: BLE001
        out["e2e_classify_error"] = repr(e)[:200]
    # mixed plane: 8 writers + 8 readers concurrently (VERDICT r4 #6) —
    # the workload the reference's process-wide rw lock serializes
    try:
        out.update(run(text_tr, workload="mixed",
                       measure=TEXT_MEASURE_SECONDS))
    except Exception as e:  # noqa: BLE001
        out["e2e_mixed_error"] = repr(e)[:200]
    # forensics overhead A/B (ISSUE 4): span store + slow log on vs off,
    # p50 ratio of record with a <2% budget
    try:
        out.update(run_tracing_overhead(text_tr))
    except Exception as e:  # noqa: BLE001
        out["e2e_tracing_overhead_error"] = repr(e)[:200]
    # full observability-plane overhead A/B (ISSUE 7): forensics +
    # time-series sampling + SLO evaluation on vs everything off,
    # same <2% p50 budget
    try:
        out.update(run_observability_overhead(text_tr))
    except Exception as e:  # noqa: BLE001
        out["e2e_observability_overhead_error"] = repr(e)[:200]
    # continuous-profiling overhead A/B (ISSUE 8): the ~67 Hz stack
    # sampler on vs fully off, same <2% p50 budget
    try:
        out.update(run_profiling_overhead(text_tr))
    except Exception as e:  # noqa: BLE001
        out["e2e_profiling_overhead_error"] = repr(e)[:200]
    # event-plane overhead A/B (ISSUE 14): journal + incident triggers
    # on vs stripped, same <2% p50 budget + the per-emit microbench
    try:
        out.update(run_event_plane_overhead(text_tr))
    except Exception as e:  # noqa: BLE001
        out["e2e_event_plane_overhead_error"] = repr(e)[:200]
    # data-quality plane (ISSUE 17): recorder overhead A/B (<2% mean),
    # prequential-vs-holdout tracking, and the seeded concept-shift
    # drill (drift detection -> SLO -> incident bundle)
    try:
        out.update(run_quality(text_tr))
    except Exception as e:  # noqa: BLE001
        out["e2e_quality_error"] = repr(e)[:200]
    # usage-attribution plane (ISSUE 19): 3-tenant conservation gate
    # (accounted CPU/device within 10% of process totals) + ledger
    # overhead A/B (<2% mean)
    try:
        out.update(run_usage(text_tr))
    except Exception as e:  # noqa: BLE001
        out["e2e_usage_error"] = repr(e)[:200]
    # proxy tier: same numeric workload through the proxy hop. The
    # REPORTED keys stay best-of, but the ratio uses median-vs-median
    # over ADJACENT alternating (proxy, direct) pairs: the direct side
    # alone swings ~±12% run to run on the shared core AND trends with
    # process age, so early-direct-vs-late-proxy systematically biased
    # the ratio low (round 4 dry runs: adjacent protocol 0.83-0.87,
    # early/late split 0.79 from the same code).
    dkey = f"e2e_rpc_train_samples_per_sec_{text_tr}"
    pkey = f"e2e_rpc_train_samples_per_sec_proxy_{text_tr}"
    proxy_runs: list = []
    ratio_direct_runs: list = []
    for _ in range(max(trials, 3)):
        try:
            r = run_proxy(text_tr)
            proxy_runs.append(r.get(pkey, 0))
            if r.get(pkey, 0) > out.get(pkey, 0):
                out.update(r)
        except Exception as e:  # noqa: BLE001
            out["e2e_proxy_error"] = repr(e)[:200]
        try:
            d = run(text_tr)
            ratio_direct_runs.append(d[dkey])
            if d[dkey] > out.get(dkey, 0):
                out[dkey] = d[dkey]
        except Exception as e:  # noqa: BLE001
            out[f"e2e_{text_tr}_error"] = repr(e)[:200]
    if proxy_runs and ratio_direct_runs:
        med_d = float(_np.median(ratio_direct_runs))
        med_p = float(_np.median(proxy_runs))
        out["e2e_proxy_vs_direct"] = round(med_p / med_d, 3)
        out["e2e_proxy_vs_direct_note"] = (
            f"median of {len(proxy_runs)} proxy vs "
            f"{len(ratio_direct_runs)} direct runs, adjacent alternation")
    # elastic membership (ISSUE 10): the churn chaos bench (kill/add one
    # of N backends under the 16-client mixed load) + the join/migrate/
    # drain row-parity cycle
    try:
        out.update(run_churn(text_tr))
    except Exception as e:  # noqa: BLE001
        out["e2e_churn_error"] = repr(e)[:200]
    try:
        out.update(run_migration_cycle())
    except Exception as e:  # noqa: BLE001
        out["e2e_migration_error"] = repr(e)[:200]
    # async staleness-bounded mix (ISSUE 11): drift parity vs the sync
    # plane + cadence/stall storm (train-path stall of record)
    try:
        out.update(run_async_mix())
    except Exception as e:  # noqa: BLE001
        out["e2e_async_mix_error"] = repr(e)[:200]
    # model-integrity poison drill (ISSUE 15): armed poisoner
    # quarantined every round, guarded fleet matches a clean twin,
    # non-finite total auto-rolls back, guard-off control corrupts
    try:
        out.update(run_poison_drill())
    except Exception as e:  # noqa: BLE001
        out["e2e_poison_error"] = repr(e)[:200]
    # autoscaling flash-crowd drill (ISSUE 12): seeded 7x traffic step,
    # autoscaled vs static control fleet, plus the autoscaler-initiated
    # scale-in drain's row parity
    try:
        out.update(run_fleet())
    except Exception as e:  # noqa: BLE001
        out["e2e_fleet_error"] = repr(e)[:200]
    try:
        out.update(run_fleet_scalein())
    except Exception as e:  # noqa: BLE001
        out["e2e_fleet_scalein_error"] = repr(e)[:200]
    # durable model plane (ISSUE 18): kill-everything drill — whole
    # fleet hard-killed, rebooted from the shared snapshot store; zero
    # acked-row loss beyond the diff-chain tail, warm beats cold
    try:
        out.update(run_killall_drill())
    except Exception as e:  # noqa: BLE001
        out["e2e_killall_error"] = repr(e)[:200]
    # self-tuning plane (ISSUE 20): regret vs the hand-tuned oracle on
    # the d24 loopback psum + rounds-to-converge + observe-mode A/B
    try:
        out.update(run_tune_regret())
    except Exception as e:  # noqa: BLE001
        out["e2e_tune_error"] = repr(e)[:200]
    return out


if __name__ == "__main__":
    # --seed N (ISSUE 12 satellite): override the base traffic seed for
    # any slice; every client stream derives from [SEED, client_idx]
    if "--seed" in sys.argv:
        i = sys.argv.index("--seed")
        SEED = int(sys.argv[i + 1])
        del sys.argv[i:i + 2]
    if len(sys.argv) > 1 and sys.argv[1] == "fleet":
        # the autoscale drill on its own (flash-crowd step + scale-in
        # row parity), for ISSUE 12 iteration without the full bench
        out = {}
        nproc = int(sys.argv[2]) if len(sys.argv) > 2 else 8
        out.update(run_fleet(nproc=nproc))
        out.update(run_fleet_scalein())
        print(json.dumps(out, indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "shardedknn":
        # the ISSUE 13 query slice on its own: 10^6/10^8-row top-k,
        # single- vs N-shard (default 8)
        shards = int(sys.argv[2]) if len(sys.argv) > 2 else 8
        scales = tuple(sys.argv[3].split(",")) if len(sys.argv) > 3 \
            else ("1e6", "1e8")
        print(json.dumps(run_sharded_knn((1, shards), scales), indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "shardedivf":
        shards = int(sys.argv[2]) if len(sys.argv) > 2 else 8
        scales = tuple(sys.argv[3].split(",")) if len(sys.argv) > 3 \
            else ("1e6", "1e8")
        print(json.dumps(run_sharded_knn_ivf(scales, shards), indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "events":
        # the event-plane slice on its own (overhead A/B + per-emit
        # microbench), for ISSUE 14 iteration without the full bench
        print(json.dumps(run_event_plane_overhead(
            measure=float(sys.argv[2]) if len(sys.argv) > 2
            else TEXT_MEASURE_SECONDS), indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "quality":
        # the data-quality slice on its own (overhead A/B +
        # prequential tracking + concept-shift drill), for ISSUE 17
        # iteration without the full bench
        print(json.dumps(run_quality(
            measure=float(sys.argv[2]) if len(sys.argv) > 2
            else TEXT_MEASURE_SECONDS), indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "usage":
        # the usage-attribution slice on its own (3-tenant conservation
        # gate + ledger overhead A/B), for ISSUE 19 iteration without
        # the full bench
        print(json.dumps(run_usage(
            measure=float(sys.argv[2]) if len(sys.argv) > 2
            else TEXT_MEASURE_SECONDS), indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "killall":
        # the ISSUE 18 chaos slice on its own: kill-everything, reboot
        # from the shared snapshot store, prove bounded loss
        print(json.dumps(run_killall_drill(
            train_seconds=float(sys.argv[2]) if len(sys.argv) > 2
            else 6.0), indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "tune":
        # the self-tuning slice on its own (oracle sweep + closed-loop
        # regret + observe-mode A/B), for ISSUE 20 iteration without
        # the full bench
        print(json.dumps(run_tune_regret(
            dim_bits=int(sys.argv[2]) if len(sys.argv) > 2 else 24),
            indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "asyncmix":
        # the async-mix slice on its own (drift parity + cadence/stall
        # storm), for ISSUE 11 iteration without the full bench
        print(json.dumps(run_async_mix(), indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "poison":
        # the model-integrity slice on its own (poison drill +
        # rollback recovery + unguarded control), for ISSUE 15
        # iteration without the full bench
        print(json.dumps(run_poison_drill(
            rounds=int(sys.argv[2]) if len(sys.argv) > 2 else 6),
            indent=1))
    elif len(sys.argv) > 1 and sys.argv[1] == "churn":
        # the elastic-membership slice on its own (kill/add cycle +
        # join/migrate/drain parity), for churn iteration without the
        # full bench's half hour
        out = {}
        out.update(run_churn("python",
                             measure=float(sys.argv[2])
                             if len(sys.argv) > 2 else 60.0))
        out.update(run_migration_cycle())
        print(json.dumps(out, indent=1))
    else:
        print(json.dumps(collect(), indent=1))
