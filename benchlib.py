"""jax-free bench plumbing shared by bench.py and tools/tunnel_reprobe.py.

Lives in its own module so the long-lived re-probe daemon can import the
probe program, the liveness verdict, and the round numbering WITHOUT
pulling the jax/axon import stack into its own process — the daemon's
whole design is that device init only ever happens in short-lived child
processes (docs/PERF_NOTES.md "tunnel wedge").
"""

import json
import os
import subprocess
import sys

#: fresh-subprocess tunnel probe program, the ONE definition of how the
#: axon tunnel is probed. The child runs its own watchdog thread and
#: exits via os._exit — it is never killed mid-device-op, which is what
#: wedges the tunnel.
TUNNEL_PROBE_PROG = (
    "import os, signal, threading, time\n"
    # ignore SIGTERM (e.g. a killpg sweep from tools/tunnel_reprobe.py):
    # the default disposition would cut an in-flight device init — the
    # tunnel-wedge trigger. Lifetime stays bounded by the watchdog join
    # + os._exit below.
    "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
    "res = {}\n"
    "def probe():\n"
    "    try:\n"
    "        t0 = time.perf_counter()\n"
    "        import jax, jax.numpy as jnp\n"
    "        d = jax.devices()[0]\n"
    "        res['p'] = d.platform\n"
    "        float(jnp.arange(4).sum())\n"
    "        res['init_s'] = round(time.perf_counter() - t0, 1)\n"
    "        res['ok'] = True\n"
    "    except Exception as e:\n"
    "        res['err'] = repr(e)[:120]\n"
    "t = threading.Thread(target=probe, daemon=True)\n"
    "t.start(); t.join(%f)\n"
    "import json as _j\n"
    # flush=True: os._exit skips stdio flushing, and without it the
    # PROBE line only survives when the ambient env happens to carry
    # PYTHONUNBUFFERED=1
    "print('PROBE ' + _j.dumps(res), flush=True)\n"
    "os._exit(0)\n"
)


def probe_tunnel(probe_timeout_s: float = None) -> dict:  # type: ignore[assignment]
    """One fresh-subprocess tunnel probe; returns the child's result dict.

    Once backend init hangs in a process that process is lost for device
    work (later jax calls join the same init lock), so liveness must be
    probed out-of-process. Keys: ok, p (platform), init_s, err."""
    if probe_timeout_s is None:
        # 90 s: a healthy tunnel answers a fresh process well inside this
        # (init measured 20-40 s), while a wedged one costs each ladder
        # attempt only this much; override for unusually slow links
        probe_timeout_s = float(
            os.environ.get("JUBATUS_BENCH_TUNNEL_PROBE_TIMEOUT", "90"))
    prog = TUNNEL_PROBE_PROG % max(probe_timeout_s - 10.0,
                                   probe_timeout_s * 0.5)
    env = dict(os.environ)
    env.pop("JUBATUS_TPU_PLATFORM", None)  # probe the real platform
    try:
        # outer timeout is a DISTANT backstop (watchdog + 90 s), so the
        # child's own watchdog thread + os._exit is what ends a hung
        # probe; subprocess.run's SIGKILL only fires if the interpreter
        # itself never reached the watchdog — keep that window rare,
        # a SIGKILL mid-device-op is the tunnel-wedge trigger
        proc = subprocess.run([sys.executable, "-c", prog], env=env,
                              capture_output=True, text=True,
                              timeout=probe_timeout_s + 90.0)
        for line in proc.stdout.splitlines():
            if line.startswith("PROBE "):
                return json.loads(line[6:])
        return {"err": "no probe line", "stderr": (proc.stderr or "")[-120:]}
    except subprocess.TimeoutExpired:
        return {"err": "probe subprocess timeout"}
    except Exception as e:  # noqa: BLE001
        return {"err": repr(e)[:120]}


def tunnel_is_alive(res: dict) -> bool:
    """The shared liveness verdict over a probe_tunnel() result."""
    return bool(res.get("ok")) and res.get("p") not in (None, "cpu")


#: compact-summary key budget. The driver keeps only the LAST ~2000 chars
#: of stdout; round 4's headline keys printed first and were cut off the
#: artifact of record (VERDICT r4 "What's weak" #1). The summary stays
#: under this so metric+platform+headline always survive the window.
SUMMARY_BYTES = 1800

#: extra-keys priority for the compact summary, most critical first: the
#: platform label and headline context, then the chip/d24 axis, then the
#: serving plane, then mix. Everything else rides in BENCH_FULL only.
SUMMARY_EXACT = (
    "bench_platform",
    "full_write_error",
    "baseline_impl",
    "baseline_samples_per_sec",
    "tpu_d2^24_samples_per_sec",
    "cpu_jax_d2^24_samples_per_sec",
    "baseline_cpp_d2^24_samples_per_sec",
    "tpu_d2^24_error",
    "e2e_rpc_train_samples_per_sec_native",
    "e2e_rpc_train_samples_per_sec_python",
    "e2e_transport_ratio_native_vs_python",
    "e2e_proxy_vs_direct",
    "e2e_rpc_train_samples_per_sec_combo",
    "e2e_rpc_train_samples_per_sec_combo_python",
    "e2e_combo_native_vs_python",
    "e2e_combo_features_per_datum",
    "e2e_rpc_train_samples_per_sec_text_filter",
    "e2e_fast_path_fraction_text_filter",
    "e2e_rpc_classify_samples_per_sec_native",
    "e2e_classify_dispatches_per_sec_native",
    "e2e_classify_avg_coalesced_batch_native",
    "e2e_schema_flush_fraction_native",
    "e2e_schema_query_flush_fraction_native",
    "e2e_mixed_train_classify_samples_per_sec",
    "e2e_mixed_train_samples_per_sec",
    "e2e_mixed_classify_samples_per_sec",
    "mix_round_worst_ms",
    "mix_under_1s_target",
    "collective_round_ms_nproc4_d24",
    "collective_round_ms_nproc4_d24_bf16",
    "collective_round_d24_platform",
)
#: prefix fallback order for keys not named above
SUMMARY_PREFIX = ("e2e_", "mix_", "collective_", "chip_", "cpu_", "tpu_")


def summarize(payload: dict, full_name: str) -> dict:
    """The <=SUMMARY_BYTES digest of a full bench payload.

    Keys enter by SUMMARY_EXACT order, then SUMMARY_PREFIX groups, then
    the rest, until the serialized summary would exceed the budget;
    "keys_dropped" counts what only BENCH_FULL carries."""
    head = {k: payload[k] for k in ("metric", "value", "unit", "vs_baseline")}
    head["full"] = full_name
    extra = payload.get("extra", {})
    ordered = [k for k in SUMMARY_EXACT if k in extra]
    seen = set(ordered)
    for pref in SUMMARY_PREFIX:
        ordered += sorted(k for k in extra
                          if k.startswith(pref) and k not in seen)
        seen.update(ordered)
    ordered += sorted(k for k in extra if k not in seen)
    out = dict(head)
    out["extra"] = {}
    dropped = 0
    for k in ordered:
        trial = dict(out)
        trial["extra"] = {**out["extra"], k: extra[k]}
        # size against the WORST-CASE dropped count so the final patch
        # below can only shrink the line, never push it past the budget
        trial["keys_dropped"] = len(extra)
        if len(json.dumps(trial)) > SUMMARY_BYTES:
            dropped += 1
            continue
        out = trial
    out["keys_dropped"] = dropped
    return out


def _is_chip(platform) -> bool:
    return platform in ("tpu", "axon")


def emit(payload: dict) -> None:
    """Durable-then-compact output (VERDICT r4 next-round #1).

    The FULL payload goes to BENCH_FULL_r{N}.json in the repo (the
    durable artifact, like linear_mixer.cpp:553-558's per-round log) and
    to stderr for interactive runs; stdout gets exactly one compact JSON
    line, printed LAST, sized to survive a last-2000-chars window."""
    here = os.path.dirname(os.path.abspath(__file__))
    full_name = f"BENCH_FULL_r{current_round():02d}.json"
    # a chip capture is never clobbered: a later tunnel-down run diverts
    # to a _cpu file, and a SECOND chip run (wedge + revival, capture
    # slot 2) diverts to a numbered sibling — every capture survives
    plat = payload.get("extra", {}).get("bench_platform")
    path = os.path.join(here, full_name)
    if os.path.exists(path):
        try:
            with open(path) as f:
                prev = json.load(f).get("extra", {}).get("bench_platform")
        except (OSError, ValueError):
            prev = None
        if _is_chip(prev):
            if not _is_chip(plat):
                full_name = full_name[:-5] + "_cpu.json"
            else:
                n = 2
                while os.path.exists(os.path.join(
                        here, f"{full_name[:-5]}_{n}.json")):
                    n += 1
                full_name = f"{full_name[:-5]}_{n}.json"
            path = os.path.join(here, full_name)
    try:
        with open(path, "w") as f:
            f.write(json.dumps(payload, indent=1, sort_keys=True) + "\n")
    except OSError as e:
        payload.setdefault("extra", {})["full_write_error"] = repr(e)[:120]
        full_name = None  # the pointer must not name a file that isn't there
    # serialize AFTER any error-key mutation so stderr carries it too
    print(json.dumps(payload, indent=1, sort_keys=True), file=sys.stderr)
    sys.stderr.flush()
    print(json.dumps(summarize(payload, full_name)))
    sys.stdout.flush()


def current_round() -> int:
    """The round now in progress, from the driver's BENCH_r{N}.json trail.

    The driver writes BENCH_r{N}.json at the END of round N, so the
    in-progress round is max(N)+1. JUBATUS_BENCH_ROUND overrides (e.g. a
    re-run inside an already-captured round). Non-numeric matches are
    skipped, never fatal — bench.emit() must not crash at the end of a
    run."""
    import glob
    import re

    env = os.environ.get("JUBATUS_BENCH_ROUND")
    if env and env.isdigit():
        return int(env)
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", os.path.basename(p))
        if m:
            rounds.append(int(m.group(1)))
    return (max(rounds) + 1) if rounds else 1
