"""Generate docs/api/<engine>.rst from the framework's own routing tables
(framework/idl.py) — run after changing the tables:

    python docs/generate.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jubatus_tpu.framework.idl import SERVICES  # noqa: E402

DESCRIPTIONS = {
    "anomaly": "Online outlier detection (LOF / light-LOF over "
               "approximate nearest-neighbor backends).",
    "bandit": "Multi-armed bandit policies (epsilon-greedy, softmax, "
              "Exp3, UCB1) keyed by player.",
    "burst": "Kleinberg burst detection over keyword document streams.",
    "classifier": "Online multi-class classification: linear "
                  "(perceptron/PA/PA1/PA2/CW/AROW/NHERD) and "
                  "instance-based (NN/cosine/euclidean) methods.",
    "clustering": "Online clustering (k-means / GMM / DBSCAN) over "
                  "weighted point buckets.",
    "graph": "Distributed property graph with centrality and "
             "shortest-path preset queries.",
    "nearest_neighbor": "Approximate nearest neighbor search "
                        "(LSH / minhash / euclid-LSH signatures).",
    "recommender": "Similarity search and row completion over sparse "
                   "feature rows.",
    "regression": "Online linear regression (passive-aggressive).",
    "stat": "Windowed per-key statistics (sum/stddev/max/min/entropy/"
            "moment).",
    "weight": "fv_converter weight inspection — debug the feature "
              "extraction pipeline.",
}

BUILTINS = [
    ("get_config() -> str", "the engine's JSON config"),
    ("save(id) -> {server: path}", "checkpoint every server's model"),
    ("load(id) -> bool", "restore a checkpoint"),
    ("get_status() -> {server: {...}}", "uptime/memory/counters/trace spans"),
    ("do_mix() -> bool", "trigger a mix round now"),
]


def emit(engine: str, methods) -> str:
    title = f"{engine} service"
    out = [title, "=" * len(title), "", DESCRIPTIONS[engine], "",
           "Every call carries the cluster name as its first wire "
           "parameter; the same client works against a standalone server, "
           "a cluster member, or a proxy.", "",
           "Methods", "-------", ""]
    for m in methods:
        if m.routing == "internal":
            continue
        args = ", ".join(m.args)
        routing = m.routing + (f"({m.cht_n})" if m.routing == "cht" else "")
        out.append(f"``{m.name}({args})``")
        out.append(f"   routing **{routing}**"
                   + (f", aggregator **{m.aggregator}**"
                      if m.routing in ("broadcast", "cht") else "")
                   + f", lock *{m.lock}*")
        out.append("")
    out += ["Built-ins", "---------", ""]
    for sig, desc in BUILTINS:
        out.append(f"``{sig}``")
        out.append(f"   {desc}")
        out.append("")
    return "\n".join(out)


def main() -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    api = os.path.join(here, "api")
    os.makedirs(api, exist_ok=True)
    for engine, methods in sorted(SERVICES.items()):
        with open(os.path.join(api, f"{engine}.rst"), "w") as f:
            f.write(emit(engine, methods) + "\n")
    print(f"wrote {len(SERVICES)} files to {api}")


if __name__ == "__main__":
    main()
