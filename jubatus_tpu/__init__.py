"""jubatus_tpu — a TPU-native distributed online machine-learning framework.

A from-scratch framework with the capabilities of Jubatus (the reference
surveyed in SURVEY.md): a family of online-learning engines — classifier,
regression, recommender, nearest_neighbor, anomaly, clustering, stat, weight,
bandit, burst, graph — that train on streaming data, serve queries over
MessagePack-RPC, and scale out across a TPU pod.

Architecture (TPU-first, not a port):

- The *model plane* is JAX: model state lives in device arrays (sharded via
  ``jax.sharding`` on multi-chip meshes), learning updates are jitted XLA
  programs (``jubatus_tpu.ops``), and the distributed "mix" (model averaging,
  the reference's get_diff/put_diff RPC loop) is an XLA collective (psum over
  ICI) — see ``jubatus_tpu.parallel``.
- The *serving plane* is a MessagePack-RPC front end speaking the reference's
  wire protocol (``jubatus_tpu.rpc``) so existing jubatus clients work,
  feeding microbatched updates into the JAX runtime.
- ``jubatus_tpu.framework`` is the server lifecycle: config, save/load in the
  reference's checkpoint envelope, mixer scheduling, status.
"""

from jubatus_tpu.version import VERSION, __version__  # noqa: F401

__all__ = ["VERSION", "__version__", "Datum", "EngineServer", "create_driver"]


def __getattr__(name):
    """Lazy top-level conveniences (importing jubatus_tpu stays cheap —
    no jax import until an engine is actually constructed)."""
    if name == "Datum":
        from jubatus_tpu.core.datum import Datum

        return Datum
    if name == "EngineServer":
        from jubatus_tpu.server import EngineServer

        return EngineServer
    if name == "create_driver":
        from jubatus_tpu.server.factory import create_driver

        return create_driver
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
