"""Client library (≙ jubatus/client/, SURVEY.md §2.5).

Typed per-engine clients over a common base, same wire protocol as the
reference's generated clients (client/common/client.hpp:30-87): every call
carries the cluster name as its first parameter; the same client talks to a
standalone server, a cluster member, or a proxy.

    from jubatus_tpu.client import ClassifierClient
    c = ClassifierClient("127.0.0.1", 9199, "name")
    c.train([("spam", Datum({"subject": "win money"}))])
    c.classify([Datum({"subject": "hello"})])

Engine method sets are generated from the IDL tables
(jubatus_tpu.framework.idl) — one class per engine, one method per RPC.
Datum-typed arguments accept `Datum` objects (packed to the wire 3-tuple
automatically); datum-typed results come back as wire tuples — use
`Datum.from_msgpack` when you want the typed view.

Self-healing plane (docs/ROBUSTNESS.md): idempotent calls (classify /
estimate / get_status / ...) transparently retry on transport failures
with jittered backoff under a per-client retry budget; effectful calls
(train / push / clear) never do. Cap an operation's total latency with
``deadline_after`` (re-exported here) — the remaining budget propagates
to the server, which rejects already-expired work:

    with deadline_after(0.2):
        c.classify([Datum({"subject": "hello"})])
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from jubatus_tpu.core.datum import Datum  # noqa: F401  (re-export)
from jubatus_tpu.framework.idl import SERVICES
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.rpc.deadline import deadline_after  # noqa: F401  (re-export)


class ClientBase:
    """Common built-ins (client/common/client.hpp:30-87)."""

    ENGINE = ""

    def __init__(self, host: str, port: int, name: str, timeout: float = 10.0):
        self.name = name
        self.client = RpcClient(host, port, timeout)

    # -- built-ins -----------------------------------------------------------
    def get_config(self) -> str:
        return self.client.call("get_config", self.name)

    def save(self, model_id: str) -> Dict[str, str]:
        return self.client.call("save", self.name, model_id)

    def load(self, model_id: str) -> bool:
        return self.client.call("load", self.name, model_id)

    def get_status(self) -> Dict[str, Dict[str, Any]]:
        return self.client.call("get_status", self.name)

    def do_mix(self) -> bool:
        return self.client.call("do_mix", self.name)

    def get_proxy_status(self) -> Dict[str, Dict[str, Any]]:
        return self.client.call("get_proxy_status", self.name)

    def close(self) -> None:
        self.client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _make_method(method_name: str):
    def call(self, *args):
        return self.client.call(method_name, self.name, *args)

    call.__name__ = method_name
    return call


def _make_client_class(engine: str, methods) -> type:
    ns: Dict[str, Any] = {"ENGINE": engine, "__doc__": f"{engine} client "
                          f"(≙ {engine}_client.hpp, generated from {engine}.idl)."}
    for m in methods:
        ns[m.name] = _make_method(m.name)
    return type(f"{engine.title().replace('_', '')}Client", (ClientBase,), ns)


AnomalyClient = _make_client_class("anomaly", SERVICES["anomaly"])
BanditClient = _make_client_class("bandit", SERVICES["bandit"])
BurstClient = _make_client_class("burst", SERVICES["burst"])
ClassifierClient = _make_client_class("classifier", SERVICES["classifier"])
ClusteringClient = _make_client_class("clustering", SERVICES["clustering"])
GraphClient = _make_client_class("graph", SERVICES["graph"])
NearestNeighborClient = _make_client_class(
    "nearest_neighbor", SERVICES["nearest_neighbor"]
)
RecommenderClient = _make_client_class("recommender", SERVICES["recommender"])
RegressionClient = _make_client_class("regression", SERVICES["regression"])
StatClient = _make_client_class("stat", SERVICES["stat"])
WeightClient = _make_client_class("weight", SERVICES["weight"])

CLIENT_CLASSES = {
    "anomaly": AnomalyClient,
    "bandit": BanditClient,
    "burst": BurstClient,
    "classifier": ClassifierClient,
    "clustering": ClusteringClient,
    "graph": GraphClient,
    "nearest_neighbor": NearestNeighborClient,
    "recommender": RecommenderClient,
    "regression": RegressionClient,
    "stat": StatClient,
    "weight": WeightClient,
}
