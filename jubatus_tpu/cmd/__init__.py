"""Ops CLIs (≙ jubatus/server/cmd/ + jubavisor/, SURVEY.md §2.6).

- ``jubactl``    — cluster control: start/stop via supervisors, save/load/
                   status via the servers themselves (cmd/jubactl.cpp).
- ``jubaconfig`` — validate + write/read/delete/list engine configs in the
                   coordination store (cmd/jubaconfig.cpp).
- ``jubaconv``   — offline json→datum→fv conversion debugger
                   (cmd/jubaconv.cpp).
- ``jubavisor``  — per-host process supervisor daemon, RPC-controlled
                   (jubavisor/jubavisor.{hpp,cpp}).

Each module exposes ``main(argv)`` and runs via
``python -m jubatus_tpu.cmd.<tool>``. The coordinator location comes from
``-z`` or the ``ZK``/``JUBATUS_COORDINATOR`` environment variables (the
reference honors ``ZK``, jubactl.cpp:121-127).
"""

from __future__ import annotations

import os
from typing import Optional


def resolve_coordinator(flag: str) -> Optional[str]:
    """-z flag, else $JUBATUS_COORDINATOR, else $ZK (reference order)."""
    return flag or os.environ.get("JUBATUS_COORDINATOR") or os.environ.get("ZK")


def apply_platform_override() -> None:
    """Honor JUBATUS_TPU_PLATFORM before any jax backend initializes.

    The axon sandbox's sitecustomize pins JAX_PLATFORMS at interpreter
    start, so subprocesses can't steer jax via the environment alone; any
    entry point that may construct a driver (servers, jubaconfig's
    dry-validation) calls this first."""
    plat = os.environ.get("JUBATUS_TPU_PLATFORM", "")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
