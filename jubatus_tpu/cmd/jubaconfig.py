"""Config management CLI (≙ cmd/jubaconfig.cpp:79-137).

    jubaconfig -c write  -t classifier -n mycluster -f conf.json -z /shared
    jubaconfig -c read   -t classifier -n mycluster -z /shared
    jubaconfig -c delete -t classifier -n mycluster -z /shared
    jubaconfig -c list   -z /shared

``write`` validates the file is JSON and that the engine type is known
(the reference validates via jsonconfig before writing, jubaconfig.cpp
validate_config) before storing it at /jubatus/config/<type>/<name>.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from jubatus_tpu.cmd import apply_platform_override, resolve_coordinator
from jubatus_tpu.coord import create_coordinator, membership
from jubatus_tpu.framework.idl import ENGINES


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="jubaconfig")
    p.add_argument("-c", "--cmd", required=True,
                   choices=["write", "read", "delete", "list"])
    p.add_argument("-f", "--file", default="", help="[write] config file")
    p.add_argument("-t", "--type", default="", help="engine type")
    p.add_argument("-n", "--name", default="", help="cluster name")
    p.add_argument("-z", "--coordinator", default="",
                   help="coordination store ($JUBATUS_COORDINATOR or $ZK)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    ns = _parser().parse_args(argv)
    spec = resolve_coordinator(ns.coordinator)
    if not spec:
        print("no coordinator: pass -z or set JUBATUS_COORDINATOR/ZK",
              file=sys.stderr)
        return 1
    coord = create_coordinator(spec)
    try:
        if ns.cmd in ("write", "read", "delete"):
            if not ns.type or not ns.name:
                print(f"can't execute {ns.cmd} without -t and -n", file=sys.stderr)
                return 1
            path = membership.config_path(ns.type, ns.name)
            if ns.cmd == "write":
                if not ns.file:
                    print("write requires -f <config.json>", file=sys.stderr)
                    return 1
                with open(ns.file) as f:
                    raw = f.read()
                try:
                    parsed = json.loads(raw)
                except json.JSONDecodeError as e:
                    print(f"invalid JSON in {ns.file}: {e}", file=sys.stderr)
                    return 1
                if ns.type not in ENGINES:
                    print(f"unknown engine type {ns.type!r} "
                          f"(known: {', '.join(ENGINES)})", file=sys.stderr)
                    return 1
                # full semantic validation: dry-construct the driver, like
                # the servers' --config-test (the reference validates via
                # jsonconfig before writing, jubaconfig.cpp validate_config).
                # Override BEFORE the factory import touches jax; env/import
                # failures must not masquerade as config rejection.
                apply_platform_override()
                from jubatus_tpu.server.factory import create_driver

                try:
                    create_driver(ns.type, parsed)
                except Exception as e:  # noqa: BLE001 — report any reason
                    print(f"config rejected by {ns.type} driver: {e}",
                          file=sys.stderr)
                    return 1
                if not coord.create(path, raw.encode()):
                    coord.set(path, raw.encode())
                print(f"wrote config for {ns.type}/{ns.name}")
            elif ns.cmd == "read":
                raw = coord.read(path)
                if raw is None:
                    print(f"no config for {ns.type}/{ns.name}", file=sys.stderr)
                    return 1
                print(raw.decode())
            else:  # delete
                if coord.remove(path):
                    print(f"deleted config for {ns.type}/{ns.name}")
                else:
                    print(f"no config for {ns.type}/{ns.name}", file=sys.stderr)
                    return 1
        else:  # list: walk /jubatus/config/<type>/<name>
            for etype in coord.list(membership.CONFIG_BASE):
                for name in coord.list(f"{membership.CONFIG_BASE}/{etype}"):
                    print(f"{etype}/{name}")
        return 0
    finally:
        coord.close()


if __name__ == "__main__":
    sys.exit(main())
