"""Offline json→datum→fv conversion debugger (≙ cmd/jubaconv.cpp:131-160).

    echo '{"user": "alice", "age": 31}' | jubaconv -o datum
    echo '{"text": "hello world"}' | jubaconv -c conf.json -o fv

Input on stdin; ``-i json`` (default) or ``-i datum`` (the datum JSON shape
``{"string_values": [[k,v]...], "num_values": [[k,v]...]}``); ``-o`` picks
the pipeline stage to print: json | datum | fv. ``-o fv`` needs ``-c`` with
a converter config (same JSON schema the servers use).

JSON→datum flattening matches the reference's json_converter: nested object
keys join with '/', array elements index as '[i]'; strings become
string_values, numbers num_values, bools 1/0.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, List, Optional, Tuple

from jubatus_tpu.core.datum import Datum


def json_to_datum(obj: Any) -> Datum:
    """Flatten a JSON document into a datum (≙ core json_converter)."""
    strings: List[Tuple[str, str]] = []
    nums: List[Tuple[str, float]] = []

    def walk(prefix: str, v: Any) -> None:
        if isinstance(v, dict):
            for k, sub in v.items():
                walk(f"{prefix}/{k}" if prefix else str(k), sub)
        elif isinstance(v, list):
            for i, sub in enumerate(v):
                walk(f"{prefix}[{i}]", sub)
        elif isinstance(v, bool):
            nums.append((prefix, 1.0 if v else 0.0))
        elif isinstance(v, (int, float)):
            nums.append((prefix, float(v)))
        elif isinstance(v, str):
            strings.append((prefix, v))
        elif v is None:
            pass
        else:
            raise TypeError(f"cannot convert {type(v).__name__} at {prefix!r}")

    walk("", obj)
    d = Datum()
    d.string_values = strings
    d.num_values = nums
    return d


def datum_from_json_shape(obj: Any) -> Datum:
    d = Datum()
    d.string_values = [(str(k), str(v)) for k, v in obj.get("string_values", [])]
    d.num_values = [(str(k), float(v)) for k, v in obj.get("num_values", [])]
    return d


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="jubaconv")
    p.add_argument("-i", "--input-format", default="json",
                   choices=["json", "datum"])
    p.add_argument("-o", "--output-format", default="fv",
                   choices=["json", "datum", "fv"])
    p.add_argument("-c", "--conf", default="", help="converter config file")
    return p


def main(argv: Optional[List[str]] = None,
         stdin=None, stdout=None) -> int:
    ns = _parser().parse_args(argv)
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    try:
        doc = json.load(stdin)
    except json.JSONDecodeError:
        print(f"invalid {ns.input_format} format", file=sys.stderr)
        return 1

    if ns.output_format == "json":
        if ns.input_format != "json":
            print("cannot output json from datum input", file=sys.stderr)
            return 1
        json.dump(doc, stdout, indent=1)
        stdout.write("\n")
        return 0

    datum = (json_to_datum(doc) if ns.input_format == "json"
             else datum_from_json_shape(doc))

    if ns.output_format == "datum":
        json.dump({"string_values": [[k, v] for k, v in datum.string_values],
                   "num_values": [[k, v] for k, v in datum.num_values]},
                  stdout, indent=1)
        stdout.write("\n")
        return 0

    # fv: needs the converter config (convert_datum, jubaconv.cpp:61-75)
    if not ns.conf:
        print("-o fv requires -c <converter config>", file=sys.stderr)
        return 1
    from jubatus_tpu.core.fv.converter import make_fv_converter

    with open(ns.conf) as f:
        conf = json.load(f)
    conv = make_fv_converter(conf.get("converter", conf))
    for key, value in sorted(conv.convert_named(datum).items()):
        stdout.write(f"{key}: {value}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
