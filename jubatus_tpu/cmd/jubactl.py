"""Cluster control CLI (≙ cmd/jubactl.cpp).

    jubactl -c start  -t classifier -s jubaclassifier -n c1 -N 4 -z /shared
    jubactl -c stop   -t classifier -s jubaclassifier -n c1 -z /shared
    jubactl -c save   -t classifier -n c1 -z /shared [-i model_id]
    jubactl -c load   -t classifier -n c1 -z /shared [-i model_id]
    jubactl -c status -t classifier -n c1 -z /shared [--all]
    jubactl -c metrics -t classifier -n c1 -z /shared
    jubactl -c breakers -t classifier -n c1 -z /shared
    jubactl -c trace TRACE_ID -t classifier -n c1 -z /shared
    jubactl -c profile -t classifier -n c1 -z /shared [--folded] [--device]

start/stop fan out to every jubavisor under /jubatus/supervisors,
distributing N processes round-robin (N/visors each, remainder to the
first ones; N=0 → one per visor — jubactl.cpp:133-142,240-260). save/load
RPC every registered server of the cluster (send2server). status prints
the nodes/actives registries; ``--all`` additionally scrapes every
member's get_status map. ``metrics`` (beyond the reference) scrapes every
member's raw histogram snapshot (get_metrics) and prints a MERGED cluster
view — exact p50/p90/p99 across nodes via bucket-wise sums
(utils/tracing.py merge_snapshots). ``breakers`` (also beyond the
reference) scrapes every registered proxy's per-backend circuit breaker
and retry-budget state (rpc/breaker.py). ``trace TRACE_ID`` (ISSUE 4)
scrapes every member's span store (``get_spans``) AND every registered
proxy's (``get_proxy_spans``), stitches the parent/child edges into ONE
cross-node span tree, and renders it with per-hop timings — the
distributed answer to "where did this slow request spend its time?".
``autoscale`` (ISSUE 12) runs the autoscaling control loop in the
foreground — poll SLO burn + queue depth, spawn replicas through
registered jubavisors, drain the least-loaded member when sustained-cold
— serving its decision journal over ``get_autoscale_status``;
``--watch`` renders live frames (attaching to an already-registered
autoscaler instead of starting a second loop), ``--once`` renders one
observe-only tick. ``profile`` (ISSUE 8) scrapes every member's folded stack samples
(``get_profile``) and every proxy's own (``get_proxy_profile``), folds
them into ONE cluster profile, and renders a top-N self/cumulative
table — or ``--folded`` collapsed-stack lines for flamegraph.pl /
speedscope; ``--device`` lists or triggers on-demand XLA captures
(``profile_device``) instead. ``quality`` (ISSUE 17) scrapes the
data-quality plane (``get_quality``; proxies fold the fleet) and
renders per-group PSI drift vs the pinned reference, prequential
(test-then-train) accuracy, the confidence-calibration table, and the
recent accuracy/drift trend — see docs/OBSERVABILITY.md §10.
``usage`` (ISSUE 19) scrapes the usage-attribution plane
(``get_usage``; proxies fold the fleet) and renders the per-tenant
bill: requests/errors/retries, CPU-thread-seconds, coalescer queue +
device seconds, rows and bytes per principal, ranked by CPU — folded
with utils/usage.merge_usage (exact-table sums + heavy-hitter sketch
merge, never gauge averaging) plus the fleet capacity/saturation/
headroom picture; ``--top N`` bounds the table — see
docs/OBSERVABILITY.md §11.
``tune`` (ISSUE 20) scrapes the self-tuning performance plane
(``get_tune``) and renders per-node tuner state — mode, the mix plan
hill-climb (live/best wire+chunk, trials, convergence), coalescer and
cadence gate state, actuation backoff — plus the recent decision
journal (probe/retune/deepen/shallow/quicken/relax/blocked records,
dry-run-tagged under ``--auto-tune observe``).
Server flags (-C/-T/-D/-X/-S/-I/...) are forwarded to visor-spawned
processes (jubactl.cpp:90-110).
"""

from __future__ import annotations

import argparse
import sys
from typing import Any, Dict, List, Optional

from jubatus_tpu.cmd import resolve_coordinator
from jubatus_tpu.coord import create_coordinator, membership
from jubatus_tpu.coord.base import Coordinator, NodeInfo
from jubatus_tpu.rpc.client import RpcClient


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="jubactl")
    p.add_argument("-c", "--cmd", required=True,
                   choices=["start", "stop", "save", "load", "status",
                            "metrics", "breakers", "trace", "alerts",
                            "watch", "profile", "drain", "rebalance",
                            "autoscale", "timeline", "incident",
                            "rollback", "quality", "restore", "usage",
                            "tune"])
    p.add_argument("trace_id", nargs="?", default="",
                   help="[trace] trace id to assemble (from a slow-log "
                        "record, a /metrics exemplar, or "
                        "trace.*.last_trace_id in get_status)")
    p.add_argument("--all", action="store_true",
                   help="[status] also scrape every member's get_status")
    p.add_argument("--once", action="store_true",
                   help="[watch] render one frame and exit (scripts/CI)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="[watch] refresh period in seconds")
    p.add_argument("--window", type=float, default=60.0,
                   help="[watch] rate/quantile window in seconds "
                        "(computed from each node's get_timeseries ring)")
    p.add_argument("--seconds", type=float, default=60.0,
                   help="[profile] sampling window to fold (seconds; "
                        "0 = every retained bucket)")
    p.add_argument("--folded", action="store_true",
                   help="[profile] emit collapsed-stack 'stack count' "
                        "lines (flamegraph.pl / speedscope input) "
                        "instead of the top-N table")
    p.add_argument("--top", type=int, default=30,
                   help="[profile] rows in the self/cumulative table; "
                        "[usage] principals in the per-tenant table")
    p.add_argument("--device", action="store_true",
                   help="[profile] on-demand XLA device capture instead "
                        "of stack sampling: list existing artifacts, or "
                        "capture for --device-seconds on every backend")
    p.add_argument("--device-seconds", type=float, default=0.0,
                   help="[profile --device] capture duration in seconds "
                        "(0 = just list existing artifacts)")
    # cluster event timeline + incident bundles (ISSUE 14)
    p.add_argument("--since", type=float, default=0.0,
                   help="[timeline] only events from the last this many "
                        "seconds (0 = every retained event)")
    p.add_argument("--grep", default="",
                   help="[timeline] substring filter (subsystem, type, "
                        "node, field values; applied server-side)")
    p.add_argument("--follow", action="store_true",
                   help="[timeline] keep polling with per-node HLC "
                        "cursors and stream new events as they happen "
                        "(--interval controls the poll period)")
    p.add_argument("--list", action="store_true",
                   help="[incident] list captured bundles across the "
                        "cluster (the default)")
    p.add_argument("--pull", default="", metavar="ID",
                   help="[incident] fetch one bundle by id (from "
                        "--list) and print its full forensic JSON")
    p.add_argument("--target", default="",
                   help="[drain|rollback|restore] the member to act on, "
                        "as IP_PORT (a node name from -c status); "
                        "rollback/restore without --target act on EVERY "
                        "member (the fleet-wide recovery)")
    # durable model plane (ISSUE 18): point-in-time restore from the
    # shared snapshot store (--store-dir on the servers)
    p.add_argument("--at", default="latest", metavar="HLC|latest",
                   help="[restore] point in time to restore to: a packed "
                        "HLC (from -c timeline or store.head_hlc in "
                        "-c status) or 'latest' (the default). Each "
                        "member materializes the newest snapshot+diff "
                        "chain at/before that instant and re-imports its "
                        "owned rows under the CURRENT hash ring, so a "
                        "fleet restored at a different size than the one "
                        "that saved (N->M reshard) comes back complete")
    p.add_argument("--stop", action="store_true",
                   help="[drain] also unregister the member's nodes/ "
                        "entry when drained, firing its suicide watcher "
                        "(the process exits); default leaves it running "
                        "drained for inspection")
    p.add_argument("--drain-timeout", type=float, default=120.0,
                   help="[drain] seconds to wait for the drained state")
    # autoscaling control plane (ISSUE 12)
    p.add_argument("--watch", action="store_true",
                   help="[autoscale] render a live frame every poll "
                        "(attaches to an already-registered autoscaler's "
                        "get_autoscale_status instead of starting a "
                        "second control loop)")
    p.add_argument("--min", dest="as_min", type=int, default=1,
                   help="[autoscale] fleet floor — a fleet below it "
                        "restores immediately, bypassing confirm and "
                        "cooldown")
    p.add_argument("--max", dest="as_max", type=int, default=8,
                   help="[autoscale] fleet ceiling for scale-out")
    p.add_argument("--autoscale-interval", type=float, default=5.0,
                   help="[autoscale] control-loop poll period (seconds)")
    p.add_argument("--cooldown", type=float, default=30.0,
                   help="[autoscale] quiet period after any actuation")
    p.add_argument("--scale-out-confirm", type=int, default=2,
                   help="[autoscale] consecutive hot polls before a "
                        "scale-out fires (flap suppression)")
    p.add_argument("--scale-in-confirm", type=int, default=6,
                   help="[autoscale] consecutive cold polls before a "
                        "scale-in drains the least-loaded replica")
    p.add_argument("--burn-hot", type=float, default=2.0,
                   help="[autoscale] SLO fast-window burn rate at/above "
                        "which a poll counts hot")
    p.add_argument("--queue-hot", type=float, default=4096.0,
                   help="[autoscale] queued examples per replica "
                        "(microbatch.queue_depth) at/above which a poll "
                        "counts hot")
    p.add_argument("--autoscale-port", type=int, default=0,
                   help="[autoscale] port for the get_autoscale_status "
                        "RPC (0 = ephemeral); registered under "
                        "/jubatus/autoscalers")
    p.add_argument("--dry-run", action="store_true",
                   help="[autoscale] observe and journal decisions, "
                        "never actuate (the safe exploration mode; "
                        "--once defaults to it when no autoscaler is "
                        "registered)")
    p.add_argument("-s", "--server", default="",
                   help="server name forwarded to jubavisor "
                        "(jubaclassifier or plain engine name)")
    p.add_argument("-t", "--type", required=True, help="engine type")
    p.add_argument("-n", "--name", required=True, help="cluster name")
    p.add_argument("-N", "--num", type=int, default=0,
                   help="total processes across the cluster (0 = one per visor)")
    p.add_argument("-z", "--coordinator", default="")
    p.add_argument("-i", "--id", default="", help="[save|load] model id")
    # forwarded server flags (jubactl.cpp:90-110)
    p.add_argument("-B", "--listen-if", dest="listen_if", default="")
    p.add_argument("-C", "--thread", type=int, default=2)
    p.add_argument("-T", "--timeout", type=int, default=10)
    p.add_argument("-D", "--datadir", default="/tmp")
    p.add_argument("-L", "--logdir", default="")
    p.add_argument("-X", "--mixer", default="linear_mixer")
    p.add_argument("-S", "--interval-sec", dest="interval_sec", type=int, default=16)
    p.add_argument("-I", "--interval-count", dest="interval_count", type=int, default=512)
    p.add_argument("-Z", "--zookeeper-timeout", dest="zookeeper_timeout",
                   type=int, default=10)
    p.add_argument("-R", "--interconnect-timeout", dest="interconnect_timeout",
                   type=int, default=10)
    return p


def _visors(coord: Coordinator) -> List[NodeInfo]:
    out = []
    for child in coord.list(membership.SUPERVISOR_BASE):
        try:
            out.append(NodeInfo.from_name(child))
        except (ValueError, IndexError):
            continue
    return out


def send2supervisor(coord: Coordinator, cmd: str, engine: str, name: str,
                    num: int, argv: Dict[str, Any]) -> int:
    """Distribute start/stop over all visors (jubactl.cpp:240-280)."""
    visors = _visors(coord)
    if not visors:
        print(f"no supervisor to {cmd} {name}", file=sys.stderr)
        return -1
    total = num if num > 0 else len(visors)
    per, extra = divmod(total, len(visors))
    rc = 0
    for i, visor in enumerate(visors):
        n = per + (1 if i < extra else 0)
        if n == 0 and cmd == "start":
            continue
        print(f"sending {cmd} / {name} to {visor.name}...", end="", flush=True)
        with RpcClient(visor.host, visor.port, timeout=10.0) as c:
            if cmd == "start":
                r = c.call("start", name, n, argv)
            else:
                r = c.call("stop", name, n)
        print("ok." if r == 0 else "failed.")
        rc = rc or r
    return rc


def send2server(coord: Coordinator, cmd: str, engine: str, name: str,
                model_id: str) -> int:
    """save/load on every registered server of the cluster (send2server)."""
    nodes = membership.get_all_nodes(coord, engine, name)
    if not nodes:
        print(f"no server of {engine}/{name}", file=sys.stderr)
        return -1
    rc = 0
    for node in nodes:
        print(f"sending {cmd} / {name} to {node.name}...", end="", flush=True)
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                r = c.call(cmd, name, model_id)
            ok = bool(r)
        except Exception as e:  # noqa: BLE001 — report per-host, keep going
            print(f"failed. ({e})")
            rc = -1
            continue
        print("ok." if ok else "failed.")
        rc = rc if ok else -1
    return rc


def show_status(coord: Coordinator, engine: str, name: str,
                show_all: bool = False) -> int:
    nodes = membership.get_all_nodes(coord, engine, name)
    actives = {n.name for n in membership.get_all_actives(coord, engine, name)}
    draining = {n.name for n in membership.get_draining(coord, engine, name)}
    epoch = membership.get_epoch(coord, engine, name)
    print(f"{engine}/{name}: {len(nodes)} node(s), {len(actives)} active, "
          f"epoch {epoch}"
          + (f", {len(draining)} draining" if draining else ""))
    rc = 0
    for node in nodes:
        mark = ("draining" if node.name in draining
                else "active" if node.name in actives else "standby")
        print(f"  {node.name}  [{mark}]")
        if not show_all:
            continue
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                status = c.call("get_status", name)
        except Exception as e:  # noqa: BLE001 — report per-host, keep going
            print(f"    <get_status failed: {e}>")
            rc = -1
            continue
        for _node_name, st in sorted(status.items()):
            # model-health verdict first (ISSUE 7): the structured
            # degraded reasons /healthz carries, rendered as one line
            hs = st.get("health.status")
            if hs:
                reasons = st.get("health.reasons") or []
                kinds = ", ".join(
                    str(r.get("kind", "?")) +
                    (f":{r['name']}" if r.get("name") else "")
                    for r in reasons) if isinstance(reasons, list) else ""
                print(f"    health: {hs}" + (f" [{kinds}]" if kinds else ""))
            guard_line = _fmt_guard(st)
            if guard_line:
                print(f"    {guard_line}")
            shard_line = _fmt_shard_layout(st)
            if shard_line:
                print(f"    {shard_line}")
            ann_line = _fmt_ann(st)
            if ann_line:
                print(f"    {ann_line}")
            for k in sorted(st):
                print(f"    {k}: {st[k]}")
    return rc


def _fmt_guard(st: Dict[str, Any]) -> str:
    """One-line model-integrity summary (ISSUE 15): guard mode,
    quarantined members, snapshot/rollback state; "" when the guard is
    off and nothing ever rolled back."""
    mode = st.get("mixer.guard_mode")
    rolls = int(st.get("rollback.count", 0) or 0)
    if (not mode or mode == "off") and not rolls:
        return ""
    bits = [f"guard: {mode or 'off'}"]
    q = st.get("mixer.guard_quarantined") or []
    if q:
        names = ", ".join(s.decode() if isinstance(s, bytes) else str(s)
                          for s in q)
        bits.append(f"quarantined [{names}]")
    snaps = st.get("snapshot.count")
    if snaps:
        bits.append(f"snapshots {int(snaps)} "
                    f"(v{st.get('snapshot.last_model_version', '?')})")
    if rolls:
        bits.append(f"rollbacks {rolls}")
    return "  ".join(bits)


def _fmt_shard_layout(st: Dict[str, Any]) -> str:
    """One-line shard-layout summary from the driver.shard.* gauges
    (ISSUE 13): ``shards: N × rows/bytes per shard``; "" when the model
    is unsharded."""
    count = st.get("driver.shard.count")
    if not count:
        return ""
    count = int(count)
    rows = st.get("driver.shard.rows", 0)
    nbytes = int(st.get("driver.shard.bytes_in_use", 0))
    per = st.get("driver.shard.rows_per_shard")
    if isinstance(per, (list, tuple)) and per:
        rows_bit = "/".join(str(int(r)) for r in per[:8])
        if len(per) > 8:
            rows_bit += "/…"
        rows_bit = f"rows {rows_bit}"
    else:
        rows_bit = f"rows {int(rows)}"
    mb = nbytes / 2 ** 20
    out = (f"shards: {count} × [{rows_bit}, "
           f"{mb / max(count, 1):.1f} MB/shard]")
    merge = st.get("driver.shard.topk_merge_ms")
    if merge is not None:
        out += f" topk_merge {float(merge):.1f} ms"
    return out


def _fmt_ann(st: Dict[str, Any]) -> str:
    """One-line ANN-tier summary from the driver.ann.* gauges (ISSUE
    16): mode, cell count, last probe/rescore widths, rolling recall
    probe; "" when the tier is off."""
    mode = st.get("driver.ann.mode")
    if not mode or mode == "off":
        return ""
    bits = [f"ann: {mode}"]
    if st.get("driver.ann.degraded"):
        bits.append("DEGRADED(exact fallback)")
    cells = st.get("driver.ann.cells")
    if cells:
        bits.append(f"{int(cells)} cells "
                    f"(probe {int(st.get('driver.ann.nprobe', 0))})")
    probed = st.get("driver.ann.probed_cells")
    cand = st.get("driver.ann.rescore_candidates")
    if probed:
        bits.append(f"last {int(probed)}c/{int(cand or 0)}r")
    recall = st.get("driver.ann.recall_probe")
    if recall is not None:
        bits.append(f"recall~{float(recall):.2f}")
    return "  ".join(bits)


def _fmt_ms(v) -> str:
    return f"{v:10.3f}" if isinstance(v, (int, float)) else f"{v:>10}"


def show_metrics(coord: Coordinator, engine: str, name: str) -> int:
    """Merged cluster quantile view: scrape every member's get_metrics
    snapshot and fold bucket counts (exact at bucket resolution)."""
    from jubatus_tpu.utils import tracing

    nodes = membership.get_all_nodes(coord, engine, name)
    if not nodes:
        print(f"no server of {engine}/{name}", file=sys.stderr)
        return -1
    snaps: List[Dict[str, Any]] = []
    scraped = []
    for node in nodes:
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call("get_metrics", name)
        except Exception as e:  # noqa: BLE001 — partial view beats none
            print(f"  <{node.name}: get_metrics failed: {e}>",
                  file=sys.stderr)
            continue
        for node_name, snap in per_node.items():
            snaps.append(snap)
            scraped.append(node_name)
    if not snaps:
        print("no member answered get_metrics", file=sys.stderr)
        return -1
    merged = tracing.merge_snapshots(snaps)
    print(f"{engine}/{name}: merged metrics from {len(scraped)} node(s): "
          f"{', '.join(sorted(scraped))}")
    hists = merged.get("hists") or {}
    if hists:
        print(f"  {'span':<32} {'count':>8} {'p50_ms':>10} {'p90_ms':>10} "
              f"{'p99_ms':>10} {'max_ms':>10}")
        for span in sorted(hists):
            st = hists[span]
            qs = [tracing.state_quantile(st, q) for q in (0.5, 0.9, 0.99)]
            cells = " ".join(_fmt_ms((q or 0.0) * 1e3) for q in qs)
            print(f"  {span:<32} {st.get('count', 0):>8} {cells} "
                  f"{_fmt_ms(float(st.get('max_s', 0.0)) * 1e3)}")
    counters = merged.get("counters") or {}
    if counters:
        print("  counters:")
        for cname in sorted(counters):
            print(f"    {cname}: {counters[cname]}")
    return 0


def show_breakers(coord: Coordinator, engine: str, name: str) -> int:
    """Per-backend circuit breaker + retry-budget state from every
    registered proxy (the self-healing plane's ops view): which backends
    are open/half-open, how many trips, how full the failover budget is.
    Answers 'why is this backend getting no traffic?' without grepping
    proxy logs."""
    proxies = []
    for child in coord.list(membership.PROXY_BASE):
        try:
            proxies.append(NodeInfo.from_name(child))
        except (ValueError, IndexError):
            continue
    if not proxies:
        print("no proxy registered", file=sys.stderr)
        return -1
    rc = 0
    for proxy in proxies:
        try:
            with RpcClient(proxy.host, proxy.port, timeout=10.0) as c:
                per_node = c.call("get_breakers", name)
        except Exception as e:  # noqa: BLE001 — report per-proxy, keep going
            print(f"  <{proxy.name}: get_breakers failed: {e}>",
                  file=sys.stderr)
            rc = -1
            continue
        for node_name, doc in sorted(per_node.items()):
            breakers = doc.get("breakers") or {}
            budget = doc.get("retry_budget") or {}
            print(f"proxy {node_name}: {len(breakers)} backend(s) tracked")
            if budget:
                print(f"  retry budget: {budget.get('tokens')} tokens "
                      f"(ratio {budget.get('ratio')}, "
                      f"{budget.get('withdrawals', 0)} spent, "
                      f"{budget.get('denials', 0)} denied)")
            for backend in sorted(breakers):
                b = breakers[backend]
                print(f"  {backend:<28} {b.get('state', '?'):>9}  "
                      f"failures_in_window={b.get('failures_in_window', 0)} "
                      f"opened_total={b.get('opened_total', 0)}")
    return rc


def show_alerts(coord: Coordinator, engine: str, name: str) -> int:
    """Model-health plane (ISSUE 7): every member's + proxy's SLO state
    (``get_alerts`` / ``get_proxy_alerts``) — which alerts are FIRING,
    and every configured SLO's current fast/slow burn rates."""
    rows: List[Dict[str, Any]] = []
    scraped = 0
    for node, method in (
            [(n, "get_alerts")
             for n in membership.get_all_nodes(coord, engine, name)]
            + [(pxy, "get_proxy_alerts") for pxy in _proxies(coord)]):
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call(method, name)
        except Exception as e:  # noqa: BLE001 — partial view beats none
            print(f"  <{node.name}: {method} failed: {e}>", file=sys.stderr)
            continue
        scraped += 1
        for node_name, doc in sorted((per_node or {}).items()):
            for st in (doc or {}).get("slos") or []:
                st = dict(st)
                st["node"] = node_name
                rows.append(st)
    if not scraped:
        print(f"no member of {engine}/{name} answered get_alerts",
              file=sys.stderr)
        return -1
    firing = [r for r in rows if r.get("firing")]
    print(f"{engine}/{name}: {len(firing)} alert(s) firing, "
          f"{len(rows)} SLO state(s) across the cluster")
    if rows:
        print(f"  {'node':<22} {'slo':<28} {'state':<8} "
              f"{'burn_fast':>9} {'burn_slow':>9}")
        for r in sorted(rows, key=lambda r: (not r.get("firing"),
                                             r.get("node", ""),
                                             r.get("name", ""))):
            state = "FIRING" if r.get("firing") else "ok"
            print(f"  {r.get('node', '?'):<22} {r.get('name', '?'):<28} "
                  f"{state:<8} {r.get('burn_fast', 0.0):>9.2f} "
                  f"{r.get('burn_slow', 0.0):>9.2f}")
            if r.get("firing"):
                print(f"      {r.get('describe', '')}")
    else:
        print("  (no SLOs configured — pass --slo to the servers)")
    return 0


def collect_quality(coord: Coordinator, engine: str,
                    name: str) -> Dict[str, Dict[str, Any]]:
    """Every member's ``get_quality`` doc keyed by node name. A proxy
    answers for the whole fleet in one call (broadcast + fold), so try
    proxies first and fall back to scraping members directly."""
    docs: Dict[str, Dict[str, Any]] = {}
    for pxy in _proxies(coord):
        try:
            with RpcClient(pxy.host, pxy.port, timeout=10.0) as c:
                per_node = c.call("get_quality", name)
        except Exception as e:  # noqa: BLE001 — fall back to members
            print(f"  <{pxy.name}: get_quality failed: {e}>",
                  file=sys.stderr)
            continue
        docs.update({k: v for k, v in (per_node or {}).items() if v})
    if docs:
        return docs
    for node in membership.get_all_nodes(coord, engine, name):
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call("get_quality", name)
        except Exception as e:  # noqa: BLE001 — partial view beats none
            print(f"  <{node.name}: get_quality failed: {e}>",
                  file=sys.stderr)
            continue
        docs.update({k: v for k, v in (per_node or {}).items() if v})
    return docs


def render_quality(engine: str, name: str,
                   docs: Dict[str, Dict[str, Any]]) -> str:
    """The ``-c quality`` view (pure; asserted by tests): fleet-merged
    per-feature drift table, prequential accuracy trend, calibration
    bins. Fleet drift is recomputed from the MERGED sketches
    (utils/quality.merge_quality), not averaged node scores."""
    from jubatus_tpu.utils import quality as q

    lines: List[str] = []
    fleet = q.merge_quality(list(docs.values()))
    lines.append(f"{engine}/{name}: data quality across "
                 f"{fleet['nodes']} node(s), "
                 f"sample {fleet.get('sample', 0.0):g}")
    drift = fleet.get("drift") or {}
    ref = fleet.get("reference") or {}
    live = fleet.get("live") or {}
    if drift:
        lines.append(f"  {'feature group':<24} {'psi':>8}  "
                     f"{'ref n':>9} {'live n':>9}  verdict")
        for g in sorted(drift, key=lambda g: -drift[g]):
            rn = int(((ref.get("features") or {}).get(g) or {})
                     .get("count", 0)) if g not in (
                "labels", "label_predictions") else \
                int((ref.get("labels") or {}).get("total", 0))
            ln_ = int(((live.get("features") or {}).get(g) or {})
                      .get("count", 0)) if g not in (
                "labels", "label_predictions") else \
                int((live.get("labels") or {}).get("total", 0))
            verdict = "DRIFTING" if drift[g] >= q.DEFAULT_DRIFT_THRESHOLD \
                else "ok"
            lines.append(f"  {g:<24} {drift[g]:>8.3f}  "
                         f"{rn:>9} {ln_:>9}  {verdict}")
    else:
        lines.append("  (no drift scores yet — reference window still "
                     "filling, or the quality plane is disarmed)")
    preq = fleet.get("prequential") or {}
    n = int(preq.get("n", 0))
    if n:
        acc = q.prequential_accuracy(preq)
        mae = q.prequential_mae(preq)
        bits = [f"prequential n={n}"]
        if preq.get("correct") or (acc is not None and acc > 0):
            bits.append(f"accuracy {acc:.4f}")
        if preq.get("abs_err"):
            bits.append(f"mae {mae:.4f}")
        ece = q.calibration_ece(preq)
        if ece is not None and any(int(r[0]) for r in
                                   (preq.get("conf") or [])):
            bits.append(f"ece {ece:.4f}")
        lines.append("  " + "  ".join(bits))
        conf = preq.get("conf") or []
        if any(int(r[0]) for r in conf):
            lines.append(f"  {'confidence':<12} {'n':>7} "
                         f"{'accuracy':>9} {'mean conf':>10}")
            for i, (cn, correct, conf_sum) in enumerate(conf):
                if not cn:
                    continue
                lines.append(
                    f"  [{i / 10:.1f},{(i + 1) / 10:.1f}){'':<3} {cn:>7} "
                    f"{correct / cn:>9.3f} {conf_sum / cn:>10.3f}")
    else:
        lines.append("  (no prequential scores yet — the hook samples "
                     "the train path; raise --quality-sample)")
    trend = fleet.get("trend") or []
    accs = [p["accuracy"] for p in trend if p.get("accuracy") is not None]
    if len(accs) >= 2:
        lines.append("  accuracy trend (old -> new): "
                     + " ".join(f"{a:.3f}" for a in accs[-12:]))
    drift_pts = [p.get("drift_max", 0.0) for p in trend]
    if len(drift_pts) >= 2:
        lines.append("  drift_max trend (old -> new): "
                     + " ".join(f"{d:.2f}" for d in drift_pts[-12:]))
    return "\n".join(lines)


def show_quality(coord: Coordinator, engine: str, name: str) -> int:
    """Data-quality plane (ISSUE 17): fleet-wide drift / prequential /
    calibration view from merged ``get_quality`` sketches."""
    docs = collect_quality(coord, engine, name)
    if not docs:
        print(f"no member of {engine}/{name} answered get_quality",
              file=sys.stderr)
        return -1
    print(render_quality(engine, name, docs))
    return 0


def collect_usage(coord: Coordinator, engine: str,
                  name: str) -> Dict[str, Dict[str, Any]]:
    """Every node's ``get_usage`` ledger doc keyed by node name
    (proxy hops included — they bill their own dispatch cost). A proxy
    answers for the whole fleet in one call (broadcast + fold), so try
    proxies first and fall back to scraping members directly."""
    docs: Dict[str, Dict[str, Any]] = {}
    for pxy in _proxies(coord):
        try:
            with RpcClient(pxy.host, pxy.port, timeout=10.0) as c:
                per_node = c.call("get_usage", name)
        except Exception as e:  # noqa: BLE001 — fall back to members
            print(f"  <{pxy.name}: get_usage failed: {e}>",
                  file=sys.stderr)
            continue
        docs.update({k: v for k, v in (per_node or {}).items() if v})
    if docs:
        return docs
    for node in membership.get_all_nodes(coord, engine, name):
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call("get_usage", name)
        except Exception as e:  # noqa: BLE001 — partial view beats none
            print(f"  <{node.name}: get_usage failed: {e}>",
                  file=sys.stderr)
            continue
        docs.update({k: v for k, v in (per_node or {}).items() if v})
    return docs


def render_usage(engine: str, name: str,
                 docs: Dict[str, Dict[str, Any]], top: int = 0) -> str:
    """The ``-c usage`` view (pure; asserted by tests): the fleet-wide
    per-tenant bill from MERGED ledgers (utils/usage.merge_usage —
    exact-table sums + sketch merge, never gauge averaging), ranked by
    CPU-thread-seconds, plus the capacity/headroom footer."""
    from jubatus_tpu.utils import sketches
    from jubatus_tpu.utils import usage as u

    fleet = u.merge_usage(list(docs.values()))
    lines: List[str] = []
    lines.append(f"{engine}/{name}: usage across "
                 f"{fleet.get('nodes', 0)} node(s)")
    rows = u.principal_rows(fleet)
    shown = rows[:top] if top and top > 0 else rows
    if shown:
        lines.append(
            f"  {'principal':<24} {'req':>9} {'err':>6} {'rty':>5} "
            f"{'cpu s':>9} {'dev s':>8} {'queue s':>8} {'rows':>10} "
            f"{'MB in':>8} {'MB out':>8} {'rows/s':>8}")
        for p, agg in shown:
            lines.append(
                f"  {p:<24} {int(agg['requests']):>9} "
                f"{int(agg['errors']):>6} {int(agg['retries']):>5} "
                f"{agg['cpu_seconds']:>9.3f} "
                f"{agg['device_seconds']:>8.3f} "
                f"{agg['queue_seconds']:>8.3f} {int(agg['rows']):>10} "
                f"{agg['bytes_in'] / 2 ** 20:>8.2f} "
                f"{agg['bytes_out'] / 2 ** 20:>8.2f} "
                f"{agg['demand_rows_per_sec']:>8.1f}")
        if top and top > 0 and len(rows) > top:
            lines.append(f"  ... {len(rows) - top} more principal(s) "
                         f"(raise --top)")
    else:
        lines.append("  (no usage recorded yet — the ledger fills as "
                     "requests dispatch; tag tenants via the envelope "
                     "principal, see docs/OBSERVABILITY.md §11)")
    # heavy-hitter lane: tenants still identifiable past the exact cap
    freqs = sketches.categorical_freqs(fleet.get("sketch") or {})
    hh = [p for p, _n in sorted(freqs.items(), key=lambda kv: -kv[1])
          if p not in (fleet.get("table") or {})]
    if hh:
        lines.append("  beyond-cap heavy hitters (sketch lane): "
                     + " ".join(hh[:8]))
    cap = float(fleet.get("capacity_rows_per_sec", 0.0))
    if cap > 0.0:
        lines.append(f"  capacity {cap:g} rows/s  "
                     f"saturation {fleet.get('saturation', 0.0):.3f}  "
                     f"headroom {fleet.get('headroom', 0.0):.3f}")
    else:
        lines.append("  (no capacity estimate yet — replicas learn "
                     "theirs from measured flush throughput)")
    return "\n".join(lines)


def show_usage(coord: Coordinator, engine: str, name: str,
               top: int = 0) -> int:
    """Usage-attribution plane (ISSUE 19): fleet-wide per-tenant cost
    view from merged ``get_usage`` ledgers."""
    docs = collect_usage(coord, engine, name)
    if not docs:
        print(f"no member of {engine}/{name} answered get_usage",
              file=sys.stderr)
        return -1
    print(render_usage(engine, name, docs, top=top))
    return 0


def collect_tune(coord: Coordinator, engine: str,
                 name: str) -> Dict[str, Dict[str, Any]]:
    """Every member's ``get_tune`` doc keyed by node name. Per-node
    state (each process tunes its own knobs), so members are scraped
    directly; failures degrade per node."""
    docs: Dict[str, Dict[str, Any]] = {}
    for node in membership.get_all_nodes(coord, engine, name):
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call("get_tune", name)
        except Exception as e:  # noqa: BLE001 — partial view beats none
            print(f"  <{node.name}: get_tune failed: {e}>",
                  file=sys.stderr)
            continue
        docs.update(per_node or {})
    return docs


def render_tune(engine: str, name: str,
                docs: Dict[str, Dict[str, Any]], last: int = 8) -> str:
    """The ``-c tune`` view (pure; asserted by tests): per-node tuner
    mode + plane state + the recent decision journal."""
    lines: List[str] = [f"{engine}/{name}: auto-tune across "
                        f"{len(docs)} node(s)"]
    for node in sorted(docs):
        st = docs[node] or {}
        if not st:
            lines.append(f"  {node}: tuner off (--auto-tune off)")
            continue
        head = f"  {node}: mode {st.get('mode', '?')}"
        backoff = float(st.get("backoff_s") or 0.0)
        if backoff > 0:
            head += f"  backoff {backoff:g}s"
        lines.append(head)
        mix = st.get("mix")
        if mix:
            plan = f"{mix.get('wire')}/{mix.get('chunk_mb'):g}MB"
            bits = [f"plan {plan}", f"trials {mix.get('trials', 0)}",
                    "converged" if mix.get("converged") else "searching"]
            if mix.get("best_wire") is not None:
                bits.append(f"best {mix['best_wire']}/"
                            f"{mix['best_chunk_mb']:g}MB"
                            + (f" {mix['best_ms']:g}ms"
                               if mix.get("best_ms") is not None else ""))
            if mix.get("int8_blacklisted"):
                bits.append("int8 BLACKLISTED (ef drift)")
            lines.append("    mix: " + "  ".join(bits))
        for cname, gate in sorted((st.get("coalescers") or {}).items()):
            lines.append(f"    coalescer {cname}: streaks "
                         f"hot {gate.get('hot_streak', 0)} / "
                         f"cold {gate.get('cold_streak', 0)}")
        gate = st.get("cadence") or {}
        if gate:
            lines.append(f"    cadence: streaks "
                         f"hot {gate.get('hot_streak', 0)} / "
                         f"cold {gate.get('cold_streak', 0)}")
        journal = (st.get("journal") or [])[-max(0, last):]
        for rec in journal:
            action = rec.get("action", "?")
            tag = " [dry-run]" if rec.get("dry_run") else ""
            tgt = rec.get("target")
            sig = rec.get("signals") or {}
            detail = ""
            if "wire" in sig:
                detail = f" -> {sig.get('wire')}/{sig.get('chunk_mb')}MB"
            elif "depth" in sig:
                detail = f" -> depth {sig.get('depth')}"
            elif "interval_sec" in sig:
                detail = f" -> {sig.get('interval_sec')}s"
            err = f"  ({rec['error']})" if rec.get("error") else ""
            lines.append(f"    [{rec.get('ts', 0):.1f}] {action:<8} "
                         f"{rec.get('reason', '')}"
                         f"{' @' + str(tgt) if tgt else ''}"
                         f"{detail}{tag}{err}")
        if not journal:
            lines.append("    (no decisions journaled yet)")
    return "\n".join(lines)


def show_tune(coord: Coordinator, engine: str, name: str,
              last: int = 8) -> int:
    """Self-tuning performance plane (ISSUE 20): per-node tuner state
    and decision journal from ``get_tune``."""
    docs = collect_tune(coord, engine, name)
    if not docs:
        print(f"no member of {engine}/{name} answered get_tune",
              file=sys.stderr)
        return -1
    print(render_tune(engine, name, docs))
    return 0


def collect_watch(coord: Coordinator, engine: str, name: str,
                  window_s: float = 60.0) -> Dict[str, Any]:
    """One scrape of the whole cluster for the watch view: per-member
    get_status + get_timeseries + get_alerts, per-proxy
    get_proxy_status. Failures degrade per node (a sick node is exactly
    what the watch exists to show)."""
    from jubatus_tpu.utils.timeseries import window_from_points

    nodes = membership.get_all_nodes(coord, engine, name)
    actives = {n.name for n in membership.get_all_actives(
        coord, engine, name)}
    data: Dict[str, Any] = {"engine": engine, "name": name,
                            "window_s": window_s, "nodes": {},
                            "proxies": {}, "actives": actives,
                            "epoch": membership.get_epoch(
                                coord, engine, name),
                            "draining": {n.name for n in
                                         membership.get_draining(
                                             coord, engine, name)}}
    import time as _time

    from jubatus_tpu.utils import events as ev

    ev_since = ev.wall_to_hlc(_time.time() - max(window_s * 5, 600.0))
    for node in nodes:
        entry: Dict[str, Any] = {"error": ""}
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                status = c.call("get_status", name)
                ts = c.call("get_timeseries", name)
                alerts = c.call("get_alerts", name)
                # event plane (ISSUE 14): recent events feed the
                # last_event column and the inline firing-SLO lines
                try:
                    evs = c.call("get_events", name, ev_since, "")
                except Exception:  # noqa: BLE001 — pre-event-plane node
                    evs = {}
        except Exception as e:  # noqa: BLE001 — render the sick node
            entry["error"] = str(e)
            data["nodes"][node.name] = entry
            continue
        st = (status or {}).get(node.name) or \
            next(iter((status or {}).values()), {})
        entry["status"] = st
        points = ((ts or {}).get(node.name) or {}).get("points") or []
        entry["window"] = window_from_points(points, window_s)
        doc = (alerts or {}).get(node.name) or {}
        entry["alerts"] = [a.get("name") for a in doc.get("alerts") or []]
        entry["events"] = ((evs or {}).get(node.name) or {}).get(
            "events") or []
        data["nodes"][node.name] = entry
    for pxy in _proxies(coord):
        try:
            with RpcClient(pxy.host, pxy.port, timeout=10.0) as c:
                pst = c.call("get_proxy_status", name)
        except Exception as e:  # noqa: BLE001
            data["proxies"][pxy.name] = {"error": str(e)}
            continue
        for node_name, st in (pst or {}).items():
            data["proxies"][node_name] = {"status": st, "error": ""}
    return data


def _watch_node_row(node_name: str, entry: Dict[str, Any],
                    active: bool, draining: bool = False) -> str:
    if entry.get("error"):
        return (f"  {node_name:<22} {'DOWN':<9} "
                f"<{entry['error'][:60]}>")
    st = entry.get("status") or {}
    win = entry.get("window")
    req_s = err_s = 0.0
    p99 = None
    p99_span = ""
    if win is not None:
        for span in win.spans("rpc."):
            r = win.span_rate(span)
            req_s += r
            if r > 0:
                q = win.quantile_ms(span, 0.99)
                if q is not None and (p99 is None or q > p99):
                    p99, p99_span = q, span
        for cname in win.counter_names("rpc."):
            if cname.endswith(".errors"):
                err_s += win.counter_rate(cname)
    health = st.get("health.status", "?")
    state = (f"{health}/drain" if draining
             else health if active else f"{health}/standby")
    div = st.get("mixer.health_premix_divergence_mean",
                 st.get("mixer.health_premix_divergence"))
    stale = st.get("mixer.health_staleness_max",
                   st.get("mixer.self_staleness"))
    drift = st.get("mixer.mix_ef_contrib_residual_norm")
    mix_bits = []
    if div is not None:
        mix_bits.append(f"div {float(div):.3f}")
    if stale is not None:
        mix_bits.append(f"stale {int(stale)}")
    if st.get("mixer.model_version") is not None:
        mix_bits.append(f"v{st['mixer.model_version']}")
    if drift is not None:
        mix_bits.append(f"ef {float(drift):.3g}")
    # model-integrity plane (ISSUE 15): members this node's guard holds
    # in quarantine, and rollbacks this model took
    q = st.get("mixer.guard_quarantined")
    if q:
        mix_bits.append(f"quar {len(q)}")
    if st.get("rollback.count"):
        mix_bits.append(f"rb {int(st['rollback.count'])}")
    # async mix (ISSUE 11): this member's distance behind the fold
    # cadence and, on the master, the pending inbox
    if st.get("mixer.async_mode"):
        mix_bits.append(f"lag {int(st.get('mixer.async_lag_rounds', 0))}")
        depth = st.get("mixer.async_inbox_depth")
        if depth:
            mix_bits.append(f"inbox {int(depth)}")
    # shard layout (ISSUE 13): N shards × live rows (row stores) or
    # MB/shard (feature-sharded weight state)
    shards = st.get("driver.shard.count")
    if shards:
        nbytes = int(st.get("driver.shard.bytes_in_use", 0))
        if st.get("driver.shard.rows_per_shard") is not None:
            mix_bits.append(
                f"sh {int(shards)}x{int(st.get('driver.shard.rows', 0))}r")
        else:
            mix_bits.append(
                f"sh {int(shards)}x"
                f"{nbytes / max(int(shards), 1) / 2 ** 20:.0f}MB")
    # ANN tier (ISSUE 16): cell count when armed, or DEG on degrade
    ann_mode = st.get("driver.ann.mode")
    if ann_mode and ann_mode != "off":
        if st.get("driver.ann.degraded"):
            mix_bits.append("ann DEG")
        else:
            mix_bits.append(f"ann {int(st.get('driver.ann.cells', 0))}c")
    # ANN shadow recall (ISSUE 16 gauge, trended since ISSUE 17): sag
    # here is the early warning the recall-deficit SLO alarms on
    recall = st.get("driver.ann.recall_probe")
    if recall is not None:
        mix_bits.append(f"rec {float(recall):.2f}")
    # data-quality plane (ISSUE 17): PSI drift vs the pinned reference
    # + prequential (test-then-train) accuracy
    qd = st.get("quality.drift_max")
    if qd is not None and st.get("quality.reference_pinned"):
        mix_bits.append(f"drift {float(qd):.2f}")
    qa = st.get("quality.prequential_accuracy")
    if qa is not None:
        mix_bits.append(f"acc {float(qa):.3f}")
    # usage-attribution plane (ISSUE 19): the tenant currently
    # demanding the most of this replica + its remaining headroom
    tp = st.get("usage.top_principal")
    if tp:
        mix_bits.append(f"ten {tp}")
    hr = st.get("usage.headroom")
    if hr is not None:
        mix_bits.append(f"hr {float(hr):.2f}")
    alerts = ",".join(entry.get("alerts") or []) or "-"
    p99_cell = f"{p99:.1f} {p99_span[4:]}" if p99 is not None else "-"
    # event plane (ISSUE 14): the node's newest event + its age — one
    # glance says whether something just transitioned here
    evs = entry.get("events") or []
    if evs:
        import time as _time

        last = evs[-1]
        age = max(0.0, _time.time() - float(last.get("ts", 0.0)))
        last_evt = f"{last.get('subsystem')}.{last.get('type')} {age:.0f}s"
    else:
        last_evt = "-"
    return (f"  {node_name:<22} {state:<9} {req_s:>8.1f} {err_s:>7.2f}  "
            f"{p99_cell:<22} {' '.join(mix_bits) or '-':<28} "
            f"{last_evt:<26} {alerts}")


def render_watch_frame(data: Dict[str, Any], ts: str = "") -> str:
    """One watch frame as text (pure; asserted by tests, printed by the
    refresh loop): per-node request/error rates + windowed p99 from the
    time-series, mix health (divergence/staleness/quant drift), proxy
    breaker states, and the firing alerts."""
    lines: List[str] = []
    nodes = data.get("nodes") or {}
    proxies = data.get("proxies") or {}
    actives = data.get("actives") or set()
    draining = data.get("draining") or set()
    # event plane (ISSUE 14): the header shows not just WHICH epoch the
    # cluster is on but how long ago membership last CHANGED — the age
    # of the newest membership event across every node's journal
    import time as _time

    all_events = [e for entry in nodes.values()
                  for e in (entry.get("events") or [])]
    member_evts = [e for e in all_events
                   if e.get("subsystem") == "membership"]
    if member_evts:
        newest = max(member_evts, key=lambda e: e.get("hlc", 0))
        age = max(0.0, _time.time() - float(newest.get("ts", 0.0)))
        epoch_bit = (f"epoch {data.get('epoch', 0)} "
                     f"(last event {age:.0f}s ago)")
    else:
        epoch_bit = f"epoch {data.get('epoch', 0)}"
    lines.append(f"{data.get('engine')}/{data.get('name')}"
                 f"{'  ' + ts if ts else ''}  "
                 f"window {data.get('window_s', 0):g}s  "
                 f"{epoch_bit}  "
                 f"({len(nodes)} server(s), {len(proxies)} proxy(ies)"
                 + (f", {len(draining)} draining" if draining else "")
                 + ")")
    lines.append(f"  {'node':<22} {'state':<9} {'req/s':>8} {'err/s':>7}  "
                 f"{'p99 ms (span)':<22} {'mix health':<28} "
                 f"{'last_event':<26} alerts")
    for node_name in sorted(nodes):
        lines.append(_watch_node_row(node_name, nodes[node_name],
                                     node_name in actives,
                                     node_name in draining))
    for pname in sorted(proxies):
        p = proxies[pname]
        if p.get("error"):
            lines.append(f"  proxy {pname:<16} DOWN <{p['error'][:60]}>")
            continue
        st = p.get("status") or {}
        lines.append(
            f"  proxy {pname:<16} {st.get('breaker_open', 0)} breaker(s) "
            f"open / {st.get('breaker_backends', 0)} tracked, "
            f"forwards {st.get('forward_count', 0)} "
            f"(errors {st.get('forward_errors', 0)})")
    firing = sorted({a for e in nodes.values()
                     for a in (e.get("alerts") or [])})
    lines.append("  alerts firing: " + (", ".join(firing) or "none"))
    # firing-SLO events inline (ISSUE 14): the fire/clear EDGES of the
    # recent window, so a cleared-but-recent page is still visible
    slo_edges = sorted((e for e in all_events
                        if e.get("subsystem") == "slo"),
                       key=lambda e: e.get("hlc", 0))
    for e in slo_edges[-4:]:
        age = max(0.0, _time.time() - float(e.get("ts", 0.0)))
        lines.append(f"  ! {age:>4.0f}s ago  {e.get('node', '?'):<22} "
                     f"slo {e.get('type')} {e.get('name', '?')} "
                     f"burn_fast={e.get('burn_fast', 0)}")
    return "\n".join(lines)


def show_watch(coord: Coordinator, engine: str, name: str, *,
               once: bool = False, interval: float = 2.0,
               window_s: float = 60.0) -> int:
    """Live cluster watch (ISSUE 7): poll + render until interrupted
    (``--once`` renders a single frame — the scriptable/CI form)."""
    import time as _time

    while True:
        data = collect_watch(coord, engine, name, window_s)
        ts = _time.strftime("%H:%M:%S")
        frame = render_watch_frame(data, ts=ts)
        if once:
            print(frame)
            return 0 if data.get("nodes") else -1
        # full-frame refresh: clear + home, like watch(1)
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        try:
            _time.sleep(max(interval, 0.2))
        except KeyboardInterrupt:
            return 0


def drain_member(coord: Coordinator, engine: str, name: str, target: str,
                 stop_after: bool = False, timeout: float = 120.0) -> int:
    """Elastic membership (ISSUE 10): drive one member through the drain
    state machine — stop routing new effectful work to it, finish
    in-flight, hand its rows to the new ring owners, unregister — and
    poll until ``drained`` (or the process exits, with ``--stop``)."""
    import time as _time

    if not target:
        print("drain needs --target IP_PORT (a node name from -c status)",
              file=sys.stderr)
        return 1
    try:
        node = NodeInfo.from_name(target)
    except (ValueError, IndexError):
        print(f"bad --target {target!r}: expected IP_PORT", file=sys.stderr)
        return 1
    known = {n.name for n in membership.get_all_nodes(coord, engine, name)}
    if node.name not in known:
        print(f"{node.name} is not a registered member of {engine}/{name}",
              file=sys.stderr)
        return 1
    print(f"draining {node.name} (stop_after={stop_after})...")
    try:
        with RpcClient(node.host, node.port, timeout=10.0) as c:
            st = c.call("drain", name, bool(stop_after))
    except Exception as e:  # noqa: BLE001 — report and fail
        print(f"drain RPC failed: {e}", file=sys.stderr)
        return -1
    deadline = _time.monotonic() + max(timeout, 1.0)
    while _time.monotonic() < deadline:
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                st = c.call("drain_status", name)
        except Exception:  # noqa: BLE001 — with --stop the exit IS success
            if stop_after:
                print("member exited (drained + unregistered)")
                return 0
            raise
        state = st.get("state")
        state = state.decode() if isinstance(state, bytes) else state
        if state == "drained":
            print(f"drained: {st.get('rows_handed_off', 0)} row(s) "
                  f"({st.get('bytes_handed_off', 0)} bytes) handed off, "
                  f"epoch {st.get('epoch')}")
            if st.get("error"):
                print(f"  warning: {st['error']}", file=sys.stderr)
            return 0
        _time.sleep(0.5)
    print(f"drain timed out in state {st!r}", file=sys.stderr)
    return -1


def rollback_member(coord: Coordinator, engine: str, name: str,
                    target: str) -> int:
    """Model-integrity plane (ISSUE 15): restore one member's last-good
    model snapshot (``rollback`` RPC — the ring the server keeps under
    ``--model-snapshot-interval``). ``--target IP_PORT`` names the node
    (a name from ``-c status``); without it, every registered member
    rolls back (the fleet-wide recovery after a poisoning incident)."""
    nodes = membership.get_all_nodes(coord, engine, name)
    if not nodes:
        print(f"no server of {engine}/{name}", file=sys.stderr)
        return -1
    if target:
        try:
            node = NodeInfo.from_name(target)
        except (ValueError, IndexError):
            print(f"bad --target {target!r}: expected IP_PORT",
                  file=sys.stderr)
            return 1
        if node.name not in {n.name for n in nodes}:
            print(f"{node.name} is not a registered member of "
                  f"{engine}/{name}", file=sys.stderr)
            return 1
        nodes = [node]
    rc = 0
    for node in nodes:
        print(f"rollback {node.name}...", end="", flush=True)
        try:
            with RpcClient(node.host, node.port, timeout=60.0) as c:
                out = c.call("rollback", name, "operator")
        except Exception as e:  # noqa: BLE001 — report per-host
            print(f" failed. ({e})")
            rc = -1
            continue
        if out.get("rolled_back"):
            print(f" ok: model_version {out.get('model_version')} "
                  f"(snapshot age "
                  f"{out.get('snapshots', {}).get('last_age_s', '?')}s)")
        else:
            print(f" refused: {out.get('error')}")
            rc = -1
    return rc


def restore_fleet(coord: Coordinator, engine: str, name: str,
                  target: str, at: str) -> int:
    """Durable model plane (ISSUE 18): point-in-time restore from the
    shared snapshot store. Every member (or just ``--target``)
    materializes the newest full snapshot + diff chain at/before
    ``--at`` (a packed HLC, or ``latest``) and re-imports the rows it
    owns under the CURRENT ring — restoring an 8-shard save into a
    2-shard fleet (or 1 into 8) resharded-on-the-fly."""
    if at == "latest":
        at_hlc = 0
    else:
        try:
            at_hlc = int(at)
        except ValueError:
            print(f"bad --at {at!r}: expected a packed HLC or 'latest'",
                  file=sys.stderr)
            return 1
    nodes = membership.get_all_nodes(coord, engine, name)
    if not nodes:
        print(f"no server of {engine}/{name}", file=sys.stderr)
        return -1
    if target:
        try:
            node = NodeInfo.from_name(target)
        except (ValueError, IndexError):
            print(f"bad --target {target!r}: expected IP_PORT",
                  file=sys.stderr)
            return 1
        if node.name not in {n.name for n in nodes}:
            print(f"{node.name} is not a registered member of "
                  f"{engine}/{name}", file=sys.stderr)
            return 1
        nodes = [node]
    rc = 0
    for node in nodes:
        print(f"restore {node.name} @ {at}...", end="", flush=True)
        try:
            with RpcClient(node.host, node.port, timeout=600.0) as c:
                out = c.call("store_restore", name, at_hlc)
        except Exception as e:  # noqa: BLE001 — report per-host
            print(f" failed. ({e})")
            rc = -1
            continue
        if out.get("restored"):
            print(f" ok: model_version {out.get('model_version')} "
                  f"hlc {out.get('hlc')} chain {out.get('chain_len')} "
                  f"(+{out.get('rows_imported', 0)} row(s) resharded, "
                  f"{out.get('seconds', 0)}s)")
        else:
            print(f" refused: {out.get('error')}")
            rc = -1
    return rc


def rebalance_cluster(coord: Coordinator, engine: str, name: str) -> int:
    """Ask every member to pull the rows it owns under the CURRENT ring
    (the repair action after churn; safe to re-run — rows apply as
    overwrites)."""
    nodes = membership.get_all_nodes(coord, engine, name)
    if not nodes:
        print(f"no server of {engine}/{name}", file=sys.stderr)
        return -1
    rc = 0
    total_rows = 0
    for node in nodes:
        print(f"rebalance {node.name}...", end="", flush=True)
        try:
            with RpcClient(node.host, node.port, timeout=600.0) as c:
                out = c.call("rebalance", name)
        except Exception as e:  # noqa: BLE001 — report per-host
            print(f" failed. ({e})")
            rc = -1
            continue
        rows = out.get("rows", 0)
        total_rows += rows
        print(f" ok: {rows} row(s), {out.get('mb_per_sec', 0.0)} MB/s"
              + (f" (failed sources: {out.get('sources_failed')})"
                 if out.get("sources_failed") else ""))
    print(f"rebalance complete: {total_rows} row(s) moved, "
          f"epoch {membership.get_epoch(coord, engine, name)}")
    return rc


def _proxies(coord: Coordinator) -> List[NodeInfo]:
    out = []
    for child in coord.list(membership.PROXY_BASE):
        try:
            out.append(NodeInfo.from_name(child))
        except (ValueError, IndexError):
            continue
    return out


def collect_profiles(coord: Coordinator, engine: str, name: str,
                     seconds: float = 60.0) -> Dict[str, Dict[str, Any]]:
    """Scrape every member's folded stack profile (``get_profile``) and
    every registered proxy's own (``get_proxy_profile``) — one doc per
    node name. Per-node failures degrade (partial profile beats none,
    same stance as the trace/alert collectors)."""
    docs: Dict[str, Dict[str, Any]] = {}
    for node, method in (
            [(n, "get_profile")
             for n in membership.get_all_nodes(coord, engine, name)]
            + [(pxy, "get_proxy_profile") for pxy in _proxies(coord)]):
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call(method, name, float(seconds))
        except Exception as e:  # noqa: BLE001 — partial profile beats none
            print(f"  <{node.name}: {method} failed: {e}>", file=sys.stderr)
            continue
        for node_name, doc in (per_node or {}).items():
            if isinstance(doc, dict):
                docs[str(node_name)] = doc
    return docs


def show_profile(coord: Coordinator, engine: str, name: str, *,
                 seconds: float = 60.0, folded: bool = False,
                 top: int = 30, device: bool = False,
                 device_seconds: float = 0.0) -> int:
    """ISSUE 8: the cluster profile view. Default mode folds every
    member's (and proxy's) collapsed stacks over the last ``seconds``
    and prints a top-N self/cumulative table; ``--folded`` emits raw
    ``stack count`` lines on stdout (header on stderr) so the output
    pipes straight into flamegraph.pl or speedscope. ``--device``
    switches to the on-demand XLA capture plane: list artifacts, or
    capture ``--device-seconds`` on every backend."""
    from jubatus_tpu.utils import profiler as prof

    if device:
        nodes = membership.get_all_nodes(coord, engine, name)
        if not nodes:
            print(f"no server of {engine}/{name}", file=sys.stderr)
            return -1
        rc = 0
        for node in nodes:
            try:
                # capture blocks for its duration: size the timeout to it
                with RpcClient(node.host, node.port,
                               timeout=max(10.0, device_seconds + 10.0)) as c:
                    per_node = c.call("profile_device", name,
                                      float(device_seconds))
            except Exception as e:  # noqa: BLE001 — report per-host
                print(f"  <{node.name}: profile_device failed: {e}>",
                      file=sys.stderr)
                rc = -1
                continue
            for node_name, doc in sorted((per_node or {}).items()):
                if "error" in doc:
                    print(f"{node_name}: capture error: {doc['error']}")
                    rc = -1
                elif "artifact" in doc:
                    print(f"{node_name}: captured {doc.get('seconds')}s "
                          f"-> {doc['artifact']} ({doc.get('bytes', 0)} "
                          "bytes)")
                else:
                    arts = doc.get("artifacts") or []
                    print(f"{node_name}: {len(arts)} capture(s) in "
                          f"{doc.get('dir', '?')}")
                    for a in arts:
                        print(f"  {a.get('name')}  {a.get('bytes', 0)} bytes")
        return rc
    docs = collect_profiles(coord, engine, name, seconds)
    if not docs:
        print(f"no member of {engine}/{name} answered get_profile",
              file=sys.stderr)
        return -1
    merged = prof.fold_profiles(docs.values())
    per_node = ", ".join(
        f"{n} ({sum((d.get('folded') or {}).values())} samples)"
        for n, d in sorted(docs.items()))
    header = (f"{engine}/{name}: profile window {seconds:g}s, folded "
              f"from {len(docs)} node(s): {per_node}")
    if not merged:
        print(header, file=sys.stderr)
        print("no samples retained (is --profile-hz 0 everywhere?)",
              file=sys.stderr)
        return -1
    if folded:
        # stdout stays pure collapsed-stack lines for flamegraph.pl
        print(header, file=sys.stderr)
        for line in prof.folded_lines(merged):
            print(line)
        return 0
    print(header)
    print(prof.render_top(merged, top=top))
    snaps = [(n, s) for n, d in sorted(docs.items())
             for s in d.get("snapshots") or []]
    if snaps:
        print(f"  tail-triggered snapshots ({len(snaps)}):")
        for n, s in snaps[-8:]:
            ids = ",".join(s.get("trace_ids") or []) or "-"
            print(f"    {n}  span={s.get('span')}  "
                  f"samples={s.get('samples')}  traces={ids}")
    return 0


def collect_trace_spans(coord: Coordinator, engine: str, name: str,
                        trace_id: str) -> List[Dict[str, Any]]:
    """Scrape every member's span store (``get_spans``) and every
    registered proxy's own (``get_proxy_spans``) for one trace; each
    span record is annotated with the node it came from."""
    spans: List[Dict[str, Any]] = []
    for node, method in (
            [(n, "get_spans")
             for n in membership.get_all_nodes(coord, engine, name)]
            + [(pxy, "get_proxy_spans") for pxy in _proxies(coord)]):
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call(method, name, trace_id)
        except Exception as e:  # noqa: BLE001 — partial trace beats none
            print(f"  <{node.name}: {method} failed: {e}>", file=sys.stderr)
            continue
        for node_name, recs in (per_node or {}).items():
            for rec in recs or []:
                rec = dict(rec)
                rec.setdefault("node", node_name)
                spans.append(rec)
    return spans


def assemble_trace(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Stitch span records (possibly from many nodes) into a forest:
    each returned root carries nested ``children`` lists. A span whose
    parent was not captured anywhere (the client's side of the story, or
    a ring-evicted hop) becomes a root — partial traces still render."""
    by_id: Dict[str, Dict[str, Any]] = {}
    for rec in spans:
        node = dict(rec)
        node["children"] = []
        by_id[str(node.get("span_id", ""))] = node
    roots: List[Dict[str, Any]] = []
    for node in by_id.values():
        parent = by_id.get(str(node.get("parent_id", "")))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def _start(n: Dict[str, Any]) -> float:
        return float(n.get("ts", 0.0))
    for node in by_id.values():
        node["children"].sort(key=_start)
    roots.sort(key=_start)
    return roots


def render_trace(trace_id: str, roots: List[Dict[str, Any]],
                 out=None) -> None:
    """Print one assembled span tree with per-hop timings: duration,
    owning node, and offset from the trace's first captured span."""
    out = out or sys.stdout
    t0 = min((float(r.get("ts", 0.0)) for r in roots), default=0.0)
    count = [0]

    def _walk(node: Dict[str, Any], indent: str, last: bool) -> None:
        count[0] += 1
        branch = "└─ " if last else "├─ "
        off = (float(node.get("ts", 0.0)) - t0) * 1e3
        print(f"{indent}{branch}{node.get('name', '?'):<24} "
              f"{float(node.get('duration_ms', 0.0)):>9.3f} ms  "
              f"@{node.get('node', '?')}  [t+{off:.1f}ms]", file=out)
        child_indent = indent + ("   " if last else "│  ")
        kids = node.get("children", [])
        for i, child in enumerate(kids):
            _walk(child, child_indent, i == len(kids) - 1)

    for i, root in enumerate(roots):
        _walk(root, "", i == len(roots) - 1)
    print(f"trace {trace_id}: {count[0]} span(s), "
          f"{len(roots)} root(s)", file=out)


def show_trace(coord: Coordinator, engine: str, name: str,
               trace_id: str) -> int:
    """ISSUE 4 acceptance: assemble + render ONE cross-node span tree
    for a trace id, proxy and backend hops included."""
    if not trace_id:
        print("trace needs a TRACE_ID (jubactl -c trace TRACE_ID ...)",
              file=sys.stderr)
        return 1
    spans = collect_trace_spans(coord, engine, name, trace_id)
    if not spans:
        print(f"no spans retained for trace {trace_id} "
              "(ring-evicted, or the id never existed)", file=sys.stderr)
        return -1
    nodes = {s.get("node", "?") for s in spans}
    print(f"{engine}/{name}: trace {trace_id} across "
          f"{len(nodes)} node(s): {', '.join(sorted(nodes))}")
    render_trace(trace_id, assemble_trace(spans))
    return 0


def collect_events(coord: Coordinator, engine: str, name: str,
                   cursors: Optional[Dict[str, int]] = None,
                   since: int = 0, grep: str = ""
                   ) -> List[Dict[str, Any]]:
    """Scrape every member's event journal (``get_events``) and every
    registered proxy's own (``get_proxy_events``), each with its own
    HLC cursor (clocks differ per node — one shared cursor would skip
    or duplicate), and fold into one causally ordered timeline. Updates
    ``cursors`` in place (the ``--follow`` loop's state)."""
    from jubatus_tpu.utils import events as ev

    cursors = cursors if cursors is not None else {}
    lists: List[List[Dict[str, Any]]] = []
    for node, method in (
            [(n, "get_events")
             for n in membership.get_all_nodes(coord, engine, name)]
            + [(pxy, "get_proxy_events") for pxy in _proxies(coord)]):
        cur = cursors.get(node.name, since)
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call(method, name, int(cur), grep)
        except Exception as e:  # noqa: BLE001 — partial timeline beats none
            print(f"  <{node.name}: {method} failed: {e}>", file=sys.stderr)
            continue
        for node_name, doc in (per_node or {}).items():
            recs = (doc or {}).get("events") or []
            for rec in recs:
                rec.setdefault("node", node_name)
            lists.append(recs)
            if recs:
                cursors[node.name] = max(
                    cursors.get(node.name, since),
                    max(int(r.get("hlc", 0)) for r in recs))
    return ev.merge_events(lists)


_SEV_MARK = {"debug": " ", "info": " ", "warning": "!", "error": "E"}

#: event-record keys that are rendered structurally, not as k=v fields
_EVENT_META = ("hlc", "ts", "node", "subsystem", "type", "severity",
               "trace_id")


def render_event_line(rec: Dict[str, Any]) -> str:
    """One timeline row: wall time, severity mark, node, subsystem.type,
    the remaining fields as k=v, and the trace id when one was active."""
    import time as _time

    ts = float(rec.get("ts", 0.0))
    clock = _time.strftime("%H:%M:%S", _time.localtime(ts)) + \
        f".{int(ts * 1000) % 1000:03d}"
    sev = str(rec.get("severity", "info"))
    fields = " ".join(f"{k}={rec[k]}" for k in rec
                      if k not in _EVENT_META)
    tid = rec.get("trace_id", "")
    return (f"{clock} {_SEV_MARK.get(sev, ' ')} "
            f"{rec.get('node', '?'):<22} "
            f"{rec.get('subsystem', '?')}.{rec.get('type', '?'):<20} "
            f"{fields}"
            + (f"  trace={tid}" if tid else ""))


def show_timeline(coord: Coordinator, engine: str, name: str, *,
                  since_s: float = 0.0, grep: str = "",
                  follow: bool = False, interval: float = 2.0) -> int:
    """ISSUE 14 acceptance: ONE interleaved cluster narrative — every
    node's state-transition events merged in causal (HLC) order.
    ``--follow`` streams: per-node cursors advance to the max HLC seen,
    so each poll prints exactly the events emitted since."""
    import time as _time

    from jubatus_tpu.utils import events as ev

    since = ev.wall_to_hlc(_time.time() - since_s) if since_s > 0 else 0
    cursors: Dict[str, int] = {}
    first = True
    while True:
        recs = collect_events(coord, engine, name, cursors=cursors,
                              since=since, grep=grep)
        if first and not recs and not follow:
            print(f"no events retained for {engine}/{name}"
                  + (f" matching {grep!r}" if grep else ""),
                  file=sys.stderr)
            return -1
        if first:
            nodes = {r.get("node", "?") for r in recs}
            print(f"{engine}/{name}: {len(recs)} event(s) across "
                  f"{len(nodes)} node(s)"
                  + (f", since {since_s:g}s" if since_s else "")
                  + (f", grep {grep!r}" if grep else "")
                  + ("  [following]" if follow else ""), file=sys.stderr)
        for rec in recs:
            print(render_event_line(rec))
        if not follow:
            return 0
        first = False
        sys.stdout.flush()
        try:
            _time.sleep(max(interval, 0.2))
        except KeyboardInterrupt:
            return 0


def show_incidents(coord: Coordinator, engine: str, name: str, *,
                   pull: str = "") -> int:
    """ISSUE 14: the incident-bundle surface. Default lists every
    node's captured bundles (id, reason, age, size, correlated trace
    count); ``--pull ID`` prints one bundle's full forensic JSON on
    stdout (header on stderr — pipe it to jq/a file)."""
    import json as _json
    import time as _time

    targets = ([(n, "get_incidents")
                for n in membership.get_all_nodes(coord, engine, name)]
               + [(pxy, "get_proxy_incidents")
                  for pxy in _proxies(coord)])
    if pull:
        for node, method in targets:
            try:
                with RpcClient(node.host, node.port, timeout=10.0) as c:
                    per_node = c.call(method, name, pull)
            except Exception as e:  # noqa: BLE001 — try the next node
                print(f"  <{node.name}: {method} failed: {e}>",
                      file=sys.stderr)
                continue
            for node_name, doc in (per_node or {}).items():
                if isinstance(doc, dict) and "error" not in doc:
                    print(f"incident {pull} from {node_name}",
                          file=sys.stderr)
                    print(_json.dumps(doc, indent=2, default=str))
                    return 0
        print(f"incident {pull!r} not found on any node", file=sys.stderr)
        return -1
    rows = []
    scraped = 0
    for node, method in targets:
        try:
            with RpcClient(node.host, node.port, timeout=10.0) as c:
                per_node = c.call(method, name, "")
        except Exception as e:  # noqa: BLE001 — partial list beats none
            print(f"  <{node.name}: {method} failed: {e}>", file=sys.stderr)
            continue
        scraped += 1
        for node_name, doc in sorted((per_node or {}).items()):
            for meta in (doc or {}).get("incidents") or []:
                meta = dict(meta)
                meta["node"] = node_name
                rows.append(meta)
    if not scraped:
        print(f"no member of {engine}/{name} answered get_incidents",
              file=sys.stderr)
        return -1
    rows.sort(key=lambda m: m.get("hlc", 0))
    print(f"{engine}/{name}: {len(rows)} incident bundle(s) across "
          f"{scraped} node(s)")
    if rows:
        print(f"  {'id':<24} {'node':<22} {'age':>8} {'bytes':>9} "
              f"{'traces':>6}  reason")
        now = _time.time()
        for m in rows:
            age = now - float(m.get("ts", now))
            print(f"  {m.get('id', '?'):<24} {m.get('node', '?'):<22} "
                  f"{age:>7.0f}s {m.get('bytes', 0):>9} "
                  f"{len(m.get('trace_ids') or []):>6}  "
                  f"{m.get('reason', '')}")
    return 0


def render_autoscale_frame(doc: Dict[str, Any], ts: str = "",
                           journal_rows: int = 8) -> str:
    """One autoscaler status frame as text (pure; asserted by tests,
    printed by --watch/--once): fleet signals, controller state,
    decision counters, per-replica rows, and the journal tail."""
    lines: List[str] = []
    fleet = doc.get("fleet") or {}
    st = doc.get("state") or {}
    counters = doc.get("counters") or {}
    cfg = doc.get("config") or {}
    lines.append(
        f"{doc.get('engine')}/{doc.get('name')} autoscaler"
        f"{'  ' + ts if ts else ''}  "
        f"fleet {fleet.get('replicas', '?')} replica(s) "
        f"[{cfg.get('min_replicas', '?')}..{cfg.get('max_replicas', '?')}]"
        f"  burn {fleet.get('burn_max', 0.0):g}"
        f"  queue/replica {fleet.get('queue_per_replica', 0.0):g}"
        f"  req/s {fleet.get('req_per_sec', 0.0):g}"
        + ("  [dry-run]" if cfg.get("dry_run") else ""))
    lines.append(
        f"  state: hot_streak {st.get('hot_streak', 0)}, "
        f"cold_streak {st.get('cold_streak', 0)}, "
        f"backoff_s {st.get('backoff_s', 0.0):g}; counters: "
        + ", ".join(f"{k.split('.', 1)[1]} {counters.get(k, 0)}"
                    for k in ("autoscale.decisions", "autoscale.spawns",
                              "autoscale.drains", "autoscale.blocked")))
    for r in doc.get("replicas") or []:
        mark = ("drain" if r.get("draining")
                else "DOWN" if not r.get("reachable", True) else "ok")
        lines.append(
            f"  {r.get('name', '?'):<22} {mark:<6} "
            f"req/s {r.get('req_per_sec', 0.0):>8.1f}  "
            f"p99 {r.get('p99_ms', 0.0):>8.1f} ms  "
            f"queue {r.get('queue_depth', 0.0):>8.0f}  "
            f"burn {r.get('burn_max', 0.0):>6.2f}"
            + ("  FIRING" if r.get("firing") else ""))
    tail = (doc.get("journal") or [])[-journal_rows:]
    moves = [j for j in tail if j.get("action") != "hold"] or tail[-3:]
    lines.append(f"  journal ({len(doc.get('journal') or [])} record(s) "
                 "retained):")
    for j in moves[-journal_rows:]:
        extra = ""
        if j.get("target"):
            extra += f" target={j['target']}"
        if j.get("count"):
            extra += f" count={j['count']}"
        if j.get("error"):
            extra += f" error={j['error'][:60]}"
        if j.get("backoff_s"):
            extra += f" backoff={j['backoff_s']:g}s"
        lines.append(f"    t={j.get('ts', 0):.1f}  "
                     f"{j.get('action', '?'):<10} {j.get('reason', ''):<18}"
                     f" {j.get('signals', {})}{extra}")
    return "\n".join(lines)


def _attach_autoscaler(coord: Coordinator) -> Optional[NodeInfo]:
    """First reachable registered autoscaler, or None."""
    for node in membership.get_autoscalers(coord):
        try:
            with RpcClient(node.host, node.port, timeout=5.0) as c:
                c.call("get_autoscale_status", "", 1)
            return node
        except Exception:  # noqa: BLE001 — stale ephemeral entry
            continue
    return None


def run_autoscale(coord: Coordinator, engine: str, name: str,
                  ns: Any) -> int:
    """ISSUE 12: the autoscaling control loop. Default: run the loop in
    the foreground (spawning via registered jubavisors, draining via
    the member drain RPC), serving ``get_autoscale_status``. With an
    autoscaler already registered, ``--watch``/``--once`` ATTACH to it
    and render its journal instead of starting a competing loop; a
    bare ``--once`` with no autoscaler running does one observe-only
    (dry-run) tick and renders it."""
    import time as _time

    from jubatus_tpu.coord.autoscaler import (AutoscaleConfig, Autoscaler,
                                              VisorActuator)

    remote = _attach_autoscaler(coord) if (ns.watch or ns.once) else None
    if remote is not None:
        print(f"attached to autoscaler {remote.name}", file=sys.stderr)
        while True:
            try:
                with RpcClient(remote.host, remote.port, timeout=10.0) as c:
                    per_node = c.call("get_autoscale_status", name, 32)
            except Exception as e:  # noqa: BLE001 — it may have exited
                print(f"autoscaler {remote.name} unreachable: {e}",
                      file=sys.stderr)
                return -1
            doc = next(iter((per_node or {}).values()), {})
            frame = render_autoscale_frame(doc, ts=_time.strftime("%H:%M:%S"))
            if ns.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            try:
                _time.sleep(max(ns.interval, 0.2))
            except KeyboardInterrupt:
                return 0
    try:
        config = AutoscaleConfig(
            min_replicas=ns.as_min, max_replicas=ns.as_max,
            poll_interval_s=ns.autoscale_interval, window_s=ns.window,
            cooldown_s=ns.cooldown, scale_out_confirm=ns.scale_out_confirm,
            scale_in_confirm=ns.scale_in_confirm, burn_hot=ns.burn_hot,
            queue_hot=ns.queue_hot,
            dry_run=bool(ns.dry_run or ns.once)).validate()
    except ValueError as e:
        print(f"autoscale: {e}", file=sys.stderr)
        return 2
    if not ns.once and membership.get_autoscalers(coord):
        # a registered loop exists but did not answer — warn, continue
        print("warning: another autoscaler is registered for this "
              "coordinator (stale entry, or it will fight this one)",
              file=sys.stderr)
    actuator = VisorActuator(coord, engine, name, server_argv={
        "thread": ns.thread, "timeout": ns.timeout,
        "datadir": ns.datadir, "logdir": ns.logdir, "mixer": ns.mixer,
        "interval_sec": ns.interval_sec,
        "interval_count": ns.interval_count})
    scaler = Autoscaler(coord, engine, name, actuator, config=config)
    if ns.once:
        rec = scaler.tick()
        print(render_autoscale_frame(scaler.status()))
        return 0 if rec else -1
    port = scaler.serve(ns.autoscale_port)
    print(f"autoscaler for {engine}/{name} up "
          f"(get_autoscale_status on 127.0.0.1:{port}, "
          f"bounds [{config.min_replicas}..{config.max_replicas}]"
          + (", DRY RUN)" if config.dry_run else ")"), file=sys.stderr)
    try:
        while True:
            scaler.tick()
            if ns.watch:
                frame = render_autoscale_frame(
                    scaler.status(), ts=_time.strftime("%H:%M:%S"))
                sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
                sys.stdout.flush()
            _time.sleep(max(config.poll_interval_s, 0.2))
    except KeyboardInterrupt:
        return 0
    finally:
        scaler.stop()


def main(argv: Optional[List[str]] = None) -> int:
    ns = _parser().parse_args(argv)
    spec = resolve_coordinator(ns.coordinator)
    if not spec:
        print("no coordinator: pass -z or set JUBATUS_COORDINATOR/ZK",
              file=sys.stderr)
        return 1
    coord = create_coordinator(spec)
    try:
        if ns.cmd == "status":
            return show_status(coord, ns.type, ns.name, show_all=ns.all)
        if ns.cmd == "metrics":
            return show_metrics(coord, ns.type, ns.name)
        if ns.cmd == "breakers":
            return show_breakers(coord, ns.type, ns.name)
        if ns.cmd == "trace":
            return show_trace(coord, ns.type, ns.name, ns.trace_id)
        if ns.cmd == "alerts":
            return show_alerts(coord, ns.type, ns.name)
        if ns.cmd == "quality":
            return show_quality(coord, ns.type, ns.name)
        if ns.cmd == "usage":
            return show_usage(coord, ns.type, ns.name, top=ns.top)
        if ns.cmd == "tune":
            return show_tune(coord, ns.type, ns.name)
        if ns.cmd == "watch":
            return show_watch(coord, ns.type, ns.name, once=ns.once,
                              interval=ns.interval, window_s=ns.window)
        if ns.cmd == "timeline":
            return show_timeline(coord, ns.type, ns.name,
                                 since_s=ns.since, grep=ns.grep,
                                 follow=ns.follow, interval=ns.interval)
        if ns.cmd == "incident":
            return show_incidents(coord, ns.type, ns.name, pull=ns.pull)
        if ns.cmd == "drain":
            return drain_member(coord, ns.type, ns.name, ns.target,
                                stop_after=ns.stop,
                                timeout=ns.drain_timeout)
        if ns.cmd == "rebalance":
            return rebalance_cluster(coord, ns.type, ns.name)
        if ns.cmd == "rollback":
            return rollback_member(coord, ns.type, ns.name, ns.target)
        if ns.cmd == "restore":
            return restore_fleet(coord, ns.type, ns.name, ns.target,
                                 ns.at)
        if ns.cmd == "autoscale":
            return run_autoscale(coord, ns.type, ns.name, ns)
        if ns.cmd == "profile":
            return show_profile(coord, ns.type, ns.name,
                                seconds=ns.seconds, folded=ns.folded,
                                top=ns.top, device=ns.device,
                                device_seconds=ns.device_seconds)
        if ns.cmd in ("start", "stop"):
            server = ns.server or ns.type
            name = f"{server}/{ns.name}"
            server_argv = {
                "listen_if": ns.listen_if, "thread": ns.thread,
                "timeout": ns.timeout, "datadir": ns.datadir,
                "logdir": ns.logdir, "mixer": ns.mixer,
                "interval_sec": ns.interval_sec,
                "interval_count": ns.interval_count,
                "zookeeper_timeout": ns.zookeeper_timeout,
                "interconnect_timeout": ns.interconnect_timeout,
            } if ns.cmd == "start" else {}
            return send2supervisor(coord, ns.cmd, ns.type, name, ns.num,
                                   server_argv)
        # save / load ('name' is the default id, jubactl.cpp:144-149)
        model_id = ns.id or ns.name
        return send2server(coord, ns.cmd, ns.type, ns.name, model_id)
    finally:
        coord.close()


if __name__ == "__main__":
    sys.exit(main())
