"""jubadump — convert saved model files to JSON (≙ the reference's
jubadump tool, man/en/jubadump.1: "a tool to convert Jubatus model files
saved using save RPC to JSON").

Reads the checkpoint envelope (framework/save_load.py — same layout as
the reference's 48-byte header + system container + versioned user data,
save_load.cpp:45-158) WITHOUT constructing a driver, so any model file
can be inspected offline:

    python -m jubatus_tpu.cmd.jubadump -i /tmp/model.jubatus
    python -m jubatus_tpu.cmd.jubadump -i model.jubatus --summary

The reference supports a subset of engines; this version dumps every
engine's file because all drivers share one envelope + msgpack pytree
layout. ``--summary`` replaces large arrays with shape/dtype/stat
digests (the full dump of a 2^20-feature table is rarely what you want
in a terminal).
"""

from __future__ import annotations

import argparse
import json
import sys
import zlib
from typing import Any

import numpy as np

from jubatus_tpu.framework.save_load import (
    _HEADER,
    FORMAT_VERSION,
    MAGIC,
    SaveLoadError,
)
from jubatus_tpu.utils.serialization import unpack_obj

SUMMARY_ARRAY_LIMIT = 64  # arrays up to this many elements dump in full


def _jsonable(obj: Any, summary: bool) -> Any:
    if isinstance(obj, np.ndarray):
        if summary and obj.size > SUMMARY_ARRAY_LIMIT:
            finite = obj[np.isfinite(obj)] if obj.dtype.kind == "f" else obj
            stats = {}
            if finite.size and obj.dtype.kind in "fiu":
                stats = {
                    "min": float(np.min(finite)),
                    "max": float(np.max(finite)),
                    "nonzero": int(np.count_nonzero(obj)),
                }
            return {"__array__": {"dtype": obj.dtype.str,
                                  "shape": list(obj.shape), **stats}}
        return obj.tolist()
    if isinstance(obj, bytes):
        try:
            return obj.decode("utf-8")
        except UnicodeDecodeError:
            return {"__bytes__": obj.hex()}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, summary) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v, summary) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def dump_file(path: str, *, summary: bool = False,
              skip_user_data: bool = False) -> dict:
    """Parse + validate one model file into a JSON-ready dict. A directory
    is treated as a sharded checkpoint (framework/sharded_checkpoint.py):
    the system sidecar plus per-array shape/dtype/partition metadata —
    array bytes are never read (they may span a pod's worth of hosts)."""
    import os

    if os.path.isdir(path):
        # offline metadata inspection needs no accelerator, but orbax
        # queries jax's default backend — pin CPU so the dump works on
        # hosts without the TPU plugin on PYTHONPATH
        from jubatus_tpu.cmd import apply_platform_override

        os.environ.setdefault("JUBATUS_TPU_PLATFORM", "cpu")
        apply_platform_override()
        from jubatus_tpu.framework.sharded_checkpoint import (
            checkpoint_metadata,
        )

        out = checkpoint_metadata(path)
        system = out.get("system")
        if isinstance(system, dict) and isinstance(system.get("config"), str):
            try:
                out["system"] = dict(system,
                                     config=json.loads(system["config"]))
            except json.JSONDecodeError:
                pass
        return _jsonable(out, summary)

    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < _HEADER.size:
        raise ValueError(f"{path}: truncated header ({len(raw)} bytes)")
    magic, fmt, vmaj, vmin, vmaint, crc, ssize, usize = \
        _HEADER.unpack_from(raw)
    if magic != MAGIC:
        raise ValueError(f"{path}: bad magic {magic!r} (not a model file)")
    body = raw[_HEADER.size:]
    crc_actual = zlib.crc32(body) & 0xFFFFFFFF
    out = {
        "header": {
            "format_version": fmt,
            "jubatus_version": f"{vmaj}.{vmin}.{vmaint}",
            "crc32": f"{crc:08x}",
            "crc32_ok": crc_actual == crc,
            "system_data_size": ssize,
            "user_data_size": usize,
        },
    }
    if fmt != FORMAT_VERSION:
        out["header"]["warning"] = f"unsupported format version {fmt}"
        return out
    if len(body) != ssize + usize:
        out["header"]["warning"] = (
            f"size mismatch: header says {ssize}+{usize}, file has {len(body)}")
        return out
    # corrupt bodies (the very case crc32_ok flags) must never lose the
    # header report to an unpack traceback
    try:
        system = unpack_obj(body[:ssize])
    except Exception as e:  # noqa: BLE001 — msgpack raises various types
        out["system_error"] = f"cannot decode system container: {e}"
        return out
    if isinstance(system, dict) and isinstance(system.get("config"), str):
        try:  # present the config as structured JSON, not an escaped string
            system = dict(system, config=json.loads(system["config"]))
        except json.JSONDecodeError:
            pass
    out["system"] = _jsonable(system, summary)
    if usize == 0:
        # sharded-checkpoint sidecars (system.jubatus) carry no user data;
        # the model lives in the orbax state/ tree next to them
        out["user_data"] = None
    elif not skip_user_data:
        try:
            user_version, user_data = unpack_obj(body[ssize:ssize + usize])
        except Exception as e:  # noqa: BLE001
            out["user_data_error"] = f"cannot decode user data: {e}"
            return out
        out["user_data_version"] = user_version
        out["user_data"] = _jsonable(user_data, summary)
    return out


def _live_call(target: str, method: str, flag: str, name: str,
               *extra: Any, timeout: float = 10.0) -> Any:
    """One RPC against a live HOST:PORT target (the --mix-history /
    --slow-log live-dump paths share the parse + call shape)."""
    from jubatus_tpu.rpc.client import RpcClient

    host, _, port = target.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"{flag} wants HOST:PORT, got {target!r}")
    with RpcClient(host, int(port), timeout=timeout) as c:
        return _jsonable(c.call(method, name, *extra), False)


def dump_mix_history(target: str, name: str = "",
                     timeout: float = 10.0) -> list:
    """Pull a live server's mix-round flight records (``get_mix_history``
    RPC — the bounded ring framework/mixer.py keeps per mixer)."""
    return _live_call(target, "get_mix_history", "--mix-history", name,
                      timeout=timeout)


def dump_slow_log(target: str, name: str = "",
                  timeout: float = 10.0) -> dict:
    """Pull a live server's (or proxy's) slow-request ring — the
    tail-based capture of utils/slowlog.py, keyed by node name. Against
    a proxy the reply also folds in every backend's ring."""
    return _live_call(target, "get_slow_log", "--slow-log", name,
                      timeout=timeout)


def dump_profile(target: str, name: str = "", seconds: float = 0.0,
                 timeout: float = 10.0) -> dict:
    """Pull a live server's (or proxy's) folded stack profile — the
    always-on sampler of utils/profiler.py (collapsed stacks + sampler
    stats + tail-triggered snapshots), keyed by node name. Against a
    proxy the reply also folds in every backend's samples. ``seconds``
    bounds the window (0 = every retained bucket)."""
    return _live_call(target, "get_profile", "--profile", name,
                      float(seconds), timeout=timeout)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="jubadump",
        description="convert saved jubatus_tpu model files to JSON, or "
                    "dump a live server's mix-round flight records")
    p.add_argument("-i", "--input", metavar="FILE")
    p.add_argument("--summary", action="store_true",
                   help="digest large arrays instead of dumping them")
    p.add_argument("--no-user-data", action="store_true",
                   help="header + system container only")
    p.add_argument("--mix-history", metavar="HOST:PORT",
                   help="dump the mix flight recorder of a LIVE server "
                        "(get_mix_history RPC) instead of reading a file")
    p.add_argument("--slow-log", metavar="HOST:PORT", dest="slow_log",
                   help="dump the slow-request ring of a LIVE server or "
                        "proxy (get_slow_log RPC): tail-based capture of "
                        "requests at/above the --slowlog-quantile of "
                        "their own latency histogram")
    p.add_argument("--profile", metavar="HOST:PORT", dest="profile",
                   help="dump the folded stack profile of a LIVE server "
                        "or proxy (get_profile RPC): collapsed stacks "
                        "from the always-on sampler, sampler stats, and "
                        "tail-triggered snapshots")
    p.add_argument("--seconds", type=float, default=0.0,
                   help="[--profile] window to fold (seconds; 0 = every "
                        "retained bucket)")
    p.add_argument("-n", "--name", default="",
                   help="[--mix-history/--slow-log/--profile] cluster "
                        "name to pass the RPC")
    ns = p.parse_args(argv)
    if sum(map(bool, (ns.input, ns.mix_history, ns.slow_log,
                      ns.profile))) != 1:
        print("exactly one of -i FILE, --mix-history HOST:PORT, "
              "--slow-log HOST:PORT, or --profile HOST:PORT required",
              file=sys.stderr)
        return 1
    try:
        if ns.mix_history:
            out: Any = dump_mix_history(ns.mix_history, ns.name)
        elif ns.slow_log:
            out = dump_slow_log(ns.slow_log, ns.name)
        elif ns.profile:
            out = dump_profile(ns.profile, ns.name, ns.seconds)
        else:
            out = dump_file(ns.input, summary=ns.summary,
                            skip_user_data=ns.no_user_data)
    except (OSError, ValueError, SaveLoadError) as e:
        print(str(e), file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001 — RPC failures print, not raise
        print(f"live dump failed: {e}", file=sys.stderr)
        return 1
    json.dump(out, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
