"""Per-host process supervisor daemon (≙ jubavisor/jubavisor.{hpp,cpp}).

An RPC-controlled process manager: ``jubactl -c start`` asks every
registered jubavisor to spawn N engine servers; ``stop`` kills them.

RPC surface (jubavisor.hpp:36-86, wire names identical):
- ``start(name, N, argv) -> int``   name = "<server>/<cluster>"
  (e.g. "jubaclassifier/mycluster" — the reference passes the executable
  name; plain engine names work too), argv = flag map forwarded to each
  spawned server. 0 on success.
- ``stop(name, N) -> int``          kills all children of that name
  (the reference ignores N and stops all, jubavisor.hpp:47-49).

Children are ``python -m jubatus_tpu.server <engine> ...`` subprocesses
given ports from a pool [port+1, port+max] (jubavisor.cpp port_pool_); a
reaper thread collects exits and recycles ports (≙ SIGCHLD handler);
``stop_all`` runs at exit (atexit_ kill-all). Registers ephemerally under
/jubatus/supervisors so jubactl can find it (membership.cpp).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from jubatus_tpu.cmd import resolve_coordinator
from jubatus_tpu.coord import create_coordinator, membership
from jubatus_tpu.framework.idl import ENGINES
from jubatus_tpu.rpc.server import RpcServer

log = logging.getLogger(__name__)

#: jubactl argv-map keys → our server CLI flags
_FLAG_MAP = {
    "listen_if": "--listen-addr",
    "thread": "--thread",
    "timeout": "--timeout",
    "datadir": "--datadir",
    "logdir": "--logdir",
    "mixer": "--mixer",
    "interval_sec": "--interval-sec",
    "interval_count": "--interval-count",
    "zookeeper_timeout": "--coordinator-timeout",
    "interconnect_timeout": "--interconnect-timeout",
}


def parse_engine(name: str) -> str:
    """"jubaclassifier/c1" | "classifier/c1" → engine name."""
    server = name.split("/", 1)[0]
    engine = server[4:] if server.startswith("juba") else server
    if engine not in ENGINES:
        raise ValueError(f"unknown engine in {name!r}")
    return engine


class _Child:
    __slots__ = ("proc", "port", "name")

    def __init__(self, proc: subprocess.Popen, port: int, name: str) -> None:
        self.proc = proc
        self.port = port
        self.name = name


class Jubavisor:
    def __init__(self, coordinator: str, port: int = 9198, max_children: int = 10,
                 logfile: str = "", host: str = "127.0.0.1") -> None:
        self.coordinator = coordinator
        self.host = host
        self.port = port
        self.logfile = logfile
        self.coord = create_coordinator(coordinator)
        self.rpc = RpcServer()
        self.rpc.register("start", self.start_procs, arity=3)
        self.rpc.register("stop", self.stop_procs, arity=2)
        self._mu = threading.Lock()
        self.max_children = max_children
        self._pool: List[int] = []  # filled in start() once the port is known
        self._children: Dict[str, List[_Child]] = {}
        self._stop_event = threading.Event()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True,
                                        name="visor-reaper")

    # -- RPC: start (jubavisor.cpp start_) -----------------------------------
    def start_procs(self, name: str, n: int, argv: Optional[Dict[str, Any]]) -> int:
        try:
            engine = parse_engine(name)
        except ValueError as e:
            log.error("%s", e)
            return -1
        cluster = name.split("/", 1)[1] if "/" in name else name
        argv = argv or {}
        with self._mu:
            for _ in range(int(n)):
                if not self._pool:
                    log.error("port pool exhausted (max children reached)")
                    return -1
                port = self._pool.pop(0)
                cmd = [sys.executable, "-m", "jubatus_tpu.server", engine,
                       "-z", self.coordinator, "-n", cluster, "-p", str(port)]
                for key, flag in _FLAG_MAP.items():
                    if key in argv and argv[key] not in ("", None):
                        cmd += [flag, str(argv[key])]
                out = (open(self.logfile, "ab") if self.logfile
                       else subprocess.DEVNULL)
                try:
                    proc = subprocess.Popen(cmd, stdout=out, stderr=out)
                except OSError as e:
                    log.error("spawn failed: %s", e)
                    self._pool.insert(0, port)
                    return -1
                finally:
                    if out is not subprocess.DEVNULL:
                        out.close()
                self._children.setdefault(name, []).append(
                    _Child(proc, port, name))
                log.info("started %s on port %d (pid %d)", name, port, proc.pid)
        return 0

    # -- RPC: stop (reference stops ALL processes of the name) ---------------
    def stop_procs(self, name: str, _n: int = 0) -> int:
        with self._mu:
            children = self._children.pop(name, [])
        for c in children:
            self._kill(c)
        log.info("stopped %d process(es) of %s", len(children), name)
        return 0

    def stop_all(self) -> None:
        with self._mu:
            everything = [c for lst in self._children.values() for c in lst]
            self._children.clear()
        for c in everything:
            self._kill(c)

    def _kill(self, child: _Child) -> None:
        try:
            child.proc.terminate()
            try:
                child.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                child.proc.kill()
                child.proc.wait(timeout=5.0)
        except OSError:
            pass
        with self._mu:
            self._pool.append(child.port)

    def _reap_loop(self) -> None:
        """Collect dead children, recycle their ports (≙ SIGCHLD reaping)."""
        while not self._stop_event.wait(1.0):
            with self._mu:
                for name, lst in list(self._children.items()):
                    for c in list(lst):
                        if c.proc.poll() is not None:
                            lst.remove(c)
                            self._pool.append(c.port)
                            log.warning("child %s port %d exited with %s",
                                        name, c.port, c.proc.returncode)
                    if not lst:
                        self._children.pop(name, None)

    def status(self) -> Dict[str, List[int]]:
        with self._mu:
            return {name: [c.port for c in lst]
                    for name, lst in self._children.items()}

    # -- lifecycle -----------------------------------------------------------
    def start(self, port: Optional[int] = None) -> int:
        actual = self.rpc.serve_background(
            port if port is not None else self.port, host="0.0.0.0")
        self.port = actual
        # child ports [port+1, port+max] (jubavisor.cpp port_pool_)
        self._pool = list(range(actual + 1, actual + 1 + self.max_children))
        membership.register_supervisor(self.coord, self.host, actual)
        self._reaper.start()
        log.info("jubavisor listening on %d", actual)
        return actual

    def join(self) -> None:
        self._stop_event.wait()

    def stop(self) -> None:
        self._stop_event.set()
        self.stop_all()
        self.rpc.stop()
        self.coord.close()


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="jubavisor")
    p.add_argument("-p", "--rpc-port", type=int, default=9198)
    p.add_argument("-z", "--coordinator", default="")
    p.add_argument("-m", "--max", type=int, default=10,
                   help="max children (= port pool size)")
    p.add_argument("-l", "--logfile", default="",
                   help="redirect child output here")
    p.add_argument("-b", "--host", default="127.0.0.1",
                   help="address to register in the supervisor registry")
    ns = p.parse_args(argv)
    spec = resolve_coordinator(ns.coordinator)
    if not spec:
        print("no coordinator: pass -z or set JUBATUS_COORDINATOR/ZK",
              file=sys.stderr)
        return 1
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s [jubavisor] %(message)s")
    visor = Jubavisor(spec, ns.rpc_port, ns.max, ns.logfile, host=ns.host)
    signal.signal(signal.SIGTERM, lambda *_: visor.stop())
    signal.signal(signal.SIGINT, lambda *_: visor.stop())
    visor.start()
    visor.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
