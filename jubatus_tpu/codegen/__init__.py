"""IDL compiler (≙ tools/jenerator/, OCaml — rebuilt in Python).

The reference generates server bindings, proxy routing tables, and client
libraries for five languages from msgpack-IDL files with three decorator
groups per RPC — routing / lock / aggregator
(tools/jenerator/src/syntax.ml:41-66, README.rst:34-47). Here:

- ``parser``  — parse the same .idl dialect into an AST,
- ``emit``    — emit the framework's routing table (framework/idl.py
  SERVICES entries) and typed Python client modules.

The checked-in ``framework.idl`` table is cross-validated against the
reference .idl files by tests/test_codegen.py, which replaces the
reference's build-time codegen step with a parity test.
"""

from jubatus_tpu.codegen.parser import (  # noqa: F401
    IdlFile,
    Message,
    MethodDecl,
    Service,
    parse_idl,
    parse_idl_file,
)
from jubatus_tpu.codegen.emit import (  # noqa: F401
    emit_python_client,
    emit_rst,
    emit_service_table,
    to_methods,
)
