import sys

from jubatus_tpu.codegen.emit import main

sys.exit(main())
