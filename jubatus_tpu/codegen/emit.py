"""Emitters (≙ jenerator's cpp.ml/python.ml backends, Python-targeted).

``to_methods``         — AST service → framework.idl Method tuple (the
                         routing table the server/proxy/client consume).
``emit_service_table`` — source text for a SERVICES entry.
``emit_python_client`` — a standalone typed client module for one service,
                         mirroring the reference's generated clients
                         (client/common/client.hpp base + per-RPC methods).
"""

from __future__ import annotations

from typing import Tuple

from jubatus_tpu.codegen.parser import IdlFile, MethodDecl, Service
from jubatus_tpu.framework.idl import Method


def to_methods(service: Service) -> Tuple[Method, ...]:
    out = []
    for d in service.methods:
        out.append(Method(
            name=d.name,
            args=tuple(a.name for a in d.args),
            routing=d.routing,
            cht_n=d.cht_n,
            lock={"update": "update", "analysis": "analysis"}.get(d.lock, "nolock"),
            aggregator=d.aggregator,
        ))
    return tuple(out)


def emit_service_table(service: Service) -> str:
    """SERVICES-entry source for framework/idl.py."""
    lines = [f'    "{service.name}": (']
    for d in service.methods:
        inner = ", ".join(f'"{a.name}"' for a in d.args)
        args = f"({inner},)" if len(d.args) == 1 else f"({inner})"
        parts = [f'"{d.name}"', args, d.routing.upper()
                 if d.routing in ("random", "broadcast", "cht") else '"internal"']
        if d.routing == "cht":
            parts.append(str(d.cht_n))
        parts.append(f'lock="{d.lock}"')
        if d.aggregator != "pass":
            parts.append(f'agg="{d.aggregator}"')
        lines.append(f"        _m({', '.join(parts)}),")
    lines.append("    ),")
    return "\n".join(lines)


def _py_type(idl_type: str) -> str:
    """IDL type → Python annotation (documentation only; wire is msgpack)."""
    prim = {"string": "str", "int": "int", "long": "int", "ulong": "int",
            "uint": "int", "short": "int", "ushort": "int", "byte": "int",
            "double": "float", "float": "float", "bool": "bool",
            "datum": "Datum", "void": "None", "raw": "bytes"}
    t = idl_type.strip()
    if t in prim:
        return prim[t]
    if t.startswith("list<") and t.endswith(">"):
        return f"List[{_py_type(t[5:-1])}]"
    if t.startswith("map<") and t.endswith(">"):
        k, _, v = t[4:-1].partition(",")
        return f"Dict[{_py_type(k)}, {_py_type(v)}]"
    if t.startswith("tuple<") and t.endswith(">"):
        inner = ", ".join(_py_type(x) for x in t[6:-1].split(","))
        return f"Tuple[{inner}]"
    return "Any"  # message types travel as msgpack lists


def emit_python_client(idl: IdlFile, service_name: str) -> str:
    """A generated, static, typed client module (≙ jenerator python.ml)."""
    svc = idl.service(service_name)
    cls = service_name.title().replace("_", "")
    out = [
        f'"""Generated {service_name} client — jubatus_tpu.codegen, from '
        f'{service_name}.idl. Do not edit."""',
        "",
        "from __future__ import annotations",
        "",
        "from typing import Any, Dict, List, Tuple",
        "",
        "from jubatus_tpu.client import ClientBase",
        "from jubatus_tpu.core.datum import Datum  # noqa: F401",
        "",
        "",
        f"class {cls}Client(ClientBase):",
        f'    ENGINE = "{service_name}"',
        "",
    ]
    for d in svc.methods:
        params = "".join(
            f", {a.name}: {_py_type(a.type)}" for a in d.args
        )
        ret = _py_type(d.return_type)
        call_args = "".join(f", {a.name}" for a in d.args)
        out += [
            f"    def {d.name}(self{params}) -> {ret}:",
            f'        """#{d.routing}'
            + (f"({d.cht_n})" if d.routing == "cht" else "")
            + f" #{d.lock} #{d.aggregator}\"\"\"",
            f'        return self.client.call("{d.name}", self.name{call_args})',
            "",
        ]
    return "\n".join(out)


def emit_rst(idl: IdlFile, service_name: str) -> str:
    """RST API documentation for one service (≙ tools/jubadoc rst_generator:
    same inputs — the .idl with its '#-' doc comments — same RST target)."""
    svc = idl.service(service_name)
    title = f"{service_name} API"
    out = [title, "=" * len(title), ""]
    if idl.messages:
        out += ["Data structures", "-" * len("Data structures"), ""]
        for msg in idl.messages:
            out.append(f".. describe:: {msg.name}")
            out.append("")
            for f in msg.fields:
                out.append(f"   :{f.index}: ``{f.type}`` {f.name}")
            out.append("")
    out += ["Methods", "-------", ""]
    for d in svc.methods:
        sig = ", ".join(f"{a.type} {a.name}" for a in d.args)
        out.append(f".. function:: {d.return_type} {d.name}({sig})")
        out.append("")
        routing = d.routing + (f"({d.cht_n})" if d.routing == "cht" else "")
        out.append(f"   :routing: {routing}")
        out.append(f"   :lock: {d.lock}")
        out.append(f"   :aggregator: {d.aggregator}")
        out.append("")
        for line in d.docs:
            out.append(f"   {line}" if line else "")
        if d.docs:
            out.append("")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    """CLI: ``python -m jubatus_tpu.codegen <file.idl> [--client SERVICE]
    [--lang python|cpp|ruby|java|go] [--out DIR] [--table SERVICE]
    [--rst SERVICE]`` — single-file output prints to stdout; multi-file
    languages (cpp/ruby/java/go, ≙ jenerator's 5 client backends) write
    into --out (default '.')."""
    import argparse
    import os
    import sys

    from jubatus_tpu.codegen.parser import parse_idl_file

    p = argparse.ArgumentParser(prog="jubatus_tpu.codegen")
    p.add_argument("idl")
    p.add_argument("--client", default="", metavar="SERVICE")
    p.add_argument("--lang", default="python",
                   choices=("python", "cpp", "ruby", "java", "go"),
                   help="client language (with --client)")
    p.add_argument("--out", default=".", metavar="DIR",
                   help="output dir for multi-file languages")
    p.add_argument("--table", default="", metavar="SERVICE")
    p.add_argument("--rst", default="", metavar="SERVICE",
                   help="emit RST API docs (jubadoc)")
    ns = p.parse_args(argv)
    idl = parse_idl_file(ns.idl)
    if ns.client:
        if ns.lang == "python":
            sys.stdout.write(emit_python_client(idl, ns.client))
        else:
            from jubatus_tpu.codegen.emit_clients import (
                emit_go_client,
                emit_java_client,
                emit_ruby_client,
            )
            from jubatus_tpu.codegen.emit_cpp import emit_cpp_client

            emitter = {"cpp": emit_cpp_client, "ruby": emit_ruby_client,
                       "java": emit_java_client, "go": emit_go_client}[ns.lang]
            files = emitter(idl, ns.client)
            os.makedirs(ns.out, exist_ok=True)
            for fn, src in files.items():
                path = os.path.join(ns.out, fn)
                with open(path, "w", encoding="utf-8") as f:
                    f.write(src)
                print(path, file=sys.stderr)
    elif ns.rst:
        sys.stdout.write(emit_rst(idl, ns.rst))
    elif ns.table:
        sys.stdout.write(emit_service_table(idl.service(ns.table)))
    else:
        for svc in idl.services:
            sys.stdout.write(emit_service_table(svc) + "\n")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
