"""Ruby / Java / Go client emitters (≙ jenerator's ruby.ml/java.ml/go.ml).

codestyle: allow-tabs (the Go template below is tab-indented, as gofmt requires)

The reference generates client libraries for five languages from the same
IDL (tools/jenerator/src/{cpp,python,ruby,java,go}.ml); here C++ and Python
have first-class runtimes (emit_cpp.py, emit.py) and these three emit
idiomatic sources over each ecosystem's standard msgpack stack:

- Ruby: ``msgpack`` gem + TCPSocket, one generated file per service plus a
  shared ``jubatus_common.rb`` runtime (self-contained, like the C++ one).
- Java: POJOs + client over ``org.msgpack`` (the stack the reference's
  generated Java clients use).
- Go: typed structs with ``msgpack:",as_array"`` tags over
  ``github.com/vmihailenco/msgpack`` + a shared ``client.go`` runtime.

Wire behavior is identical across languages: [0, msgid, method,
[name, args...]] requests, message structs packed as field arrays in IDL
index order.
"""

from __future__ import annotations

from typing import Dict, List

from jubatus_tpu.codegen.parser import (
    IdlFile,
    Message,
    Service,
    split_top_commas as _split_top,
)



def _camel(name: str) -> str:
    return "".join(p.title() for p in name.split("_"))


# --------------------------------------------------------------------- Ruby

RUBY_COMMON = '''# jubatus_common.rb — shared client runtime for generated jubatus_tpu
# Ruby clients (≙ the jubatus ruby client gem's common base). Wire protocol:
# msgpack-rpc [0, msgid, method, [name, args...]]; message structs travel as
# field arrays in IDL index order.
require "msgpack"
require "socket"

module JubatusTpu
  module Common
    class RpcError < StandardError; end

    class ClientBase
      def initialize(host, port, name, timeout = 10)
        @host, @port, @name, @timeout = host, port, name, timeout
        @msgid = 0
        @sock = Socket.tcp(host, port, connect_timeout: timeout)
        @sock.setsockopt(Socket::IPPROTO_TCP, Socket::TCP_NODELAY, 1)
        @unpacker = MessagePack::Unpacker.new
      end

      def close
        @sock&.close
        @sock = nil
      end

      attr_accessor :name

      # -- built-ins (client/common/client.hpp:30-87) ---------------------
      def get_config = call("get_config")
      def save(id) = call("save", id)
      def load(id) = call("load", id)
      def get_status = call("get_status")
      def do_mix = call("do_mix")
      def get_proxy_status = call("get_proxy_status")

      def call(method, *args)
        @msgid += 1
        wire = [0, @msgid, method.to_s, [@name, *args.map { |a| wireify(a) }]]
        @sock.write(wire.to_msgpack)
        loop do
          @unpacker.feed_each(read_chunk) do |msg|
            next unless msg.is_a?(Array) && msg.length == 4 &&
                        msg[0] == 1 && msg[1] == @msgid
            raise RpcError, describe_error(msg[2]) unless msg[2].nil?
            return msg[3]
          end
        end
      end

      private

      def read_chunk
        data = @sock.wait_readable(@timeout) ? @sock.readpartial(65_536) : nil
        raise RpcError, "timeout waiting for response" if data.nil?
        data
      end

      def describe_error(err)
        return "method not found" if err == 1
        return "argument error" if err == 2
        err.to_s
      end

      # structs (and nested containers of structs) → wire arrays
      def wireify(x)
        case x
        when Struct then x.to_a.map { |e| wireify(e) }
        when Array then x.map { |e| wireify(e) }
        when Hash then x.transform_values { |v| wireify(v) }
        else x
        end
      end
    end

    Datum = Struct.new(:string_values, :num_values, :binary_values) do
      def self.make(h = {})
        d = new([], [], [])
        h.each { |k, v| v.is_a?(String) ? d.string_values << [k.to_s, v] : d.num_values << [k.to_s, v.to_f] }
        d
      end

      def self.from_wire(a)
        new(a[0] || [], a[1] || [], a[2] || [])
      end
    end
  end
end
'''


def _ruby_cast(idl_type: str, expr: str, messages: set) -> str:
    """Wire value → typed value expression (Ruby)."""
    t = idl_type.strip()
    if t == "datum":
        return f"JubatusTpu::Common::Datum.from_wire({expr})"
    if t in messages:
        return f"{_camel(t)}.from_wire({expr})"
    if t.startswith("list<"):
        inner = t[5:-1].strip()
        sub = _ruby_cast(inner, "e", messages)
        return expr if sub == "e" else f"{expr}.map {{ |e| {sub} }}"
    if t.startswith("map<"):
        k, v = _split_top(t[4:-1])
        sub = _ruby_cast(v, "v", messages)
        return expr if sub == "v" else \
            f"{expr}.transform_values {{ |v| {sub} }}"
    if t.startswith("tuple<"):
        parts = _split_top(t[6:-1])
        casts = [_ruby_cast(p, f"{expr}[{j}]", messages)
                 for j, p in enumerate(parts)]
        return f"[{', '.join(casts)}]"
    return expr  # primitive


def emit_ruby_client(idl: IdlFile, service_name: str) -> Dict[str, str]:
    messages = {m.name for m in idl.messages}
    mod = _camel(service_name)
    out = [
        f"# {service_name}_client.rb — generated from {service_name}.idl by",
        "# jubatus_tpu.codegen (--lang ruby). *** DO NOT EDIT ***",
        'require_relative "jubatus_common"',
        "",
        "module JubatusTpu",
        f"  module {mod}",
    ]
    for msg in idl.messages:
        fields = sorted(msg.fields, key=lambda f: f.index)
        names = ", ".join(f":{f.name}" for f in fields)
        out.append(f"    {_camel(msg.name)} = Struct.new({names}) do")
        casts = [
            _ruby_cast(f.type, f"a[{j}]", messages) for j, f in enumerate(fields)
        ]
        out.append(f"      def self.from_wire(a)")
        out.append(f"        new({', '.join(casts)})")
        out.append("      end")
        out.append("    end")
        out.append("")
    out.append("    class Client < JubatusTpu::Common::ClientBase")
    svc: Service = idl.service(service_name)
    for d in svc.methods:
        args = ", ".join(a.name for a in d.args)
        callargs = "".join(f", {a.name}" for a in d.args)
        routing = d.routing + (f"({d.cht_n})" if d.routing == "cht" else "")
        out.append(f"      # #{routing} #{d.lock} #{d.aggregator} "
                   f"-> {d.return_type}")
        out.append(f"      def {d.name}({args})")
        cast = _ruby_cast(d.return_type, "res", messages)
        if cast == "res":
            out.append(f'        call("{d.name}"{callargs})')
        else:
            out.append(f'        res = call("{d.name}"{callargs})')
            out.append(f"        {cast}")
        out.append("      end")
        out.append("")
    out += ["    end", "  end", "end", ""]
    return {
        f"{service_name}_client.rb": "\n".join(out),
        "jubatus_common.rb": RUBY_COMMON,
    }


# --------------------------------------------------------------------- Java

_JAVA_PRIM = {
    "string": "String", "bool": "boolean", "double": "double",
    "float": "float", "int": "int", "long": "long", "short": "short",
    "byte": "byte", "uint": "long", "ulong": "long", "ushort": "int",
    "raw": "byte[]", "datum": "Datum", "void": "void",
}
_JAVA_BOX = {"boolean": "Boolean", "double": "Double", "float": "Float",
             "int": "Integer", "long": "Long", "short": "Short",
             "byte": "Byte"}


def _java_type(t: str, boxed: bool = False) -> str:
    t = t.strip()
    if t in _JAVA_PRIM:
        j = _JAVA_PRIM[t]
        return _JAVA_BOX.get(j, j) if boxed else j
    if t.startswith("list<"):
        return f"List<{_java_type(t[5:-1], True)}>"
    if t.startswith("map<"):
        k, v = _split_top(t[4:-1])
        return f"Map<{_java_type(k, True)}, {_java_type(v, True)}>"
    if t.startswith("tuple<"):
        a, b = _split_top(t[6:-1])
        return f"Tuple<{_java_type(a, True)}, {_java_type(b, True)}>"
    return _camel(t)


JAVA_CLIENT_BASE = '''// ClientBase.java — shared base for generated jubatus_tpu Java clients
// (≙ the jubatus java client's common base over org.msgpack.rpc). Results
// decode through explicit msgpack Templates — reflection on erased
// List.class/Map.class cannot recover element types, which is why the
// reference's jenerator emits template expressions too.
package us.jubatus_tpu.common;

import java.io.IOException;
import java.util.Map;
import org.msgpack.MessagePack;
import org.msgpack.rpc.Client;
import org.msgpack.rpc.loop.EventLoop;
import org.msgpack.template.Template;
import org.msgpack.template.Templates;
import org.msgpack.type.Value;
import org.msgpack.unpacker.Converter;

public class ClientBase {
  protected final Client c;
  protected String name;
  protected final MessagePack msgpack = new MessagePack();

  private static final Template<Map<String, String>> T_STR_MAP =
      Templates.tMap(Templates.TString, Templates.TString);
  private static final Template<Map<String, Map<String, String>>> T_STATUS =
      Templates.tMap(Templates.TString,
          Templates.tMap(Templates.TString, Templates.TString));

  public ClientBase(String host, int port, String name, double timeoutSec)
      throws Exception {
    EventLoop loop = EventLoop.defaultEventLoop();
    this.c = new Client(host, port, loop);
    this.c.setRequestTimeout((int) timeoutSec);
    this.name = name;
  }

  public void close() { c.close(); }
  public String getName() { return name; }
  public void setName(String name) { this.name = name; }

  protected Value call(String method, Object... args) {
    Object[] full = new Object[args.length + 1];
    full[0] = name;
    System.arraycopy(args, 0, full, 1, args.length);
    return c.callApply(method, full);
  }

  protected <T> T callTyped(Template<T> template, String method,
      Object... args) {
    try {
      return new Converter(msgpack, call(method, args)).read(template);
    } catch (IOException e) {
      throw new RuntimeException(e);
    }
  }

  @SuppressWarnings("unchecked")
  protected <T> Template<T> lookup(Class<T> type) {
    return (Template<T>) msgpack.lookup(type);
  }

  // built-ins (client/common/client.hpp:30-87)
  public String getConfig() {
    return callTyped(Templates.TString, "get_config");
  }
  public Map<String, String> save(String id) {
    return callTyped(T_STR_MAP, "save", id);
  }
  public boolean load(String id) {
    return callTyped(Templates.TBoolean, "load", id);
  }
  public Map<String, Map<String, String>> getStatus() {
    return callTyped(T_STATUS, "get_status");
  }
  public boolean doMix() {
    return callTyped(Templates.TBoolean, "do_mix");
  }
  public Map<String, Map<String, String>> getProxyStatus() {
    return callTyped(T_STATUS, "get_proxy_status");
  }
}
'''

JAVA_TUPLE_TEMPLATE = '''// TupleTemplate.java — msgpack Template for IDL tuple<A, B> (wire 2-array).
package us.jubatus_tpu.common;

import java.io.IOException;
import org.msgpack.packer.Packer;
import org.msgpack.template.AbstractTemplate;
import org.msgpack.template.Template;
import org.msgpack.unpacker.Unpacker;

public class TupleTemplate<A, B> extends AbstractTemplate<Tuple<A, B>> {
  private final Template<A> ta;
  private final Template<B> tb;

  public TupleTemplate(Template<A> ta, Template<B> tb) {
    this.ta = ta;
    this.tb = tb;
  }

  public void write(Packer pk, Tuple<A, B> v, boolean required)
      throws IOException {
    pk.writeArrayBegin(2);
    ta.write(pk, v.first);
    tb.write(pk, v.second);
    pk.writeArrayEnd();
  }

  public Tuple<A, B> read(Unpacker u, Tuple<A, B> to, boolean required)
      throws IOException {
    u.readArrayBegin();
    Tuple<A, B> out = new Tuple<A, B>(ta.read(u, null, true),
                                      tb.read(u, null, true));
    u.readArrayEnd();
    return out;
  }
}
'''

JAVA_DATUM = '''// Datum.java — client/common/datum.hpp mirror (wire 3-tuple).
package us.jubatus_tpu.common;

import java.util.ArrayList;
import java.util.List;
import org.msgpack.annotation.Message;

@Message
public class Datum {
  public List<Tuple<String, String>> stringValues = new ArrayList<Tuple<String, String>>();
  public List<Tuple<String, Double>> numValues = new ArrayList<Tuple<String, Double>>();
  public List<Tuple<String, byte[]>> binaryValues = new ArrayList<Tuple<String, byte[]>>();

  public Datum addString(String key, String value) {
    stringValues.add(new Tuple<String, String>(key, value));
    return this;
  }
  public Datum addNumber(String key, double value) {
    numValues.add(new Tuple<String, Double>(key, value));
    return this;
  }
  public Datum addBinary(String key, byte[] value) {
    binaryValues.add(new Tuple<String, byte[]>(key, value));
    return this;
  }
}
'''

JAVA_TUPLE = '''// Tuple.java — IDL tuple<A, B> (wire 2-array).
package us.jubatus_tpu.common;

import org.msgpack.annotation.Message;

@Message
public class Tuple<A, B> {
  public A first;
  public B second;

  public Tuple() {}
  public Tuple(A first, B second) {
    this.first = first;
    this.second = second;
  }
}
'''


def _java_lower_camel(name: str) -> str:
    c = _camel(name)
    return c[0].lower() + c[1:]


_JAVA_TEMPLATE_PRIM = {
    "string": "Templates.TString", "bool": "Templates.TBoolean",
    "double": "Templates.TDouble", "float": "Templates.TFloat",
    "int": "Templates.TInteger", "long": "Templates.TLong",
    "short": "Templates.TShort", "byte": "Templates.TByte",
    "uint": "Templates.TLong", "ulong": "Templates.TLong",
    "ushort": "Templates.TInteger", "raw": "Templates.TByteArray",
    "datum": "lookup(Datum.class)",
}


def _java_template(t: str) -> str:
    """IDL type → msgpack Template expression (recovers full element types;
    ≙ the template expressions jenerator emits)."""
    t = t.strip()
    if t in _JAVA_TEMPLATE_PRIM:
        return _JAVA_TEMPLATE_PRIM[t]
    if t.startswith("list<"):
        return f"Templates.tList({_java_template(t[5:-1])})"
    if t.startswith("map<"):
        k, v = _split_top(t[4:-1])
        return f"Templates.tMap({_java_template(k)}, {_java_template(v)})"
    if t.startswith("tuple<"):
        a, b = _split_top(t[6:-1])
        return (f"new TupleTemplate<{_java_type(a, True)}, {_java_type(b, True)}>"
                f"({_java_template(a)}, {_java_template(b)})")
    return f"lookup({_camel(t)}.class)"  # @Message POJO


def _emit_java_message(msg: Message, service_name: str) -> str:
    """One public @Message POJO per file (Java allows a single public
    top-level class per file — inline package-private classes would make
    the client API uncallable from user packages)."""
    name = _camel(msg.name)
    out = [
        f"// {name}.java — generated from {service_name}.idl by",
        "// jubatus_tpu.codegen (--lang java). *** DO NOT EDIT ***",
        f"package us.jubatus_tpu.{service_name};",
        "",
        "import java.util.List;",
        "import java.util.Map;",
        "import org.msgpack.annotation.Message;",
        "import us.jubatus_tpu.common.Datum;",
        "import us.jubatus_tpu.common.Tuple;",
        "",
        "@Message",
        f"public class {name} {{",
    ]
    for f in sorted(msg.fields, key=lambda f: f.index):
        out.append(f"  public {_java_type(f.type)} {_java_lower_camel(f.name)};")
    out += ["}", ""]
    return "\n".join(out)


def emit_java_client(idl: IdlFile, service_name: str) -> Dict[str, str]:
    cls = f"{_camel(service_name)}Client"
    out = [
        f"// {cls}.java — generated from {service_name}.idl by",
        "// jubatus_tpu.codegen (--lang java). *** DO NOT EDIT ***",
        "//",
        "// Runs over org.msgpack (the stack the reference's generated Java",
        "// clients use); message classes are @Message POJOs packed as field",
        "// arrays in IDL index order, one public class per file.",
        f"package us.jubatus_tpu.{service_name};",
        "",
        "import java.util.List;",
        "import java.util.Map;",
        "import org.msgpack.template.Templates;",
        "import us.jubatus_tpu.common.ClientBase;",
        "import us.jubatus_tpu.common.Datum;",
        "import us.jubatus_tpu.common.Tuple;",
        "import us.jubatus_tpu.common.TupleTemplate;",
        "",
    ]
    out.append(f"public class {cls} extends ClientBase {{")
    out.append(f"  public {cls}(String host, int port, String name, "
               "double timeoutSec) throws Exception {")
    out.append("    super(host, port, name, timeoutSec);")
    out.append("  }")
    out.append("")
    svc = idl.service(service_name)
    for d in svc.methods:
        ret = _java_type(d.return_type)
        params = ", ".join(
            f"{_java_type(a.type)} {_java_lower_camel(a.name)}" for a in d.args)
        callargs = "".join(f", {_java_lower_camel(a.name)}" for a in d.args)
        routing = d.routing + (f"({d.cht_n})" if d.routing == "cht" else "")
        out.append(f"  // #{routing} #{d.lock} #{d.aggregator}")
        if ret == "void":
            out.append(f"  public void {_java_lower_camel(d.name)}({params}) {{")
            out.append(f'    call("{d.name}"{callargs});')
        else:
            out.append(f"  public {ret} {_java_lower_camel(d.name)}({params}) {{")
            out.append(f"    return callTyped({_java_template(d.return_type)}, "
                       f'"{d.name}"{callargs});')
        out.append("  }")
        out.append("")
    out += ["}", ""]
    files = {
        f"{cls}.java": "\n".join(out),
        "ClientBase.java": JAVA_CLIENT_BASE,
        "Datum.java": JAVA_DATUM,
        "Tuple.java": JAVA_TUPLE,
        "TupleTemplate.java": JAVA_TUPLE_TEMPLATE,
    }
    reserved = set(files)
    for msg in idl.messages:
        fn = f"{_camel(msg.name)}.java"
        if fn in files:  # would silently clobber an earlier file
            what = ("reserved file (client class, ClientBase, Datum, Tuple, "
                    "or TupleTemplate)" if fn in reserved
                    else "another message that camel-cases to the same name")
            raise ValueError(
                f"message name {msg.name!r} collides with {what} at {fn} — "
                "rename the message for the Java backend")
        files[fn] = _emit_java_message(msg, service_name)
    return files


# ----------------------------------------------------------------------- Go

GO_COMMON = '''// client.go — shared runtime for generated jubatus_tpu Go clients.
// Wire protocol: msgpack-rpc [0, msgid, method, [name, args...]]; message
// structs use `msgpack:",as_array"` so they pack as field arrays in IDL
// index order (the reference's MSGPACK_DEFINE layout).
package jubatus_tpu

import (
	"fmt"
	"net"
	"time"

	"github.com/vmihailenco/msgpack/v5"
)

type RPCError struct{ Message string }

func (e *RPCError) Error() string { return e.Message }

type ClientBase struct {
	Name    string
	conn    net.Conn
	dec     *msgpack.Decoder
	timeout time.Duration
	msgid   uint64
}

func NewClientBase(host string, port int, name string, timeout time.Duration) (*ClientBase, error) {
	conn, err := net.DialTimeout("tcp", fmt.Sprintf("%s:%d", host, port), timeout)
	if err != nil {
		return nil, err
	}
	return &ClientBase{Name: name, conn: conn, dec: msgpack.NewDecoder(conn), timeout: timeout}, nil
}

func (c *ClientBase) Close() error { return c.conn.Close() }

type response struct {
	_msgpack struct{}           `msgpack:",as_array"`
	Type     int                `msgpack:"type"`
	Msgid    uint64             `msgpack:"msgid"`
	Error    msgpack.RawMessage `msgpack:"error"`
	Result   msgpack.RawMessage `msgpack:"result"`
}

// Call fires one msgpack-rpc request; args must NOT include the cluster
// name (it is prepended here), out receives the decoded result.
func (c *ClientBase) Call(method string, args []interface{}, out interface{}) error {
	c.msgid++
	params := append([]interface{}{c.Name}, args...)
	req := []interface{}{0, c.msgid, method, params}
	payload, err := msgpack.Marshal(req)
	if err != nil {
		return err
	}
	if err := c.conn.SetDeadline(time.Now().Add(c.timeout)); err != nil {
		return err
	}
	if _, err := c.conn.Write(payload); err != nil {
		return err
	}
	for {
		var resp response
		if err := c.dec.Decode(&resp); err != nil {
			return err
		}
		if resp.Type != 1 || resp.Msgid != c.msgid {
			continue
		}
		var errField interface{}
		_ = msgpack.Unmarshal(resp.Error, &errField)
		if errField != nil {
			return &RPCError{Message: describeError(errField)}
		}
		if out == nil {
			return nil
		}
		return msgpack.Unmarshal(resp.Result, out)
	}
}

func describeError(e interface{}) string {
	switch v := e.(type) {
	case int8, int16, int32, int64, uint8, uint16, uint32, uint64, int:
		if fmt.Sprintf("%v", v) == "1" {
			return "method not found"
		}
		if fmt.Sprintf("%v", v) == "2" {
			return "argument error"
		}
	}
	return fmt.Sprintf("%v", e)
}

// Built-ins (client/common/client.hpp:30-87).
func (c *ClientBase) GetConfig() (string, error) {
	var s string
	err := c.Call("get_config", nil, &s)
	return s, err
}

func (c *ClientBase) Save(id string) (map[string]string, error) {
	var m map[string]string
	err := c.Call("save", []interface{}{id}, &m)
	return m, err
}

func (c *ClientBase) Load(id string) (bool, error) {
	var b bool
	err := c.Call("load", []interface{}{id}, &b)
	return b, err
}

func (c *ClientBase) GetStatus() (map[string]map[string]interface{}, error) {
	var m map[string]map[string]interface{}
	err := c.Call("get_status", nil, &m)
	return m, err
}

func (c *ClientBase) DoMix() (bool, error) {
	var b bool
	err := c.Call("do_mix", nil, &b)
	return b, err
}

func (c *ClientBase) GetProxyStatus() (map[string]map[string]interface{}, error) {
	var m map[string]map[string]interface{}
	err := c.Call("get_proxy_status", nil, &m)
	return m, err
}

// Datum mirrors client/common/datum.hpp: three kv lists, wire 3-tuple.
type Datum struct {
	_msgpack     struct{}        `msgpack:",as_array"`
	StringValues [][2]interface{} `msgpack:"string_values"`
	NumValues    [][2]interface{} `msgpack:"num_values"`
	BinaryValues [][2]interface{} `msgpack:"binary_values"`
}

func NewDatum() *Datum {
	return &Datum{StringValues: [][2]interface{}{}, NumValues: [][2]interface{}{},
		BinaryValues: [][2]interface{}{}}
}

func (d *Datum) AddString(key, value string) *Datum {
	d.StringValues = append(d.StringValues, [2]interface{}{key, value})
	return d
}

func (d *Datum) AddNumber(key string, value float64) *Datum {
	d.NumValues = append(d.NumValues, [2]interface{}{key, value})
	return d
}
'''

_GO_PRIM = {
    "string": "string", "bool": "bool", "double": "float64",
    "float": "float32", "int": "int64", "long": "int64", "short": "int64",
    "byte": "int64", "uint": "uint64", "ulong": "uint64", "ushort": "uint64",
    "raw": "[]byte", "datum": "Datum",
}


def _go_type(t: str) -> str:
    t = t.strip()
    if t in _GO_PRIM:
        return _GO_PRIM[t]
    if t.startswith("list<"):
        return f"[]{_go_type(t[5:-1])}"
    if t.startswith("map<"):
        k, v = _split_top(t[4:-1])
        return f"map[{_go_type(k)}]{_go_type(v)}"
    if t.startswith("tuple<"):
        a, b = _split_top(t[6:-1])
        return f"[]interface{{}} /* tuple<{a}, {b}> */"
    return _camel(t)


def emit_go_client(idl: IdlFile, service_name: str) -> Dict[str, str]:
    cls = f"{_camel(service_name)}Client"
    out = [
        f"// {service_name}_client.go — generated from {service_name}.idl by",
        "// jubatus_tpu.codegen (--lang go). *** DO NOT EDIT ***",
        "package jubatus_tpu",
        "",
        "import (",
        '\t"time"',
        ")",
        "",
    ]
    for msg in idl.messages:
        out.append(f"type {_camel(msg.name)} struct {{")
        out.append("\t_msgpack struct{} `msgpack:\",as_array\"`")
        for f in sorted(msg.fields, key=lambda f: f.index):
            out.append(f"\t{_camel(f.name)} {_go_type(f.type)} "
                       f"`msgpack:\"{f.name}\"`")
        out.append("}")
        out.append("")
    out.append(f"type {cls} struct {{")
    out.append("\tClientBase")
    out.append("}")
    out.append("")
    out.append(f"func New{cls}(host string, port int, name string, "
               f"timeout time.Duration) (*{cls}, error) {{")
    out.append("\tbase, err := NewClientBase(host, port, name, timeout)")
    out.append("\tif err != nil {")
    out.append("\t\treturn nil, err")
    out.append("\t}")
    out.append(f"\treturn &{cls}{{ClientBase: *base}}, nil")
    out.append("}")
    out.append("")
    svc = idl.service(service_name)
    for d in svc.methods:
        params = ", ".join(f"{a.name} {_go_type(a.type)}" for a in d.args)
        callargs = ", ".join(a.name for a in d.args)
        routing = d.routing + (f"({d.cht_n})" if d.routing == "cht" else "")
        out.append(f"// {_camel(d.name)}: #{routing} #{d.lock} #{d.aggregator}")
        if d.return_type.strip() == "void":
            out.append(f"func (c *{cls}) {_camel(d.name)}({params}) error {{")
            out.append(f'\treturn c.Call("{d.name}", '
                       f"[]interface{{}}{{{callargs}}}, nil)")
            out.append("}")
        else:
            ret = _go_type(d.return_type)
            out.append(f"func (c *{cls}) {_camel(d.name)}({params}) "
                       f"({ret}, error) {{")
            out.append(f"\tvar out {ret}")
            out.append(f'\terr := c.Call("{d.name}", '
                       f"[]interface{{}}{{{callargs}}}, &out)")
            out.append("\treturn out, err")
            out.append("}")
        out.append("")
    return {
        f"{service_name}_client.go": "\n".join(out),
        "client.go": GO_COMMON,
    }
