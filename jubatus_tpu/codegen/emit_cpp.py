"""C++ client emitter (≙ jenerator's cpp.ml client backend).

Generates, for one IDL service, a self-contained typed C++ client header
mirroring the reference's generated clients (classifier_client.hpp:19-60:
same class layout ``jubatus_tpu::<engine>::client::<engine>`` over a common
base, same method signatures) — but over the framework's own dependency-free
runtime header (templates/jubatus_tpu_client.hpp) instead of the external
jubatus_msgpack-rpc stack, so a generated client builds with nothing but
``g++`` and talks to any wire-compatible server (this framework's or the
reference's).

``emit_cpp_client(idl, service)`` returns ``{filename: source}`` — the
generated ``<service>_client.hpp`` plus the (constant) runtime header.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List

from jubatus_tpu.codegen.parser import (
    IdlFile,
    Message,
    Service,
    split_top_commas as _split_top,
)

_TEMPLATE_DIR = os.path.join(os.path.dirname(__file__), "templates")
RUNTIME_HEADER_NAME = "jubatus_tpu_client.hpp"

_PRIMITIVES = {
    "string": "std::string",
    "datum": "jubatus_tpu::datum",
    "bool": "bool",
    "double": "double",
    "float": "float",
    "int": "int64_t",
    "long": "int64_t",
    "short": "int64_t",
    "byte": "int64_t",
    "uint": "uint64_t",
    "ulong": "uint64_t",
    "ushort": "uint64_t",
    "raw": "std::string",
}


def runtime_header() -> str:
    with open(os.path.join(_TEMPLATE_DIR, RUNTIME_HEADER_NAME)) as f:
        return f.read()



def cpp_type(idl_type: str, qualify: str = "") -> str:
    """IDL type expression → C++ type. ``qualify`` prefixes message-struct
    names (needed where the emitted code sits outside their namespace, i.e.
    the conv<> specializations at jubatus_tpu scope)."""
    t = idl_type.strip()
    if t in _PRIMITIVES:
        return _PRIMITIVES[t]
    for outer, tmpl in (("list<", "std::vector<{} >"),
                        ("map<", "std::map<{} >"),
                        ("tuple<", "std::pair<{} >")):
        if t.startswith(outer) and t.endswith(">"):
            inner = _split_top(t[len(outer):-1])
            return tmpl.format(", ".join(cpp_type(x, qualify) for x in inner))
    return f"{qualify}::{t}" if qualify else t  # a message struct


def _emit_struct(msg: Message, ns: str) -> str:
    lines = [f"struct {msg.name} {{"]
    for f in sorted(msg.fields, key=lambda f: f.index):
        lines.append(f"  {cpp_type(f.type)} {f.name};")
    lines.append("};")
    return "\n".join(lines)


def _emit_conv(msg: Message, ns: str) -> str:
    """conv<> specialization: a message packs as the array of its fields in
    index order (the reference's MSGPACK_DEFINE layout)."""
    qual = f"{ns}::{msg.name}"
    fields = sorted(msg.fields, key=lambda f: f.index)
    to_lines = [f"    mp::value v = mp::v_arr();"]
    for f in fields:
        to_lines.append(
            f"    v.a.push_back(conv<{cpp_type(f.type, ns)} >::to(x.{f.name}));")
    to_lines.append("    return v;")
    from_lines = [f"    const std::vector<mp::value>& a = v.as_arr();",
                  f"    {qual} x;"]
    for j, f in enumerate(fields):
        from_lines.append(
            f"    x.{f.name} = conv<{cpp_type(f.type, ns)} >::from(a.at({j}));")
    from_lines.append("    return x;")
    return "\n".join(
        [f"template <>",
         f"struct conv<{qual} > {{",
         f"  static mp::value to(const {qual}& x) {{"]
        + to_lines
        + ["  }",
           f"  static {qual} from(const mp::value& v) {{"]
        + from_lines
        + ["  }", "};"])


def _emit_method(d) -> str:
    ret = cpp_type(d.return_type)
    params = ", ".join(
        f"const {cpp_type(a.type)}& {a.name}"
        if cpp_type(a.type) not in ("bool", "double", "float", "int64_t", "uint64_t")
        else f"{cpp_type(a.type)} {a.name}"
        for a in d.args)
    body = ["    std::vector<mp::value> p = args();"]
    for a in d.args:
        body.append(f"    p.push_back(conv<{cpp_type(a.type)} >::to({a.name}));")
    call = f'call("{d.name}", p)'
    if d.return_type.strip() == "void":
        body.append(f"    {call};")
        sig_ret = "void"
    else:
        body.append(f"    return conv<{ret} >::from({call});")
        sig_ret = ret
    routing = d.routing + (f"({d.cht_n})" if d.routing == "cht" else "")
    return "\n".join(
        [f"  // #{routing} #{d.lock} #{d.aggregator}",
         f"  {sig_ret} {d.name}({params}) {{"] + body + ["  }"])


def _topo_messages(messages: List[Message]) -> List[Message]:
    """Dependency order: a message's conv<> must be visible before any
    message (or container) that embeds it references conv<> of it."""
    names = {m.name for m in messages}
    by_name = {m.name: m for m in messages}
    deps = {
        m.name: {w for f in m.fields
                 for w in re.findall(r"\w+", f.type) if w in names}
        for m in messages
    }
    out, done = [], set()

    def visit(n: str, stack: frozenset = frozenset()) -> None:
        if n in done or n in stack:
            return
        for d in sorted(deps[n]):
            visit(d, stack | {n})
        done.add(n)
        out.append(by_name[n])

    for m in messages:
        visit(m.name)
    return out


def emit_cpp_client(idl: IdlFile, service_name: str) -> Dict[str, str]:
    svc: Service = idl.service(service_name)
    ns = service_name
    guard = f"JUBATUS_TPU_CLIENT_{service_name.upper()}_CLIENT_HPP_"

    out = [
        f"// {service_name}_client.hpp — generated from {service_name}.idl by",
        "// jubatus_tpu.codegen (--lang cpp). *** DO NOT EDIT ***",
        "//",
        "// Mirrors the reference's generated client API",
        f"// (jubatus/client/{service_name}_client.hpp) over the self-contained",
        f"// runtime in {RUNTIME_HEADER_NAME} (no external dependencies).",
        f"#ifndef {guard}",
        f"#define {guard}",
        "",
        "#include <map>",
        "#include <string>",
        "#include <utility>",
        "#include <vector>",
        "",
        f'#include "{RUNTIME_HEADER_NAME}"',
        "",
        "namespace jubatus_tpu {",
        f"namespace {ns} {{",
        "",
    ]
    ordered = _topo_messages(idl.messages)
    for msg in ordered:
        out.append(_emit_struct(msg, ns))
        out.append("")
    out.append(f"}}  // namespace {ns}")
    out.append("")
    if ordered:
        out.append("// msgpack layout: array of fields in IDL index order")
        for msg in ordered:
            out.append(_emit_conv(msg, ns))
            out.append("")
    out += [
        f"namespace {ns} {{",
        "namespace client {",
        "",
        f"class {service_name} : public jubatus_tpu::client::common::client {{",
        " public:",
        f"  {service_name}(const std::string& host, uint64_t port,",
        "      const std::string& name, double timeout_sec = 10.0)",
        "      : jubatus_tpu::client::common::client(host, port, name, timeout_sec) {",
        "  }",
        "",
    ]
    # bring emitted struct names used in signatures into scope of conv<> refs:
    # conv specializations are fully qualified, struct refs resolve inside ns.
    for d in svc.methods:
        out.append(_emit_method(d))
        out.append("")
    out += [
        "};",
        "",
        "}  // namespace client",
        f"}}  // namespace {ns}",
        "}  // namespace jubatus_tpu",
        "",
        f"#endif  // {guard}",
        "",
    ]
    return {
        f"{service_name}_client.hpp": "\n".join(out),
        RUNTIME_HEADER_NAME: runtime_header(),
    }
