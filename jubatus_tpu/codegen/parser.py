"""msgpack-IDL parser (≙ tools/jenerator/src/{jdl_lexer.mll,jdl_parser.mly}).

Grammar subset actually used by the 11 engine IDLs:

    message NAME[("c++ alias")] { <idx>: <type> <field> ... }
    service NAME { [#@decorators] <rettype> <method>(<idx>: <type> <arg>, ...) }

Decorator tokens (syntax.ml:41-66): routing ``#@random | #@broadcast |
#@cht[(n)] | #@internal``; lock ``#@update | #@analysis | #@nolock``;
aggregator ``#@pass | #@all_and | #@all_or | #@concat | #@merge`` (plus
``#@add``, accepted because the reducer exists in aggregators.hpp:51 even
though no shipped .idl uses it).
``#@cht`` without an argument means 2 successors (jenerator README.rst:40).
``#-`` lines are docs, other ``#`` lines comments.

Types are kept as strings ("list<labeled_datum>", "map<string, ulong>") —
the wire is msgpack either way; emitters use them for docstrings only.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

ROUTINGS = {"random", "broadcast", "cht", "internal"}
LOCKS = {"update", "analysis", "nolock"}
AGGREGATORS = {"pass", "all_and", "all_or", "concat", "merge", "add"}


class IdlSyntaxError(ValueError):
    pass


@dataclass
class Field:
    index: int
    type: str
    name: str


@dataclass
class Message:
    name: str
    fields: List[Field] = field(default_factory=list)
    alias: str = ""  # C++ mapping annotation, e.g. "std::pair<...>"


@dataclass
class MethodDecl:
    name: str
    return_type: str
    args: List[Field] = field(default_factory=list)
    routing: str = "random"
    cht_n: int = 2
    lock: str = "nolock"
    aggregator: str = "pass"
    #: '#-' doc comment lines preceding the decl (consumed by the RST
    #: emitter, ≙ tools/jubadoc)
    docs: List[str] = field(default_factory=list)


@dataclass
class Service:
    name: str
    methods: List[MethodDecl] = field(default_factory=list)


@dataclass
class IdlFile:
    messages: List[Message] = field(default_factory=list)
    services: List[Service] = field(default_factory=list)

    def service(self, name: str) -> Service:
        for s in self.services:
            if s.name == name:
                return s
        raise KeyError(name)


_MESSAGE_RE = re.compile(r'^message\s+(\w+)\s*(?:\(\s*"([^"]*)"\s*\))?\s*\{')
_SERVICE_RE = re.compile(r"^service\s+(\w+)\s*\{")
_FIELD_RE = re.compile(r"^(\d+)\s*:\s*(.+?)\s+(\w+)$")
_METHOD_RE = re.compile(r"^(.+?)\s+(\w+)\s*\((.*)\)$", re.S)
_DECORATOR_RE = re.compile(r"#@(\w+)(?:\((\d+)\))?")


def split_top_commas(text: str) -> List[str]:
    """Split on commas outside <> nesting — shared by the parser (method
    arg lists) and every typed client emitter (template argument lists)."""
    out, depth, cur = [], 0, []
    for ch in text:
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur and "".join(cur).strip():
        out.append("".join(cur))
    return [a.strip() for a in out if a.strip()]


_split_args = split_top_commas


def _parse_field(text: str, where: str) -> Field:
    m = _FIELD_RE.match(text.strip())
    if not m:
        raise IdlSyntaxError(f"bad field {text!r} in {where}")
    return Field(int(m.group(1)), m.group(2).strip(), m.group(3))


def parse_idl(text: str, name: str = "<idl>") -> IdlFile:
    idl = IdlFile()
    current_message: Optional[Message] = None
    current_service: Optional[Service] = None
    pending: List[Tuple[str, Optional[str]]] = []  # decorator (name, arg)
    pending_docs: List[str] = []  # '#-' doc lines for the next decl
    # join continuation lines: a method/field spans until its parens balance
    buffer = ""

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if line.startswith("#@"):
            pending.extend((d, a or None) for d, a in _DECORATOR_RE.findall(line))
            continue
        if line.startswith("#-"):
            pending_docs.append(line[2:].lstrip(" "))
            continue
        if not line or line.startswith("#"):
            continue  # plain comments
        if line.startswith("%include"):
            continue  # C++ header pragma for the jenerator cpp backend
        # strip trailing comments (burst.idl has '...) # //@broadcast')
        if "#" in line:
            line = line[: line.index("#")].strip()
            if not line:
                continue
        if buffer:
            line = f"{buffer} {line}"
            buffer = ""

        if current_message is None and current_service is None:
            m = _MESSAGE_RE.match(line)
            if m:
                current_message = Message(m.group(1), alias=m.group(2) or "")
                pending_docs = []  # block-level docs don't belong to a field
                continue
            m = _SERVICE_RE.match(line)
            if m:
                current_service = Service(m.group(1))
                pending_docs = []  # service docs don't belong to method #1
                continue
            raise IdlSyntaxError(f"{name}:{lineno}: unexpected {line!r}")

        if line == "}":
            if current_message is not None:
                idl.messages.append(current_message)
                current_message = None
            else:
                idl.services.append(current_service)
                current_service = None
            pending = []
            pending_docs = []
            continue

        if current_message is not None:
            current_message.fields.append(_parse_field(line, current_message.name))
            continue

        # inside a service: a method decl (may span lines)
        if line.count("(") > line.count(")") or "(" not in line:
            buffer = line
            continue
        m = _METHOD_RE.match(line)
        if not m:
            raise IdlSyntaxError(f"{name}:{lineno}: bad method {line!r}")
        decl = MethodDecl(name=m.group(2), return_type=m.group(1).strip(),
                          docs=pending_docs)
        pending_docs = []
        decl.args = [_parse_field(a, decl.name) for a in _split_args(m.group(3))]
        for dec, arg in pending:
            if dec in ROUTINGS:
                decl.routing = dec
                if dec == "cht":
                    decl.cht_n = int(arg) if arg else 2
            elif dec in LOCKS:
                decl.lock = dec
            elif dec in AGGREGATORS:
                decl.aggregator = dec
            else:
                raise IdlSyntaxError(
                    f"{name}:{lineno}: unknown decorator #@{dec}")
        pending = []
        current_service.methods.append(decl)

    if buffer or current_message is not None or current_service is not None:
        raise IdlSyntaxError(f"{name}: unexpected end of file")
    return idl


def parse_idl_file(path: str) -> IdlFile:
    with open(path) as f:
        return parse_idl(f.read(), name=path)


def parse_reference_idls(root: str) -> Dict[str, IdlFile]:
    """Parse every .idl under a directory (e.g. the reference's server dir)."""
    import glob
    import os

    out = {}
    for path in sorted(glob.glob(os.path.join(root, "*.idl"))):
        engine = os.path.splitext(os.path.basename(path))[0]
        out[engine] = parse_idl_file(path)
    return out
