// jubatus_tpu_client.hpp — self-contained C++ client runtime for the
// jubatus_tpu MessagePack-RPC plane.
//
// Equivalent of the reference's client stack (jubatus/client/common/client.hpp
// over jubatus_msgpack-rpc), redesigned as one dependency-free header: a
// minimal MessagePack codec, a blocking TCP RPC client, the datum type
// (client/common/datum.hpp), and the common client base with the built-ins
// get_config/save/load/get_status/do_mix/get_proxy_status
// (client/common/client.hpp:30-87). Generated <engine>_client.hpp headers
// (jubatus_tpu.codegen, --lang cpp) include this file.
//
// Requires C++11 and POSIX sockets. Wire protocol: msgpack-rpc
// [type, msgid, method, params] requests / [1, msgid, error, result]
// responses, identical to the reference servers and to jubatus_tpu's
// rpc/server.py, so this client talks to either. The parser accepts both
// old (pre-2.0 raw) and new (str/bin) msgpack encodings; the packer emits
// the new format by default — call rpc_client::set_legacy_format(true)
// when talking to a reference jubatus server (its vendored msgpack fork
// predates str8/bin and rejects those type bytes).
#ifndef JUBATUS_TPU_CLIENT_HPP_
#define JUBATUS_TPU_CLIENT_HPP_

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace jubatus_tpu {

// ---------------------------------------------------------------- msgpack --
namespace mp {

struct value {
  enum kind_t { NIL, BOOLEAN, INT, UINT, FLOAT, STR, BIN, ARR, MAP };
  kind_t k;
  bool b;
  int64_t i;
  uint64_t u;
  double f;
  std::string s;                              // STR and BIN payloads
  std::vector<value> a;                       // ARR elements
  std::vector<std::pair<value, value> > m;    // MAP entries, wire order

  value() : k(NIL), b(false), i(0), u(0), f(0) {}

  bool is_nil() const { return k == NIL; }

  int64_t as_int() const {
    switch (k) {
      case INT: return i;
      case UINT: return static_cast<int64_t>(u);
      case FLOAT: return static_cast<int64_t>(f);
      case BOOLEAN: return b ? 1 : 0;
      default: throw std::runtime_error("msgpack: value is not an integer");
    }
  }
  uint64_t as_uint() const {
    switch (k) {
      case UINT: return u;
      case INT:
        if (i < 0) throw std::runtime_error("msgpack: negative as_uint");
        return static_cast<uint64_t>(i);
      case FLOAT: return static_cast<uint64_t>(f);
      case BOOLEAN: return b ? 1u : 0u;
      default: throw std::runtime_error("msgpack: value is not an integer");
    }
  }
  double as_double() const {
    switch (k) {
      case FLOAT: return f;
      case INT: return static_cast<double>(i);
      case UINT: return static_cast<double>(u);
      default: throw std::runtime_error("msgpack: value is not a number");
    }
  }
  bool as_bool() const {
    if (k == BOOLEAN) return b;
    return as_int() != 0;
  }
  // Lenient: status maps carry numbers the reference stringifies; do the same.
  std::string as_str() const {
    switch (k) {
      case STR: case BIN: return s;
      case INT: { std::ostringstream o; o << i; return o.str(); }
      case UINT: { std::ostringstream o; o << u; return o.str(); }
      case FLOAT: { std::ostringstream o; o << f; return o.str(); }
      case BOOLEAN: return b ? "true" : "false";
      case NIL: return "";
      default: throw std::runtime_error("msgpack: value is not a string");
    }
  }
  const std::vector<value>& as_arr() const {
    if (k != ARR) throw std::runtime_error("msgpack: value is not an array");
    return a;
  }
};

inline value v_nil() { return value(); }
inline value v_bool(bool x) { value v; v.k = value::BOOLEAN; v.b = x; return v; }
inline value v_int(int64_t x) { value v; v.k = value::INT; v.i = x; return v; }
inline value v_uint(uint64_t x) { value v; v.k = value::UINT; v.u = x; return v; }
inline value v_double(double x) { value v; v.k = value::FLOAT; v.f = x; return v; }
inline value v_str(const std::string& x) { value v; v.k = value::STR; v.s = x; return v; }
inline value v_bin(const std::string& x) { value v; v.k = value::BIN; v.s = x; return v; }
inline value v_arr() { value v; v.k = value::ARR; return v; }
inline value v_map() { value v; v.k = value::MAP; return v; }

// -- packing ---------------------------------------------------------------
inline void put_be(std::string& out, uint64_t x, int nbytes) {
  for (int s = (nbytes - 1) * 8; s >= 0; s -= 8)
    out.push_back(static_cast<char>((x >> s) & 0xff));
}

inline void pack_uint(std::string& out, uint64_t x) {
  if (x < 0x80) { out.push_back(static_cast<char>(x)); }
  else if (x <= 0xff) { out.push_back('\xcc'); put_be(out, x, 1); }
  else if (x <= 0xffff) { out.push_back('\xcd'); put_be(out, x, 2); }
  else if (x <= 0xffffffffULL) { out.push_back('\xce'); put_be(out, x, 4); }
  else { out.push_back('\xcf'); put_be(out, x, 8); }
}

inline void pack_int(std::string& out, int64_t x) {
  if (x >= 0) { pack_uint(out, static_cast<uint64_t>(x)); return; }
  if (x >= -32) { out.push_back(static_cast<char>(x)); }
  else if (x >= -128) { out.push_back('\xd0'); put_be(out, static_cast<uint8_t>(x), 1); }
  else if (x >= -32768) { out.push_back('\xd1'); put_be(out, static_cast<uint16_t>(x), 2); }
  else if (x >= -2147483648LL) { out.push_back('\xd2'); put_be(out, static_cast<uint32_t>(x), 4); }
  else { out.push_back('\xd3'); put_be(out, static_cast<uint64_t>(x), 8); }
}

// legacy=true emits pre-2.0 msgpack (fixraw/raw16/raw32 only; no str8, no
// bin family) for the reference's vendored msgpack fork.
inline void pack(std::string& out, const value& v, bool legacy = false) {
  switch (v.k) {
    case value::NIL: out.push_back('\xc0'); break;
    case value::BOOLEAN: out.push_back(v.b ? '\xc3' : '\xc2'); break;
    case value::INT: pack_int(out, v.i); break;
    case value::UINT: pack_uint(out, v.u); break;
    case value::FLOAT: {
      out.push_back('\xcb');
      uint64_t bits;
      std::memcpy(&bits, &v.f, 8);
      put_be(out, bits, 8);
      break;
    }
    case value::STR:
    case value::BIN: {
      size_t n = v.s.size();
      bool as_raw = legacy || v.k == value::STR;
      if (!as_raw) {  // new-format bin 8/16/32
        if (n <= 0xff) { out.push_back('\xc4'); put_be(out, n, 1); }
        else if (n <= 0xffff) { out.push_back('\xc5'); put_be(out, n, 2); }
        else { out.push_back('\xc6'); put_be(out, n, 4); }
      } else if (n < 32) {
        out.push_back(static_cast<char>(0xa0 | n));
      } else if (!legacy && n <= 0xff) {
        out.push_back('\xd9');  // str8: new format only
        put_be(out, n, 1);
      } else if (n <= 0xffff) {
        out.push_back('\xda');
        put_be(out, n, 2);
      } else {
        out.push_back('\xdb');
        put_be(out, n, 4);
      }
      out.append(v.s);
      break;
    }
    case value::ARR: {
      size_t n = v.a.size();
      if (n < 16) out.push_back(static_cast<char>(0x90 | n));
      else if (n <= 0xffff) { out.push_back('\xdc'); put_be(out, n, 2); }
      else { out.push_back('\xdd'); put_be(out, n, 4); }
      for (size_t j = 0; j < n; ++j) pack(out, v.a[j], legacy);
      break;
    }
    case value::MAP: {
      size_t n = v.m.size();
      if (n < 16) out.push_back(static_cast<char>(0x80 | n));
      else if (n <= 0xffff) { out.push_back('\xde'); put_be(out, n, 2); }
      else { out.push_back('\xdf'); put_be(out, n, 4); }
      for (size_t j = 0; j < n; ++j) {
        pack(out, v.m[j].first, legacy);
        pack(out, v.m[j].second, legacy);
      }
      break;
    }
  }
}

// -- parsing (incremental: returns false when the buffer is incomplete) ----
inline bool need(const std::string& buf, size_t pos, size_t n) {
  return buf.size() - pos >= n;
}

inline uint64_t get_be(const std::string& buf, size_t pos, int nbytes) {
  uint64_t x = 0;
  for (int j = 0; j < nbytes; ++j)
    x = (x << 8) | static_cast<uint8_t>(buf[pos + j]);
  return x;
}

inline bool parse(const std::string& buf, size_t& pos, value& out);

inline bool parse_seq(const std::string& buf, size_t& pos, value& out, size_t n,
                      bool is_map) {
  if (is_map) {
    out.k = value::MAP;
    out.m.reserve(n);
    for (size_t j = 0; j < n; ++j) {
      value k, v;
      if (!parse(buf, pos, k) || !parse(buf, pos, v)) return false;
      out.m.push_back(std::make_pair(k, v));
    }
  } else {
    out.k = value::ARR;
    out.a.reserve(n);
    for (size_t j = 0; j < n; ++j) {
      value v;
      if (!parse(buf, pos, v)) return false;
      out.a.push_back(v);
    }
  }
  return true;
}

inline bool parse(const std::string& buf, size_t& pos, value& out) {
  if (!need(buf, pos, 1)) return false;
  uint8_t c = static_cast<uint8_t>(buf[pos++]);
  if (c < 0x80) { out = v_uint(c); return true; }
  if (c >= 0xe0) { out = v_int(static_cast<int8_t>(c)); return true; }
  if (c >= 0xa0 && c < 0xc0) {  // fixstr
    size_t n = c & 0x1f;
    if (!need(buf, pos, n)) return false;
    out = v_str(buf.substr(pos, n));
    pos += n;
    return true;
  }
  if (c >= 0x90 && c < 0xa0) return parse_seq(buf, pos, out, c & 0x0f, false);
  if (c >= 0x80 && c < 0x90) return parse_seq(buf, pos, out, c & 0x0f, true);
  size_t n;
  switch (c) {
    case 0xc0: out = v_nil(); return true;
    case 0xc2: out = v_bool(false); return true;
    case 0xc3: out = v_bool(true); return true;
    case 0xcc: case 0xcd: case 0xce: case 0xcf: {
      int w = 1 << (c - 0xcc);
      if (!need(buf, pos, w)) return false;
      out = v_uint(get_be(buf, pos, w));
      pos += w;
      return true;
    }
    case 0xd0: case 0xd1: case 0xd2: case 0xd3: {
      int w = 1 << (c - 0xd0);
      if (!need(buf, pos, w)) return false;
      uint64_t raw = get_be(buf, pos, w);
      pos += w;
      int64_t x;
      switch (w) {
        case 1: x = static_cast<int8_t>(raw); break;
        case 2: x = static_cast<int16_t>(raw); break;
        case 4: x = static_cast<int32_t>(raw); break;
        default: x = static_cast<int64_t>(raw); break;
      }
      out = v_int(x);
      return true;
    }
    case 0xca: {
      if (!need(buf, pos, 4)) return false;
      uint32_t bits = static_cast<uint32_t>(get_be(buf, pos, 4));
      pos += 4;
      float x;
      std::memcpy(&x, &bits, 4);
      out = v_double(x);
      return true;
    }
    case 0xcb: {
      if (!need(buf, pos, 8)) return false;
      uint64_t bits = get_be(buf, pos, 8);
      pos += 8;
      double x;
      std::memcpy(&x, &bits, 8);
      out = v_double(x);
      return true;
    }
    case 0xd9: case 0xda: case 0xdb:        // str 8/16/32
    case 0xc4: case 0xc5: case 0xc6: {      // bin 8/16/32
      int w = (c >= 0xd9) ? (1 << (c - 0xd9)) : (1 << (c - 0xc4));
      if (!need(buf, pos, w)) return false;
      n = get_be(buf, pos, w);
      pos += w;
      if (!need(buf, pos, n)) return false;
      out = (c >= 0xd9) ? v_str(buf.substr(pos, n)) : v_bin(buf.substr(pos, n));
      pos += n;
      return true;
    }
    case 0xdc: case 0xdd: {                 // array 16/32
      int w = (c == 0xdc) ? 2 : 4;
      if (!need(buf, pos, w)) return false;
      n = get_be(buf, pos, w);
      pos += w;
      return parse_seq(buf, pos, out, n, false);
    }
    case 0xde: case 0xdf: {                 // map 16/32
      int w = (c == 0xde) ? 2 : 4;
      if (!need(buf, pos, w)) return false;
      n = get_be(buf, pos, w);
      pos += w;
      return parse_seq(buf, pos, out, n, true);
    }
    default:
      throw std::runtime_error("msgpack: unsupported type byte");
  }
}

// skip: completeness scan without building a value tree — linear, no
// allocations. Used by the client to cheaply test "is one full message
// buffered yet?" before paying for a real parse.
inline bool skip(const std::string& buf, size_t& pos) {
  if (!need(buf, pos, 1)) return false;
  uint8_t c = static_cast<uint8_t>(buf[pos++]);
  if (c < 0x80 || c >= 0xe0) return true;            // fixint
  if (c >= 0xa0 && c < 0xc0) {                       // fixstr
    size_t n = c & 0x1f;
    if (!need(buf, pos, n)) return false;
    pos += n;
    return true;
  }
  size_t count = 0, width = 0, payload = 0;
  bool is_map = false;
  if (c >= 0x90 && c < 0xa0) { count = c & 0x0f; }
  else if (c >= 0x80 && c < 0x90) { count = c & 0x0f; is_map = true; }
  else {
    switch (c) {
      case 0xc0: case 0xc2: case 0xc3: return true;
      case 0xcc: case 0xcd: case 0xce: case 0xcf: width = 1 << (c - 0xcc); break;
      case 0xd0: case 0xd1: case 0xd2: case 0xd3: width = 1 << (c - 0xd0); break;
      case 0xca: width = 4; break;
      case 0xcb: width = 8; break;
      case 0xd9: case 0xda: case 0xdb:
      case 0xc4: case 0xc5: case 0xc6: {
        int w = (c >= 0xd9) ? (1 << (c - 0xd9)) : (1 << (c - 0xc4));
        if (!need(buf, pos, w)) return false;
        payload = get_be(buf, pos, w);
        pos += w;
        if (!need(buf, pos, payload)) return false;
        pos += payload;
        return true;
      }
      case 0xdc: case 0xdd: case 0xde: case 0xdf: {
        int w = (c == 0xdc || c == 0xde) ? 2 : 4;
        if (!need(buf, pos, w)) return false;
        count = get_be(buf, pos, w);
        pos += w;
        is_map = (c >= 0xde);
        break;
      }
      default:
        throw std::runtime_error("msgpack: unsupported type byte");
    }
    if (width) {
      if (!need(buf, pos, width)) return false;
      pos += width;
      return true;
    }
  }
  size_t items = is_map ? count * 2 : count;
  for (size_t j = 0; j < items; ++j)
    if (!skip(buf, pos)) return false;
  return true;
}

}  // namespace mp

// -------------------------------------------------------------- rpc client --
class rpc_error : public std::runtime_error {
 public:
  explicit rpc_error(const std::string& what) : std::runtime_error(what) {}
};

class rpc_client {
 public:
  rpc_client(const std::string& host, int port, double timeout_sec = 10.0)
      : fd_(-1), msgid_(0), legacy_(false) {
    connect_(host, port, timeout_sec);
  }

  // pre-2.0 msgpack encodings for reference jubatus servers (their
  // vendored msgpack fork rejects str8/bin type bytes)
  void set_legacy_format(bool on) { legacy_ = on; }
  ~rpc_client() { close(); }

  void close() {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }

  mp::value call(const std::string& method, const std::vector<mp::value>& params) {
    uint64_t id = ++msgid_;
    mp::value req = mp::v_arr();
    req.a.push_back(mp::v_uint(0));
    req.a.push_back(mp::v_uint(id));
    req.a.push_back(mp::v_str(method));
    mp::value pv = mp::v_arr();
    pv.a = params;
    req.a.push_back(pv);
    std::string out;
    mp::pack(out, req, legacy_);
    send_all_(out);
    for (;;) {
      mp::value msg = read_message_();
      if (msg.k != mp::value::ARR || msg.a.size() != 4) continue;
      if (msg.a[0].as_uint() != 1 || msg.a[1].as_uint() != id) continue;
      if (!msg.a[2].is_nil()) throw rpc_error(method + ": " + describe_(msg.a[2]));
      return msg.a[3];
    }
  }

 private:
  void connect_(const std::string& host, int port, double timeout_sec) {
    struct addrinfo hints, *res = NULL;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    std::ostringstream p;
    p << port;
    if (getaddrinfo(host.c_str(), p.str().c_str(), &hints, &res) != 0 || !res)
      throw rpc_error("cannot resolve " + host);
    fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd_ < 0) {
      freeaddrinfo(res);
      throw rpc_error("cannot create socket");
    }
    struct timeval tv;
    tv.tv_sec = static_cast<long>(timeout_sec);
    tv.tv_usec = static_cast<long>((timeout_sec - tv.tv_sec) * 1e6);
    setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int rc = ::connect(fd_, res->ai_addr, res->ai_addrlen);
    freeaddrinfo(res);
    if (rc != 0) {
      close();
      throw rpc_error("cannot connect to " + host + ":" + p.str());
    }
  }

  void send_all_(const std::string& data) {
    size_t sent = 0;
    while (sent < data.size()) {
      ssize_t n = ::send(fd_, data.data() + sent, data.size() - sent, 0);
      if (n <= 0) throw rpc_error("send failed (connection lost or timeout)");
      sent += static_cast<size_t>(n);
    }
  }

  mp::value read_message_() {
    for (;;) {
      // cheap no-alloc completeness scan first; build the tree only once
      size_t end = 0;
      if (!rbuf_.empty() && mp::skip(rbuf_, end)) {
        size_t pos = 0;
        mp::value out;
        mp::parse(rbuf_, pos, out);
        rbuf_.erase(0, pos);
        return out;
      }
      char chunk[65536];
      ssize_t n = ::recv(fd_, chunk, sizeof(chunk), 0);
      if (n <= 0) throw rpc_error("recv failed (connection lost or timeout)");
      rbuf_.append(chunk, static_cast<size_t>(n));
    }
  }

  static std::string describe_(const mp::value& err) {
    // msgpack-rpc integer codes (rpc/errors.py, mprpc convention)
    if (err.k == mp::value::INT || err.k == mp::value::UINT) {
      int64_t code = err.as_int();
      if (code == 1) return "method not found";
      if (code == 2) return "argument error";
      std::ostringstream o;
      o << "remote error code " << code;
      return o.str();
    }
    if (err.k == mp::value::STR) return err.s;
    std::string out;
    mp::pack(out, err);
    return "remote error (" + out + ")";
  }

  int fd_;
  uint64_t msgid_;
  bool legacy_;
  std::string rbuf_;
};

// -------------------------------------------------- typed conversion layer --
// conv<T>: T <-> mp::value. Generated headers add specializations for their
// IDL message structs; containers compose through the partial specializations.
template <class T>
struct conv;

template <>
struct conv<int64_t> {
  static mp::value to(int64_t x) { return mp::v_int(x); }
  static int64_t from(const mp::value& v) { return v.as_int(); }
};

template <>
struct conv<int32_t> {
  static mp::value to(int32_t x) { return mp::v_int(x); }
  static int32_t from(const mp::value& v) { return static_cast<int32_t>(v.as_int()); }
};

template <>
struct conv<uint64_t> {
  static mp::value to(uint64_t x) { return mp::v_uint(x); }
  static uint64_t from(const mp::value& v) { return v.as_uint(); }
};

template <>
struct conv<uint32_t> {
  static mp::value to(uint32_t x) { return mp::v_uint(x); }
  static uint32_t from(const mp::value& v) { return static_cast<uint32_t>(v.as_uint()); }
};

template <>
struct conv<double> {
  static mp::value to(double x) { return mp::v_double(x); }
  static double from(const mp::value& v) { return v.as_double(); }
};

template <>
struct conv<float> {
  static mp::value to(float x) { return mp::v_double(x); }
  static float from(const mp::value& v) { return static_cast<float>(v.as_double()); }
};

template <>
struct conv<bool> {
  static mp::value to(bool x) { return mp::v_bool(x); }
  static bool from(const mp::value& v) { return v.as_bool(); }
};

template <>
struct conv<std::string> {
  static mp::value to(const std::string& x) { return mp::v_str(x); }
  static std::string from(const mp::value& v) { return v.as_str(); }
};

template <class T>
struct conv<std::vector<T> > {
  static mp::value to(const std::vector<T>& xs) {
    mp::value v = mp::v_arr();
    v.a.reserve(xs.size());
    for (size_t j = 0; j < xs.size(); ++j) v.a.push_back(conv<T>::to(xs[j]));
    return v;
  }
  static std::vector<T> from(const mp::value& v) {
    const std::vector<mp::value>& a = v.as_arr();
    std::vector<T> out;
    out.reserve(a.size());
    for (size_t j = 0; j < a.size(); ++j) out.push_back(conv<T>::from(a[j]));
    return out;
  }
};

template <class K, class V>
struct conv<std::map<K, V> > {
  static mp::value to(const std::map<K, V>& xs) {
    mp::value v = mp::v_map();
    for (typename std::map<K, V>::const_iterator it = xs.begin(); it != xs.end(); ++it)
      v.m.push_back(std::make_pair(conv<K>::to(it->first), conv<V>::to(it->second)));
    return v;
  }
  static std::map<K, V> from(const mp::value& v) {
    if (v.k != mp::value::MAP) throw std::runtime_error("msgpack: value is not a map");
    std::map<K, V> out;
    for (size_t j = 0; j < v.m.size(); ++j)
      out[conv<K>::from(v.m[j].first)] = conv<V>::from(v.m[j].second);
    return out;
  }
};

template <class A, class B>
struct conv<std::pair<A, B> > {
  static mp::value to(const std::pair<A, B>& x) {
    mp::value v = mp::v_arr();
    v.a.push_back(conv<A>::to(x.first));
    v.a.push_back(conv<B>::to(x.second));
    return v;
  }
  static std::pair<A, B> from(const mp::value& v) {
    const std::vector<mp::value>& a = v.as_arr();
    return std::make_pair(conv<A>::from(a.at(0)), conv<B>::from(a.at(1)));
  }
};

// --------------------------------------------------------------- datum ----
// ≙ jubatus/client/common/datum.hpp: three kv lists, wire 3-tuple.
struct datum {
  std::vector<std::pair<std::string, std::string> > string_values;
  std::vector<std::pair<std::string, double> > num_values;
  std::vector<std::pair<std::string, std::string> > binary_values;

  datum& add_string(const std::string& key, const std::string& v) {
    string_values.push_back(std::make_pair(key, v));
    return *this;
  }
  datum& add_number(const std::string& key, double v) {
    num_values.push_back(std::make_pair(key, v));
    return *this;
  }
  datum& add_binary(const std::string& key, const std::string& v) {
    binary_values.push_back(std::make_pair(key, v));
    return *this;
  }
};

template <>
struct conv<datum> {
  static mp::value to(const datum& d) {
    mp::value v = mp::v_arr();
    v.a.push_back(conv<std::vector<std::pair<std::string, std::string> > >::to(d.string_values));
    v.a.push_back(conv<std::vector<std::pair<std::string, double> > >::to(d.num_values));
    mp::value bins = mp::v_arr();
    for (size_t j = 0; j < d.binary_values.size(); ++j) {
      mp::value kv = mp::v_arr();
      kv.a.push_back(mp::v_str(d.binary_values[j].first));
      kv.a.push_back(mp::v_bin(d.binary_values[j].second));
      bins.a.push_back(kv);
    }
    v.a.push_back(bins);
    return v;
  }
  static datum from(const mp::value& v) {
    const std::vector<mp::value>& a = v.as_arr();
    datum d;
    if (a.size() > 0)
      d.string_values = conv<std::vector<std::pair<std::string, std::string> > >::from(a[0]);
    if (a.size() > 1)
      d.num_values = conv<std::vector<std::pair<std::string, double> > >::from(a[1]);
    if (a.size() > 2)
      d.binary_values = conv<std::vector<std::pair<std::string, std::string> > >::from(a[2]);
    return d;
  }
};

// ---------------------------------------------------------- client base ----
// ≙ jubatus::client::common::client (client/common/client.hpp:30-87).
namespace client {
namespace common {

class client {
 public:
  client(const std::string& host, uint64_t port, const std::string& name,
         double timeout_sec)
      : c_(host, static_cast<int>(port), timeout_sec), name_(name) {}

  rpc_client& get_client() { return c_; }

  std::string get_config() {
    return conv<std::string>::from(call("get_config", args()));
  }
  std::map<std::string, std::string> save(const std::string& id) {
    std::vector<mp::value> p = args();
    p.push_back(mp::v_str(id));
    return conv<std::map<std::string, std::string> >::from(call("save", p));
  }
  bool load(const std::string& id) {
    std::vector<mp::value> p = args();
    p.push_back(mp::v_str(id));
    return conv<bool>::from(call("load", p));
  }
  std::map<std::string, std::map<std::string, std::string> > get_status() {
    return conv<std::map<std::string, std::map<std::string, std::string> > >::from(
        call("get_status", args()));
  }
  bool do_mix() { return conv<bool>::from(call("do_mix", args())); }
  std::map<std::string, std::map<std::string, std::string> > get_proxy_status() {
    return conv<std::map<std::string, std::map<std::string, std::string> > >::from(
        call("get_proxy_status", args()));
  }

  std::string get_name() const { return name_; }
  void set_name(const std::string& name) { name_ = name; }

 protected:
  std::vector<mp::value> args() {
    std::vector<mp::value> p;
    p.push_back(mp::v_str(name_));
    return p;
  }
  mp::value call(const std::string& method, const std::vector<mp::value>& params) {
    return c_.call(method, params);
  }

  rpc_client c_;
  std::string name_;
};

}  // namespace common
}  // namespace client

}  // namespace jubatus_tpu

#endif  // JUBATUS_TPU_CLIENT_HPP_
