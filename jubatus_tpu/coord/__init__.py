"""Coordination / cluster membership (≙ jubatus/server/common/, SURVEY.md §2.1).

The reference coordinates replicas through ZooKeeper behind a `lock_service`
ABC (common/lock_service.hpp:33-118). We keep the same seam — a small
`Coordinator` interface for ephemeral membership, config storage, locks, and
id minting — with two built-in backends:

- `MemoryCoordinator` — in-process, for tests and single-process clusters
  (the mock the reference admits it never wrote, common/zk.hpp:36);
- `FileCoordinator` — a shared directory for multi-process single-host (and
  NFS-backed multi-host) clusters: ephemeral nodes are lease files refreshed
  by a heartbeat thread, locks are O_EXCL lease files, ids are a counter file
  under flock.

On a TPU pod the *data plane* needs no coordinator at all — the mesh is
static and mix is a collective (parallel/mix.py). The coordinator carries the
*control plane*: membership for proxies and the RPC mixer, config
distribution (jubaconfig), and actor registration (jubactl/jubavisor).
A ZooKeeper backend can be slotted in behind the same interface unchanged.
"""

from jubatus_tpu.coord.base import (  # noqa: F401
    Coordinator,
    CoordinatorError,
    NodeInfo,
)
from jubatus_tpu.coord.memory import MemoryCoordinator  # noqa: F401
from jubatus_tpu.coord.file import FileCoordinator  # noqa: F401
from jubatus_tpu.coord.membership import (  # noqa: F401
    ACTOR_BASE,
    register_actor,
    register_active,
    unregister_active,
    get_all_nodes,
    get_all_actives,
)
from jubatus_tpu.coord.cht import CHT, make_hash  # noqa: F401
from jubatus_tpu.coord.idgen import IdGenerator  # noqa: F401


def create_coordinator(spec: str) -> Coordinator:
    """Build a coordinator from a locator string (≙ create_lock_service).

    "" → None-like in the reference means standalone; callers handle that.
    "memory" / "memory://"        → process-local MemoryCoordinator
    "/path" / "file:///path"      → FileCoordinator on that directory
    "tcp://host:port", "host:port" → RemoteCoordinator session on the
                                     coordination service (coord/server.py)
    "zk://host:port[,host:port...]" → ZkCoordinator session on a real
                                     ZooKeeper ensemble (coord/zk.py) —
                                     drop-in for existing deployments
    """
    if spec in ("memory", "memory://"):
        return MemoryCoordinator.shared()
    if spec.startswith("zk://"):
        from jubatus_tpu.coord.zk import ZkCoordinator

        return ZkCoordinator.from_locator(spec)
    if spec.startswith("file://"):
        return FileCoordinator(spec[len("file://") :])
    if spec.startswith("/") or spec.startswith("."):
        return FileCoordinator(spec)
    if spec.startswith("tcp://") or (":" in spec and
                                     spec.rpartition(":")[2].isdigit()):
        from jubatus_tpu.coord.remote import RemoteCoordinator

        return RemoteCoordinator.from_locator(spec)
    raise CoordinatorError(f"unsupported coordinator spec {spec!r}")
