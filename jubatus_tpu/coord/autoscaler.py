"""Autoscaling control plane (ISSUE 12): close the loop from telemetry
to fleet size.

Every prerequisite already exists — elastic membership with drain/join/
migration (ISSUE 10), SLO burn rates and windowed telemetry (ISSUE 7),
churn-tolerant async mix (ISSUE 11) — but an operator still resizes the
fleet by hand. This module is the missing loop:

    signals ──> decision ──> actuation
    (poll)      (hysteresis)  (spawn / drain)

- **Signals** (:func:`poll_fleet`): one ``get_timeseries`` scrape per
  active member yields windowed request rates and worst p99 (the same
  math as ``jubactl -c watch``), the coalescer backpressure gauges
  (``microbatch.queue_depth`` / ``microbatch.arrival_per_sec``, sampled
  into the ring by the telemetry tick), and the live SLO burn gauges
  (``slo.*.burn_fast`` / ``.firing``). Draining members are excluded
  from capacity accounting.
- **Decision** (:class:`AutoscalerCore`): a pure, clock-injected
  hysteresis/cooldown state machine — scale-out only after
  ``scale_out_confirm`` consecutive hot polls (SLO burn at/above
  ``burn_hot`` or queued examples per replica at/above ``queue_hot``),
  scale-in only after a longer cold streak, both inside ``min/max``
  bounds, everything rate-limited by ``cooldown_s``. A fleet below the
  floor (a dead replica) restores immediately, bypassing confirm and
  cooldown. Scale-in picks the least-loaded replica (queue depth, then
  request rate).
- **Actuation** (:class:`VisorActuator` / :class:`HookActuator`):
  scale-out spawns replicas through jubavisor's ``start`` RPC
  (round-robin over registered visors); scale-in fires the ISSUE 10
  drain state machine on the chosen member. Test harnesses plug a
  spawn/drain hook instead. Both paths run through the
  ``autoscale.spawn`` / ``autoscale.drain`` fault sites, and a failing
  actuation backs off exponentially with the journal recording
  ``blocked`` — a broken spawn path must never hot-loop.

Every decision lands in a bounded **journal** of structured records and
bumps the ``autoscale.{decisions,spawns,drains,blocked}`` counters;
``get_autoscale_status`` (served when :meth:`Autoscaler.serve` is up,
registered under ``/jubatus/autoscalers``) exposes config, live state,
and the journal tail to ``jubactl -c autoscale --watch``.

ISSUE 20 extracted the generic halves — the confirm-streak/cooldown
hysteresis and the journal/backoff/fault-site actuation discipline —
into coord/controller.py (:class:`~jubatus_tpu.coord.controller
.StreakGate` / :class:`~jubatus_tpu.coord.controller.ControllerLoop`)
so the self-tuning performance plane (coord/perf_tuner.py) rides the
same machinery. This module keeps the fleet-specific halves: signal
polling, the min/max-bounded scale decision, and the visor actuators.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from jubatus_tpu.coord import membership
from jubatus_tpu.coord.base import Coordinator, NodeInfo
from jubatus_tpu.coord.controller import ControllerLoop, StreakGate
from jubatus_tpu.utils.timeseries import window_from_points
from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

__all__ = [
    "AutoscaleConfig", "ReplicaStats", "FleetSnapshot", "Decision",
    "AutoscalerCore", "Autoscaler", "HookActuator", "VisorActuator",
    "poll_fleet",
]


@dataclasses.dataclass
class AutoscaleConfig:
    """Knobs of the control loop; defaults target a small serving fleet
    polled every few seconds. Everything an operator tunes rides
    ``jubactl -c autoscale`` flags."""
    min_replicas: int = 1
    max_replicas: int = 8
    #: control-loop period; also the unit the SLO-violation clock counts
    poll_interval_s: float = 5.0
    #: timeseries window for request rates / p99 (like watch --window)
    window_s: float = 30.0
    #: hot when any member's fast burn is at/above this (utils/slo.py
    #: burn semantics: 2.0 = spending error budget twice as fast as it
    #: accrues) ...
    burn_hot: float = 2.0
    #: ... or when queued examples PER NON-DRAINING REPLICA reach this
    queue_hot: float = 4096.0
    #: cold only when burn is under 1.0, nothing fires, and the queue
    #: sits below this fraction of queue_hot
    queue_cold_fraction: float = 0.1
    #: consecutive hot polls before a scale-out fires (flap suppression)
    scale_out_confirm: int = 2
    #: consecutive cold polls before a scale-in fires (asymmetric on
    #: purpose: growing too late burns SLO, shrinking too eagerly flaps)
    scale_in_confirm: int = 6
    #: replicas added per scale-out decision
    scale_out_step: int = 1
    #: quiet period after any actuation (floor restores are exempt)
    cooldown_s: float = 30.0
    #: actuation-failure backoff (doubles per failure up to the max)
    backoff_initial_s: float = 2.0
    backoff_max_s: float = 60.0
    journal_capacity: int = 256
    #: observe + journal, never actuate (the static-control twin and
    #: the safe default for `jubactl -c autoscale --once` exploration)
    dry_run: bool = False

    def validate(self) -> "AutoscaleConfig":
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.poll_interval_s <= 0:
            raise ValueError("poll_interval_s must be > 0")
        if self.scale_out_confirm < 1 or self.scale_in_confirm < 1:
            raise ValueError("confirm streaks must be >= 1")
        if self.burn_hot <= 0 or self.queue_hot <= 0:
            raise ValueError("burn_hot / queue_hot must be > 0")
        if self.backoff_initial_s <= 0 or \
                self.backoff_max_s < self.backoff_initial_s:
            raise ValueError("backoff bounds must be 0 < initial <= max")
        return self


@dataclasses.dataclass
class ReplicaStats:
    """One member's view for a single poll."""
    name: str
    req_per_sec: float = 0.0
    p99_ms: float = 0.0
    queue_depth: float = 0.0
    arrival_per_sec: float = 0.0
    burn_max: float = 0.0
    firing: bool = False
    draining: bool = False
    reachable: bool = True


@dataclasses.dataclass
class FleetSnapshot:
    """Everything one control tick decides from."""
    ts: float
    replicas: List[ReplicaStats] = dataclasses.field(default_factory=list)
    errors: List[str] = dataclasses.field(default_factory=list)

    @property
    def serving(self) -> List[ReplicaStats]:
        return [r for r in self.replicas if not r.draining]

    @property
    def size(self) -> int:
        return len(self.serving)

    @property
    def burn_max(self) -> float:
        return max((r.burn_max for r in self.serving), default=0.0)

    @property
    def firing(self) -> bool:
        return any(r.firing for r in self.serving)

    @property
    def queue_total(self) -> float:
        return sum(r.queue_depth for r in self.serving)

    @property
    def queue_per_replica(self) -> float:
        return self.queue_total / self.size if self.size else 0.0

    @property
    def req_per_sec(self) -> float:
        return sum(r.req_per_sec for r in self.serving)

    def signals(self) -> Dict[str, Any]:
        return {"replicas": self.size,
                "burn_max": round(self.burn_max, 4),
                "firing": self.firing,
                "queue_per_replica": round(self.queue_per_replica, 1),
                "req_per_sec": round(self.req_per_sec, 1)}


def _stats_from_points(name: str, points: List[Dict[str, Any]],
                       window_s: float) -> ReplicaStats:
    """Fold one member's ``get_timeseries`` points into a ReplicaStats:
    windowed request rate + worst p99 (watch math), newest-point
    coalescer gauges, and the worst live SLO burn gauge."""
    r = ReplicaStats(name)
    win = window_from_points(points, window_s)
    if win is not None:
        for span in win.spans("rpc."):
            rate = win.span_rate(span)
            r.req_per_sec += rate
            if rate > 0:
                q = win.quantile_ms(span, 0.99)
                if q is not None:
                    r.p99_ms = max(r.p99_ms, q)
    gauges = (points[-1].get("gauges") or {}) if points else {}
    r.queue_depth = float(gauges.get("microbatch.queue_depth", 0.0))
    r.arrival_per_sec = float(gauges.get("microbatch.arrival_per_sec", 0.0))
    for key, val in gauges.items():
        if key.startswith("slo.") and key.endswith(".burn_fast"):
            r.burn_max = max(r.burn_max, float(val))
        elif key.startswith("slo.") and key.endswith(".firing") and val:
            r.firing = True
    return r


def poll_fleet(coord: Coordinator, engine: str, name: str, *,
               window_s: float = 30.0, timeout: float = 5.0,
               now: Optional[float] = None) -> FleetSnapshot:
    """One scrape of the cluster's autoscaling signals (one
    ``get_timeseries`` RPC per active member). Unreachable members
    degrade per node — they stay in the snapshot as zero-signal rows so
    the floor-restore logic still counts the fleet honestly shrunken
    only when the registration is actually gone."""
    from jubatus_tpu.rpc.client import RpcClient

    snap = FleetSnapshot(ts=time.time() if now is None else float(now))
    draining = {n.name for n in membership.get_draining(coord, engine, name)}
    for node in membership.get_all_actives(coord, engine, name):
        try:
            with RpcClient(node.host, node.port, timeout=timeout) as c:
                ts = c.call("get_timeseries", name)
        except Exception as e:  # broad-ok — a sick member is a signal
            snap.errors.append(f"{node.name}: {e}")
            r = ReplicaStats(node.name, reachable=False)
            r.draining = node.name in draining
            snap.replicas.append(r)
            continue
        points = ((ts or {}).get(node.name) or {}).get("points") or []
        r = _stats_from_points(node.name, points, window_s)
        r.draining = node.name in draining
        snap.replicas.append(r)
    return snap


@dataclasses.dataclass
class Decision:
    """What one control tick decided (pre-actuation intent)."""
    action: str               # hold | scale_out | scale_in
    reason: str
    count: int = 0            # scale_out: replicas to add
    target: str = ""          # scale_in: member to drain


class AutoscalerCore(StreakGate):
    """The pure decision state machine — no RPC, no threads, clock
    injected: synthetic burn/queue timelines drive it in tests exactly
    like production snapshots do. The streak/cooldown half is the
    shared :class:`StreakGate` (coord/controller.py); this class adds
    the fleet-shape classification and the bounded scale decision."""

    def __init__(self, config: AutoscaleConfig) -> None:
        self.config = config.validate()
        StreakGate.__init__(self, config.scale_out_confirm,
                            config.scale_in_confirm, config.cooldown_s)
        self.last_floor_restore_ts = 0.0

    # -- classification ------------------------------------------------------
    def is_hot(self, snap: FleetSnapshot) -> bool:
        return snap.burn_max >= self.config.burn_hot or \
            snap.queue_per_replica >= self.config.queue_hot

    def is_cold(self, snap: FleetSnapshot) -> bool:
        return (snap.burn_max < 1.0 and not snap.firing
                and snap.queue_per_replica <=
                self.config.queue_cold_fraction * self.config.queue_hot)

    @staticmethod
    def least_loaded(snap: FleetSnapshot) -> Optional[ReplicaStats]:
        """Scale-in victim: fewest queued examples, then lowest request
        rate — draining it shifts the least traffic."""
        serving = [r for r in snap.serving if r.reachable]
        if not serving:
            return None
        return min(serving,
                   key=lambda r: (r.queue_depth, r.req_per_sec, r.name))

    # -- the tick ------------------------------------------------------------
    def observe(self, snap: FleetSnapshot,
                now: Optional[float] = None) -> Decision:
        cfg = self.config
        now = snap.ts if now is None else float(now)
        n = snap.size
        hot, cold = self.is_hot(snap), self.is_cold(snap)
        self.step(hot, cold)
        # floor restore: a dead replica must come back NOW — no confirm
        # streak, and a cooldown from a prior hot/cold action does not
        # delay it (the bench kills a member and times this). Repeat
        # restores ARE spaced by cooldown_s though: freshly-spawned
        # replicas take seconds to register, and re-spawning on every
        # poll until they do is a spawn storm, not a recovery.
        if n < cfg.min_replicas:
            if self.last_floor_restore_ts and \
                    now - self.last_floor_restore_ts < cfg.cooldown_s:
                return Decision("hold", "floor_restore_pending")
            self.last_floor_restore_ts = now
            self.last_action_ts = now
            return Decision("scale_out", "below_min_floor",
                            count=cfg.min_replicas - n)
        if hot and self.hot_confirmed:
            if n >= cfg.max_replicas:
                return Decision("hold", "hot_at_max")
            if self.in_cooldown(now):
                return Decision("hold", "cooldown")
            self.fired_hot(now)
            return Decision(
                "scale_out", "sustained_hot",
                count=min(cfg.scale_out_step, cfg.max_replicas - n))
        if cold and self.cold_confirmed:
            if n <= cfg.min_replicas:
                return Decision("hold", "cold_at_min")
            if self.in_cooldown(now):
                return Decision("hold", "cooldown")
            victim = self.least_loaded(snap)
            if victim is None:
                return Decision("hold", "no_drainable_replica")
            self.fired_cold(now)
            return Decision("scale_in", "sustained_cold",
                            target=victim.name)
        if hot:
            return Decision("hold", "hot_unconfirmed")
        if cold:
            return Decision("hold", "cold_unconfirmed")
        return Decision("hold", "steady")

    def state(self) -> Dict[str, Any]:
        return dict(self.gate_state(),
                    last_floor_restore_ts=self.last_floor_restore_ts)


class HookActuator:
    """Pluggable actuation for test harnesses and in-process benches:
    ``spawn_fn(count)`` boots replicas, ``drain_fn(member_name)`` drains
    one. Either raising marks the actuation failed (backoff + blocked
    journal record)."""

    def __init__(self, spawn_fn: Callable[[int], Any],
                 drain_fn: Callable[[str], Any]) -> None:
        self.spawn_fn = spawn_fn
        self.drain_fn = drain_fn

    def spawn(self, count: int) -> None:
        self.spawn_fn(count)

    def drain(self, target: str) -> None:
        self.drain_fn(target)


class VisorActuator:
    """Production actuation: spawn replicas through registered
    jubavisors (round-robin, like ``jubactl -c start``), drain through
    the member's own ISSUE 10 drain RPC (``stop_after=True`` so the
    supervised child exits and its port recycles)."""

    def __init__(self, coord: Coordinator, engine: str, name: str,
                 server_argv: Optional[Dict[str, Any]] = None,
                 timeout: float = 10.0) -> None:
        self.coord = coord
        self.engine = engine
        self.name = name
        self.server_argv = dict(server_argv or {})
        #: ISSUE 18: replicas spawned with --store-dir warm-boot from
        #: the shared model store instead of cold-joining — scale-out
        #: recovery is bounded by snapshot download, not re-training
        self.warm_spawn = bool(self.server_argv.get("store_dir"))
        self.timeout = timeout
        self._rr = 0  # round-robin cursor over visors

    def _visors(self) -> List[NodeInfo]:
        out = []
        for child in self.coord.list(membership.SUPERVISOR_BASE):
            try:
                out.append(NodeInfo.from_name(child))
            except (ValueError, IndexError):
                continue
        return out

    def spawn(self, count: int) -> None:
        from jubatus_tpu.rpc.client import RpcClient

        visors = self._visors()
        if not visors:
            raise RuntimeError("no jubavisor registered to spawn on")
        target = f"{self.engine}/{self.name}"
        if self.warm_spawn:
            log.info("spawning %d replica(s) with --store-dir: they will "
                     "warm-boot from the shared model store", count)
        for i in range(int(count)):
            visor = visors[(self._rr + i) % len(visors)]
            with RpcClient(visor.host, visor.port,
                           timeout=self.timeout) as c:
                rc = c.call("start", target, 1, self.server_argv)
            if rc != 0:
                raise RuntimeError(
                    f"jubavisor {visor.name} start returned {rc}")
        self._rr += count

    def drain(self, target: str) -> None:
        from jubatus_tpu.rpc.client import RpcClient

        node = NodeInfo.from_name(target)
        with RpcClient(node.host, node.port, timeout=self.timeout) as c:
            c.call("drain", self.name, True)


class Autoscaler(ControllerLoop):
    """The control loop: poll → decide → actuate → journal.

    ``tick()`` runs one cycle (tests and ``--once`` call it directly);
    ``start()`` runs it on a daemon thread every ``poll_interval_s``;
    ``serve()`` additionally exposes ``get_autoscale_status`` over RPC
    and registers under ``/jubatus/autoscalers`` for the watch view.
    The journal/eventing/backoff machinery is the shared
    :class:`ControllerLoop` (coord/controller.py)."""

    subsystem = "autoscale"

    def __init__(self, coord: Coordinator, engine: str, name: str,
                 actuator: Any, config: Optional[AutoscaleConfig] = None,
                 registry: Optional[Registry] = None,
                 poller: Optional[Callable[..., FleetSnapshot]] = None
                 ) -> None:
        self.config = (config or AutoscaleConfig()).validate()
        ControllerLoop.__init__(self, self.config.journal_capacity,
                                registry)
        self.coord = coord
        self.engine = engine
        self.name = name
        self.actuator = actuator
        self.core = AutoscalerCore(self.config)
        self._poller = poller
        self.last_snapshot: Optional[FleetSnapshot] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rpc = None
        self.start_time = time.time()  # wall-clock

    # -- ControllerLoop hooks ------------------------------------------------
    def _counter_suffix(self, action: str,
                        extra: Dict[str, Any]) -> Optional[str]:
        return {"scale_out": "spawns", "scale_in": "drains",
                "blocked": "blocked"}.get(action)

    def _event_fields(self, signals: Dict[str, Any],
                      extra: Dict[str, Any]) -> Dict[str, Any]:
        return {"target": extra.get("target") or None,
                "count": extra.get("count") or None,
                "dry_run": extra.get("dry_run") or None,
                "replicas": signals["replicas"]}

    def _gauge_signals(self, signals: Dict[str, Any]) -> None:
        self.registry.gauge("autoscale.replicas",
                            float(signals["replicas"]))
        self.registry.gauge("autoscale.burn_max", signals["burn_max"])
        self.registry.gauge("autoscale.queue_per_replica",
                            signals["queue_per_replica"])

    def _on_actuation_failure(self) -> None:
        # a failed actuation must not start the cooldown clock (or
        # the floor-restore spacing) — the retry after backoff
        # would otherwise wait both out
        self.core.reset_clock()
        self.core.last_floor_restore_ts = 0.0

    def _backoff_bounds(self):
        return self.config.backoff_initial_s, self.config.backoff_max_s

    # -- journal -------------------------------------------------------------
    def _record(self, action: str, reason: str, snap: FleetSnapshot,
                now: float, **extra: Any) -> Dict[str, Any]:
        return self.record(action, reason, snap.signals(), now, **extra)

    # -- actuation (fault sites + backoff live in ControllerLoop) ------------
    def _actuate(self, decision: Decision, snap: FleetSnapshot,
                 now: float) -> Dict[str, Any]:
        site = "autoscale.spawn" if decision.action == "scale_out" \
            else "autoscale.drain"
        if decision.action == "scale_out":
            fn = lambda: self.actuator.spawn(decision.count)  # noqa: E731
        else:
            fn = lambda: self.actuator.drain(decision.target)  # noqa: E731
        ok, blocked = self.guarded(
            site, fn, reason=decision.reason, signals=snap.signals(),
            now=now, wanted=decision.action, target=decision.target,
            count=decision.count)
        if not ok:
            return blocked
        extra: Dict[str, Any] = {}
        if decision.action == "scale_out" and \
                getattr(self.actuator, "warm_spawn", False):
            # ISSUE 18: the journal/timeline distinguishes warm scale-out
            # (replicas boot from the shared store) from cold
            extra["warm_spawn"] = True
        return self._record(decision.action, decision.reason, snap, now,
                            target=decision.target, count=decision.count,
                            dry_run=False, **extra)

    # -- one control cycle ---------------------------------------------------
    def tick(self, snap: Optional[FleetSnapshot] = None,
             now: Optional[float] = None) -> Dict[str, Any]:
        if snap is None:
            poller = self._poller or poll_fleet
            snap = poller(self.coord, self.engine, self.name,
                          window_s=self.config.window_s)
        now = snap.ts if now is None else float(now)
        self.last_snapshot = snap
        decision = self.core.observe(snap, now=now)
        if decision.action == "hold":
            return self._record("hold", decision.reason, snap, now)
        if self.in_backoff(now):
            # intent survives (streaks rebuilt next tick), attempt
            # suppressed: this is the "never hot-loop" half of backoff
            self.core.reset_clock()
            return self._record(
                "hold", "backoff", snap, now, wanted=decision.action,
                backoff_remaining_s=round(self.backoff_until - now, 3))
        if self.config.dry_run:
            return self._record(
                decision.action, decision.reason, snap, now,
                target=decision.target, count=decision.count,
                dry_run=True)
        return self._actuate(decision, snap, now)

    # -- status / RPC --------------------------------------------------------
    def status(self, last: int = 32) -> Dict[str, Any]:
        tail = self.journal_tail(last)
        doc: Dict[str, Any] = {
            "engine": self.engine, "name": self.name,
            "uptime_s": int(time.time() - self.start_time),  # wall-clock
            "config": dataclasses.asdict(self.config),
            "state": dict(self.core.state(), **self.backoff_state()),
            "counters": {k: v for k, v in self.registry.counters().items()
                         if k.startswith("autoscale.")},
            "gauges": {k: v for k, v in self.registry.gauges().items()
                       if k.startswith("autoscale.")},
            "journal": tail,
        }
        if self.last_snapshot is not None:
            doc["fleet"] = self.last_snapshot.signals()
            doc["replicas"] = [dataclasses.asdict(r)
                               for r in self.last_snapshot.replicas]
        return doc

    def get_autoscale_status(self, _name: str = "",
                             last: int = 32) -> Dict[str, Any]:
        """RPC surface: the status doc keyed like get_status (one map
        entry per autoscaler node)."""
        port = self.rpc.port if self.rpc is not None else 0
        me = NodeInfo("127.0.0.1", port or 0)
        return {me.name: self.status(last=int(last or 32))}

    # -- lifecycle -----------------------------------------------------------
    def serve(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Serve ``get_autoscale_status`` and register under
        ``/jubatus/autoscalers`` so the watch view finds us."""
        from jubatus_tpu.rpc.server import RpcServer

        self.rpc = RpcServer()
        self.rpc.register("get_autoscale_status", self.get_autoscale_status,
                          arity=2)
        actual = self.rpc.serve_background(port, host=host)
        membership.register_autoscaler(self.coord, host, actual)
        return actual

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.poll_interval_s):
            try:
                self.tick()
            except Exception:  # broad-ok — the loop must survive a bad poll
                log.warning("autoscaler tick failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.rpc is not None:
            try:
                self.rpc.stop()
            except Exception:  # broad-ok — teardown
                pass
            self.rpc = None
