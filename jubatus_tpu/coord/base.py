"""Coordinator interface (≙ lock_service ABC, common/lock_service.hpp:33-118).

Path-keyed hierarchical store with ephemeral nodes, watchers, locks, and
64-bit id minting — the subset of ZooKeeper the reference actually uses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional


class CoordinatorError(RuntimeError):
    pass


@dataclass(frozen=True)
class NodeInfo:
    """A cluster member (ip, port) — the reference stores these as znode
    names "<ip>_<port>" (membership.cpp:59-66)."""

    host: str
    port: int

    @property
    def name(self) -> str:
        return f"{self.host}_{self.port}"

    @classmethod
    def from_name(cls, name: str) -> "NodeInfo":
        host, _, port = name.rpartition("_")
        return cls(host, int(port))


class Coordinator:
    """ABC. All paths are '/'-separated strings rooted at '/'."""

    # -- node CRUD (≙ lock_service create/set/remove/exists/read/list) ------
    def create(self, path: str, payload: bytes = b"", ephemeral: bool = False) -> bool:
        """Create a node (parents auto-created). False if it exists.
        Ephemeral nodes vanish when their creator session ends."""
        raise NotImplementedError

    def create_seq(self, path: str, payload: bytes = b"") -> Optional[str]:
        """Create an ephemeral-sequence node; returns the actual path
        (≙ zk.cpp:203-205)."""
        raise NotImplementedError

    def set(self, path: str, payload: bytes) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> Optional[bytes]:
        raise NotImplementedError

    def remove(self, path: str) -> bool:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list(self, path: str) -> List[str]:
        """Child names (not full paths), sorted."""
        raise NotImplementedError

    # -- watchers (≙ bind_watcher/bind_child_watcher/bind_delete_watcher) ---
    def watch_children(self, path: str, fn: Callable[[str], None]) -> None:
        """fn(path) fires on any child add/remove under path (persistent
        watch — unlike ZK's one-shot, so callers need no re-arm dance)."""
        raise NotImplementedError

    def watch_delete(self, path: str, fn: Callable[[str], None]) -> None:
        """fn(path) fires when the node is deleted (suicide watcher,
        server_helper.cpp:91-94)."""
        raise NotImplementedError

    # -- locks (≙ zkmutex, common/zk.hpp:126-139) ---------------------------
    def try_lock(self, path: str) -> bool:
        raise NotImplementedError

    def unlock(self, path: str) -> bool:
        raise NotImplementedError

    # -- id minting (≙ create_id, global_id_generator_zk.cpp:32-56) ---------
    def create_id(self, path: str) -> int:
        """Monotonic uint64, cluster-unique per path."""
        raise NotImplementedError

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        """End the session: ephemeral nodes vanish, locks release."""

    def run_cleanup(self) -> None:
        """≙ lock_service cleanup stack — close is our cleanup."""
        self.close()
