"""Consistent hashing (≙ common/cht.{hpp,cpp}).

Same construction as the reference: an MD5 ring with 8 virtual nodes per
server (cht.hpp:36, ring entries md5(f"{node}_{i}"), cht.cpp:77-93);
`find(key, n)` returns the n distinct servers succeeding md5(key) clockwise
(cht.cpp:107-143).

Design difference: the reference materializes the ring in ZooKeeper (every
node writes its vnode hashes under .../cht) so all parties agree; here the
ring is a pure function of the member list — every observer of the same
membership computes the identical ring, so nothing needs storing. On a TPU
mesh the same idea degenerates further: keys → static shard index
(`shard_for`), the mesh replacing the ring.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

from jubatus_tpu.coord.base import Coordinator, NodeInfo
from jubatus_tpu.coord import membership

NUM_VSERV = 8  # common/cht.hpp:36


def make_hash(key: str) -> str:
    """Hex MD5 — the reference's ring coordinate (cht.cpp make_hash)."""
    return hashlib.md5(key.encode("utf-8")).hexdigest()


def ring_key(members: Sequence[NodeInfo]) -> Tuple[str, ...]:
    """Canonical identity of a ring: the sorted member names. Two member
    lists with the same key build the IDENTICAL ring — the cache key for
    the proxy/backend ring caches (elastic membership, ISSUE 10)."""
    return tuple(sorted(m.name for m in members))


class CHT:
    def __init__(self, members: Sequence[NodeInfo], epoch: int = 0) -> None:
        self.members = list(members)
        #: membership epoch this ring was built from (0 = unknown/static).
        #: Monotone across joins/leaves (coord/membership.py); consumers
        #: treat ANY difference as "refresh", never as an ordering.
        self.epoch = int(epoch)
        ring: List[Tuple[str, NodeInfo]] = []
        for m in self.members:
            for i in range(NUM_VSERV):
                ring.append((make_hash(f"{m.name}_{i}"), m))
        ring.sort(key=lambda e: e[0])
        self._ring = ring

    @property
    def key(self) -> Tuple[str, ...]:
        return ring_key(self.members)

    @classmethod
    def from_coordinator(
        cls, coord: Coordinator, engine: str, name: str, actives_only: bool = True
    ) -> "CHT":
        get = membership.get_all_actives if actives_only else membership.get_all_nodes
        return cls(get(coord, engine, name),
                   epoch=membership.get_epoch(coord, engine, name))

    def find(self, key: str, n: int = 2) -> List[NodeInfo]:
        """n distinct successors of md5(key) on the ring (cht.cpp:107-143).
        Fewer than n members → all members, primary first."""
        if not self._ring:
            return []
        h = make_hash(key)
        # first ring entry with hash > h, wrapping
        lo, hi = 0, len(self._ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._ring[mid][0] <= h:
                lo = mid + 1
            else:
                hi = mid
        out: List[NodeInfo] = []
        seen = set()
        for i in range(len(self._ring)):
            node = self._ring[(lo + i) % len(self._ring)][1]
            if node.name not in seen:
                seen.add(node.name)
                out.append(node)
                if len(out) == n:
                    break
        return out

    def primary(self, key: str) -> Optional[NodeInfo]:
        found = self.find(key, 1)
        return found[0] if found else None


def shard_for(key: str, n_shards: int) -> int:
    """Static mesh placement: the TPU-plane replacement for the ring —
    stable key → shard mapping over a fixed device mesh."""
    return int(make_hash(key)[:8], 16) % max(1, n_shards)
