"""Shared controller core (ISSUE 20): the signal→decision→actuator
machinery the autoscaler grew, extracted so every control loop rides it.

The autoscaler (ISSUE 12) proved the shape: a **pure, clock-injected
decision state machine** (hysteresis confirm streaks + cooldown), a
**bounded journal** of structured records cross-linked into the cluster
event timeline, and **fault-site-wrapped actuation** that backs off
exponentially instead of hot-looping when the actuation path is down.
The self-tuning performance plane (coord/perf_tuner.py) needs exactly
the same machinery pointed at different knobs — chunk size, wire mode,
microbatch depth, mix cadence — so the shared pieces live here:

- :class:`StreakGate`: hot/cold confirm streaks + the cooldown clock.
  Pure and clock-injected: synthetic timelines drive it in tests exactly
  like production ticks do. ``AutoscalerCore`` and every tuner core
  subclass or compose it.
- :class:`ControllerLoop`: the journal/eventing/counters/backoff half.
  ``record()`` appends one structured journal entry (HLC-stamped, with
  non-hold actions emitting a typed timeline event whose id the entry
  cross-links), bumps ``<subsystem>.decisions`` plus a per-action
  counter, and gauges the signals; ``guarded()`` runs one actuation
  through its fault site and, on failure, journals ``blocked`` and arms
  exponential backoff — the never-hot-loop guarantee every actuator
  inherits for free.

Behavior contract: the autoscaler's 29-test suite ran unchanged across
the extraction — this module IS the autoscaler's old inner machinery,
not a reinterpretation of it.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from jubatus_tpu.utils import events, faults
from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

__all__ = ["StreakGate", "ControllerLoop"]


class StreakGate:
    """Clock-injected hysteresis: a decision fires only after
    ``hot_confirm`` consecutive hot observations (or ``cold_confirm``
    cold ones), and any fired action starts a ``cooldown_s`` quiet
    window. Alternating signals reset the streaks — flap suppression by
    construction."""

    def __init__(self, hot_confirm: int, cold_confirm: int,
                 cooldown_s: float) -> None:
        self.hot_confirm = int(hot_confirm)
        self.cold_confirm = int(cold_confirm)
        self.cooldown_s = float(cooldown_s)
        self.hot_streak = 0
        self.cold_streak = 0
        self.last_action_ts = 0.0

    def step(self, hot: bool, cold: bool) -> None:
        """Fold one observation into the streaks (a tick is hot, cold,
        or neither — never both)."""
        self.hot_streak = self.hot_streak + 1 if hot else 0
        self.cold_streak = self.cold_streak + 1 if cold else 0

    @property
    def hot_confirmed(self) -> bool:
        return self.hot_streak >= self.hot_confirm

    @property
    def cold_confirmed(self) -> bool:
        return self.cold_streak >= self.cold_confirm

    def in_cooldown(self, now: float) -> bool:
        return now - self.last_action_ts < self.cooldown_s \
            and self.last_action_ts > 0

    def fired_hot(self, now: float) -> None:
        self.last_action_ts = now
        self.hot_streak = 0

    def fired_cold(self, now: float) -> None:
        self.last_action_ts = now
        self.cold_streak = 0

    def reset_clock(self) -> None:
        """A failed actuation must not start the cooldown clock — the
        retry after backoff would otherwise wait both out."""
        self.last_action_ts = 0.0

    def gate_state(self) -> Dict[str, Any]:
        return {"hot_streak": self.hot_streak,
                "cold_streak": self.cold_streak,
                "last_action_ts": self.last_action_ts}


class ControllerLoop:
    """Journal + events + counters + fault-wrapped actuation with
    exponential backoff. Subclasses set :attr:`subsystem` (the event
    subsystem AND the metric key prefix) and override the small hooks;
    everything else — HLC stamping, timeline cross-links, the blocked/
    backoff discipline — is shared verbatim with the autoscaler."""

    #: event-plane subsystem and ``<subsystem>.decisions`` counter prefix
    subsystem = "controller"

    def __init__(self, journal_capacity: int,
                 registry: Optional[Registry] = None) -> None:
        self.registry = registry or Registry()
        self.journal: deque = deque(maxlen=int(journal_capacity))
        self._jlock = threading.Lock()
        #: actuation-failure backoff state (the never-hot-loop guard)
        self.backoff_until = 0.0
        self._backoff_s = 0.0

    # -- subclass hooks ------------------------------------------------------
    def _counter_suffix(self, action: str,
                        extra: Dict[str, Any]) -> Optional[str]:
        """Per-action counter name under the subsystem prefix (e.g. the
        autoscaler's scale_out → ``spawns``); None counts nothing."""
        return None

    def _event_fields(self, signals: Dict[str, Any],
                      extra: Dict[str, Any]) -> Dict[str, Any]:
        """Extra fields stamped onto the timeline event of a non-hold
        record."""
        return {}

    def _gauge_signals(self, signals: Dict[str, Any]) -> None:
        """Publish the record's signals as gauges (subclass-specific
        keys so the catalog stays literal)."""

    def _on_actuation_failure(self) -> None:
        """Called when an actuation fails, before the blocked record —
        the autoscaler resets its cooldown clocks here so the retry
        after backoff is not additionally cooldown-delayed."""

    def _backoff_bounds(self) -> Tuple[float, float]:
        """(initial_s, max_s) of the exponential actuation backoff."""
        return 2.0, 60.0

    # -- journal -------------------------------------------------------------
    def record(self, action: str, reason: str, signals: Dict[str, Any],
               now: float, **extra: Any) -> Dict[str, Any]:
        """One structured journal entry. Entries ride the event plane's
        HLC helper (ordering agrees with ``jubactl -c timeline``), and
        every decision of consequence emits a timeline event whose id
        the journal entry cross-links (``event_hlc``)."""
        h = events.hlc_now()
        rec = {"ts": round(now, 3), "hlc": h, "action": action,
               "reason": reason, "signals": signals}
        rec.update(extra)
        if action != "hold":
            evt = self.registry.events.emit(
                self.subsystem, action,
                severity="warning" if action == "blocked" else "info",
                reason=reason, **self._event_fields(signals, extra))
            if evt is not None:
                rec["event_hlc"] = evt["hlc"]
        with self._jlock:
            self.journal.append(rec)
        self.registry.count(f"{self.subsystem}.decisions")
        if extra.get("dry_run"):
            pass  # intent only: the per-action counters count actuations
        else:
            suffix = self._counter_suffix(action, extra)
            if suffix:
                self.registry.count(f"{self.subsystem}.{suffix}")
        self._gauge_signals(signals)
        if action != "hold":
            log.info("%s %s (%s): %s%s", self.subsystem, action, reason,
                     signals,
                     f" target={extra.get('target')}"
                     if extra.get("target") else "")
        return rec

    def journal_tail(self, last: int = 32) -> list:
        with self._jlock:
            return list(self.journal)[-max(0, int(last)):]

    # -- actuation (fault sites + backoff live here) -------------------------
    def in_backoff(self, now: float) -> bool:
        return now < self.backoff_until

    def guarded(self, site: str, fn: Callable[[], Any], *, reason: str,
                signals: Dict[str, Any], now: float,
                **blocked_extra: Any
                ) -> Tuple[bool, Optional[Dict[str, Any]]]:
        """Run one actuation through its fault site. On failure the
        journal records ``blocked``, the backoff doubles (capped), and
        ``(False, blocked_record)`` comes back; on success the backoff
        resets and the CALLER records the action (it knows the
        decision's fields)."""
        try:
            faults.fire(site)
            fn()
        except Exception as e:  # broad-ok — actuation failure is a
            # first-class outcome: journal it, back off, never hot-loop
            initial, cap = self._backoff_bounds()
            self._backoff_s = min(cap, (self._backoff_s * 2) or initial)
            self.backoff_until = now + self._backoff_s
            self._on_actuation_failure()
            rec = self.record(
                "blocked", reason, signals, now,
                error=repr(e)[:200],
                backoff_s=round(self._backoff_s, 3), **blocked_extra)
            return False, rec
        self._backoff_s = 0.0
        self.backoff_until = 0.0
        return True, None

    def backoff_state(self) -> Dict[str, Any]:
        return {"backoff_until": round(self.backoff_until, 3),
                "backoff_s": round(self._backoff_s, 3)}
