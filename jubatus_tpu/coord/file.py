"""File-based coordinator — multi-process clusters without ZooKeeper.

Maps the coordinator tree onto a shared directory:

- node /a/b/c            → <root>/a/b/c.node          (payload file)
- ephemeral node         → payload + <path>.lease file whose mtime a
                           background heartbeat refreshes every LEASE/3 s;
                           a node whose lease is older than LEASE is dead
                           (the reference's ZK session-expiry failure
                           detector, membership.cpp:100-112)
- lock /x                → <root>/x.lock created O_EXCL with pid+session,
                           stale if its lease expires
- counter /y             → <root>/y.ctr under an O_EXCL spin-lock

Works on local disk for single-host multi-process deployments; on a shared
filesystem it extends to multi-host (with the usual NFS mtime caveats — a
real ZK/etcd backend slots in behind the same ABC for production).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from jubatus_tpu.coord.base import Coordinator, CoordinatorError

LEASE_SEC = 10.0
_WATCH_POLL_SEC = 0.5


class FileCoordinator(Coordinator):
    def __init__(self, root: str, lease_sec: float = LEASE_SEC) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.lease_sec = lease_sec
        self.session = uuid.uuid4().hex
        self._ephemerals: List[str] = []  # fs paths of my lease files
        self._locks: List[str] = []
        self._mu = threading.Lock()
        self._closed = False
        self._watch_thread: Optional[threading.Thread] = None
        self._child_watchers: Dict[str, List[Callable[[str], None]]] = {}
        self._delete_watchers: Dict[str, List[Callable[[str], None]]] = {}
        self._hb = threading.Thread(target=self._heartbeat, daemon=True,
                                    name="coord-heartbeat")
        self._hb.start()

    # -- path mapping --------------------------------------------------------
    def _fs(self, path: str, suffix: str = ".node") -> str:
        clean = path.strip("/")
        if ".." in clean.split("/"):
            raise CoordinatorError(f"bad path {path!r}")
        return os.path.join(self.root, clean + suffix) if clean else self.root

    def _dir(self, path: str) -> str:
        clean = path.strip("/")
        return os.path.join(self.root, clean) if clean else self.root

    def _alive(self, fs_node: str) -> bool:
        lease = fs_node[: -len(".node")] + ".lease"
        if not os.path.exists(lease):
            return True  # persistent node
        try:
            return (time.time() - os.stat(lease).st_mtime) <= self.lease_sec
        except OSError:
            return False

    # -- heartbeat -----------------------------------------------------------
    def _heartbeat(self) -> None:
        while not self._closed:
            time.sleep(self.lease_sec / 3)
            with self._mu:
                paths = list(self._ephemerals) + [
                    p + ".hb" for p in self._locks
                ]
            now = time.time()
            for p in paths:
                real = p[: -len(".hb")] if p.endswith(".hb") else p
                with contextlib.suppress(OSError):
                    os.utime(real, (now, now))

    # -- node CRUD -----------------------------------------------------------
    def create(self, path: str, payload: bytes = b"", ephemeral: bool = False) -> bool:
        fs = self._fs(path)
        os.makedirs(os.path.dirname(fs), exist_ok=True)
        if os.path.exists(fs) and self._alive(fs):
            if not ephemeral:
                return False
            # Ephemeral nodes are identity-keyed (ip_port): a crash-restarted
            # process re-claiming its own path must take the lease over, or
            # the stale lease expires under it and the suicide watcher kills
            # the healthy new process. Newest claimant wins (unlike ZK, which
            # blocks until the old session expires).
            lease = fs[: -len(".node")] + ".lease"
            try:
                with open(lease, "r") as f:
                    if f.read().strip() == self.session:
                        return False  # genuinely ours already
            except OSError:
                return False  # persistent node of someone else
        tmp = fs + f".tmp.{self.session}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, fs)
        lease = fs[: -len(".node")] + ".lease"
        if ephemeral:
            with open(lease, "wb") as f:
                f.write(self.session.encode())
            with self._mu:
                self._ephemerals.append(lease)
        else:
            # a dead session's stale lease must not shadow the new
            # persistent node
            with contextlib.suppress(OSError):
                os.remove(lease)
        return True

    def create_seq(self, path: str, payload: bytes = b"") -> Optional[str]:
        for _ in range(1000):
            n = self.create_id("/__seq__" + path)
            actual = f"{path}{n:010d}"
            if self.create(actual, payload, ephemeral=True):
                return actual
        return None

    def set(self, path: str, payload: bytes) -> bool:
        fs = self._fs(path)
        os.makedirs(os.path.dirname(fs), exist_ok=True)
        tmp = fs + f".tmp.{self.session}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, fs)
        return True

    def read(self, path: str) -> Optional[bytes]:
        fs = self._fs(path)
        if not os.path.exists(fs) or not self._alive(fs):
            return None
        try:
            with open(fs, "rb") as f:
                return f.read()
        except OSError:
            return None

    def remove(self, path: str) -> bool:
        fs = self._fs(path)
        removed = False
        with contextlib.suppress(OSError):
            os.remove(fs)
            removed = True
        with contextlib.suppress(OSError):
            os.remove(fs[: -len(".node")] + ".lease")
        return removed

    def exists(self, path: str) -> bool:
        fs = self._fs(path)
        return os.path.exists(fs) and self._alive(fs)

    def list(self, path: str) -> List[str]:
        d = self._dir(path)
        if not os.path.isdir(d):
            return []
        out = []
        for entry in os.listdir(d):
            if entry.endswith(".node"):
                if self._alive(os.path.join(d, entry)):
                    out.append(entry[: -len(".node")])
            elif os.path.isdir(os.path.join(d, entry)):
                out.append(entry)
        return sorted(set(out))

    # -- watchers (polling) --------------------------------------------------
    def _ensure_watch_thread(self) -> None:
        if self._watch_thread is None:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True, name="coord-watch"
            )
            self._watch_thread.start()

    def _watch_loop(self) -> None:
        last_children: Dict[str, List[str]] = {}
        while not self._closed:
            time.sleep(_WATCH_POLL_SEC)
            with self._mu:
                child_paths = list(self._child_watchers)
                delete_paths = list(self._delete_watchers)
            for p in child_paths:
                cur = self.list(p)
                if p in last_children and cur != last_children[p]:
                    for fn in list(self._child_watchers.get(p, ())):
                        with contextlib.suppress(Exception):
                            fn(p)
                last_children[p] = cur
            for p in delete_paths:
                if not self.exists(p):
                    with self._mu:
                        fns = self._delete_watchers.pop(p, [])
                    for fn in fns:
                        with contextlib.suppress(Exception):
                            fn(p)

    def watch_children(self, path: str, fn: Callable[[str], None]) -> None:
        with self._mu:
            self._child_watchers.setdefault(path, []).append(fn)
        self._ensure_watch_thread()

    def watch_delete(self, path: str, fn: Callable[[str], None]) -> None:
        with self._mu:
            self._delete_watchers.setdefault(path, []).append(fn)
        self._ensure_watch_thread()

    # -- locks ---------------------------------------------------------------
    def try_lock(self, path: str) -> bool:
        fs = self._fs(path, ".lock")
        os.makedirs(os.path.dirname(fs), exist_ok=True)
        try:
            fd = os.open(fs, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            # steal stale locks (holder's heartbeat stopped)
            try:
                with open(fs, "r") as f:
                    holder = f.read().split()[0]
                if holder == self.session:
                    return True
                if (time.time() - os.stat(fs).st_mtime) > self.lease_sec:
                    # rename is the atomic claim: exactly one stealer wins;
                    # a plain remove would let a second stealer delete the
                    # winner's freshly created lock (two masters)
                    stale = fs + f".stale.{self.session}"
                    os.rename(fs, stale)
                    os.remove(stale)
                    return self.try_lock(path)
            except (OSError, IndexError):
                pass
            return False
        with os.fdopen(fd, "w") as f:
            f.write(f"{self.session} {os.getpid()}")
        with self._mu:
            self._locks.append(fs)
        return True

    def unlock(self, path: str) -> bool:
        fs = self._fs(path, ".lock")
        try:
            with open(fs, "r") as f:
                if f.read().split()[0] != self.session:
                    return False
            os.remove(fs)
        except (OSError, IndexError):
            return False
        with self._mu:
            with contextlib.suppress(ValueError):
                self._locks.remove(fs)
        return True

    # -- ids -----------------------------------------------------------------
    def create_id(self, path: str) -> int:
        fs = self._fs(path, ".ctr")
        os.makedirs(os.path.dirname(fs), exist_ok=True)
        guard = fs + ".guard"
        deadline = time.time() + 10.0
        while True:
            try:
                fd = os.open(guard, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                break
            except FileExistsError:
                if time.time() > deadline:
                    with contextlib.suppress(OSError):
                        os.remove(guard)  # stale guard from a dead process
                else:
                    time.sleep(0.002)
        try:
            cur = 0
            with contextlib.suppress(OSError, ValueError):
                with open(fs, "r") as f:
                    cur = int(f.read() or 0)
            nxt = cur + 1
            tmp = fs + f".tmp.{self.session}"
            with open(tmp, "w") as f:
                f.write(str(nxt))
            os.replace(tmp, fs)
            return nxt
        finally:
            with contextlib.suppress(OSError):
                os.remove(guard)

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._mu:
            leases = list(self._ephemerals)
            locks = list(self._locks)
            self._ephemerals.clear()
            self._locks.clear()
        for lease in leases:
            with contextlib.suppress(OSError):
                os.remove(lease)
            with contextlib.suppress(OSError):
                os.remove(lease[: -len(".lease")] + ".node")
        for lk in locks:
            with contextlib.suppress(OSError):
                os.remove(lk)
