"""Cluster-unique uint64 ids (≙ common/global_id_generator_*).

Standalone mode counts locally (global_id_generator_standalone); distributed
mode mints through the coordinator (global_id_generator_zk.cpp:32-56 uses the
ZK version counter on .../id_generator).
"""

from __future__ import annotations

import threading
from typing import Optional

from jubatus_tpu.coord.base import Coordinator


class IdGenerator:
    def __init__(
        self, coord: Optional[Coordinator] = None, path: str = "/jubatus/id_generator"
    ) -> None:
        self._coord = coord
        self._path = path
        self._counter = 0
        self._mu = threading.Lock()

    def generate(self) -> int:
        if self._coord is not None:
            return self._coord.create_id(self._path)
        with self._mu:
            self._counter += 1
            return self._counter
