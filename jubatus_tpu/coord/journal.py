"""Durable-state journal for the coordination service (jubacoordd).

The reference gets control-plane durability from the ZooKeeper quorum's
transaction log; round 1's coordd was memory-only (a crash lost every
config and counter). This journal persists the DURABLE subset of the
store — persistent nodes, id counters, the sequence counter — as
msgpack-framed append-only records, replayed on boot and compacted to a
snapshot at open.

Ephemerals and locks are deliberately NOT journaled: they belong to
sessions, and a restarted coordd has no sessions — clients re-establish
them through session resumption (coord/remote.py).

Availability model (documented, not hidden): appends flush to the OS on
every record, so a killed/restarted process loses nothing; a HOST crash
may lose the tail. Counter records are hi-lo reservations (the journal
stores an upper bound, minting advances in memory), so a lost tail can
only skip ids, never reissue one.

Record shapes: ("c", path, payload) persistent create/set,
("r", path) remove, ("cnt", path, hi) id-counter reservation,
("seq", hi) sequence-counter reservation.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional, Tuple

import msgpack

log = logging.getLogger(__name__)

#: ids/sequences are reserved in blocks: one journal record per
#: RESERVE_BLOCK mints, and recovery resumes at the reserved bound
RESERVE_BLOCK = 1000


class Journal:
    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._fh = None

    # -- recovery -------------------------------------------------------------
    def replay_into(self, store) -> int:
        """Apply journaled durable state to a fresh _Store. Returns the
        record count (pre-compaction)."""
        n = 0
        if not os.path.exists(self.path):
            return 0
        with open(self.path, "rb") as f:
            unpacker = msgpack.Unpacker(f, raw=True, strict_map_key=False)
            for rec in unpacker:
                n += 1
                try:
                    self._apply(store, rec)
                except Exception:  # noqa: BLE001 — a torn tail record
                    log.warning("journal: stopping at malformed record %d", n)
                    break
        return n

    @staticmethod
    def _apply(store, rec) -> None:
        kind = rec[0].decode() if isinstance(rec[0], bytes) else rec[0]
        if kind == "c":
            path = rec[1].decode() if isinstance(rec[1], bytes) else rec[1]
            payload = rec[2] if isinstance(rec[2], bytes) else bytes(rec[2])
            parts = path.strip("/").split("/")
            cur = ""
            for p in parts[:-1]:
                cur += "/" + p
                store.nodes.setdefault(cur, (b"", None))
            store.nodes[path] = (payload, None)
        elif kind == "r":
            path = rec[1].decode() if isinstance(rec[1], bytes) else rec[1]
            store.nodes.pop(path, None)
        elif kind == "cnt":
            path = rec[1].decode() if isinstance(rec[1], bytes) else rec[1]
            hi = int(rec[2])
            store.counters[path] = max(store.counters.get(path, 0), hi)
            store.counter_res[path] = max(store.counter_res.get(path, 0), hi)
        elif kind == "seq":
            hi = int(rec[1])
            store.seq = max(store.seq, hi)
            store.seq_res = max(store.seq_res, hi)

    # -- writing --------------------------------------------------------------
    def open_and_compact(self, store) -> None:
        """Rewrite the journal as a snapshot of the current durable state
        (bounds growth across restarts), then keep it open for appends."""
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(tmp, "wb") as f:
            for path, (payload, owner) in sorted(store.nodes.items()):
                if owner is None and path != "/":
                    f.write(msgpack.packb(("c", path, payload)))
            for path, hi in sorted(store.counter_res.items()):
                f.write(msgpack.packb(("cnt", path, hi)))
            if store.seq_res:
                f.write(msgpack.packb(("seq", store.seq_res)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._fh = open(self.path, "ab")

    def append(self, rec: Tuple) -> None:
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(msgpack.packb(rec))
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except OSError:
                    pass
                self._fh.close()
                self._fh = None
