"""Cluster registry path schema (≙ common/membership.{hpp,cpp}).

Same tree as the reference (membership.hpp:32-36, membership.cpp:59-66):

    /jubatus/actors/<type>/<name>/nodes/<ip>_<port>     all booted servers
    /jubatus/actors/<type>/<name>/actives/<ip>_<port>   mix-current servers
    /jubatus/actors/<type>/<name>/master_lock           per-round mix master
    /jubatus/actors/<type>/<name>/id_generator          cluster id counter
    /jubatus/config/<type>/<name>                       engine JSON config
    /jubatus/supervisors/<ip>_<port>                    jubavisor daemons
    /jubatus/jubaproxies/<ip>_<port>                    proxies

Beyond the reference — elastic membership (ISSUE 10):

    .../membership_epoch        monotone counter node (create_id bumps)
    .../membership_epoch_value  readable mirror of the last minted epoch
    .../draining/<ip>_<port>    members mid-drain (quorum excludes them)

Every ACTUAL actives change (a create that created, a remove that
removed) mints a new **membership epoch** through the coordinator's
atomic counter — the ring version proxies and backends compare to decide
whether their CHT view is current. The mirror node makes the epoch
READABLE without bumping it; concurrent bumps may briefly publish the
smaller value, which is harmless because every consumer treats ANY
difference as "refresh the ring", never as an ordering.
"""

from __future__ import annotations

import logging
from typing import List

from jubatus_tpu.coord.base import Coordinator, NodeInfo

log = logging.getLogger(__name__)

JUBATUS_BASE = "/jubatus"
ACTOR_BASE = f"{JUBATUS_BASE}/actors"
CONFIG_BASE = f"{JUBATUS_BASE}/config"
SUPERVISOR_BASE = f"{JUBATUS_BASE}/supervisors"
PROXY_BASE = f"{JUBATUS_BASE}/jubaproxies"
#: autoscaler control loops (ISSUE 12): ephemeral, one per fleet —
#: jubactl -c autoscale --watch finds the journal/status RPC here
AUTOSCALER_BASE = f"{JUBATUS_BASE}/autoscalers"


def actor_path(engine: str, name: str) -> str:
    return f"{ACTOR_BASE}/{engine}/{name}"


def config_path(engine: str, name: str) -> str:
    return f"{CONFIG_BASE}/{engine}/{name}"


def register_actor(
    coord: Coordinator, engine: str, name: str, host: str, port: int
) -> str:
    """Ephemeral registration under nodes/ (membership.cpp:68-112).
    Returns the node path so the caller can arm a suicide watcher."""
    path = f"{actor_path(engine, name)}/nodes/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path


def register_active(
    coord: Coordinator, engine: str, name: str, host: str, port: int
) -> str:
    """Join the actives list (membership.cpp:115-145) — proxies route only
    to actives; the mixer drives transitions on put_diff success/failure.
    An ACTUAL join (the node was not already active) mints a new
    membership epoch — re-registration after every healthy put_diff
    does not."""
    path = f"{actor_path(engine, name)}/actives/{NodeInfo(host, port).name}"
    if coord.create(path, ephemeral=True):
        bump_epoch(coord, engine, name)
    return path


def unregister_active(
    coord: Coordinator, engine: str, name: str, host: str, port: int
) -> bool:
    removed = coord.remove(
        f"{actor_path(engine, name)}/actives/{NodeInfo(host, port).name}"
    )
    if removed:
        bump_epoch(coord, engine, name)
    return removed


# -- membership epoch (elastic membership, ISSUE 10) --------------------------

def epoch_path(engine: str, name: str) -> str:
    return f"{actor_path(engine, name)}/membership_epoch"


def bump_epoch(coord: Coordinator, engine: str, name: str) -> int:
    """Mint the next membership epoch (coordinator-atomic counter) and
    mirror it into the readable value node. Returns the minted epoch.
    Failures are survivable — the epoch is a freshness signal, not a
    correctness gate (consumers refresh on ANY mismatch)."""
    path = epoch_path(engine, name)
    try:
        epoch = coord.create_id(path)
    except Exception:  # broad-ok — a coord hiccup must not kill a join
        log.warning("membership epoch bump failed for %s/%s", engine, name,
                    exc_info=True)
        return 0
    try:
        coord.set(f"{path}_value", str(epoch).encode())
    except Exception:  # broad-ok — mirror is best-effort
        log.debug("epoch mirror write failed", exc_info=True)
    # event plane (ISSUE 14): the ring version changing is the root of
    # most reshard/re-route cascades — first line of any timeline.
    # Default journal: this module has no registry; get_events merges it.
    from jubatus_tpu.utils import events

    events.emit("membership", "epoch_bump", epoch=epoch,
                cluster=f"{engine}/{name}")
    return epoch


def get_epoch(coord: Coordinator, engine: str, name: str) -> int:
    """Last published membership epoch (0 before the first join/leave)."""
    try:
        raw = coord.read(f"{epoch_path(engine, name)}_value")
    except Exception:  # broad-ok — treated as "unknown", epoch 0
        return 0
    if not raw:
        return 0
    try:
        return int(raw.decode())
    except (ValueError, UnicodeDecodeError):
        return 0


# -- drain markers (elastic membership, ISSUE 10) -----------------------------

def draining_path(engine: str, name: str) -> str:
    return f"{actor_path(engine, name)}/draining"


def mark_draining(coord: Coordinator, engine: str, name: str,
                  host: str, port: int) -> str:
    """Announce a member is draining: still booted (nodes/), no longer
    routable or quorum-countable. Ephemeral — a drain that dies with its
    process clears itself."""
    path = f"{draining_path(engine, name)}/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path


def clear_draining(coord: Coordinator, engine: str, name: str,
                   host: str, port: int) -> bool:
    return coord.remove(
        f"{draining_path(engine, name)}/{NodeInfo(host, port).name}")


def get_draining(coord: Coordinator, engine: str, name: str) -> List[NodeInfo]:
    return _nodes_under(coord, draining_path(engine, name))


def _nodes_under(coord: Coordinator, path: str) -> List[NodeInfo]:
    out = []
    for child in coord.list(path):
        try:
            out.append(NodeInfo.from_name(child))
        except (ValueError, IndexError):
            continue
    return out


def get_all_nodes(coord: Coordinator, engine: str, name: str) -> List[NodeInfo]:
    """All booted members (membership get_all_nodes)."""
    return _nodes_under(coord, f"{actor_path(engine, name)}/nodes")


def get_all_actives(coord: Coordinator, engine: str, name: str) -> List[NodeInfo]:
    return _nodes_under(coord, f"{actor_path(engine, name)}/actives")


def register_proxy(coord: Coordinator, host: str, port: int) -> str:
    path = f"{PROXY_BASE}/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path


def register_supervisor(coord: Coordinator, host: str, port: int) -> str:
    path = f"{SUPERVISOR_BASE}/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path


def register_autoscaler(coord: Coordinator, host: str, port: int) -> str:
    """Ephemeral autoscaler registration (ISSUE 12) — dies with the
    control loop, so a crashed autoscaler never shadows a new one."""
    path = f"{AUTOSCALER_BASE}/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path


def get_autoscalers(coord: Coordinator) -> List[NodeInfo]:
    return _nodes_under(coord, AUTOSCALER_BASE)
