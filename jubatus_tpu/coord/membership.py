"""Cluster registry path schema (≙ common/membership.{hpp,cpp}).

Same tree as the reference (membership.hpp:32-36, membership.cpp:59-66):

    /jubatus/actors/<type>/<name>/nodes/<ip>_<port>     all booted servers
    /jubatus/actors/<type>/<name>/actives/<ip>_<port>   mix-current servers
    /jubatus/actors/<type>/<name>/master_lock           per-round mix master
    /jubatus/actors/<type>/<name>/id_generator          cluster id counter
    /jubatus/config/<type>/<name>                       engine JSON config
    /jubatus/supervisors/<ip>_<port>                    jubavisor daemons
    /jubatus/jubaproxies/<ip>_<port>                    proxies
"""

from __future__ import annotations

from typing import List

from jubatus_tpu.coord.base import Coordinator, NodeInfo

JUBATUS_BASE = "/jubatus"
ACTOR_BASE = f"{JUBATUS_BASE}/actors"
CONFIG_BASE = f"{JUBATUS_BASE}/config"
SUPERVISOR_BASE = f"{JUBATUS_BASE}/supervisors"
PROXY_BASE = f"{JUBATUS_BASE}/jubaproxies"


def actor_path(engine: str, name: str) -> str:
    return f"{ACTOR_BASE}/{engine}/{name}"


def config_path(engine: str, name: str) -> str:
    return f"{CONFIG_BASE}/{engine}/{name}"


def register_actor(
    coord: Coordinator, engine: str, name: str, host: str, port: int
) -> str:
    """Ephemeral registration under nodes/ (membership.cpp:68-112).
    Returns the node path so the caller can arm a suicide watcher."""
    path = f"{actor_path(engine, name)}/nodes/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path


def register_active(
    coord: Coordinator, engine: str, name: str, host: str, port: int
) -> str:
    """Join the actives list (membership.cpp:115-145) — proxies route only
    to actives; the mixer drives transitions on put_diff success/failure."""
    path = f"{actor_path(engine, name)}/actives/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path


def unregister_active(
    coord: Coordinator, engine: str, name: str, host: str, port: int
) -> bool:
    return coord.remove(
        f"{actor_path(engine, name)}/actives/{NodeInfo(host, port).name}"
    )


def _nodes_under(coord: Coordinator, path: str) -> List[NodeInfo]:
    out = []
    for child in coord.list(path):
        try:
            out.append(NodeInfo.from_name(child))
        except (ValueError, IndexError):
            continue
    return out


def get_all_nodes(coord: Coordinator, engine: str, name: str) -> List[NodeInfo]:
    """All booted members (membership get_all_nodes)."""
    return _nodes_under(coord, f"{actor_path(engine, name)}/nodes")


def get_all_actives(coord: Coordinator, engine: str, name: str) -> List[NodeInfo]:
    return _nodes_under(coord, f"{actor_path(engine, name)}/actives")


def register_proxy(coord: Coordinator, host: str, port: int) -> str:
    path = f"{PROXY_BASE}/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path


def register_supervisor(coord: Coordinator, host: str, port: int) -> str:
    path = f"{SUPERVISOR_BASE}/{NodeInfo(host, port).name}"
    coord.create(path, ephemeral=True)
    return path
