"""In-memory coordinator — the test/standalone backend.

One process-wide store; each `MemoryCoordinator` instance is a *session*
(ephemeral nodes die with the instance), so multi-node logic (membership,
master locks, suicide watchers) is testable in-process — the ZK mock the
reference never wrote (common/zk.hpp:36 TODO).
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

from jubatus_tpu.coord.base import Coordinator


class _Store:
    """Shared node tree: path → (payload, owner_session_or_None)."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.nodes: Dict[str, Tuple[bytes, Optional[int]]] = {"/": (b"", None)}
        self.locks: Dict[str, int] = {}  # lock path → owner session
        self.counters: Dict[str, int] = {}
        self.seq = 0
        #: hi-lo reservations already journaled (coord/journal.py); minting
        #: below the reservation needs no IO
        self.counter_res: Dict[str, int] = {}
        self.seq_res = 0
        #: durable-mutation hook (set by CoordServer to the journal's
        #: append); called UNDER the store lock so record order matches
        #: mutation order
        self.on_durable: Optional[Callable[[tuple], None]] = None
        self.child_watchers: Dict[str, List[Callable[[str], None]]] = {}
        self.delete_watchers: Dict[str, List[Callable[[str], None]]] = {}

    def durable(self, rec: tuple) -> None:
        if self.on_durable is not None:
            self.on_durable(rec)

    def next_seq(self) -> int:
        n = self.seq
        self.seq += 1
        if self.seq > self.seq_res and self.on_durable is not None:
            from jubatus_tpu.coord.journal import RESERVE_BLOCK

            self.seq_res = self.seq + RESERVE_BLOCK
            self.on_durable(("seq", self.seq_res))
        return n

    def fire_child(self, parent: str) -> None:
        for fn in list(self.child_watchers.get(parent, ())):
            try:
                fn(parent)
            except Exception:  # noqa: BLE001 — watcher errors are theirs
                pass

    def fire_delete(self, path: str) -> None:
        for fn in list(self.delete_watchers.get(path, ())):
            try:
                fn(path)
            except Exception:  # noqa: BLE001
                pass


def _parent(path: str) -> str:
    return path.rsplit("/", 1)[0] or "/"


class MemoryCoordinator(Coordinator):
    _shared: Optional[_Store] = None
    _shared_lock = threading.Lock()
    _session_ids = itertools.count(1)

    def __init__(self, store: Optional[_Store] = None) -> None:
        self._store = store if store is not None else _Store()
        self._session = next(self._session_ids)
        self._closed = False

    @classmethod
    def shared(cls) -> "MemoryCoordinator":
        """A new session on the process-wide shared store."""
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = _Store()
            return cls(cls._shared)

    @classmethod
    def reset_shared(cls) -> None:
        with cls._shared_lock:
            cls._shared = None

    # -- helpers -------------------------------------------------------------
    def _mkparents(self, path: str) -> None:
        parts = path.strip("/").split("/")
        cur = ""
        for p in parts[:-1]:
            cur += "/" + p
            self._store.nodes.setdefault(cur, (b"", None))

    # -- node CRUD -----------------------------------------------------------
    # Watchers always fire AFTER the store lock is released: a suicide
    # watcher may call EngineServer.stop() which joins threads that are
    # themselves blocked on coordinator reads — firing under the lock would
    # deadlock them.

    def create(self, path: str, payload: bytes = b"", ephemeral: bool = False) -> bool:
        with self._store.lock:
            # a closed session must not leave ephemerals behind: close()
            # already swept its nodes, so anything created after would be
            # orphaned forever (dead member stuck in the registry)
            if ephemeral and self._closed:
                return False
            if path in self._store.nodes:
                return False
            self._mkparents(path)
            owner = self._session if ephemeral else None
            self._store.nodes[path] = (payload, owner)
            if owner is None:
                self._store.durable(("c", path, payload))
        self._store.fire_child(_parent(path))
        return True

    def create_seq(self, path: str, payload: bytes = b"") -> Optional[str]:
        with self._store.lock:
            if self._closed:
                return None
            actual = f"{path}{self._store.next_seq():010d}"
            self._mkparents(actual)
            self._store.nodes[actual] = (payload, self._session)
        self._store.fire_child(_parent(actual))
        return actual

    def set(self, path: str, payload: bytes) -> bool:
        created = False
        with self._store.lock:
            if path not in self._store.nodes:
                self._mkparents(path)
                self._store.nodes[path] = (payload, None)
                created = True
                self._store.durable(("c", path, payload))
            else:
                _, owner = self._store.nodes[path]
                self._store.nodes[path] = (payload, owner)
                if owner is None:
                    self._store.durable(("c", path, payload))
        if created:
            self._store.fire_child(_parent(path))
        return True

    def read(self, path: str) -> Optional[bytes]:
        with self._store.lock:
            node = self._store.nodes.get(path)
            return node[0] if node else None

    def remove(self, path: str) -> bool:
        with self._store.lock:
            node = self._store.nodes.pop(path, None)
            if node is None:
                return False
            if node[1] is None:
                self._store.durable(("r", path))
        self._store.fire_delete(path)
        self._store.fire_child(_parent(path))
        return True

    def exists(self, path: str) -> bool:
        with self._store.lock:
            return path in self._store.nodes

    def list(self, path: str) -> List[str]:
        with self._store.lock:
            prefix = path.rstrip("/") + "/"
            out: Set[str] = set()
            for p in self._store.nodes:
                if p.startswith(prefix):
                    out.add(p[len(prefix) :].split("/", 1)[0])
            return sorted(out)

    # -- watchers ------------------------------------------------------------
    def watch_children(self, path: str, fn: Callable[[str], None]) -> None:
        with self._store.lock:
            self._store.child_watchers.setdefault(path, []).append(fn)

    def watch_delete(self, path: str, fn: Callable[[str], None]) -> None:
        with self._store.lock:
            self._store.delete_watchers.setdefault(path, []).append(fn)

    # -- locks ---------------------------------------------------------------
    def try_lock(self, path: str) -> bool:
        with self._store.lock:
            if self._closed:
                return False  # a dead session's lock would never release
            if path in self._store.locks:
                return self._store.locks[path] == self._session
            self._store.locks[path] = self._session
            return True

    def unlock(self, path: str) -> bool:
        with self._store.lock:
            if self._store.locks.get(path) == self._session:
                del self._store.locks[path]
                return True
            return False

    # -- ids -----------------------------------------------------------------
    def create_id(self, path: str) -> int:
        with self._store.lock:
            nxt = self._store.counters.get(path, 0) + 1
            self._store.counters[path] = nxt
            if nxt > self._store.counter_res.get(path, 0) \
                    and self._store.on_durable is not None:
                from jubatus_tpu.coord.journal import RESERVE_BLOCK

                hi = nxt + RESERVE_BLOCK
                self._store.counter_res[path] = hi
                self._store.on_durable(("cnt", path, hi))
            return nxt

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._store.lock:
            mine = [
                p
                for p, (_, owner) in self._store.nodes.items()
                if owner == self._session
            ]
            for p in mine:
                del self._store.nodes[p]
            held = [p for p, s in self._store.locks.items() if s == self._session]
            for p in held:
                del self._store.locks[p]
        # fire watchers outside the node mutation loop
        for p in mine:
            self._store.fire_delete(p)
            self._store.fire_child(_parent(p))
