"""Self-tuning performance plane (ISSUE 20): close the loop from
telemetry to knobs.

The repo measures everything — per-phase mix timings, coalescer
arrival/queue gauges, ``mix.premix_divergence_max``, EF residual drift —
but every performance knob was a static flag an operator re-picks per
fleet shape; per EQuARX (PAPERS.md) the wrong wire default alone costs
2–4x, and the TensorFlow paper's lesson is that runtime tuning decisions
belong in the system, not the launch script. This module rides the
shared controller core (coord/controller.py, the machinery the
autoscaler proved) and points it at three knob families:

- **mix plane** (:class:`MixPlanCore`): picks the wire mode
  (``off|bf16|int8``) and psum chunk size per process by hill-climbing
  on the MEASURED round time — the same quantity
  ``bench_mix_chunk_sweep`` hand-optimizes — with the measured ship
  fraction ordering the probes (a ship-dominated round tries the
  compression ladder first) and ``mix.ef_residual_drift_rate`` as the
  int8 guardrail (drifting residuals blacklist int8 and step back to
  bf16). Actuation is ``CollectiveMixer.set_wire_plan``: the plan rides
  the prepare signature, so a fleet applying a change
  non-simultaneously falls back to the RPC mix for at most one round
  per transition — never a wedged collective.
- **coalescer** (:class:`CoalescerCore`): adapts each microbatch
  queue's ``max_batch`` to the trailing arrival rate via a Little's-law
  residency target (depth ≈ arrival × target residency), bounded
  multiplicative steps, never below 1.
- **async-mix cadence** (:class:`CadenceCore`): speeds fold ticks when
  ``mix.premix_divergence_max`` runs hot, relaxes them when quiescent,
  inside an operator-set floor/ceiling.

All three run off the existing telemetry tick (one thread owns all
periodic observability work), journal every decision through
:class:`~jubatus_tpu.coord.controller.ControllerLoop` (evented,
timeline-visible, ``jubactl -c tune`` renders state + journal), and obey
the ``--auto-tune {off,observe,on}`` ladder — ``observe`` journals
dry-run recommendations without touching a knob. Actuations run through
the fault sites ``tune.{mix,coalescer,cadence}.apply``; a failing apply
journals ``blocked`` and backs off exponentially, and because cores
advance their internal plan only on COMMIT (after a successful apply),
a failed actuation never leaves the tuner's belief diverged from the
fleet's actual knobs.

Every knob default lives in :data:`TUNER_DEFAULTS` — the codestyle gate
(tools/codestyle) bans new hard-coded knob constants in tuner-actuated
paths outside this table.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from jubatus_tpu.coord.controller import ControllerLoop, StreakGate
from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

__all__ = ["TUNER_DEFAULTS", "TunerConfig", "MixPlanCore", "CoalescerCore",
           "CadenceCore", "PerfTuner", "ServerTuneAdapter"]

#: THE defaults table: every tuner-actuated knob's ladder, bound, and
#: step lives here (and only here — the codestyle gate bans new
#: hard-coded knob constants in the actuated paths). Values are the
#: TunerConfig defaults; flags/config override per fleet.
TUNER_DEFAULTS: Dict[str, Any] = {
    # mix plane
    "chunk_ladder_mb": (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
    "wire_ladder": ("off", "bf16", "int8"),
    "improve_margin": 0.05,     # a move must win by 5% to displace best
    "settle_rounds": 2,         # rounds measured before judging a plan
    "ef_drift_max": 1e-3,       # int8 guardrail: residual norm growth/round
    # coalescer (Little's law: depth = arrival_rate x residency target)
    "residency_target_s": 0.05,
    "depth_floor": 1,
    "depth_ceiling": 65536,
    "depth_step_max": 2.0,      # max multiplicative step per decision
    "depth_band": 0.5,          # dead band: act only past +/-50% deviation
    # async-mix cadence
    "interval_floor_s": 1.0,
    "interval_ceiling_s": 120.0,
    "cadence_step": 2.0,        # halve/double per decision
    "divergence_hot": 0.25,
    "divergence_cold": 0.02,
    # controller
    "confirm": 2,
    "cooldown_s": 30.0,
    "backoff_initial_s": 2.0,
    "backoff_max_s": 60.0,
    "journal_capacity": 256,
}


@dataclass
class TunerConfig:
    """--auto-tune configuration. ``mode``: ``off`` (tuner absent),
    ``observe`` (journal recommendations, touch nothing), ``on``
    (actuate). Everything else defaults from :data:`TUNER_DEFAULTS`."""

    mode: str = "off"
    confirm: int = TUNER_DEFAULTS["confirm"]
    cooldown_s: float = TUNER_DEFAULTS["cooldown_s"]
    backoff_initial_s: float = TUNER_DEFAULTS["backoff_initial_s"]
    backoff_max_s: float = TUNER_DEFAULTS["backoff_max_s"]
    journal_capacity: int = TUNER_DEFAULTS["journal_capacity"]
    chunk_ladder: Tuple[float, ...] = TUNER_DEFAULTS["chunk_ladder_mb"]
    wire_ladder: Tuple[str, ...] = TUNER_DEFAULTS["wire_ladder"]
    improve_margin: float = TUNER_DEFAULTS["improve_margin"]
    settle_rounds: int = TUNER_DEFAULTS["settle_rounds"]
    ef_drift_max: float = TUNER_DEFAULTS["ef_drift_max"]
    residency_target_s: float = TUNER_DEFAULTS["residency_target_s"]
    depth_floor: int = TUNER_DEFAULTS["depth_floor"]
    depth_ceiling: int = TUNER_DEFAULTS["depth_ceiling"]
    depth_step_max: float = TUNER_DEFAULTS["depth_step_max"]
    depth_band: float = TUNER_DEFAULTS["depth_band"]
    interval_floor_s: float = TUNER_DEFAULTS["interval_floor_s"]
    interval_ceiling_s: float = TUNER_DEFAULTS["interval_ceiling_s"]
    cadence_step: float = TUNER_DEFAULTS["cadence_step"]
    divergence_hot: float = TUNER_DEFAULTS["divergence_hot"]
    divergence_cold: float = TUNER_DEFAULTS["divergence_cold"]

    def __post_init__(self) -> None:
        if self.mode not in ("off", "observe", "on"):
            raise ValueError(f"auto-tune mode must be off|observe|on, "
                             f"got {self.mode!r}")
        if self.interval_floor_s > self.interval_ceiling_s:
            raise ValueError("tune interval floor exceeds ceiling")
        if self.depth_floor < 1:
            raise ValueError("depth floor must be >= 1")


Plan = Tuple[str, float]  # (wire mode, chunk MB)


class MixPlanCore:
    """Pure hill-climb over the (wire mode, chunk MB) plan grid, scored
    by measured round milliseconds — the same quantity the hand sweep
    (tools/bench_mix_chunk_sweep.py) records, which is why the tuned
    fleet converges toward the swept optimum instead of a proxy's.

    Propose-then-commit: ``observe()`` returns a proposal; the owner
    actuates it and calls ``commit()`` only on success. A failed apply
    (or observe mode) therefore never advances this core's belief about
    the live plan. ``observe()`` folds ``settle_rounds`` round times
    into one median score per plan, probes the unscored neighbors of
    the best-known plan (wire moves first when the round is
    ship-dominated — the wire is the bottleneck, EQuARX's 2–4x lever),
    and settles on the best plan once the neighborhood is exhausted.
    The EF-drift guardrail blacklists int8 the moment residual norms
    grow faster than ``ef_drift_max`` per round and steps back down the
    wire ladder."""

    def __init__(self, cfg: TunerConfig, mode: str = "off",
                 chunk_mb: float = 8.0) -> None:
        self.cfg = cfg
        self.plan: Plan = (mode, float(chunk_mb))
        #: plan -> best settled median round ms
        self.scores: Dict[Plan, float] = {}
        self._samples: List[float] = []
        self.int8_blacklisted = False
        self.trials = 0
        self.converged = False

    # -- internals -----------------------------------------------------------
    def _wires(self) -> List[str]:
        return [w for w in self.cfg.wire_ladder
                if not (self.int8_blacklisted and w == "int8")]

    def _neighbors(self, plan: Plan,
                   ship_frac: Optional[float]) -> List[Plan]:
        mode, chunk = plan
        wires = self._wires()
        ladder = list(self.cfg.chunk_ladder)
        wire_moves: List[Plan] = []
        if mode in wires:
            wi = wires.index(mode)
            if wi + 1 < len(wires):
                wire_moves.append((wires[wi + 1], chunk))
            if wi > 0:
                wire_moves.append((wires[wi - 1], chunk))
        elif wires:
            wire_moves.append((wires[0], chunk))
        chunk_moves: List[Plan] = []
        if chunk in ladder:
            ci = ladder.index(chunk)
            if ci + 1 < len(ladder):
                chunk_moves.append((mode, ladder[ci + 1]))
            if ci > 0:
                chunk_moves.append((mode, ladder[ci - 1]))
        else:
            # operator started off-ladder (env override): probe the
            # nearest rungs in each direction
            up = [c for c in ladder if c > chunk]
            dn = [c for c in ladder if c < chunk]
            if up:
                chunk_moves.append((mode, up[0]))
            if dn:
                chunk_moves.append((mode, dn[-1]))
        if ship_frac is not None and ship_frac >= 0.5:
            return wire_moves + chunk_moves
        return chunk_moves + wire_moves

    def best(self) -> Optional[Plan]:
        if not self.scores:
            return None
        return min(self.scores, key=lambda p: self.scores[p])

    def _next_probe(self, ship_frac: Optional[float]) -> Optional[Plan]:
        best = self.best()
        if best is None:
            return None
        for nb in self._neighbors(best, ship_frac):
            if nb not in self.scores:
                return nb
        return None

    # -- the decision step ---------------------------------------------------
    def observe(self, round_ms: float, ef_drift: Optional[float] = None,
                ship_frac: Optional[float] = None
                ) -> Optional[Dict[str, Any]]:
        """Fold one measured round; return a proposal dict
        ``{action, plan, reason}`` or None (hold)."""
        cfg = self.cfg
        mode, chunk = self.plan
        if mode == "int8" and ef_drift is not None \
                and ef_drift > cfg.ef_drift_max:
            # guardrail: quantization error is accumulating faster than
            # error feedback telescopes it away — int8 is off the table
            # until restart, and the plan steps back down the wire ladder
            self.int8_blacklisted = True
            self.scores = {p: s for p, s in self.scores.items()
                           if p[0] != "int8"}
            self._samples = []
            self.converged = False
            wires = self._wires()
            fallback = wires[-1] if wires else "off"
            return {"action": "retune", "plan": (fallback, chunk),
                    "reason": "ef_drift_guardrail"}
        self._samples.append(float(round_ms))
        if len(self._samples) < cfg.settle_rounds:
            return None
        score = sorted(self._samples)[len(self._samples) // 2]
        self._samples = []
        prev = self.scores.get(self.plan)
        self.scores[self.plan] = score if prev is None else min(prev, score)
        probe = self._next_probe(ship_frac)
        if probe is not None:
            return {"action": "probe", "plan": probe, "reason": "hill_climb"}
        best = self.best()
        self.converged = True
        if best is not None and best != self.plan and \
                self.scores[self.plan] > \
                self.scores[best] * (1.0 + cfg.improve_margin):
            return {"action": "retune", "plan": best,
                    "reason": "settle_on_best"}
        return None

    def commit(self, plan: Plan) -> None:
        """The proposal was successfully actuated: advance the belief.
        New plan, fresh sample window; probing may resume (a commit can
        open an unscored neighborhood)."""
        self.plan = (plan[0], float(plan[1]))
        self._samples = []
        self.trials += 1
        self.converged = False

    def state(self) -> Dict[str, Any]:
        best = self.best()
        return {"wire": self.plan[0], "chunk_mb": self.plan[1],
                "trials": self.trials, "converged": self.converged,
                "int8_blacklisted": self.int8_blacklisted,
                "plans_scored": len(self.scores),
                "best_wire": best[0] if best else None,
                "best_chunk_mb": best[1] if best else None,
                "best_ms": round(self.scores[best], 3) if best else None}


class CoalescerCore(StreakGate):
    """Little's-law depth controller for one microbatch queue: target
    depth ≈ arrival rate × residency target, a dead band suppresses
    noise, steps are bounded multiplicatively, and the floor is never
    below 1 (a zero depth would wedge every submit). Idle queues
    (arrival 0) hold — shrinking an idle queue's depth would punish the
    next burst for the quiet period."""

    def __init__(self, cfg: TunerConfig) -> None:
        StreakGate.__init__(self, cfg.confirm, cfg.confirm, cfg.cooldown_s)
        self.cfg = cfg

    def observe(self, now: float, arrival_per_sec: float,
                depth: int) -> Optional[Dict[str, Any]]:
        cfg = self.cfg
        target = arrival_per_sec * cfg.residency_target_s
        target = min(max(target, float(cfg.depth_floor)),
                     float(cfg.depth_ceiling))
        hot = target > depth * (1.0 + cfg.depth_band)
        cold = arrival_per_sec > 0.0 and \
            target < depth * (1.0 - cfg.depth_band) and \
            depth > cfg.depth_floor
        self.step(hot, cold)
        if self.in_cooldown(now):
            return None
        if hot and self.hot_confirmed:
            new = int(round(min(target, depth * cfg.depth_step_max)))
            new = max(1, min(new, cfg.depth_ceiling))
            if new <= depth:
                return None
            self.fired_hot(now)
            return {"action": "deepen", "depth": new,
                    "target": round(target, 1)}
        if cold and self.cold_confirmed:
            new = int(round(max(target, depth / cfg.depth_step_max)))
            new = max(1, cfg.depth_floor, new)
            if new >= depth:
                return None
            self.fired_cold(now)
            return {"action": "shallow", "depth": new,
                    "target": round(target, 1)}
        return None


class CadenceCore(StreakGate):
    """Async-mix cadence controller: fold faster while replicas diverge
    (``mix.premix_divergence_max`` hot), relax toward the ceiling when
    quiescent — inside the operator's floor/ceiling."""

    def __init__(self, cfg: TunerConfig) -> None:
        StreakGate.__init__(self, cfg.confirm, cfg.confirm, cfg.cooldown_s)
        self.cfg = cfg

    def observe(self, now: float, divergence: float,
                interval_sec: float) -> Optional[Dict[str, Any]]:
        cfg = self.cfg
        hot = divergence >= cfg.divergence_hot
        cold = divergence <= cfg.divergence_cold
        self.step(hot, cold)
        if self.in_cooldown(now):
            return None
        if hot and self.hot_confirmed and \
                interval_sec > cfg.interval_floor_s:
            new = max(cfg.interval_floor_s,
                      interval_sec / cfg.cadence_step)
            self.fired_hot(now)
            return {"action": "quicken", "interval_sec": round(new, 3),
                    "divergence": round(divergence, 6)}
        if cold and self.cold_confirmed and \
                interval_sec < cfg.interval_ceiling_s:
            new = min(cfg.interval_ceiling_s,
                      interval_sec * cfg.cadence_step)
            self.fired_cold(now)
            return {"action": "relax", "interval_sec": round(new, 3),
                    "divergence": round(divergence, 6)}
        return None


class PerfTuner(ControllerLoop):
    """The assembled loop: reads signals through an adapter (so tests
    and the regret bench drive it with synthetic fleets), runs the three
    cores, and actuates through the ``tune.*.apply`` fault sites with
    the shared journal/event/backoff discipline.

    The adapter duck-type::

        mix_signals()       -> dict | None   (rounds, round_ms, wire,
                                              chunk_mb, ef_drift, ship_frac)
        apply_mix(wire, chunk_mb)
        coalescer_signals() -> [dict]        (name, arrival_per_sec, depth)
        apply_coalescer(name, depth)
        cadence_signals()   -> dict | None   (divergence, interval_sec)
        apply_cadence(interval_sec)

    ``apply_*`` raise on failure; signal readers return None/[] when the
    corresponding plane does not exist on this server."""

    subsystem = "tune"

    def __init__(self, config: TunerConfig, adapter: Any,
                 registry: Optional[Registry] = None,
                 clock: Any = time.monotonic) -> None:
        ControllerLoop.__init__(self, config.journal_capacity, registry)
        self.config = config
        self.adapter = adapter
        self._clock = clock
        #: lazily seeded from the first mix signal (needs the live plan)
        self.mix: Optional[MixPlanCore] = None
        self.coalescers: Dict[str, CoalescerCore] = {}
        self.cadence = CadenceCore(config)
        self._last_mix_rounds = -1

    # -- ControllerLoop hooks ------------------------------------------------
    def _counter_suffix(self, action: str,
                        extra: Dict[str, Any]) -> Optional[str]:
        if action == "blocked":
            return "blocked"
        if action != "hold":
            return "applies"
        return None

    def _event_fields(self, signals: Dict[str, Any],
                      extra: Dict[str, Any]) -> Dict[str, Any]:
        return {"target": extra.get("target"),
                "dry_run": extra.get("dry_run") or None,
                "wire": signals.get("wire"),
                "chunk_mb": signals.get("chunk_mb"),
                "depth": signals.get("depth"),
                "interval_sec": signals.get("interval_sec")}

    def _gauge_signals(self, signals: Dict[str, Any]) -> None:
        v = signals.get("chunk_mb")
        if isinstance(v, (int, float)):
            self.registry.gauge("tune.mix.chunk_mb", float(v))
        w = signals.get("wire")
        if isinstance(w, str) and w in self.config.wire_ladder:
            # numeric so the time-series ring and SLO grammar can ride
            # it: the wire ladder index (0=off, 1=bf16, 2=int8)
            self.registry.gauge("tune.mix.wire_mode",
                                float(self.config.wire_ladder.index(w)))
        v = signals.get("depth")
        if isinstance(v, (int, float)):
            self.registry.gauge("tune.coalescer.max_batch", float(v))
        v = signals.get("interval_sec")
        if isinstance(v, (int, float)):
            self.registry.gauge("tune.cadence.interval_s", float(v))

    def _backoff_bounds(self) -> Tuple[float, float]:
        return self.config.backoff_initial_s, self.config.backoff_max_s

    # -- the tick ------------------------------------------------------------
    @property
    def dry_run(self) -> bool:
        return self.config.mode == "observe"

    def tick(self, now: Optional[float] = None) -> None:
        """One pass over all three planes; rides the server's telemetry
        tick. Never raises — a sick adapter must not kill the telemetry
        thread that owns every other periodic plane."""
        if self.config.mode == "off":
            return
        now = self._clock() if now is None else now
        if self.in_backoff(now):
            return
        for step in (self._tick_mix, self._tick_coalescers,
                     self._tick_cadence):
            try:
                step(now)
            except Exception:  # broad-ok — see docstring
                log.warning("perf tuner %s failed", step.__name__,
                            exc_info=True)
            if self.in_backoff(now):
                # an actuation just failed: stand down for the rest of
                # the tick instead of moving more knobs (a later success
                # would also clear the backoff the failure just armed)
                return

    def _tick_mix(self, now: float) -> None:
        sig = self.adapter.mix_signals()
        if not sig:
            return
        rounds = int(sig.get("rounds", 0))
        if rounds <= self._last_mix_rounds:
            return  # no new round measured since the last tick
        first = self._last_mix_rounds < 0
        self._last_mix_rounds = rounds
        if self.mix is None:
            self.mix = MixPlanCore(self.config,
                                   mode=sig.get("wire", "off"),
                                   chunk_mb=float(sig.get("chunk_mb", 8.0)))
        if first:
            return  # anchor only; the next round yields a clean sample
        proposal = self.mix.observe(float(sig.get("round_ms", 0.0)),
                                    ef_drift=sig.get("ef_drift"),
                                    ship_frac=sig.get("ship_frac"))
        if proposal is None:
            return
        wire, chunk = proposal["plan"]
        signals = {"round_ms": round(float(sig.get("round_ms", 0.0)), 3),
                   "wire": wire, "chunk_mb": chunk,
                   "from_wire": self.mix.plan[0],
                   "from_chunk_mb": self.mix.plan[1]}
        if self.dry_run:
            self.record(proposal["action"], proposal["reason"], signals,
                        now, dry_run=True, target="mix")
            return
        ok, _ = self.guarded(
            "tune.mix.apply",
            lambda: self.adapter.apply_mix(wire, chunk),
            reason=proposal["reason"], signals=signals, now=now,
            wanted=proposal["action"], target="mix")
        if ok:
            self.mix.commit((wire, chunk))
            self.record(proposal["action"], proposal["reason"], signals,
                        now, target="mix")

    def _tick_coalescers(self, now: float) -> None:
        for sig in self.adapter.coalescer_signals() or []:
            name = sig["name"]
            core = self.coalescers.get(name)
            if core is None:
                core = self.coalescers[name] = CoalescerCore(self.config)
            decision = core.observe(now,
                                    float(sig.get("arrival_per_sec", 0.0)),
                                    int(sig.get("depth", 1)))
            if decision is None:
                continue
            signals = {"coalescer": name, "depth": decision["depth"],
                       "from_depth": int(sig.get("depth", 1)),
                       "target": decision["target"],
                       "arrival_per_sec":
                           round(float(sig.get("arrival_per_sec", 0.0)), 1)}
            if self.dry_run:
                self.record(decision["action"], "littles_law", signals,
                            now, dry_run=True, target=name)
                continue
            depth = decision["depth"]
            ok, _ = self.guarded(
                "tune.coalescer.apply",
                lambda d=depth, n=name: self.adapter.apply_coalescer(n, d),
                reason="littles_law", signals=signals, now=now,
                wanted=decision["action"], target=name)
            if ok:
                self.record(decision["action"], "littles_law", signals,
                            now, target=name)

    def _tick_cadence(self, now: float) -> None:
        sig = self.adapter.cadence_signals()
        if not sig:
            return
        decision = self.cadence.observe(
            now, float(sig.get("divergence", 0.0)),
            float(sig.get("interval_sec", 0.0)))
        if decision is None:
            return
        signals = {"interval_sec": decision["interval_sec"],
                   "from_interval_sec":
                       round(float(sig.get("interval_sec", 0.0)), 3),
                   "divergence": decision["divergence"]}
        if self.dry_run:
            self.record(decision["action"], "divergence_band", signals,
                        now, dry_run=True, target="cadence")
            return
        sec = decision["interval_sec"]
        ok, _ = self.guarded(
            "tune.cadence.apply",
            lambda: self.adapter.apply_cadence(sec),
            reason="divergence_band", signals=signals, now=now,
            wanted=decision["action"], target="cadence")
        if ok:
            self.record(decision["action"], "divergence_band", signals,
                        now, target="cadence")

    # -- status --------------------------------------------------------------
    def status(self, last: int = 16) -> Dict[str, Any]:
        st: Dict[str, Any] = {"mode": self.config.mode}
        st.update(self.backoff_state())
        if self.mix is not None:
            st["mix"] = self.mix.state()
        if self.coalescers:
            st["coalescers"] = {n: c.gate_state()
                                for n, c in self.coalescers.items()}
        st["cadence"] = self.cadence.gate_state()
        st["journal"] = self.journal_tail(last)
        return st


class ServerTuneAdapter:
    """The production adapter: reads signals straight off an
    EngineServer's mixer/coalescers/registry and actuates the real
    knobs. Every reader degrades to None/[] when the plane is absent
    (standalone servers have no mixer; query-only servers may have no
    train coalescer)."""

    def __init__(self, server: Any) -> None:
        self._server = server

    # -- mix plane -----------------------------------------------------------
    def mix_signals(self) -> Optional[Dict[str, Any]]:
        mixer = getattr(self._server, "mixer", None)
        if mixer is None or not hasattr(mixer, "set_wire_plan"):
            return None
        sched = getattr(mixer, "_scheduler", None)
        if sched is None or sched.mix_count <= 0:
            return None
        from jubatus_tpu.parallel.collective import (DEFAULT_CHUNK_MB,
                                                     _norm_compress)

        phases = getattr(mixer, "last_phases", None) or {}
        ship_frac = None
        ship = phases.get("ship_ms")
        total = sum(float(phases.get(k) or 0.0)
                    for k in ("ship_ms", "reduce_ms", "readback_ms"))
        if isinstance(ship, (int, float)) and total > 0:
            ship_frac = float(ship) / total
        gauges = self._server.rpc.trace.gauges()
        chunk = mixer.chunk_mb
        return {
            "rounds": int(sched.mix_count),
            "round_ms": float(sched.last_mix_duration) * 1e3,
            "wire": _norm_compress(mixer.compress),
            "chunk_mb": float(DEFAULT_CHUNK_MB if chunk is None else chunk),
            "ef_drift": gauges.get("mix.ef_residual_drift_rate"),
            "ship_frac": ship_frac,
        }

    def apply_mix(self, wire: str, chunk_mb: float) -> None:
        self._server.mixer.set_wire_plan(chunk_mb=chunk_mb, compress=wire)

    # -- coalescer plane -----------------------------------------------------
    def coalescer_signals(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for name, co in (getattr(self._server, "coalescers", None)
                         or {}).items():
            if not (hasattr(co, "arrival_per_sec")
                    and hasattr(co, "set_max_batch")):
                continue
            out.append({"name": name,
                        "arrival_per_sec": co.arrival_per_sec(),
                        "depth": co.max_batch})
        return out

    def apply_coalescer(self, name: str, depth: int) -> None:
        co = (getattr(self._server, "coalescers", None) or {})[name]
        co.set_max_batch(depth)

    # -- cadence plane -------------------------------------------------------
    def cadence_signals(self) -> Optional[Dict[str, Any]]:
        mixer = getattr(self._server, "mixer", None)
        sched = getattr(mixer, "_scheduler", None)
        if sched is None:
            return None
        div = self._server.rpc.trace.gauges().get(
            "mix.premix_divergence_max")
        if div is None:
            return None
        return {"divergence": float(div),
                "interval_sec": float(sched.interval_sec)}

    def apply_cadence(self, interval_sec: float) -> None:
        self._server.mixer._scheduler.set_interval(interval_sec)
