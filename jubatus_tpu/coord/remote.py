"""Remote coordinator client — sessions on the coordination service
(coord/server.py), selected by a ``tcp://host:port`` locator.

Semantics match the ZooKeeper client the reference uses (common/zk.cpp):

- ephemeral nodes and locks belong to a server-side session kept alive by
  a heartbeat thread (lease/3 cadence, ≙ ZK ticks);
- on heartbeat failure or an expired-session reply the client first tries
  to RESUME: re-open a session and re-create its ephemerals from the
  local registry, retrying for ``resume_window_sec`` (3 leases). This is
  what lets a journaled coordd (coord/server.py --journal) restart
  without losing cluster membership — the reference instead suicides on
  ZK session expiry and relies on jubavisor to respawn the process.
  Locks are NOT resumed (they were observably lost; holders re-acquire
  per round, linear_mixer master_lock semantics);
- only when resumption times out do my ephemerals count as gone
  cluster-wide: the client fires its delete watchers (→ the server's
  suicide watcher stops it) and closes, the same cleanup contract as the
  reference's connection-loss stack (zk push_cleanup(&shutdown_server),
  server_helper.cpp:56);
- watches are client-side polls (0.5 s): child watchers diff list(path),
  delete watchers poll exists(path) — the cached_zk/file-backend pattern.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Set

from jubatus_tpu.coord.base import Coordinator, CoordinatorError
from jubatus_tpu.rpc.client import RpcClient

log = logging.getLogger(__name__)

_WATCH_POLL_SEC = 0.5
_HEARTBEAT_FAILURE_LIMIT = 3


class RemoteCoordinator(Coordinator):
    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 resume_window_sec: Optional[float] = None) -> None:
        self.host = host
        self.port = port
        self._client = RpcClient(host, port, timeout)
        self._lock = threading.Lock()
        self._closed = False
        #: my live ephemerals (path → payload), re-created on session resume
        self._ephemerals: Dict[str, bytes] = {}
        try:
            sid, lease = self._client.call("coord_open")
        except Exception as e:
            raise CoordinatorError(
                f"cannot reach coordination service {host}:{port}: {e}") from e
        self._sid = int(sid)
        self.lease_sec = float(lease)
        self.resume_window_sec = (resume_window_sec
                                  if resume_window_sec is not None
                                  else 3.0 * self.lease_sec)
        self._child_watchers: Dict[str, List[Callable[[str], None]]] = {}
        self._child_snapshot: Dict[str, Set[str]] = {}
        self._delete_watchers: Dict[str, List[Callable[[str], None]]] = {}
        self._watch_thread: Optional[threading.Thread] = None
        #: set while the session is suspect (heartbeat failing / resuming):
        #: delete-watcher polls pause, or a poll racing the resume would
        #: see the restarted coordd before the ephemerals are re-created
        #: and suicide a healthy server
        self._suspect = threading.Event()
        self._hb_stop = threading.Event()
        self._hb = threading.Thread(target=self._heartbeat_loop, daemon=True,
                                    name="coord-remote-hb")
        self._hb.start()

    @classmethod
    def from_locator(cls, spec: str) -> "RemoteCoordinator":
        """"tcp://host:port" → client."""
        rest = spec[len("tcp://"):] if spec.startswith("tcp://") else spec
        host, _, port = rest.rpartition(":")
        if not host or not port.isdigit():
            raise CoordinatorError(f"bad coordinator locator {spec!r}")
        return cls(host, int(port))

    # -- session keepalive ----------------------------------------------------
    def _heartbeat_loop(self) -> None:
        failures = 0
        while not self._hb_stop.wait(self.lease_sec / 3):
            try:
                ok = self._client.call("coord_heartbeat", self._sid)
            except Exception:  # noqa: BLE001 — connection trouble
                self._suspect.set()
                failures += 1
                log.warning("coordinator heartbeat failed (%d/%d)",
                            failures, _HEARTBEAT_FAILURE_LIMIT)
                if failures >= _HEARTBEAT_FAILURE_LIMIT:
                    if self._try_resume():
                        failures = 0
                        self._suspect.clear()
                        continue
                    if self._closed:
                        return  # intentional shutdown, not a lost session
                    self._session_lost()
                    return
                continue
            if not ok:  # server says the session expired
                self._suspect.set()
                if self._try_resume():
                    failures = 0
                    self._suspect.clear()
                    continue
                if self._closed:
                    return
                self._session_lost()
                return
            failures = 0
            self._suspect.clear()

    def _try_resume(self) -> bool:
        """Re-establish the session after a coordd restart/expiry: open a
        fresh session and re-create my ephemerals, retrying for the resume
        window. True = resumed (heartbeating continues on the new sid)."""
        import time

        deadline = time.monotonic() + self.resume_window_sec
        old_sid = self._sid
        while not self._closed and time.monotonic() < deadline:
            try:
                sid, lease = self._client.call("coord_open")
            except Exception:  # noqa: BLE001 — coordd still down
                if self._hb_stop.wait(min(1.0, self.lease_sec / 3)):
                    return False
                continue
            try:
                # same coordd, old session still alive: free its ephemerals
                # so the re-creates below can't collide. Heartbeat-verify
                # first — after a coordd restart old_sid is unknown (or, if
                # ids could ever repeat, someone ELSE's session; coordd
                # mints from a random 63-bit space to make that impossible,
                # and this check keeps even a misconfigured store safe)
                if self._client.call("coord_heartbeat", old_sid):
                    self._client.call("coord_close", old_sid)
            except Exception:  # noqa: BLE001 — restarted coordd: no-op
                pass
            self._sid = int(sid)
            self.lease_sec = float(lease)
            with self._lock:
                mine = dict(self._ephemerals)
            ok = True
            for path, payload in mine.items():
                try:
                    if not self._client.call("coord_create", self._sid, path,
                                             payload, True):
                        # someone else now owns the path (e.g. a replacement
                        # node took my slot) — that is a real loss
                        ok = False
                except Exception:  # noqa: BLE001
                    ok = False
                    break
            if ok:
                log.warning("coordination session resumed (sid %d -> %d, "
                            "%d ephemerals re-created)",
                            old_sid, self._sid, len(mine))
                return True
            old_sid = self._sid  # free the half-resumed session next try
        return False

    def _session_lost(self) -> None:
        """My ephemerals are gone cluster-wide — run the cleanup contract:
        fire every delete watcher (suicide path), then close."""
        log.error("coordination session lost; firing delete watchers")
        with self._lock:
            # take ownership atomically: the watch loop pops under the same
            # lock, so no watcher can fire twice (once from each thread)
            taken = self._delete_watchers
            self._delete_watchers = {}
        watchers = [(p, fn) for p, fns in taken.items() for fn in fns]
        for path, fn in watchers:
            try:
                fn(path)
            except Exception:  # noqa: BLE001 — watcher errors are theirs
                log.exception("delete watcher failed for %s", path)
        self.close()

    # -- RPC plumbing ---------------------------------------------------------
    def _call(self, method: str, *args):
        if self._closed:
            raise CoordinatorError("coordinator session closed")
        return self._client.call(method, *args)

    # -- node CRUD ------------------------------------------------------------
    def create(self, path: str, payload: bytes = b"", ephemeral: bool = False) -> bool:
        ok = bool(self._call("coord_create", self._sid, path, payload,
                             ephemeral))
        if ok and ephemeral:
            with self._lock:
                self._ephemerals[path] = payload
        return ok

    def create_seq(self, path: str, payload: bytes = b"") -> Optional[str]:
        out = self._call("coord_create_seq", self._sid, path, payload)
        return out.decode() if isinstance(out, bytes) else out

    def set(self, path: str, payload: bytes) -> bool:
        return bool(self._call("coord_set", path, payload))

    def read(self, path: str) -> Optional[bytes]:
        out = self._call("coord_read", path)
        if out is None:
            return None
        return out if isinstance(out, bytes) else str(out).encode()

    def remove(self, path: str) -> bool:
        # drop the resume-registry entry only after the server confirms:
        # a failed RPC leaves the node alive server-side, and a later
        # session resume must still know to re-create/track it
        ok = bool(self._call("coord_remove", path))
        with self._lock:
            self._ephemerals.pop(path, None)
        return ok

    def exists(self, path: str) -> bool:
        return bool(self._call("coord_exists", path))

    def list(self, path: str) -> List[str]:
        return [c.decode() if isinstance(c, bytes) else c
                for c in self._call("coord_list", path)]

    # -- watchers (client-side polling) ---------------------------------------
    def _ensure_watch_thread(self) -> None:
        with self._lock:
            if self._watch_thread is not None:
                return
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True, name="coord-remote-watch")
        self._watch_thread.start()

    def _watch_loop(self) -> None:
        while not self._hb_stop.wait(_WATCH_POLL_SEC):
            with self._lock:
                child_paths = list(self._child_watchers)
                delete_paths = list(self._delete_watchers)
            for path in child_paths:
                try:
                    now = set(self.list(path))
                except Exception:  # noqa: BLE001 — transient; retry next tick
                    continue
                old = self._child_snapshot.get(path)
                self._child_snapshot[path] = now
                if old is not None and now != old:
                    with self._lock:
                        fns = list(self._child_watchers.get(path, ()))
                    for fn in fns:
                        try:
                            fn(path)
                        except Exception:  # noqa: BLE001
                            log.exception("child watcher failed for %s", path)
            for path in delete_paths:
                if self._suspect.is_set():
                    break  # session suspect: absence may be transient
                try:
                    alive = self.exists(path)
                except Exception:  # noqa: BLE001
                    continue
                if not alive:
                    # ZK semantics: watches only fire within a valid
                    # session. A vanished node + a dead session means a
                    # coordd restart the resume path will repair — firing
                    # here would suicide a healthy server.
                    try:
                        if not self._client.call("coord_heartbeat",
                                                 self._sid):
                            self._suspect.set()
                            continue
                    except Exception:  # noqa: BLE001
                        self._suspect.set()
                        continue
                    with self._lock:
                        fns = self._delete_watchers.pop(path, [])
                    for fn in fns:
                        try:
                            fn(path)
                        except Exception:  # noqa: BLE001
                            log.exception("delete watcher failed for %s", path)

    def watch_children(self, path: str, fn: Callable[[str], None]) -> None:
        with self._lock:
            self._child_watchers.setdefault(path, []).append(fn)
        try:
            self._child_snapshot.setdefault(path, set(self.list(path)))
        except Exception:  # noqa: BLE001 — first poll will seed it
            pass
        self._ensure_watch_thread()

    def watch_delete(self, path: str, fn: Callable[[str], None]) -> None:
        with self._lock:
            self._delete_watchers.setdefault(path, []).append(fn)
        self._ensure_watch_thread()

    # -- locks / ids -----------------------------------------------------------
    def try_lock(self, path: str) -> bool:
        return bool(self._call("coord_try_lock", self._sid, path))

    def unlock(self, path: str) -> bool:
        return bool(self._call("coord_unlock", self._sid, path))

    def create_id(self, path: str) -> int:
        return int(self._call("coord_create_id", path))

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._hb_stop.set()
        try:
            self._client.call("coord_close", self._sid)
        except Exception:  # noqa: BLE001 — session will lease-expire anyway
            pass
        self._client.close()
