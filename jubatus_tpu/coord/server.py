"""Standalone coordination service — the framework's own ZooKeeper-role
daemon (``jubacoordd``).

The reference outsources membership/config/locks to a ZooKeeper quorum
(common/zk.cpp). This framework ships its own single-process coordination
server speaking the same MessagePack-RPC wire as everything else, so a
multi-host cluster needs no shared filesystem and no external system:

    python -m jubatus_tpu.coord.server -p 2199
    python -m jubatus_tpu.server classifier -z tcp://host:2199 -n c1

Sessions are leases: each remote client opens a session and heartbeats
every lease/3 s; a session silent for a full lease expires and its
ephemeral nodes and locks are released (ZK session-expiry semantics,
the failure detector of SURVEY.md §5). Every session is backed by a
MemoryCoordinator on one shared store, so node/lock/watch semantics are
identical to the in-process backend the tests use.

Durability (``--journal FILE``): persistent nodes (configs), id counters,
and the sequence counter journal to disk (coord/journal.py) and recover
on restart; ephemerals and locks die with their sessions, and clients
RESUME sessions across a coordd restart (coord/remote.py re-opens and
re-creates its ephemerals within the resume window), so a kill/restart
of coordd loses neither configs nor membership. Availability model: a
single process, like a one-node ZK — down during the restart (clients
retry), journaled against process death, not host loss; a quorum backend
remains possible behind the Coordinator ABC.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.rpc.server import RpcServer

log = logging.getLogger(__name__)

DEFAULT_LEASE_SEC = 10.0


class CoordServer:
    def __init__(self, lease_sec: float = DEFAULT_LEASE_SEC,
                 journal_path: Optional[str] = None) -> None:
        self.store = _Store()
        self.journal = None
        if journal_path:
            from jubatus_tpu.coord.journal import Journal

            self.journal = Journal(journal_path)
            n = self.journal.replay_into(self.store)
            if n:
                log.info("journal: recovered %d records from %s",
                         n, journal_path)
            self.journal.open_and_compact(self.store)
            self.store.on_durable = self.journal.append
        self.lease_sec = lease_sec
        self.rpc = RpcServer()
        self._mu = threading.Lock()
        #: session id → (session-scoped MemoryCoordinator, last heartbeat)
        self._sessions: Dict[int, Tuple[MemoryCoordinator, float]] = {}
        #: serves the sessionless ops (set/read/list/...) — never owns
        #: ephemerals or locks, so one shared instance is fine
        self._root = MemoryCoordinator(self.store)
        self._stop_event = threading.Event()
        self._reaper = threading.Thread(target=self._expire_loop, daemon=True,
                                        name="coord-expire")
        for name, fn, arity in [
            ("coord_open", self.open_session, 0),
            ("coord_heartbeat", self.heartbeat, 1),
            ("coord_close", self.close_session, 1),
            ("coord_create", self.create, 4),
            ("coord_create_seq", self.create_seq, 3),
            ("coord_set", self.set, 2),
            ("coord_read", self.read, 1),
            ("coord_remove", self.remove, 1),
            ("coord_exists", self.exists, 1),
            ("coord_list", self.list, 1),
            ("coord_try_lock", self.try_lock, 2),
            ("coord_unlock", self.unlock, 2),
            ("coord_create_id", self.create_id, 1),
        ]:
            self.rpc.register(name, fn, arity=arity)

    # -- session lifecycle ----------------------------------------------------
    def open_session(self) -> List:
        import secrets

        with self._mu:
            # random 63-bit ids: a restarted coordd must never mint a sid a
            # previous incarnation handed out — a client resuming across the
            # restart calls coord_close(old_sid), and with sequential ids
            # that could close ANOTHER client's fresh session (membership
            # flapping during recovery)
            while True:
                sid = secrets.randbits(63) or 1
                if sid not in self._sessions:
                    break
            self._sessions[sid] = (MemoryCoordinator(self.store),
                                   time.monotonic())
        log.info("session %d opened", sid)
        return [sid, self.lease_sec]

    def heartbeat(self, sid: int) -> bool:
        with self._mu:
            entry = self._sessions.get(int(sid))
            if entry is None:
                return False  # expired: client must treat this as fatal
            self._sessions[int(sid)] = (entry[0], time.monotonic())
            return True

    def close_session(self, sid: int) -> bool:
        with self._mu:
            entry = self._sessions.pop(int(sid), None)
        if entry is None:
            return False
        entry[0].close()  # drops ephemerals + locks, fires watchers
        log.info("session %d closed", sid)
        return True

    def _expire_loop(self) -> None:
        while not self._stop_event.wait(self.lease_sec / 3):
            horizon = time.monotonic() - self.lease_sec
            with self._mu:
                dead = [sid for sid, (_mc, hb) in self._sessions.items()
                        if hb < horizon]
                entries = [self._sessions.pop(sid) for sid in dead]
            for sid, (mc, _hb) in zip(dead, entries):
                log.warning("session %d expired (no heartbeat)", sid)
                mc.close()

    def _mc(self, sid: int) -> MemoryCoordinator:
        with self._mu:
            entry = self._sessions.get(int(sid))
        if entry is None:
            raise KeyError(f"unknown or expired session {sid}")
        return entry[0]

    # -- store operations ------------------------------------------------------
    def create(self, sid: int, path: str, payload: bytes, ephemeral: bool) -> bool:
        return self._mc(sid).create(path, payload or b"", bool(ephemeral))

    def create_seq(self, sid: int, path: str, payload: bytes) -> Optional[str]:
        return self._mc(sid).create_seq(path, payload or b"")

    def set(self, path: str, payload: bytes) -> bool:
        return self._root.set(path, payload or b"")

    def read(self, path: str) -> Optional[bytes]:
        return self._root.read(path)

    def remove(self, path: str) -> bool:
        return self._root.remove(path)

    def exists(self, path: str) -> bool:
        return self._root.exists(path)

    def list(self, path: str) -> List[str]:
        return self._root.list(path)

    def try_lock(self, sid: int, path: str) -> bool:
        return self._mc(sid).try_lock(path)

    def unlock(self, sid: int, path: str) -> bool:
        return self._mc(sid).unlock(path)

    def create_id(self, path: str) -> int:
        return self._root.create_id(path)

    # -- lifecycle ------------------------------------------------------------
    def start(self, port: int = 2199, host: str = "0.0.0.0") -> int:
        actual = self.rpc.serve_background(port, nthreads=4, host=host)
        self._reaper.start()
        log.info("coordination service listening on %s:%d (lease %.1fs)",
                 host, actual, self.lease_sec)
        return actual

    def join(self) -> None:
        self._stop_event.wait()

    def stop(self) -> None:
        self._stop_event.set()
        self.rpc.stop()
        with self._mu:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for mc, _hb in sessions:
            mc.close()
        if self.journal is not None:
            self.journal.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="jubatus_tpu.coord.server",
                                description="jubatus_tpu coordination service")
    p.add_argument("-p", "--rpc-port", type=int, default=2199)
    p.add_argument("-b", "--listen-addr", default="0.0.0.0")
    p.add_argument("--lease-sec", type=float, default=DEFAULT_LEASE_SEC)
    p.add_argument("--journal", default="",
                   help="journal durable state (configs, id counters) to "
                        "this file and recover it on restart")
    ns = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s [jubacoordd] %(message)s")
    srv = CoordServer(lease_sec=ns.lease_sec, journal_path=ns.journal or None)
    signal.signal(signal.SIGTERM, lambda *_: srv.stop())
    signal.signal(signal.SIGINT, lambda *_: srv.stop())
    srv.start(ns.rpc_port, ns.listen_addr)
    srv.join()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
