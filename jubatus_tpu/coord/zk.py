"""Real ZooKeeper backend for the Coordinator ABC (``zk://host:port``).

Existing jubatus deployments run a ZK quorum and drive it with jubactl
muscle memory (/root/reference/jubatus/server/common/zk.cpp:88-675);
drop-in parity needs this framework to join the SAME quorum. The image
ships no ZK client library, so this module speaks the ZooKeeper wire
protocol (jute serialization) directly over TCP — the subset the
reference uses: session handshake + pings, create (persistent /
ephemeral / sequence), delete, exists, getData, setData, getChildren,
one-shot watches (re-armed internally so the Coordinator ABC's
persistent-watch contract holds), and closeSession.

Semantics mapped onto the ABC:

- ``try_lock``: non-blocking ephemeral-create of the lock node (the
  reference zkmutex's try_lock is the same race: whoever creates the
  ephemeral wins; session death releases it, zk.hpp:126-139).
- ``create_id``: setData on the id node and use the returned stat
  version — each set bumps the version atomically, which is exactly how
  global_id_generator_zk mints ids (global_id_generator_zk.cpp:32-56).
- parents are auto-created (persistent) to honor the ABC contract; ZK
  itself requires explicit parents.

Connection model: one socket; a reader thread demultiplexes replies by
xid and delivers watch events (xid -1); a ping thread keeps the session
alive at timeout/3. Loss of the SOCKET is not loss of the SESSION: the
reader reconnects across the host list re-presenting sessionId+passwd
within the negotiated timeout — exactly libzookeeper's behavior
(reference zk.cpp:88 session watcher, zk.cpp:139-150 connect-wait) — and
the coordinator re-arms its watches, firing any delete that happened
while disconnected. In-flight calls during the gap fail with a retryable
connection-loss error (ZK cannot say whether they applied — same
contract as ZCONNECTIONLOSS). Only a server-side session expiry (resume
answered with session 0) fails all pending calls and fires delete
watchers (session-lost contract, same as coord/remote.py).

Tested against an in-process fake speaking the same wire
(tests/fake_zk.py) always, and against a REAL ZooKeeper when
``JUBATUS_TPU_ZK`` points at one (integration-gated like the
reference's --enable-zktest, wscript:138-139).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from jubatus_tpu.coord.base import Coordinator, CoordinatorError

log = logging.getLogger(__name__)

# ZooKeeper opcodes
OP_CREATE, OP_DELETE, OP_EXISTS, OP_GETDATA, OP_SETDATA = 1, 2, 3, 4, 5
OP_GETCHILDREN = 8
OP_PING, OP_CLOSE = 11, -11
XID_WATCH, XID_PING = -1, -2

# error codes (subset)
ZOK = 0
ZNONODE = -101
ZNODEEXISTS = -110
ZNOTEMPTY = -111
ZBADVERSION = -103
ZCONNECTIONLOSS = -4

# event types
EV_CREATED, EV_DELETED, EV_CHANGED, EV_CHILD = 1, 2, 3, 4

# create flags
F_EPHEMERAL, F_SEQUENCE = 1, 2

#: world:anyone ALL — the ACL the reference passes (ZOO_OPEN_ACL_UNSAFE)
_OPEN_ACL = (31, "world", "anyone")

#: event-queue sentinel: session resumed on a new socket, re-arm watches
_RECONNECTED = object()


class _Buf:
    """jute reader over a bytes span."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.off = 0

    def i32(self) -> int:
        v = struct.unpack_from(">i", self.data, self.off)[0]
        self.off += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.data, self.off)[0]
        self.off += 8
        return v

    def b(self) -> bool:
        v = self.data[self.off] != 0
        self.off += 1
        return v

    def buf(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        out = self.data[self.off:self.off + n]
        self.off += n
        return out

    def s(self) -> str:
        raw = self.buf()
        return raw.decode("utf-8") if raw is not None else ""

    def stat(self) -> Dict[str, int]:
        names = ("czxid", "mzxid", "ctime", "mtime")
        st = {k: self.i64() for k in names}
        st["version"] = self.i32()
        st["cversion"] = self.i32()
        st["aversion"] = self.i32()
        st["ephemeralOwner"] = self.i64()
        st["dataLength"] = self.i32()
        st["numChildren"] = self.i32()
        st["pzxid"] = self.i64()
        return st


def _s(out: List[bytes], v: str) -> None:
    raw = v.encode("utf-8")
    out.append(struct.pack(">i", len(raw)) + raw)


def _buf(out: List[bytes], v: Optional[bytes]) -> None:
    if v is None:
        out.append(struct.pack(">i", -1))
    else:
        out.append(struct.pack(">i", len(v)) + v)


class ZkError(CoordinatorError):
    def __init__(self, code: int, path: str = "") -> None:
        super().__init__(f"zookeeper error {code} ({path})")
        self.code = code


class _SessionExpired(Exception):
    """Resume handshake answered with session 0: ZK expired the session."""


class ZkConnection:
    """One ZK session over one socket; thread-safe request dispatch."""

    def __init__(self, hosts: List[Tuple[str, int]],
                 session_timeout_ms: int = 10000) -> None:
        self.hosts = hosts
        self.session_timeout_ms = session_timeout_ms
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._xid = 0
        self._xid_lock = threading.Lock()
        self._pending: Dict[int, Any] = {}  # xid -> [event, reply|None]
        self._pending_lock = threading.Lock()
        self._closed = False
        self.session_id = 0
        self._passwd = b"\x00" * 16
        #: set while a live socket carries the session; cleared during
        #: reconnect so call() can wait instead of failing spuriously
        self._up = threading.Event()
        self.on_event: Optional[Callable[[int, int, str], None]] = None
        self.on_session_lost: Optional[Callable[[], None]] = None
        #: fired (on the event-dispatch thread) after a successful
        #: in-session reconnect — the coordinator re-arms its watches here
        self.on_reconnected: Optional[Callable[[], None]] = None
        #: successful in-session reconnects (observability + tests)
        self.reconnect_count = 0
        #: events dispatch from their own thread — handlers re-arm watches
        #: with blocking calls, which would deadlock the reader (the reader
        #: is the only thread that can deliver those calls' replies)
        import queue

        self._events: "queue.Queue" = queue.Queue()
        self._connect()
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="zk-reader")
        self._reader.start()
        self._dispatcher = threading.Thread(target=self._event_loop,
                                            daemon=True, name="zk-events")
        self._dispatcher.start()
        self._pinger = threading.Thread(target=self._ping_loop, daemon=True,
                                        name="zk-ping")
        self._pinger.start()

    # -- wiring ---------------------------------------------------------------
    def _connect(self, resume: bool = False) -> None:
        """Establish a socket carrying this session. ``resume=True``
        re-presents sessionId+passwd (the reconnect path, ≙ libzookeeper's
        in-timeout reconnect, zk.cpp:139-150); raises _SessionExpired when
        ZK answers with session 0 — the session is genuinely gone."""
        last: Optional[Exception] = None
        for host, port in self.hosts:
            try:
                sock = socket.create_connection((host, port), timeout=10)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # ConnectRequest
                req = b"".join([
                    struct.pack(">i", 0),            # protocolVersion
                    struct.pack(">q", 0),            # lastZxidSeen
                    struct.pack(">i", self.session_timeout_ms),
                    struct.pack(">q", self.session_id if resume else 0),
                    struct.pack(">i", len(self._passwd) if resume else 16),
                    self._passwd if resume else b"\x00" * 16,
                ])
                sock.sendall(struct.pack(">i", len(req)) + req)
                resp = self._read_frame_from(sock)
                rb = _Buf(resp)
                rb.i32()                              # protocolVersion
                negotiated = rb.i32()
                sid = rb.i64()
                passwd = rb.buf()
                if negotiated <= 0 or sid == 0:
                    if resume:
                        sock.close()
                        raise _SessionExpired()
                    raise CoordinatorError("zookeeper rejected the session")
                self.negotiated_ms = negotiated
                self.session_id = sid
                if passwd:
                    self._passwd = passwd
                # the connect timeout must NOT persist: the reader blocks in
                # recv between pings (interval = negotiated/3, which may
                # exceed 10s), and a spurious socket.timeout there would
                # fire the session-lost suicide path on a healthy session
                sock.settimeout(None)
                self._sock = sock
                self._up.set()
                return
            except (OSError, struct.error, CoordinatorError) as e:
                last = e
                continue
        raise CoordinatorError(f"cannot reach zookeeper at {self.hosts}: {last}")

    @staticmethod
    def _read_frame_from(sock: socket.socket) -> bytes:
        hdr = b""
        while len(hdr) < 4:
            chunk = sock.recv(4 - len(hdr))
            if not chunk:
                raise OSError("zookeeper connection closed")
            hdr += chunk
        (n,) = struct.unpack(">i", hdr)
        body = b""
        while len(body) < n:
            chunk = sock.recv(n - len(body))
            if not chunk:
                raise OSError("zookeeper connection closed")
            body += chunk
        return body

    def _read_loop(self) -> None:
        while not self._closed:
            try:
                frame = self._read_frame_from(self._sock)
                rb = _Buf(frame)
                xid = rb.i32()
                rb.i64()  # zxid
                err = rb.i32()
                if xid == XID_WATCH:
                    ev_type = rb.i32()
                    state = rb.i32()
                    path = rb.s()
                    self._events.put((ev_type, state, path))
                    continue
                if xid == XID_PING:
                    continue
            except Exception:  # noqa: BLE001 — a corrupt/truncated frame
                # means the stream is unusable, exactly like a dead socket:
                # resume the session on a fresh connection or die loudly —
                # the reader must NEVER exit silently (call()s would all
                # time out and the suicide contract would never fire)
                if self._closed:
                    break
                log.warning("zookeeper stream error; reconnecting",
                            exc_info=True)
                if self._try_resume():
                    continue
                self._fail_all()
                return
            with self._pending_lock:
                slot = self._pending.pop(xid, None)
            if slot is not None:
                slot[1] = (err, rb)
                slot[0].set()

    def _try_resume(self) -> bool:
        """Socket died: reconnect across the host list with the existing
        session credentials before the server expires the session. True =
        the session lives on a fresh socket (watch re-arm is queued for
        the dispatcher); False = expired or out of time — session is lost."""
        import time as _time

        self._up.clear()
        try:
            self._sock.close()
        except OSError:
            pass
        # in-flight replies died with the socket; their outcome is unknown
        # (ZCONNECTIONLOSS semantics — the op may or may not have applied)
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for slot in pending:
            slot[1] = (ZCONNECTIONLOSS, None)
            slot[0].set()
        deadline = _time.monotonic() + self.negotiated_ms / 1000.0
        while not self._closed and _time.monotonic() < deadline:
            try:
                self._connect(resume=True)
            except _SessionExpired:
                log.error("zookeeper expired session 0x%x during reconnect",
                          self.session_id)
                return False
            except (CoordinatorError, OSError, struct.error):
                _time.sleep(0.2)
                continue
            log.warning("zookeeper session 0x%x resumed on a new socket",
                        self.session_id)
            self.reconnect_count += 1
            # dispatcher thread re-arms watches (blocking calls would
            # deadlock here: this IS the reader that delivers replies)
            self._events.put(_RECONNECTED)
            return True
        return False

    def _event_loop(self) -> None:
        while True:
            ev = self._events.get()
            if ev is None:
                return
            if ev is _RECONNECTED:
                if self.on_reconnected is not None:
                    try:
                        self.on_reconnected()
                    except Exception:  # noqa: BLE001
                        log.exception("zk reconnect re-arm failed")
                continue
            if self.on_event is not None:
                try:
                    self.on_event(*ev)
                except Exception:  # noqa: BLE001 — watcher's problem
                    log.exception("zk watch handler failed")

    def _fail_all(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._up.set()  # unblock call()s parked on the reconnect gate
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for slot in pending:
            slot[1] = (ZNONODE, None)  # delivered as session-lost below
            slot[0].set()
        self._events.put(None)  # stop the dispatcher
        if self.on_session_lost is not None:
            try:
                self.on_session_lost()
            except Exception:  # noqa: BLE001
                log.exception("zk session-lost handler failed")

    def _ping_loop(self) -> None:
        interval = max(self.negotiated_ms / 3000.0, 0.5)
        while not self._closed:
            threading.Event().wait(interval)
            if self._closed:
                return
            if not self._up.is_set():
                continue  # reconnect in progress; the reader owns recovery
            sock = self._sock  # the socket THIS ping used: shutting down
            # self._sock after a concurrent resume would kill the fresh one
            try:
                hdr = struct.pack(">ii", XID_PING, OP_PING)
                with self._wlock:
                    sock.sendall(struct.pack(">i", len(hdr)) + hdr)
            except OSError:
                # wake the reader (it may be blocked in recv on a socket
                # that only fails on write); it drives resume-or-die
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    # -- request plumbing -----------------------------------------------------
    def call(self, opcode: int, payload: bytes, timeout: float = 10.0):
        if self._closed:
            raise CoordinatorError("zookeeper session closed")
        if not self._up.wait(timeout):
            # mid-reconnect and it didn't come back in time
            raise CoordinatorError("zookeeper connection lost (reconnecting)")
        if self._closed:
            raise CoordinatorError("zookeeper session closed")
        with self._xid_lock:
            self._xid += 1
            xid = self._xid
        slot = [threading.Event(), None]
        with self._pending_lock:
            self._pending[xid] = slot
        frame = struct.pack(">ii", xid, opcode) + payload
        sock = self._sock  # shut down the socket WE failed on, never a
        # fresh one a concurrent resume may have installed
        try:
            with self._wlock:
                sock.sendall(struct.pack(">i", len(frame)) + frame)
        except OSError as e:
            # socket died under us: the reader notices and resumes the
            # session; THIS call's outcome is unknown (connection loss)
            with self._pending_lock:
                self._pending.pop(xid, None)
            try:
                sock.shutdown(socket.SHUT_RDWR)  # wake the reader
            except OSError:
                pass
            raise CoordinatorError(
                f"zookeeper connection lost during send: {e}") from e
        if not slot[0].wait(timeout):
            with self._pending_lock:
                self._pending.pop(xid, None)
            raise CoordinatorError("zookeeper request timed out")
        err, rb = slot[1]
        if rb is None:
            if err == ZCONNECTIONLOSS:
                raise CoordinatorError(
                    "zookeeper connection lost mid-request (outcome "
                    "unknown; session resuming)")
            raise CoordinatorError("zookeeper session lost")
        return err, rb

    def close(self) -> None:
        if self._closed:
            return
        try:
            frame = struct.pack(">ii", 0, OP_CLOSE)
            with self._wlock:
                self._sock.sendall(struct.pack(">i", len(frame)) + frame)
        except OSError:
            pass
        self._closed = True
        self._up.set()  # unblock call()s parked on the reconnect gate
        # fail any in-flight call immediately: a thread blocked in call()
        # must not sit out its full timeout reporting a bogus "timed out"
        # when the session was intentionally closed
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for slot in pending:
            slot[1] = (ZNONODE, None)
            slot[0].set()
        self._events.put(None)
        try:
            self._sock.close()
        except OSError:
            pass


class ZkCoordinator(Coordinator):
    """Coordinator ABC over a live ZooKeeper ensemble."""

    def __init__(self, hosts: List[Tuple[str, int]],
                 session_timeout_ms: int = 10000) -> None:
        self._conn = ZkConnection(hosts, session_timeout_ms)
        self._conn.on_event = self._on_event
        self._conn.on_session_lost = self._session_lost
        self._conn.on_reconnected = self._on_reconnected
        self._lock = threading.Lock()
        self._child_watchers: Dict[str, List[Callable[[str], None]]] = {}
        self._delete_watchers: Dict[str, List[Callable[[str], None]]] = {}
        self._held_locks: set = set()
        self._closed = False

    @classmethod
    def from_locator(cls, spec: str) -> "ZkCoordinator":
        """"zk://host:port[,host:port...]" → coordinator."""
        rest = spec[len("zk://"):] if spec.startswith("zk://") else spec
        hosts = []
        for part in rest.split(","):
            host, _, port = part.rpartition(":")
            if not host or not port.isdigit():
                raise CoordinatorError(f"bad zookeeper locator {spec!r}")
            hosts.append((host, int(port)))
        return cls(hosts)

    # -- watch re-arm machinery ----------------------------------------------
    def _on_event(self, ev_type: int, _state: int, path: str) -> None:
        # ZK watches are one-shot: re-arm BEFORE delivering so no change
        # slips between the event and the re-watch
        if ev_type == EV_CHILD or ev_type in (EV_CREATED, EV_DELETED):
            with self._lock:
                child_fns = list(self._child_watchers.get(path, ()))
            if child_fns:
                try:
                    if self._get_children(path, watch=True) is None:
                        # watched node deleted: a getChildren watch cannot
                        # arm on a missing node — fall back to an exists
                        # watch so recreation (EV_CREATED) re-enters here
                        # and restores the child watch
                        self._exists(path, watch=True)
                except CoordinatorError:
                    log.warning("child watch re-arm failed for %s "
                                "(will retry on next event)", path,
                                exc_info=True)
                for fn in child_fns:
                    try:
                        fn(path)
                    except Exception:  # noqa: BLE001
                        log.exception("child watcher failed for %s", path)
        if ev_type == EV_DELETED:
            with self._lock:
                del_fns = self._delete_watchers.pop(path, [])
            for fn in del_fns:
                try:
                    fn(path)
                except Exception:  # noqa: BLE001
                    log.exception("delete watcher failed for %s", path)
        elif ev_type in (EV_CREATED, EV_CHANGED):
            # a delete watch armed via exists() also fires on create/change;
            # re-arm it
            with self._lock:
                has_del = path in self._delete_watchers
            if has_del:
                try:
                    self._exists(path, watch=True)
                except CoordinatorError:
                    pass

    def _on_reconnected(self) -> None:
        """The session survived a socket loss on a fresh connection: ZK
        dropped our one-shot watches with the old socket, so re-arm every
        registered watch, and deliver anything that changed while we were
        away — a delete-watched node that vanished fires its handler NOW
        (the event itself is gone forever), and child watchers get one
        synthetic notification so membership readers resync."""
        from jubatus_tpu.utils import tracing

        tracing.count("zk.session.reconnects")
        with self._lock:
            child_paths = list(self._child_watchers)
            del_paths = list(self._delete_watchers)
        for p in del_paths:
            try:
                present = self._exists(p, watch=True) is not None
            except CoordinatorError:
                log.warning("delete-watch re-arm failed for %s", p,
                            exc_info=True)
                continue
            if not present:
                with self._lock:
                    fns = self._delete_watchers.pop(p, [])
                for fn in fns:
                    try:
                        fn(p)
                    except Exception:  # noqa: BLE001
                        log.exception("delete watcher failed for %s", p)
        for p in child_paths:
            try:
                if self._get_children(p, watch=True) is None:
                    self._exists(p, watch=True)
            except CoordinatorError:
                log.warning("child-watch re-arm failed for %s", p,
                            exc_info=True)
            with self._lock:
                fns = list(self._child_watchers.get(p, ()))
            for fn in fns:
                try:
                    fn(p)
                except Exception:  # noqa: BLE001
                    log.exception("child watcher failed for %s", p)

    def _session_lost(self) -> None:
        log.error("zookeeper session lost; firing delete watchers")
        from jubatus_tpu.utils import tracing

        tracing.count("zk.session.lost")
        with self._lock:
            taken = self._delete_watchers
            self._delete_watchers = {}
        for path, fns in taken.items():
            for fn in fns:
                try:
                    fn(path)
                except Exception:  # noqa: BLE001
                    log.exception("delete watcher failed for %s", path)

    # -- raw ops --------------------------------------------------------------
    def _create(self, path: str, payload: bytes, flags: int) -> Tuple[int, str]:
        out: List[bytes] = []
        _s(out, path)
        _buf(out, payload)
        perms, scheme, ident = _OPEN_ACL
        out.append(struct.pack(">i", 1))  # one ACL
        out.append(struct.pack(">i", perms))
        _s(out, scheme)
        _s(out, ident)
        out.append(struct.pack(">i", flags))
        err, rb = self._conn.call(OP_CREATE, b"".join(out))
        return err, (rb.s() if err == ZOK else "")

    def _mkparents(self, path: str) -> None:
        parts = path.strip("/").split("/")
        cur = ""
        for p in parts[:-1]:
            cur += "/" + p
            err, _ = self._create(cur, b"", 0)
            if err not in (ZOK, ZNODEEXISTS):
                raise ZkError(err, cur)

    def _exists(self, path: str, watch: bool = False) -> Optional[Dict]:
        out: List[bytes] = []
        _s(out, path)
        out.append(b"\x01" if watch else b"\x00")
        err, rb = self._conn.call(OP_EXISTS, b"".join(out))
        if err == ZNONODE:
            return None
        if err != ZOK:
            raise ZkError(err, path)
        return rb.stat()

    def _get_children(self, path: str,
                      watch: bool = False) -> Optional[List[str]]:
        """None = node absent (and, NB, no child watch armed — ZK refuses
        getChildren watches on missing nodes; callers that need to survive
        deletion must fall back to an exists watch)."""
        out: List[bytes] = []
        _s(out, path)
        out.append(b"\x01" if watch else b"\x00")
        err, rb = self._conn.call(OP_GETCHILDREN, b"".join(out))
        if err == ZNONODE:
            return None
        if err != ZOK:
            raise ZkError(err, path)
        n = rb.i32()
        return sorted(rb.s() for _ in range(n))

    # -- Coordinator ABC ------------------------------------------------------
    def create(self, path: str, payload: bytes = b"",
               ephemeral: bool = False) -> bool:
        self._mkparents(path)
        err, _ = self._create(path, payload,
                              F_EPHEMERAL if ephemeral else 0)
        if err == ZNODEEXISTS:
            return False
        if err != ZOK:
            raise ZkError(err, path)
        return True

    def create_seq(self, path: str, payload: bytes = b"") -> Optional[str]:
        self._mkparents(path)
        err, actual = self._create(path, payload, F_EPHEMERAL | F_SEQUENCE)
        if err != ZOK:
            raise ZkError(err, path)
        return actual

    def set(self, path: str, payload: bytes) -> bool:
        out: List[bytes] = []
        _s(out, path)
        _buf(out, payload)
        out.append(struct.pack(">i", -1))  # any version
        err, _ = self._conn.call(OP_SETDATA, b"".join(out))
        if err == ZNONODE:
            self._mkparents(path)
            cerr, _ = self._create(path, payload, 0)
            if cerr == ZOK:
                return True
            if cerr == ZNODEEXISTS:
                return self.set(path, payload)
            raise ZkError(cerr, path)
        if err != ZOK:
            raise ZkError(err, path)
        return True

    def read(self, path: str) -> Optional[bytes]:
        out: List[bytes] = []
        _s(out, path)
        out.append(b"\x00")
        err, rb = self._conn.call(OP_GETDATA, b"".join(out))
        if err == ZNONODE:
            return None
        if err != ZOK:
            raise ZkError(err, path)
        return rb.buf() or b""

    def remove(self, path: str) -> bool:
        out: List[bytes] = []
        _s(out, path)
        out.append(struct.pack(">i", -1))
        err, _ = self._conn.call(OP_DELETE, b"".join(out))
        if err == ZNONODE:
            return False
        if err == ZNOTEMPTY:
            # the ABC removes subtrees implicitly nowhere, but membership
            # cleanup may target non-empty dirs: refuse like ZK does
            raise ZkError(err, path)
        if err != ZOK:
            raise ZkError(err, path)
        return True

    def exists(self, path: str) -> bool:
        return self._exists(path) is not None

    def list(self, path: str) -> List[str]:
        return self._get_children(path) or []

    def watch_children(self, path: str, fn: Callable[[str], None]) -> None:
        with self._lock:
            self._child_watchers.setdefault(path, []).append(fn)
        # parents must exist for the watch to arm
        self._mkparents(path + "/x")
        err, _ = self._create(path, b"", 0)
        if err not in (ZOK, ZNODEEXISTS):
            raise ZkError(err, path)
        self._get_children(path, watch=True)

    def watch_delete(self, path: str, fn: Callable[[str], None]) -> None:
        with self._lock:
            self._delete_watchers.setdefault(path, []).append(fn)
        self._exists(path, watch=True)

    def try_lock(self, path: str) -> bool:
        with self._lock:
            if path in self._held_locks:
                return True
        self._mkparents(path)
        err, _ = self._create(path, b"", F_EPHEMERAL)
        if err == ZOK:
            with self._lock:
                self._held_locks.add(path)
            return True
        if err == ZNODEEXISTS:
            return False
        raise ZkError(err, path)

    def unlock(self, path: str) -> bool:
        with self._lock:
            if path not in self._held_locks:
                return False
        # attempt the remove FIRST and drop membership only once the node
        # is verifiably gone: discarding up front made a connection blip
        # during the remove wedge the lock forever — every retry saw the
        # path absent from _held_locks and returned False while the
        # ephemeral node survived the reconnect (session_grace keeps the
        # session alive). A remove that raises keeps membership, so the
        # caller's retry loop works across reconnects.
        removed = self.remove(path)
        with self._lock:
            self._held_locks.discard(path)
        # ZNONODE (removed False) means the node is already gone — the
        # lock is no longer held either way, so the release succeeded
        return True

    def create_id(self, path: str) -> int:
        # setData bumps the node version atomically — the version IS the
        # counter (global_id_generator_zk.cpp:32-56 uses the same trick)
        out: List[bytes] = []
        _s(out, path)
        _buf(out, b"")
        out.append(struct.pack(">i", -1))
        err, rb = self._conn.call(OP_SETDATA, b"".join(out))
        if err == ZNONODE:
            self._mkparents(path)
            cerr, _ = self._create(path, b"", 0)
            if cerr not in (ZOK, ZNODEEXISTS):
                raise ZkError(cerr, path)
            return self.create_id(path)
        if err != ZOK:
            raise ZkError(err, path)
        return rb.stat()["version"]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._conn.on_session_lost = None  # intentional close: no suicide
        self._conn.close()
