"""Core kernel library (the rebuild of the jubatus_core surface, SURVEY.md §2.9).

- ``datum``: the user-facing input record (string/num/binary key-values).
- ``fv``: the feature-vector converter — config-driven datum → weighted sparse
  feature vector, hashed into a fixed 2^k feature space (hashing trick) so the
  model plane is dense JAX arrays instead of string-keyed hash maps.
- ``sparse``: padded batched sparse-vector representation fed to XLA kernels.
"""

from jubatus_tpu.core.datum import Datum  # noqa: F401
from jubatus_tpu.core.sparse import SparseBatch, SparseVector  # noqa: F401
