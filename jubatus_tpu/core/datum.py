"""The datum type — the universal input record.

Equivalent of core::fv_converter::datum consumed throughout the reference
(client side mirror: /root/reference/jubatus/client/common/datum.hpp). A datum
is three lists of (key, value) pairs: string, numeric, and binary. On the wire
(MessagePack-RPC) it is the 3-tuple of those lists, which is the reference's
msgpack layout for datum.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Tuple


class Datum:
    """An input record: string, numeric and binary key-value pairs."""

    __slots__ = ("string_values", "num_values", "binary_values")

    def __init__(
        self,
        values: Any = None,
        *,
        string_values: Iterable[Tuple[str, str]] = (),
        num_values: Iterable[Tuple[str, float]] = (),
        binary_values: Iterable[Tuple[str, bytes]] = (),
    ) -> None:
        self.string_values: List[Tuple[str, str]] = list(string_values)
        self.num_values: List[Tuple[str, float]] = list(num_values)
        self.binary_values: List[Tuple[str, bytes]] = list(binary_values)
        if values is not None:
            # Convenience constructor: Datum({"age": 25, "name": "x"}) routes
            # each value to the right list by Python type.
            for k, v in (values.items() if isinstance(values, dict) else values):
                self.add(k, v)

    def add(self, key: str, value: Any) -> "Datum":
        if isinstance(value, bool):
            raise TypeError("datum values must be str, number, or bytes")
        if isinstance(value, str):
            self.string_values.append((key, value))
        elif isinstance(value, (int, float)):
            self.num_values.append((key, float(value)))
        elif isinstance(value, (bytes, bytearray)):
            self.binary_values.append((key, bytes(value)))
        else:
            raise TypeError(f"unsupported datum value type: {type(value)!r}")
        return self

    add_string = add
    add_number = add
    add_binary = add

    # -- wire format (msgpack tuple of three kv lists) ----------------------
    def to_msgpack(self):
        return (
            [list(kv) for kv in self.string_values],
            [list(kv) for kv in self.num_values],
            [list(kv) for kv in self.binary_values],
        )

    @classmethod
    def from_msgpack(cls, obj) -> "Datum":
        d = cls()
        if obj is None:
            return d
        sv = obj[0] if len(obj) > 0 else []
        nv = obj[1] if len(obj) > 1 else []
        bv = obj[2] if len(obj) > 2 else []

        def _s(x):
            return x.decode("utf-8", "replace") if isinstance(x, bytes) else x

        def _b(x):
            # legacy (pre-bin) clients pack binary values as old-raw, which
            # the transports decode with surrogateescape; re-encoding with
            # surrogateescape restores the exact original bytes
            if isinstance(x, str):
                return x.encode("utf-8", "surrogateescape")
            return x

        d.string_values = [(_s(k), _s(v)) for k, v in sv]
        d.num_values = [(_s(k), float(v)) for k, v in nv]
        d.binary_values = [(_s(k), _b(v)) for k, v in bv]
        return d

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Datum(string_values={self.string_values!r}, "
            f"num_values={self.num_values!r}, "
            f"binary_values=<{len(self.binary_values)} items>)"
        )

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Datum)
            and self.string_values == other.string_values
            and self.num_values == other.num_values
            and self.binary_values == other.binary_values
        )
