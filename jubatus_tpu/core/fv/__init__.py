"""Feature-vector converter (rebuild of core::fv_converter, SURVEY.md §2.9).

Pipeline: datum → (filters) → per-rule feature extraction → weighting
(sample_weight × global_weight) → combination features → hashed sparse vector
in a fixed 2^k index space.

The hashing trick replaces the reference's string-keyed sparse weight maps:
models become dense JAX arrays indexed by feature hash, which is what lets
updates run as XLA scatter/gather kernels and lets mix run as a psum.
"""

from jubatus_tpu.core.fv.converter import (  # noqa: F401
    ConverterConfig,
    DatumToFVConverter,
    make_fv_converter,
)
from jubatus_tpu.core.fv.hashing import FeatureHasher  # noqa: F401
from jubatus_tpu.core.fv.weight_manager import WeightManager  # noqa: F401
