"""Config-driven datum → weighted sparse feature vector.

Implements the converter JSON schema used by every engine config in the
reference (e.g. /root/reference/config/classifier/pa.json,
config/weight/default.json): string/num filter types+rules, string/num
types+rules, combination types+rules, with sample weights (bin/tf/log_tf) and
global weights (bin/idf/weight).

Feature naming follows the reference's convention so weight-engine dumps and
decode paths read the same:
  string features:  "<key>$<value>@<type>#<sample_weight>/<global_weight>"
  num features:     "<key>@num" / "<key>@log" / "<key>$<value>@str"
  combinations:     "<left>&<right>"

Output is hashed into the FeatureHasher's 2^k index space (core/fv/hashing.py)
— the dense-array model plane starts here.

Plugin ("dynamic") types — the reference's dlopen'd mecab/ux/image plugins
(SURVEY.md §2.8) — are resolved through a Python registry
(register_string_type / register_num_type) instead of so_factory.
"""

from __future__ import annotations

import math
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv.hashing import FeatureHasher
from jubatus_tpu.core.fv.weight_manager import WeightManager
from jubatus_tpu.core.sparse import CSRBatch, SparseVector


def _count_nonfinite(n: int) -> None:
    """Count ingest-rejected non-finite num values into the process
    default registry (ISSUE 15) — surfaces as
    ``trace.counter.fv.nonfinite_rejected`` in every server's
    get_status and on /metrics."""
    from jubatus_tpu.utils import tracing

    _registry = tracing.default_registry()
    _registry.count("fv.nonfinite_rejected", n)


class ConverterError(ValueError):
    pass


# ---------------------------------------------------------------------------
# key matchers: "*" all, "prefix*", "*suffix", exact
# ---------------------------------------------------------------------------
def make_key_matcher(pattern: str) -> Callable[[str], bool]:
    if pattern == "*":
        return lambda key: True
    if pattern.endswith("*"):
        prefix = pattern[:-1]
        return lambda key: key.startswith(prefix)
    if pattern.startswith("*"):
        suffix = pattern[1:]
        return lambda key: key.endswith(suffix)
    return lambda key: key == pattern


# ---------------------------------------------------------------------------
# plugin registry (replaces so_factory + "dynamic" method, SURVEY.md §2.8)
# ---------------------------------------------------------------------------
_STRING_TYPE_PLUGINS: Dict[str, Callable[[Dict[str, str]], "Splitter"]] = {}
_NUM_TYPE_PLUGINS: Dict[str, Callable[[Dict[str, str]], Callable]] = {}


def register_string_type(name: str, factory) -> None:
    _STRING_TYPE_PLUGINS[name] = factory


def register_num_type(name: str, factory) -> None:
    _NUM_TYPE_PLUGINS[name] = factory


# ---------------------------------------------------------------------------
# string splitters
# ---------------------------------------------------------------------------
Splitter = Callable[[str], List[str]]


def _split_whole(text: str) -> List[str]:
    return [text] if text else []


def _split_space(text: str) -> List[str]:
    return text.split()


def _make_ngram(char_num: int) -> Splitter:
    def split(text: str) -> List[str]:
        return [text[i : i + char_num] for i in range(len(text) - char_num + 1)]

    return split


def _make_regexp_splitter(pattern: str, group: int) -> Splitter:
    rx = re.compile(pattern)

    def split(text: str) -> List[str]:
        return [m.group(group) for m in rx.finditer(text)]

    return split


def _build_string_type(name: str, params: Dict[str, str]) -> Splitter:
    method = params.get("method")
    if method == "ngram":
        char_num = int(params.get("char_num", "1"))
        if char_num < 1:
            raise ConverterError(f"ngram char_num must be >= 1: {char_num}")
        return _make_ngram(char_num)
    if method == "regexp":
        return _make_regexp_splitter(params["pattern"], int(params.get("group", "0")))
    if method == "dynamic":
        # registry first (register_string_type), then load by path —
        # the so_factory dlopen path (plugins.py)
        plug = params.get("function") or params.get("path", "")
        if plug in _STRING_TYPE_PLUGINS:
            return _STRING_TYPE_PLUGINS[plug](params)
        if params.get("path"):
            from jubatus_tpu.core.fv.plugins import load_string_plugin

            return load_string_plugin(params)
        raise ConverterError(f"unknown dynamic string type plugin: {plug!r}")
    raise ConverterError(f"unknown string type method {method!r} for {name!r}")


# ---------------------------------------------------------------------------
# filters
# ---------------------------------------------------------------------------
def _build_string_filter(params: Dict[str, str]) -> Callable[[str], str]:
    method = params.get("method")
    if method == "regexp":
        rx = re.compile(params["pattern"])
        replace = params.get("replace", "")
        return lambda text: rx.sub(replace, text)
    raise ConverterError(f"unknown string filter method {method!r}")


def _build_num_filter(params: Dict[str, str]) -> Callable[[float], float]:
    method = params.get("method")
    if method == "add":
        value = float(params["value"])
        return lambda x: x + value
    if method == "linear_normalization":
        lo, hi = float(params["min"]), float(params["max"])
        if hi <= lo:
            raise ConverterError("linear_normalization requires max > min")
        return lambda x: (min(max(x, lo), hi) - lo) / (hi - lo)
    if method == "gaussian_normalization":
        mean = float(params["average"])
        std = float(params["standard_deviation"])
        if std <= 0:
            raise ConverterError("gaussian_normalization requires positive stddev")
        return lambda x: (x - mean) / std
    if method == "sigmoid_normalization":
        gain, bias = float(params["gain"]), float(params["bias"])
        return lambda x: 1.0 / (1.0 + math.exp(-gain * (x - bias)))
    raise ConverterError(f"unknown num filter method {method!r}")


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------
class StringRule:
    def __init__(self, key: str, type_name: str, sample_weight: str, global_weight: str):
        self.matcher = make_key_matcher(key)
        self.type_name = type_name
        if sample_weight not in ("bin", "tf", "log_tf"):
            raise ConverterError(f"unknown sample_weight {sample_weight!r}")
        if global_weight not in ("bin", "idf", "weight"):
            raise ConverterError(f"unknown global_weight {global_weight!r}")
        self.sample_weight = sample_weight
        self.global_weight = global_weight


class NumRule:
    def __init__(self, key: str, type_name: str):
        self.matcher = make_key_matcher(key)
        self.type_name = type_name


class FilterRule:
    def __init__(self, key: str, type_name: str, suffix: str):
        self.matcher = make_key_matcher(key)
        self.type_name = type_name
        self.suffix = suffix


class CombinationRule:
    def __init__(self, key_left: str, key_right: str, type_name: str):
        self.match_left = make_key_matcher(key_left)
        self.match_right = make_key_matcher(key_right)
        self.type_name = type_name


class ConverterConfig:
    """Parsed "converter" block of an engine config JSON."""

    def __init__(self, raw: Optional[dict] = None):
        raw = raw or {}
        self.raw = raw

        self.string_types: Dict[str, Splitter] = {
            "str": _split_whole,
            "space": _split_space,
        }
        for name, params in (raw.get("string_types") or {}).items():
            self.string_types[name] = _build_string_type(name, params)

        self.string_filters: Dict[str, Callable[[str], str]] = {}
        for name, params in (raw.get("string_filter_types") or {}).items():
            self.string_filters[name] = _build_string_filter(params)

        self.num_filters: Dict[str, Callable[[float], float]] = {}
        for name, params in (raw.get("num_filter_types") or {}).items():
            self.num_filters[name] = _build_num_filter(params)

        # built-in num types: num / log / str; "dynamic" via registry
        self.num_types: Dict[str, str] = {"num": "num", "log": "log", "str": "str"}
        self.num_type_fns: Dict[str, Callable] = {}
        for name, params in (raw.get("num_types") or {}).items():
            method = params.get("method")
            if method == "dynamic":
                plug = params.get("function") or params.get("path", "")
                if plug in _NUM_TYPE_PLUGINS:
                    self.num_type_fns[name] = _NUM_TYPE_PLUGINS[plug](params)
                elif params.get("path"):
                    from jubatus_tpu.core.fv.plugins import load_feature_plugin

                    self.num_type_fns[name] = load_feature_plugin(params)
                else:
                    raise ConverterError(
                        f"unknown dynamic num type plugin: {plug!r}")
            elif method in ("num", "log", "str"):
                self.num_types[name] = method
            else:
                raise ConverterError(f"unknown num type method {method!r}")

        # binary types are dynamic plugins only (the reference's sole binary
        # consumer is the image_feature plugin, plugin/src/fv_converter)
        self.binary_type_fns: Dict[str, Callable] = {}
        for name, params in (raw.get("binary_types") or {}).items():
            if params.get("method") != "dynamic" or not params.get("path"):
                raise ConverterError(
                    f"binary type {name!r}: only dynamic plugins supported")
            from jubatus_tpu.core.fv.plugins import load_feature_plugin

            self.binary_type_fns[name] = load_feature_plugin(params)

        self.string_rules = [
            StringRule(
                r["key"],
                r["type"],
                r.get("sample_weight", "bin"),
                r.get("global_weight", "bin"),
            )
            for r in (raw.get("string_rules") or [])
        ]
        self.num_rules = [NumRule(r["key"], r["type"]) for r in (raw.get("num_rules") or [])]
        self.string_filter_rules = [
            FilterRule(r["key"], r["type"], r["suffix"])
            for r in (raw.get("string_filter_rules") or [])
        ]
        self.num_filter_rules = [
            FilterRule(r["key"], r["type"], r["suffix"])
            for r in (raw.get("num_filter_rules") or [])
        ]
        self.binary_rules = [
            NumRule(r["key"], r["type"]) for r in (raw.get("binary_rules") or [])
        ]
        # combination types: built-ins mul/add, or named with method mul/add
        self.combination_types: Dict[str, str] = {"mul": "mul", "add": "add"}
        for name, params in (raw.get("combination_types") or {}).items():
            method = params.get("method")
            if method not in ("mul", "add"):
                raise ConverterError(f"unknown combination method {method!r}")
            self.combination_types[name] = method
        self.combination_rules = [
            CombinationRule(r["key_left"], r["key_right"], r["type"])
            for r in (raw.get("combination_rules") or [])
        ]

        # "hash_max_size": caps the hashed feature space (reference core's
        # converter_config optional member; there hash % size, here the
        # next power of two NOT EXCEEDING it so the [L, D] tables keep the
        # mask-indexed layout — the memory cap the option exists for holds)
        hms = raw.get("hash_max_size")
        if hms is not None:
            if not isinstance(hms, int) or hms < 16:
                raise ConverterError(
                    f"hash_max_size must be an int >= 16, got {hms!r}")
            self.dim_bits: Optional[int] = hms.bit_length() - 1
        else:
            self.dim_bits = None

        # validate referenced type names exist
        for r in self.string_rules:
            if r.type_name not in self.string_types:
                raise ConverterError(f"string rule references unknown type {r.type_name!r}")
        for r in self.num_rules:
            if r.type_name not in self.num_types and r.type_name not in self.num_type_fns:
                raise ConverterError(f"num rule references unknown type {r.type_name!r}")
        for r in self.binary_rules:
            if r.type_name not in self.binary_type_fns:
                raise ConverterError(f"binary rule references unknown type {r.type_name!r}")
        for r in self.string_filter_rules:
            if r.type_name not in self.string_filters:
                raise ConverterError(f"string filter rule references unknown type {r.type_name!r}")
        for r in self.num_filter_rules:
            if r.type_name not in self.num_filters:
                raise ConverterError(f"num filter rule references unknown type {r.type_name!r}")
        for r in self.combination_rules:
            if r.type_name not in self.combination_types:
                raise ConverterError(f"combination rule references unknown type {r.type_name!r}")


# ---------------------------------------------------------------------------
# the converter
# ---------------------------------------------------------------------------
#: global-weight kind codes carried through the batch pipeline's flat arrays
_GW_BIN, _GW_IDF, _GW_USER = 0, 1, 2
_GW_CODE = {"bin": _GW_BIN, "idf": _GW_IDF, "weight": _GW_USER}

#: default bound for the tokenization/name memo caches (entries, not bytes);
#: overridable per converter via set_cache_size (--fv-cache-size)
DEFAULT_CACHE_SIZE = 1 << 16


class _ComboPlan:
    """The combination cross product as a pure function of the BASE
    feature-name schema (which repeats across a feed's datums): slot
    names, hashed indices, gw kinds, and the bilinear terms feeding each
    slot. On a schema hit the whole string/pair stage of _apply_combos is
    replayed as numpy gathers + multiplies over the batch — the Python
    mirror of the native parser's combo plan (native/fast_ingest.cpp)."""

    __slots__ = ("slot_idx", "slot_kind", "a_idx", "b_idx", "mul_mask",
                 "t_starts", "slot_names")

    def __init__(self, slot_names, slot_idx, slot_kind,
                 a_idx, b_idx, mul_mask, t_starts):
        self.slot_names = slot_names
        self.slot_idx = slot_idx      # int32 [S]
        self.slot_kind = slot_kind    # uint8 [S]
        self.a_idx = a_idx            # int32 [T] base column of left term
        self.b_idx = b_idx            # int32 [T]
        self.mul_mask = mul_mask      # bool  [T] mul (True) vs add
        self.t_starts = t_starts      # int64 [S] first term per slot

    def slot_values(self, base_vals: np.ndarray) -> np.ndarray:
        """[G, nbase] float64 base values → [G, S] slot values."""
        va = base_vals[:, self.a_idx]
        vb = base_vals[:, self.b_idx]
        tv = np.where(self.mul_mask, va * vb, va + vb)
        if self.t_starts.shape[0] == tv.shape[1]:
            return tv  # one term per slot — the common case
        return np.add.reduceat(tv, self.t_starts, axis=1)


class DatumToFVConverter:
    """datum → hashed weighted sparse feature vector.

    Two entry points: ``convert`` (per-datum, reference semantics) and
    ``convert_batch`` (batch-native: memoized tokenization, one hash
    sweep, vectorized global weights, CSR output — the serving hot
    path). Both run the same extraction code, so they cannot drift."""

    def __init__(
        self,
        config: ConverterConfig,
        hasher: Optional[FeatureHasher] = None,
        weights: Optional[WeightManager] = None,
        cache_size: int = DEFAULT_CACHE_SIZE,
    ):
        self.config = config
        self.hasher = hasher or FeatureHasher()
        self.weights = weights or WeightManager(self.hasher.dim)
        # bounded memo caches (clear-on-full — the native parser's
        # discipline; hot keys repopulate in one batch). Caches hold only
        # weight-INDEPENDENT facts (tokenizations, filter outputs, hashed
        # indices, gw kinds) so they can never serve a stale idf/user
        # weighted value.
        self._cache_max = max(int(cache_size), 0)
        self._filter_memo: Dict[tuple, str] = {}
        self._token_memo: Dict[tuple, tuple] = {}
        self._name_memo: Dict[str, Tuple[int, int]] = {}
        self._combo_plans: Dict[tuple, _ComboPlan] = {}
        # optional data-quality recorder: called from convert_batch with
        # (flat feature names, weighted values); the callee self-samples
        self.quality_hook = None

    @property
    def dim(self) -> int:
        return self.hasher.dim

    def set_cache_size(self, n: int) -> None:
        """Rebound the tokenization/name memo caches (--fv-cache-size);
        0 disables memoization."""
        self._cache_max = max(int(n), 0)
        for memo in (self._filter_memo, self._token_memo, self._name_memo):
            if len(memo) > self._cache_max:
                memo.clear()

    def _memo_put(self, memo: dict, key, value):
        if self._cache_max:
            if len(memo) >= self._cache_max:
                memo.clear()
            memo[key] = value
        return value

    # -- filters ------------------------------------------------------------
    def _apply_filters(self, datum: Datum) -> Datum:
        cfg = self.config
        out = Datum(
            string_values=datum.string_values,
            num_values=datum.num_values,
            binary_values=datum.binary_values,
        )
        memo = self._filter_memo
        for fi, rule in enumerate(cfg.string_filter_rules):
            fn = cfg.string_filters[rule.type_name]
            for key, value in list(out.string_values):
                if rule.matcher(key):
                    fkey = (fi, value)
                    fv = memo.get(fkey)
                    if fv is None:
                        fv = self._memo_put(memo, fkey, fn(value))
                    out.string_values.append((key + rule.suffix, fv))
        for rule in cfg.num_filter_rules:
            fn = cfg.num_filters[rule.type_name]
            for key, value in list(out.num_values):
                if rule.matcher(key):
                    out.num_values.append((key + rule.suffix, fn(value)))
        return out

    def _term_counts(self, type_name: str, splitter: Splitter,
                     text: str) -> tuple:
        """Distinct (term, tf) pairs in first-seen order, memoized per
        (splitter type, input string) — repeated hot strings (headers,
        categorical values) skip re-splitting entirely."""
        tkey = (type_name, text)
        cached = self._token_memo.get(tkey)
        if cached is not None:
            return cached
        counts: Dict[str, int] = {}
        for term in splitter(text):
            counts[term] = counts.get(term, 0) + 1
        return self._memo_put(self._token_memo, tkey, tuple(counts.items()))

    # -- extraction ---------------------------------------------------------
    def _base_named_features(self, datum: Datum) -> Dict[str, float]:
        """The weighted feature dict BEFORE combination rules — the
        snapshot the combo cross product feeds on."""
        cfg = self.config
        datum = self._apply_filters(datum)
        # ingest hardening (ISSUE 15): a single inf/NaN num value from
        # a client would flow straight into the weights (train adds the
        # feature value into the model; NaN is absorbing and the next
        # mix round would broadcast it fleet-wide). Reject non-finite
        # num values HERE — after filters, so a filter emitting
        # non-finite output is caught too — counted, never silently
        # trained. Runs for every convert path (per-datum, batch,
        # named).
        if datum.num_values and any(
                isinstance(v, float) and not math.isfinite(v)
                for _, v in datum.num_values):
            kept = [kv for kv in datum.num_values
                    if not (isinstance(kv[1], float)
                            and not math.isfinite(kv[1]))]
            _count_nonfinite(len(datum.num_values) - len(kept))
            datum = Datum(string_values=datum.string_values,
                          num_values=kept,
                          binary_values=datum.binary_values)
        features: Dict[str, float] = {}

        # string rules
        for rule in cfg.string_rules:
            splitter = cfg.string_types[rule.type_name]
            suffix = (f"@{rule.type_name}"
                      f"#{rule.sample_weight}/{rule.global_weight}")
            for key, text in datum.string_values:
                if not rule.matcher(key):
                    continue
                for term, tf in self._term_counts(
                        rule.type_name, splitter, text):
                    if rule.sample_weight == "bin":
                        sw = 1.0
                    elif rule.sample_weight == "tf":
                        sw = float(tf)
                    else:  # log_tf
                        sw = math.log(1.0 + tf)
                    name = f"{key}${term}{suffix}"
                    features[name] = features.get(name, 0.0) + sw

        # num rules
        for rule in cfg.num_rules:
            kind = cfg.num_types.get(rule.type_name)
            fn = cfg.num_type_fns.get(rule.type_name)
            for key, value in datum.num_values:
                if not rule.matcher(key):
                    continue
                if fn is not None:
                    for name, v in fn(key, value):
                        features[name] = features.get(name, 0.0) + v
                    continue
                tname = rule.type_name
                if kind == "num":
                    name = f"{key}@{tname}"
                    features[name] = features.get(name, 0.0) + value
                elif kind == "log":
                    name = f"{key}@{tname}"
                    features[name] = features.get(name, 0.0) + math.log(max(1.0, value))
                elif kind == "str":
                    name = f"{key}${_format_num(value)}@{tname}"
                    features[name] = features.get(name, 0.0) + 1.0

        # binary rules (image_feature-style plugins)
        for rule in cfg.binary_rules:
            fn = cfg.binary_type_fns[rule.type_name]
            for key, value in datum.binary_values:
                if not rule.matcher(key):
                    continue
                for name, v in fn(key, value):
                    features[name] = features.get(name, 0.0) + v

        return features

    def _apply_combos(self, features: Dict[str, float]) -> None:
        """Combination features over the features produced so far, added
        in place. Each rule emits each unordered pair once (canonical
        name order), regardless of which side matched which matcher;
        values accumulate across rules."""
        cfg = self.config
        base = list(features.items())
        for rule in cfg.combination_rules:
            op = cfg.combination_types[rule.type_name]
            seen = set()
            for lname, lval in base:
                if not rule.match_left(lname):
                    continue
                for rname, rval in base:
                    if lname == rname or not rule.match_right(rname):
                        continue
                    a, b = (lname, rname) if lname < rname else (rname, lname)
                    if (a, b) in seen:
                        continue
                    seen.add((a, b))
                    cval = lval * rval if op == "mul" else lval + rval
                    name = f"{a}&{b}"
                    features[name] = features.get(name, 0.0) + cval

    def _named_features(self, datum: Datum) -> Dict[str, float]:
        """Produce the weighted feature dict keyed by full feature name."""
        features = self._base_named_features(datum)
        if self.config.combination_rules:
            self._apply_combos(features)
        return features

    # -- hashing + global weights -------------------------------------------
    def convert(self, datum: Datum, update_weights: bool = False) -> SparseVector:
        """Convert to hashed (index, value) pairs, applying global weights.

        update_weights=True is the train path (reference's
        convert_and_update_weight): document frequencies are recorded before
        idf lookup.
        """
        named = self._named_features(datum)
        # hash (one native batch call when built) + resolve global weights
        hashed: Dict[int, float] = {}
        idf_indices = []
        entries: List[Tuple[int, float, str]] = []
        names = list(named.keys())
        for idx, name in zip(self.hasher.index_many(names), names):
            value = named[name]
            gw_kind = _global_weight_kind(name)
            entries.append((idx, value, gw_kind))
            if gw_kind == "idf":
                idf_indices.append(idx)
        if update_weights and idf_indices:
            self.weights.observe(set(idf_indices))
        for idx, value, gw_kind in entries:
            if gw_kind == "idf":
                value *= self.weights.idf(idx)
            elif gw_kind == "weight":
                value *= self.weights.user_weight(idx)
            hashed[idx] = hashed.get(idx, 0.0) + value
        return sorted(hashed.items())

    # -- batch pipeline ------------------------------------------------------
    def _resolve_names(self, names: List[str]):
        """names → (int32 indices, uint8 gw kinds): memo lookups plus ONE
        ``index_array`` sweep for the misses. The memo holds only pure
        facts (hash, kind parsed from the name) — never weighted values."""
        n = len(names)
        idx = np.empty(n, dtype=np.int32)
        kind = np.empty(n, dtype=np.uint8)
        memo = self._name_memo
        miss_pos: List[int] = []
        miss_names: List[str] = []
        for i, nm in enumerate(names):
            e = memo.get(nm)
            if e is None:
                miss_pos.append(i)
                miss_names.append(nm)
            else:
                idx[i] = e[0]
                kind[i] = e[1]
        if miss_names:
            new_idx = self.hasher.index_array(miss_names)
            for p, nm, ix in zip(miss_pos, miss_names, new_idx.tolist()):
                k = _GW_CODE[_global_weight_kind(nm)]
                self._memo_put(memo, nm, (ix, k))
                idx[p] = ix
                kind[p] = k
        return idx, kind

    def _combo_plan_for(self, base_names: tuple) -> _ComboPlan:
        """Build (or fetch) the combo plan for one base-name schema —
        a symbolic replay of _apply_combos with values left abstract."""
        plan = self._combo_plans.get(base_names)
        if plan is not None:
            return plan
        cfg = self.config
        slot_names: List[str] = []
        slot_map: Dict[str, int] = {}
        slot_terms: List[List[Tuple[int, int, bool]]] = []
        for rule in cfg.combination_rules:
            mul = cfg.combination_types[rule.type_name] == "mul"
            seen = set()
            left = [i for i, nm in enumerate(base_names)
                    if rule.match_left(nm)]
            right = [i for i, nm in enumerate(base_names)
                     if rule.match_right(nm)]
            for li in left:
                ln = base_names[li]
                for ri in right:
                    if li == ri:
                        continue
                    rn = base_names[ri]
                    a, b = (ln, rn) if ln < rn else (rn, ln)
                    if (a, b) in seen:
                        continue
                    seen.add((a, b))
                    name = f"{a}&{b}"
                    s = slot_map.get(name)
                    if s is None:
                        s = len(slot_names)
                        slot_map[name] = s
                        slot_names.append(name)
                        slot_terms.append([])
                    slot_terms[s].append((li, ri, mul))
        a_idx, b_idx, mul_mask, t_starts = [], [], [], []
        for terms in slot_terms:
            t_starts.append(len(a_idx))
            for li, ri, mul in terms:
                a_idx.append(li)
                b_idx.append(ri)
                mul_mask.append(mul)
        sidx, skind = self._resolve_names(slot_names)
        plan = _ComboPlan(
            slot_names, sidx, skind,
            np.asarray(a_idx, dtype=np.int32),
            np.asarray(b_idx, dtype=np.int32),
            np.asarray(mul_mask, dtype=bool),
            np.asarray(t_starts, dtype=np.int64),
        )
        if len(self._combo_plans) >= 64:
            self._combo_plans.clear()
        self._combo_plans[base_names] = plan
        return plan

    def convert_batch(self, data: Sequence[Datum],
                      update_weights: bool = False) -> CSRBatch:
        """Batch-native conversion: tokenize/filter with the memo caches,
        hash every feature name in one sweep, apply global weights as
        numpy gathers, and emit an arena-style CSR triple — no per-datum
        SparseVector objects on the hot path.

        Semantics match per-datum ``convert`` exactly, with ONE
        documented difference under ``update_weights=True``: document
        frequencies for the WHOLE batch are observed first (one
        ``observe_batch`` call — the idf batch-collapse fix), then every
        row's idf reflects the full batch's counts. Per-datum convert
        interleaves observe/lookup per document, so a document sees only
        its predecessors; intra-batch arrival order was never a contract
        (the microbatch coalescer already merges concurrent requests in
        arbitrary order), and the two agree for batch size 1 and
        converge as counts grow."""
        b = len(data)
        if b == 0:
            return CSRBatch(np.zeros(0, np.int32), np.zeros(0, np.float32),
                            np.zeros(1, np.int64))
        base = [self._base_named_features(d) for d in data]
        combo = bool(self.config.combination_rules)

        row_idx: List[np.ndarray] = [None] * b  # type: ignore[list-item]
        row_val: List[np.ndarray] = [None] * b  # type: ignore[list-item]
        row_kind: List[np.ndarray] = [None] * b  # type: ignore[list-item]
        if not combo:
            flat_names: List[str] = []
            counts = np.empty(b, dtype=np.int64)
            for i, nd in enumerate(base):
                flat_names.extend(nd.keys())
                counts[i] = len(nd)
            idx, kind = self._resolve_names(flat_names)
            val = np.empty(len(flat_names), dtype=np.float64)
            pos = 0
            for nd in base:
                for v in nd.values():
                    val[pos] = v
                    pos += 1
            flat_idx, flat_val, flat_kind = idx, val, kind
            entry_rows = np.repeat(np.arange(b, dtype=np.int64), counts)
        else:
            # group rows by base-name schema; the cross product becomes
            # one vectorized bilinear evaluation per group (fixed key
            # schemas — the production shape — form a single group)
            groups: Dict[tuple, List[int]] = {}
            for i, nd in enumerate(base):
                groups.setdefault(tuple(nd.keys()), []).append(i)
            for names_t, members in groups.items():
                bidx, bkind = self._resolve_names(list(names_t))
                plan = self._combo_plan_for(names_t)
                bvals = np.array(
                    [list(base[r].values()) for r in members],
                    dtype=np.float64).reshape(len(members), len(names_t))
                svals = plan.slot_values(bvals) if len(plan.slot_names) \
                    else np.zeros((len(members), 0))
                gidx = np.concatenate([bidx, plan.slot_idx])
                gkind = np.concatenate([bkind, plan.slot_kind])
                for gi, r in enumerate(members):
                    row_idx[r] = gidx
                    row_kind[r] = gkind
                    row_val[r] = np.concatenate([bvals[gi], svals[gi]])
            counts = np.fromiter((a.shape[0] for a in row_idx),
                                 dtype=np.int64, count=b)
            flat_idx = np.concatenate(row_idx) if b else np.zeros(0, np.int32)
            flat_val = np.concatenate(row_val)
            flat_kind = np.concatenate(row_kind)
            entry_rows = np.repeat(np.arange(b, dtype=np.int64), counts)

        # global weights — vectorized gathers instead of per-index calls.
        # observe() runs ONCE for the whole batch (before any lookup), so
        # every row sees the post-batch document counts.
        idf_mask = flat_kind == _GW_IDF
        if idf_mask.any():
            if update_weights:
                self.weights.observe_batch(flat_idx[idf_mask],
                                           entry_rows[idf_mask])
            flat_val[idf_mask] *= self.weights.idf_many(flat_idx[idf_mask])
        user_mask = flat_kind == _GW_USER
        if user_mask.any():
            flat_val[user_mask] *= self.weights.user_weight_many(
                flat_idx[user_mask])

        hook = self.quality_hook
        if hook is not None:
            try:
                if not combo:
                    hook(flat_names, flat_val)
                else:
                    row_names: List[List[str]] = [[]] * b
                    for names_t, members in groups.items():
                        nm = list(names_t) + \
                            list(self._combo_plan_for(names_t).slot_names)
                        for r in members:
                            row_names[r] = nm
                    hook([n for rn in row_names for n in rn], flat_val)
            except Exception:  # broad-ok — quality stats must not break FV
                pass

        # per-row merge by hashed index (convert()'s sorted-dict
        # semantics): stable lexsort keeps insertion order for colliding
        # entries, so float accumulation order matches the per-datum dict
        if flat_idx.shape[0] == 0:
            return CSRBatch(np.zeros(0, np.int32), np.zeros(0, np.float32),
                            np.zeros(b + 1, np.int64))
        order = np.lexsort((flat_idx, entry_rows))
        srow = entry_rows[order]
        sidx = flat_idx[order]
        sval = flat_val[order]
        boundary = np.ones(sidx.shape[0], dtype=bool)
        boundary[1:] = (srow[1:] != srow[:-1]) | (sidx[1:] != sidx[:-1])
        starts = np.flatnonzero(boundary)
        midx = sidx[starts].astype(np.int32)
        mval = np.add.reduceat(sval, starts)
        mrows = srow[starts]
        mcounts = np.bincount(mrows, minlength=b)
        off = np.zeros(b + 1, dtype=np.int64)
        np.cumsum(mcounts, out=off[1:])
        return CSRBatch(midx, mval.astype(np.float32), off)

    def convert_named(self, datum: Datum, update_weights: bool = False) -> Dict[str, float]:
        """Named (unhashed) features with global weights applied — for the
        weight engine's calc_weight/update and for tests. Runs the extraction
        pipeline once; update_weights records document frequencies first."""
        named = self._named_features(datum)
        entries = [(name, self.hasher.index(name), value) for name, value in named.items()]
        if update_weights:
            idf_idx = {i for name, i, _ in entries if _global_weight_kind(name) == "idf"}
            if idf_idx:
                self.weights.observe(idf_idx)
        out = {}
        for name, idx, value in entries:
            gw_kind = _global_weight_kind(name)
            if gw_kind == "idf":
                value *= self.weights.idf(idx)
            elif gw_kind == "weight":
                value *= self.weights.user_weight(idx)
            out[name] = value
        return out

    def revert_feature(self, index: int) -> Optional[Tuple[str, str]]:
        """Best-effort hash→(key, value) decode, for decode_row-style APIs."""
        name = self.hasher.name_of(index)
        if name is None:
            return None
        if "$" in name:
            key, rest = name.split("$", 1)
            value = rest.split("@", 1)[0]
            return key, value
        return name.split("@", 1)[0], ""


def _format_num(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(v)


def _global_weight_kind(name: str) -> str:
    if "/" in name:
        return name.rsplit("/", 1)[1]
    return "bin"


def make_fv_converter(
    converter_block: Optional[dict],
    dim_bits: int = 20,
    weights: Optional[WeightManager] = None,
    cache_size: int = DEFAULT_CACHE_SIZE,
) -> DatumToFVConverter:
    """Factory mirroring core::fv_converter::make_fv_converter
    (reference usage: jubatus/server/server/classifier_serv.cpp:110).

    A "hash_max_size" in the converter block overrides ``dim_bits`` — the
    config is the deployment's statement of model scale, same as the
    reference core's converter_config member. ``cache_size`` bounds the
    tokenization/name memo caches (--fv-cache-size)."""
    config = ConverterConfig(converter_block)
    if config.dim_bits is not None:
        dim_bits = config.dim_bits
    hasher = FeatureHasher(dim_bits=dim_bits)
    return DatumToFVConverter(config, hasher,
                              weights or WeightManager(hasher.dim),
                              cache_size=cache_size)
