"""Feature-name hashing into a fixed 2^k index space.

The reference stores features by full string name in sparse maps
(core::fv_converter sfv → local_storage string-keyed rows). TPU-native models
are dense arrays, so feature names are hashed to indices with the hashing
trick. Index 0 is reserved as the padding slot: real features map to
[1, dim-1], so padded (index=0, value=0) entries can never alias a live
feature's gradient in scatter updates.

An optional bounded reverse table keeps hash→name for the engines that need
to *decode* features back to names (weight engine's calc_weight dump, the
recommender's decode_row, fv_converter::revert — SURVEY.md §2.9).
"""

from __future__ import annotations

import zlib
from typing import Dict, Optional


class FeatureHasher:
    """Stable string→index hashing with optional reverse lookup."""

    def __init__(self, dim_bits: int = 20, reverse_capacity: int = 1 << 16):
        if not (4 <= dim_bits <= 31):
            raise ValueError("dim_bits must be in [4, 31]")
        self.dim_bits = dim_bits
        self.dim = 1 << dim_bits
        self._mask = self.dim - 1
        self._reverse: Dict[int, str] = {}
        self._reverse_capacity = reverse_capacity

    def index(self, name: str, remember: bool = True) -> int:
        # crc32 is stable across processes/platforms (unlike Python's hash()).
        h = zlib.crc32(name.encode("utf-8")) & self._mask
        if h == 0:
            h = 1  # index 0 is the padding slot
        if remember and len(self._reverse) < self._reverse_capacity:
            self._reverse.setdefault(h, name)
        return h

    def name_of(self, index: int) -> Optional[str]:
        """Reverse lookup (best effort; None if evicted or never seen)."""
        return self._reverse.get(int(index))

    def clear_reverse(self) -> None:
        self._reverse.clear()
