"""Feature-name hashing into a fixed 2^k index space.

The reference stores features by full string name in sparse maps
(core::fv_converter sfv → local_storage string-keyed rows). TPU-native models
are dense arrays, so feature names are hashed to indices with the hashing
trick. Index 0 is reserved as the padding slot: real features map to
[1, dim-1], so padded (index=0, value=0) entries can never alias a live
feature's gradient in scatter updates.

An optional bounded reverse table keeps hash→name for the engines that need
to *decode* features back to names (weight engine's calc_weight dump, the
recommender's decode_row, fv_converter::revert — SURVEY.md §2.9).
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, Optional

_NATIVE_OK: Optional[bool] = None


def _native_batch_enabled() -> bool:
    """Opt-in native batch hashing. The env var is read per call (cheap,
    and tests flip it); the library-load probe is cached for the process."""
    if os.environ.get("JUBATUS_TPU_NATIVE", "") not in ("1", "true", "yes"):
        return False
    global _NATIVE_OK
    if _NATIVE_OK is None:
        from jubatus_tpu import native

        _NATIVE_OK = native._load() is not None
    return _NATIVE_OK


class FeatureHasher:
    """Stable string→index hashing with optional reverse lookup."""

    def __init__(self, dim_bits: int = 20, reverse_capacity: int = 1 << 16):
        if not (4 <= dim_bits <= 31):
            raise ValueError("dim_bits must be in [4, 31]")
        self.dim_bits = dim_bits
        self.dim = 1 << dim_bits
        self._mask = self.dim - 1
        self._reverse: Dict[int, str] = {}
        self._reverse_capacity = reverse_capacity

    def index(self, name: str, remember: bool = True) -> int:
        # crc32 is stable across processes/platforms (unlike Python's
        # hash()). surrogateescape: legacy clients may carry non-UTF8
        # string values (admitted wire-wide with surrogateescape), and the
        # hash must cover the ORIGINAL bytes — the C++ ingest path hashes
        # raw bytes, so strict encoding here would either crash (surrogates
        # not allowed) or diverge from the native fast path.
        h = zlib.crc32(name.encode("utf-8", "surrogateescape")) & self._mask
        if h == 0:
            h = 1  # index 0 is the padding slot
        if remember and len(self._reverse) < self._reverse_capacity:
            self._reverse.setdefault(h, name)
        return h

    def _remember_many(self, idxs, names) -> None:
        """Grow the reverse map from a batch, honoring reverse_capacity —
        ONE owner for every batch path (index_many, index_array). The cap
        is re-checked per entry, not per batch: a single oversized batch
        must not blow past the bound."""
        rev = self._reverse
        cap = self._reverse_capacity
        if len(rev) >= cap:
            return
        for h, name in zip(idxs, names):
            if len(rev) >= cap:
                break
            rev.setdefault(int(h), name)

    def index_many(self, names, remember: bool = True):
        """Batch hashing. The C batch path (jubatus_tpu.native.hash_names)
        is bit-identical but measured SLOWER than this loop at realistic
        batch sizes — zlib.crc32 is already C and the ctypes marshalling
        costs more than it saves — so it's opt-in (JUBATUS_TPU_NATIVE=1)
        for platforms where zlib underperforms. Returns ints aligned with
        `names`."""
        if not _native_batch_enabled():
            return [self.index(n, remember) for n in names]
        from jubatus_tpu import native

        idxs = native.hash_names(list(names), self._mask)
        if remember:
            self._remember_many(idxs.tolist(), names)
        return [int(i) for i in idxs]

    def index_array(self, names, remember: bool = True):
        """Batch hashing to an int32 numpy array — the batch converter's
        sweep (core/fv/converter.py convert_batch). Bit-identical to
        index()/index_many; the reverse map grows through the same
        capacity-bounded path."""
        import numpy as np

        crc = zlib.crc32
        out = np.fromiter(
            (crc(n.encode("utf-8", "surrogateescape")) for n in names),
            dtype=np.uint32, count=len(names))
        out &= np.uint32(self._mask)
        idxs = out.astype(np.int32)
        idxs[idxs == 0] = 1  # index 0 is the padding slot
        if remember:
            self._remember_many(idxs.tolist(), names)
        return idxs

    def name_of(self, index: int) -> Optional[str]:
        """Reverse lookup (best effort; None if evicted or never seen)."""
        return self._reverse.get(int(index))

    def clear_reverse(self) -> None:
        self._reverse.clear()
