"""fv_converter plugin loading (≙ core so_factory + plugin/src/fv_converter).

The reference loads shared objects by path from server config and calls
their ``extern "C" create(const map<string,string>&)`` factory
(mecab_splitter.cpp:203-230); servers pass a so_factory into
make_fv_converter (classifier_serv.cpp:110). Here the same config shape —

    "string_types": {
      "mecab": {"method": "dynamic",
                "path": "jubatus_tpu/plugins/mecab_splitter.py",
                "function": "create", "arg": "-d /usr/lib/mecab/..."}
    }

— loads a **Python module** by file path (or a bare name resolved against
the built-in ``jubatus_tpu/plugins/`` directory) and calls its
``create(params) -> splitter`` factory. A returned object may be a plain
callable ``text -> [tokens]`` or expose ``.split(text)`` (the reference's
word_splitter interface). ``.so`` paths load through the C ABI bridge in
jubatus_tpu.native (ctypes), keeping the native-plugin door open.

Loaded modules are cached by resolved path, like dlopen handle caching in
so_factory.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading
from typing import Any, Callable, Dict, List

from jubatus_tpu.core.fv.converter import ConverterError

#: built-in plugin directory (≙ the reference's installed plugin dir)
BUILTIN_PLUGIN_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "plugins")

_cache: Dict[str, Any] = {}
_cache_lock = threading.Lock()


def resolve_path(path: str) -> str:
    """Bare names resolve against the built-in plugin dir; explicit paths
    pass through (the reference resolves bare .so names against its
    configured plugin directory)."""
    if os.path.sep not in path:
        name = path if path.endswith((".py", ".so")) else path + ".py"
        candidate = os.path.join(BUILTIN_PLUGIN_DIR, name)
        if os.path.exists(candidate):
            return candidate
    return path


def _load_module(path: str):
    resolved = os.path.abspath(resolve_path(path))
    with _cache_lock:
        mod = _cache.get(resolved)
        if mod is not None:
            return mod
        if not os.path.exists(resolved):
            raise ConverterError(f"plugin not found: {path!r} "
                                 f"(resolved {resolved!r})")
        # path hash in the module name: two plugins that share a basename
        # (e.g. /opt/a/tokenizer.py and /opt/b/tokenizer.py) must not
        # clobber each other's sys.modules entry
        import hashlib

        digest = hashlib.md5(resolved.encode()).hexdigest()[:8]
        modname = (f"jubatus_tpu_plugin_"
                   f"{os.path.splitext(os.path.basename(resolved))[0]}_{digest}")
        spec = importlib.util.spec_from_file_location(modname, resolved)
        if spec is None or spec.loader is None:
            raise ConverterError(f"cannot load plugin {resolved!r}")
        mod = importlib.util.module_from_spec(spec)
        sys.modules[modname] = mod
        try:
            spec.loader.exec_module(mod)
        except Exception as e:
            sys.modules.pop(modname, None)
            raise ConverterError(f"plugin {resolved!r} failed to import: {e}")
        _cache[resolved] = mod
        return mod


def _as_splitter(obj: Any) -> Callable[[str], List[str]]:
    if callable(obj) and not hasattr(obj, "split"):
        return obj
    if hasattr(obj, "split"):
        return obj.split
    raise ConverterError(
        f"plugin factory returned {type(obj)!r}; need a callable or an "
        "object with .split(text)")


def load_string_plugin(params: Dict[str, str]) -> Callable[[str], List[str]]:
    """``{"method": "dynamic", "path": ..., "function": ...}`` → splitter."""
    path = params.get("path", "")
    if not path:
        raise ConverterError('dynamic string type needs a "path"')
    if path.endswith(".so"):
        from jubatus_tpu.native import load_native_splitter

        return load_native_splitter(path, params)
    mod = _load_module(path)
    fn_name = params.get("function", "create")
    factory = getattr(mod, fn_name, None)
    if factory is None:
        raise ConverterError(f"plugin {path!r} has no factory {fn_name!r}")
    return _as_splitter(factory(dict(params)))


def load_feature_plugin(params: Dict[str, str]) -> Callable:
    """Dynamic num/binary feature extractor: the factory returns a callable
    ``(key, value) -> iterable[(feature_name, weight)]`` or an object with
    ``.extract`` of that shape (the converter's num_type_fns protocol)."""
    path = params.get("path", "")
    if not path:
        raise ConverterError('dynamic feature type needs a "path"')
    mod = _load_module(path)
    factory = getattr(mod, params.get("function", "create"), None)
    if factory is None:
        raise ConverterError(f"plugin {path!r} has no factory")
    obj = factory(dict(params))
    return obj.extract if hasattr(obj, "extract") else obj


#: back-compat alias
load_num_plugin = load_feature_plugin


def clear_cache() -> None:
    with _cache_lock:
        _cache.clear()
