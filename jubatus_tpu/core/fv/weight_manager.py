"""Global-weight state: document frequencies for idf + user-set weights.

The reference's core::fv_converter::weight_manager accumulates per-feature
document counts (for idf/bm25 global weights) and user weights set through the
weight engine's `update` RPC; it is itself a mixable so counts converge across
the cluster (SURVEY.md §2.4 weight engine, §2.9).

TPU-native design: document-frequency counts live in a dense float32 array
over the hashed feature space. That makes the mix diff a dense array — exactly
psum-able over ICI with the model diffs in the same collective, instead of a
string-keyed map merge.
"""

from __future__ import annotations

import math
import threading
from typing import Dict

import numpy as np


class WeightManager:
    """Tracks df counts and user weights over the hashed feature space.

    ``lock`` serializes the native ingest path's in-place df mutation
    (native/fast_ingest.cpp jt_ingest_parse_w writes ``_df_diff`` and
    ``_ndocs_diff`` directly) against mixes/unpacks that swap or zero
    these buffers. ``_ndocs_diff`` is a 1-element float64 array for the
    same reason — C++ increments it through a pointer."""

    def __init__(self, dim: int):
        self.dim = dim
        # master = state as of last mix; diff = local updates since.
        self._df_master = np.zeros(dim, dtype=np.float32)
        self._df_diff = np.zeros(dim, dtype=np.float32)
        self._ndocs_master = 0.0
        self._ndocs_diff = np.zeros(1, dtype=np.float64)
        self._user_weights: Dict[int, float] = {}
        self.lock = threading.Lock()

    # -- ingest -------------------------------------------------------------
    def observe(self, indices) -> None:
        """Record one document's feature occurrence (unique indices)."""
        with self.lock:
            self._df_diff[np.asarray(list(indices), dtype=np.int64)] += 1.0
            self._ndocs_diff[0] += 1.0

    def observe_batch(self, indices: np.ndarray, rows: np.ndarray) -> None:
        """Record a whole batch of documents in ONE lock acquisition —
        the batch converter's train path (convert_batch) and the flush-
        time deferred-idf path (server/service.py).

        ``indices``/``rows`` are parallel flat arrays: entry j says
        document ``rows[j]`` contained feature ``indices[j]``. Duplicate
        (row, index) pairs are deduplicated here (df counts one per
        document, like per-datum observe's set()); the number of
        documents is taken from the distinct row ids. The per-datum
        ``observe()`` loop this replaces serialized conversion under this
        lock once per datum — the idf batch-collapse."""
        if indices.size == 0:
            return
        rows = np.asarray(rows, dtype=np.int64)
        pair = rows * np.int64(self.dim) + np.asarray(indices, np.int64)
        uniq = np.unique(pair)
        ndocs = int(np.unique(rows).size)
        uidx = uniq % np.int64(self.dim)
        with self.lock:
            np.add.at(self._df_diff, uidx, 1.0)
            self._ndocs_diff[0] += float(ndocs)

    def observe_rows(self, idx: np.ndarray) -> None:
        """observe_batch for a padded [B, K] index matrix (the native
        ingest interchange shape): each row is one document; index 0 is
        the padding slot and is never counted."""
        b = idx.shape[0]
        if b == 0:
            return
        rows = np.repeat(np.arange(b, dtype=np.int64), idx.shape[1])
        flat = idx.reshape(-1)
        live = flat != 0
        self.observe_batch(flat[live], rows[live])

    def set_user_weight(self, index: int, weight: float) -> None:
        self._user_weights[index] = float(weight)

    # -- lookup -------------------------------------------------------------
    @property
    def ndocs(self) -> float:
        return self._ndocs_master + float(self._ndocs_diff[0])

    def idf(self, index: int) -> float:
        n = self.ndocs
        df = float(self._df_master[index] + self._df_diff[index])
        if n <= 0 or df <= 0:
            return 1.0
        return math.log(n / df)

    def idf_many(self, indices: np.ndarray) -> np.ndarray:
        """Vectorized idf lookup: one float64 gather over the df tables
        instead of per-index idf() calls. Bit-parity with idf(): the
        master+diff sum stays in float32 BEFORE widening (idf() does
        float(f32 + f32)), and log runs on float64."""
        ix = np.asarray(indices, dtype=np.int64)
        n = self.ndocs
        df = (self._df_master[ix] + self._df_diff[ix]).astype(np.float64)
        if n <= 0:
            return np.ones(ix.shape, dtype=np.float64)
        out = np.ones(ix.shape, dtype=np.float64)
        live = df > 0
        out[live] = np.log(n / df[live])
        return out

    def user_weight(self, index: int) -> float:
        return self._user_weights.get(index, 1.0)

    def user_weight_many(self, indices: np.ndarray) -> np.ndarray:
        """Vectorized user-weight lookup (global_weight "weight")."""
        ix = np.asarray(indices, dtype=np.int64)
        if not self._user_weights:
            return np.ones(ix.shape, dtype=np.float64)
        uw = self._user_weights
        return np.fromiter((uw.get(int(i), 1.0) for i in ix),
                           dtype=np.float64, count=ix.shape[0])

    # -- mixable protocol (parallel/mix.py) ---------------------------------
    #: mix() below is elementwise addition, so the mesh psum path applies
    MIX_IS_SUM = True

    def get_diff(self):
        with self.lock:
            return {
                "df": self._df_diff.copy(),
                "ndocs": np.float32(self._ndocs_diff[0]),
            }

    @staticmethod
    def mix(lhs, rhs):
        return {"df": lhs["df"] + rhs["df"], "ndocs": lhs["ndocs"] + rhs["ndocs"]}

    def put_diff(self, diff) -> bool:
        with self.lock:
            self._df_master += np.asarray(diff["df"])
            # wire round-trips can deliver the scalar as a shape-(1,) array
            self._ndocs_master += float(np.asarray(diff["ndocs"]).reshape(()))
            self._df_diff[:] = 0.0
            self._ndocs_diff[0] = 0.0
        return True

    # -- persistence --------------------------------------------------------
    def pack(self):
        return {
            "df": (self._df_master + self._df_diff),
            "ndocs": self.ndocs,
            "user_weights": dict(self._user_weights),
        }

    def unpack(self, obj) -> None:
        with self.lock:
            self._df_master = np.asarray(obj["df"], dtype=np.float32).copy()
            self._ndocs_master = float(obj["ndocs"])
            self._df_diff[:] = 0.0
            self._ndocs_diff[0] = 0.0
            self._user_weights = {int(k): float(v)
                                  for k, v in obj["user_weights"].items()}

    def clear(self) -> None:
        with self.lock:
            self._df_master[:] = 0.0
            self._df_diff[:] = 0.0
            self._ndocs_master = 0.0
            self._ndocs_diff[0] = 0.0
            self._user_weights.clear()
