"""Global-weight state: document frequencies for idf + user-set weights.

The reference's core::fv_converter::weight_manager accumulates per-feature
document counts (for idf/bm25 global weights) and user weights set through the
weight engine's `update` RPC; it is itself a mixable so counts converge across
the cluster (SURVEY.md §2.4 weight engine, §2.9).

TPU-native design: document-frequency counts live in a dense float32 array
over the hashed feature space. That makes the mix diff a dense array — exactly
psum-able over ICI with the model diffs in the same collective, instead of a
string-keyed map merge.
"""

from __future__ import annotations

import math
import threading
from typing import Dict

import numpy as np


class WeightManager:
    """Tracks df counts and user weights over the hashed feature space.

    ``lock`` serializes the native ingest path's in-place df mutation
    (native/fast_ingest.cpp jt_ingest_parse_w writes ``_df_diff`` and
    ``_ndocs_diff`` directly) against mixes/unpacks that swap or zero
    these buffers. ``_ndocs_diff`` is a 1-element float64 array for the
    same reason — C++ increments it through a pointer."""

    def __init__(self, dim: int):
        self.dim = dim
        # master = state as of last mix; diff = local updates since.
        self._df_master = np.zeros(dim, dtype=np.float32)
        self._df_diff = np.zeros(dim, dtype=np.float32)
        self._ndocs_master = 0.0
        self._ndocs_diff = np.zeros(1, dtype=np.float64)
        self._user_weights: Dict[int, float] = {}
        self.lock = threading.Lock()

    # -- ingest -------------------------------------------------------------
    def observe(self, indices) -> None:
        """Record one document's feature occurrence (unique indices)."""
        with self.lock:
            self._df_diff[np.asarray(list(indices), dtype=np.int64)] += 1.0
            self._ndocs_diff[0] += 1.0

    def set_user_weight(self, index: int, weight: float) -> None:
        self._user_weights[index] = float(weight)

    # -- lookup -------------------------------------------------------------
    @property
    def ndocs(self) -> float:
        return self._ndocs_master + float(self._ndocs_diff[0])

    def idf(self, index: int) -> float:
        n = self.ndocs
        df = float(self._df_master[index] + self._df_diff[index])
        if n <= 0 or df <= 0:
            return 1.0
        return math.log(n / df)

    def user_weight(self, index: int) -> float:
        return self._user_weights.get(index, 1.0)

    # -- mixable protocol (parallel/mix.py) ---------------------------------
    #: mix() below is elementwise addition, so the mesh psum path applies
    MIX_IS_SUM = True

    def get_diff(self):
        with self.lock:
            return {
                "df": self._df_diff.copy(),
                "ndocs": np.float32(self._ndocs_diff[0]),
            }

    @staticmethod
    def mix(lhs, rhs):
        return {"df": lhs["df"] + rhs["df"], "ndocs": lhs["ndocs"] + rhs["ndocs"]}

    def put_diff(self, diff) -> bool:
        with self.lock:
            self._df_master += np.asarray(diff["df"])
            # wire round-trips can deliver the scalar as a shape-(1,) array
            self._ndocs_master += float(np.asarray(diff["ndocs"]).reshape(()))
            self._df_diff[:] = 0.0
            self._ndocs_diff[0] = 0.0
        return True

    # -- persistence --------------------------------------------------------
    def pack(self):
        return {
            "df": (self._df_master + self._df_diff),
            "ndocs": self.ndocs,
            "user_weights": dict(self._user_weights),
        }

    def unpack(self, obj) -> None:
        with self.lock:
            self._df_master = np.asarray(obj["df"], dtype=np.float32).copy()
            self._ndocs_master = float(obj["ndocs"])
            self._df_diff[:] = 0.0
            self._ndocs_diff[0] = 0.0
            self._user_weights = {int(k): float(v)
                                  for k, v in obj["user_weights"].items()}

    def clear(self) -> None:
        with self.lock:
            self._df_master[:] = 0.0
            self._df_diff[:] = 0.0
            self._ndocs_master = 0.0
            self._ndocs_diff[0] = 0.0
            self._user_weights.clear()
