"""Fixed-capacity sparse row store with LRU unlearning.

The instance-data backbone for the instance-based engines (recommender,
nearest_neighbor, anomaly — SURVEY.md §7.2 "storage layer"): id-keyed rows
of hashed sparse vectors, held as padded [C, K] arrays so every similarity
kernel in ops/knn.py is one vectorized pass.

- Capacity C and pad width K grow by doubling (bounded recompiles, like
  core/sparse.py buckets).
- ``max_size`` caps the live row count with least-recently-touched eviction —
  the reference's "unlearner": "lru" configs (e.g.
  /root/reference/config/recommender/lsh_unlearn_lru.json). On fixed-HBM TPU
  a capacity bound is mandatory, not optional (SURVEY.md §7 hard part e).
- Host numpy is the source of truth (updates are per-row scatter writes);
  ``device_view()`` lazily uploads and caches the jnp arrays, invalidated by
  a version counter — queries hit HBM-resident arrays, updates don't force
  a round-trip each time.
- Mix support: ``updated_since_mix`` tracks locally-written row ids; the
  engine drivers ship them as sparse dict diffs.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from jubatus_tpu.core.sparse import SparseVector

_INITIAL_CAPACITY = 64
_INITIAL_WIDTH = 8


def _pow2_at_least(n: int, minimum: int) -> int:
    p = minimum
    while p < n:
        p *= 2
    return p


class RowStore:
    def __init__(self, max_size: Optional[int] = None,
                 keep_datum: bool = False) -> None:
        self.max_size = max_size
        self.keep_datum = keep_datum
        self._init()

    def _init(self) -> None:
        self.capacity = _INITIAL_CAPACITY
        self.width = _INITIAL_WIDTH
        self.idx = np.zeros((self.capacity, self.width), np.int32)
        self.val = np.zeros((self.capacity, self.width), np.float32)
        self.ids: List[str] = []              # slot -> id ("" = dead)
        self.slots: Dict[str, int] = {}       # id -> slot
        self._clock = 0
        self._touch: Dict[str, int] = {}      # id -> last-touch tick (LRU)
        self.datums: Dict[str, Any] = {}      # id -> original datum
        self.updated_since_mix: Dict[str, None] = {}
        self.version = 0                      # bumped on every write
        self._dev_cache: Optional[Tuple[int, Any, Any, Any]] = None

    # -- sizing --------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.slots)

    def __contains__(self, row_id: str) -> bool:
        return row_id in self.slots

    def _grow_capacity(self) -> None:
        self.capacity *= 2
        self.idx = np.vstack([self.idx, np.zeros_like(self.idx)])
        self.val = np.vstack([self.val, np.zeros_like(self.val)])

    def _grow_width(self, need: int) -> None:
        new_w = _pow2_at_least(need, self.width * 2)
        pad = new_w - self.width
        self.idx = np.pad(self.idx, ((0, 0), (0, pad)))
        self.val = np.pad(self.val, ((0, 0), (0, pad)))
        self.width = new_w

    def _free_slot(self) -> int:
        if len(self.ids) < self.capacity:
            self.ids.append("")
            return len(self.ids) - 1
        for s, rid in enumerate(self.ids):
            if not rid:
                return s
        self._grow_capacity()
        self.ids.append("")
        return len(self.ids) - 1

    # -- writes --------------------------------------------------------------
    def set_row(self, row_id: str, vec: SparseVector,
                datum: Any = None) -> int:
        """Insert or overwrite a row; returns its slot. Evicts the least
        recently touched row first when max_size is reached."""
        slot = self.slots.get(row_id)
        if slot is None:
            if self.max_size is not None and len(self.slots) >= self.max_size:
                self._evict_lru()
            slot = self._free_slot()
            self.ids[slot] = row_id
            self.slots[row_id] = slot
        if len(vec) > self.width:
            self._grow_width(len(vec))
        self.idx[slot].fill(0)
        self.val[slot].fill(0.0)
        k = len(vec)
        if k:
            self.idx[slot, :k] = [i for i, _ in vec]
            self.val[slot, :k] = [w for _, w in vec]
        if self.keep_datum and datum is not None:
            self.datums[row_id] = datum
        self.touch(row_id)
        self.updated_since_mix[row_id] = None
        self.version += 1
        return slot

    def remove_row(self, row_id: str) -> bool:
        slot = self.slots.pop(row_id, None)
        if slot is None:
            return False
        self.ids[slot] = ""
        self.idx[slot].fill(0)
        self.val[slot].fill(0.0)
        self._touch.pop(row_id, None)
        self.datums.pop(row_id, None)
        self.updated_since_mix.pop(row_id, None)
        self.version += 1
        return True

    def clear(self) -> None:
        self._init()

    def touch(self, row_id: str) -> None:
        self._clock += 1
        self._touch[row_id] = self._clock

    def _evict_lru(self) -> None:
        victim = min(self._touch, key=self._touch.get)
        self.remove_row(victim)

    # -- reads ---------------------------------------------------------------
    def get_row(self, row_id: str) -> Optional[SparseVector]:
        slot = self.slots.get(row_id)
        if slot is None:
            return None
        k = int((self.val[slot] != 0).sum())
        order = np.nonzero(self.val[slot])[0]
        return [(int(self.idx[slot, j]), float(self.val[slot, j]))
                for j in order[:k]]

    def all_ids(self) -> List[str]:
        return list(self.slots.keys())

    def iter_rows(self) -> Iterator[Tuple[str, int]]:
        return iter(self.slots.items())

    def live_mask(self) -> np.ndarray:
        m = np.zeros(self.capacity, bool)
        for s in self.slots.values():
            m[s] = True
        return m

    def device_view(self):
        """(idx, val, live_mask) as device arrays, cached per version."""
        if self._dev_cache is None or self._dev_cache[0] != self.version:
            self._dev_cache = (
                self.version,
                jnp.asarray(self.idx),
                jnp.asarray(self.val),
                jnp.asarray(self.live_mask()),
            )
        return self._dev_cache[1], self._dev_cache[2], self._dev_cache[3]

    # -- mix / persistence ----------------------------------------------------
    def pop_update_diff(self) -> Dict[str, Tuple[list, list, Any]]:
        """Rows written since the last mix as {id: (idx_list, val_list,
        datum)}; clears the tracker."""
        out = {}
        for rid in self.updated_since_mix:
            slot = self.slots.get(rid)
            if slot is None:
                continue
            nz = np.nonzero(self.val[slot])[0]
            out[rid] = (
                self.idx[slot, nz].tolist(),
                self.val[slot, nz].tolist(),
                self.datums.get(rid),
            )
        self.updated_since_mix = {}
        return out

    def apply_update_diff(self, diff: Dict[str, Tuple[list, list, Any]]) -> None:
        for rid, (ii, vv, datum) in diff.items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            vec = [(int(i), float(v)) for i, v in zip(ii, vv)]
            self.set_row(rid, vec, datum=datum)
        # rows arriving via mix are not "local updates" for the next round
        self.updated_since_mix = {}

    def pack(self) -> Any:
        return {
            "rows": {
                rid: (
                    self.idx[s][np.nonzero(self.val[s])].tolist(),
                    self.val[s][np.nonzero(self.val[s])].tolist(),
                )
                for rid, s in self.slots.items()
            },
            "datums": {rid: d.to_msgpack() if hasattr(d, "to_msgpack") else d
                       for rid, d in self.datums.items()} if self.keep_datum else {},
        }

    def unpack(self, obj: Any, datum_decoder=None) -> None:
        self._init()
        for rid, (ii, vv) in obj["rows"].items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            self.set_row(rid, [(int(i), float(v)) for i, v in zip(ii, vv)])
        for rid, d in (obj.get("datums") or {}).items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            self.datums[rid] = datum_decoder(d) if datum_decoder else d
        self.updated_since_mix = {}
