"""Padded sparse-vector batches — the host↔device interchange format.

The reference keeps feature vectors as string-keyed sparse maps
(core::fv_converter sfv, consumed per-datum under a write lock — SURVEY.md
§3.2). On TPU the model plane wants fixed shapes: a feature vector is hashed
into a 2^k index space (fv/hashing.py) and a *batch* of vectors is a pair of
dense arrays (indices, values) padded to a common nnz. Padding entries carry
value 0.0 so they are no-ops in every kernel (gathers multiply by 0, scatter
adds add 0).

Pad widths are bucketed to powers of two so XLA recompiles O(log max_nnz)
times, not per batch shape.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

# (index, weight) pairs, already hashed. The canonical sparse vector type.
SparseVector = List[Tuple[int, float]]


def _bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class CSRBatch:
    """Arena-style batch of hashed sparse feature vectors (CSR triple).

    The batch converter (core/fv/converter.py convert_batch) emits one of
    these instead of B per-datum SparseVector lists: three flat arrays,
    no per-entry Python objects, ready for a single vectorized pad into
    the device interchange format (``to_padded`` → SparseBatch).

    Attributes:
      indices:     int32   [nnz]  hashed feature indices, per-row sorted
      values:      float32 [nnz]  feature values
      row_offsets: int64   [B+1]  row i spans [row_offsets[i], row_offsets[i+1])
    """

    __slots__ = ("indices", "values", "row_offsets")

    def __init__(self, indices: np.ndarray, values: np.ndarray,
                 row_offsets: np.ndarray) -> None:
        assert indices.shape == values.shape and indices.ndim == 1
        assert row_offsets.ndim == 1 and row_offsets[-1] == indices.shape[0]
        self.indices = indices
        self.values = values
        self.row_offsets = row_offsets

    @property
    def batch_size(self) -> int:
        return self.row_offsets.shape[0] - 1

    @property
    def nnz(self) -> int:
        return self.indices.shape[0]

    def __len__(self) -> int:
        return self.batch_size

    def row(self, i: int) -> SparseVector:
        """One row as the canonical (index, value) pair list — for the
        instance engines that store per-row vectors (NN backends)."""
        lo, hi = int(self.row_offsets[i]), int(self.row_offsets[i + 1])
        return list(zip(self.indices[lo:hi].tolist(),
                        self.values[lo:hi].astype(np.float64).tolist()))

    def rows(self) -> List[SparseVector]:
        return [self.row(i) for i in range(self.batch_size)]

    @classmethod
    def from_vectors(cls, vectors: Sequence[SparseVector]) -> "CSRBatch":
        """Pack per-datum SparseVectors (the per-datum converter's output)
        — the parity bridge between the two pipelines."""
        counts = np.fromiter((len(v) for v in vectors), dtype=np.int64,
                             count=len(vectors))
        off = np.zeros(len(vectors) + 1, dtype=np.int64)
        np.cumsum(counts, out=off[1:])
        idx = np.zeros(int(off[-1]), dtype=np.int32)
        val = np.zeros(int(off[-1]), dtype=np.float32)
        for i, vec in enumerate(vectors):
            if not vec:
                continue
            lo = int(off[i])
            idx[lo:lo + len(vec)] = [j for j, _ in vec]
            val[lo:lo + len(vec)] = [w for _, w in vec]
        return cls(idx, val, off)

    def uniform_row(self) -> Optional[np.ndarray]:
        """The shared index row if EVERY row carries the same index vector
        (fixed key schema — the common production feed), else None.
        Unlocks the dense submatrix device plans (ops.*_schema)."""
        b = self.batch_size
        if b == 0:
            return None
        counts = np.diff(self.row_offsets)
        k = int(counts[0])
        if k == 0 or not (counts == k).all():
            return None
        mat = self.indices.reshape(b, k)
        if b > 1 and not (mat == mat[0]).all():
            return None
        return mat[0]

    def to_padded(self, min_width: int = 8,
                  batch_bucket: int = 1) -> "SparseBatch":
        """Vectorized pad into the [B, K] device interchange format —
        the CSR equivalent of SparseBatch.from_vectors (same pow2 width
        and optional row bucketing, no Python per-row loop)."""
        b = self.batch_size
        counts = np.diff(self.row_offsets)
        bsz = _bucket(max(b, 1), batch_bucket) if batch_bucket > 1 \
            else max(b, 1)
        width = _bucket(int(counts.max()) if b else 1, min_width)
        idx = np.zeros((bsz, width), dtype=np.int32)
        val = np.zeros((bsz, width), dtype=np.float32)
        if self.nnz:
            rows = np.repeat(np.arange(b), counts)
            cols = np.arange(self.nnz) - np.repeat(
                self.row_offsets[:-1], counts)
            idx[rows, cols] = self.indices
            val[rows, cols] = self.values
        return SparseBatch(idx, val)


class SparseBatch:
    """A batch of hashed sparse feature vectors as padded numpy arrays.

    Attributes:
      idx:  int32  [B, K] feature indices (0 for padding)
      val:  float32 [B, K] feature values (0.0 for padding)
    """

    __slots__ = ("idx", "val")

    def __init__(self, idx: np.ndarray, val: np.ndarray) -> None:
        assert idx.shape == val.shape and idx.ndim == 2
        self.idx = idx
        self.val = val

    @property
    def batch_size(self) -> int:
        return self.idx.shape[0]

    @property
    def width(self) -> int:
        return self.idx.shape[1]

    @classmethod
    def from_vectors(
        cls,
        vectors: Sequence[SparseVector],
        min_width: int = 8,
        batch_bucket: int = 1,
    ) -> "SparseBatch":
        """Pack hashed sparse vectors into padded arrays.

        Widths (and optionally batch sizes) are rounded up to power-of-two
        buckets to bound the number of distinct XLA compilations.
        """
        n = len(vectors)
        bsz = _bucket(max(n, 1), batch_bucket) if batch_bucket > 1 else max(n, 1)
        width = _bucket(max((len(v) for v in vectors), default=1), min_width)
        idx = np.zeros((bsz, width), dtype=np.int32)
        val = np.zeros((bsz, width), dtype=np.float32)
        for i, vec in enumerate(vectors):
            if not vec:
                continue
            k = len(vec)
            idx[i, :k] = [j for j, _ in vec]
            val[i, :k] = [w for _, w in vec]
        return cls(idx, val)

    def pad_aux(self, aux: Sequence, fill=0, dtype=None) -> np.ndarray:
        """Pad a per-example array (labels, targets) to this batch's row count.

        Required when batch_bucket > 1 added all-zero padding rows: training
        kernels gate updates on ||x||^2 > 0, so padded rows are no-ops for
        any in-range fill value.
        """
        out = np.full(self.batch_size, fill, dtype=dtype or np.asarray(aux).dtype)
        out[: len(aux)] = aux
        return out

    def squared_norms(self) -> np.ndarray:
        return (self.val.astype(np.float64) ** 2).sum(axis=1)

    def __len__(self) -> int:
        return self.batch_size
