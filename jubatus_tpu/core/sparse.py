"""Padded sparse-vector batches — the host↔device interchange format.

The reference keeps feature vectors as string-keyed sparse maps
(core::fv_converter sfv, consumed per-datum under a write lock — SURVEY.md
§3.2). On TPU the model plane wants fixed shapes: a feature vector is hashed
into a 2^k index space (fv/hashing.py) and a *batch* of vectors is a pair of
dense arrays (indices, values) padded to a common nnz. Padding entries carry
value 0.0 so they are no-ops in every kernel (gathers multiply by 0, scatter
adds add 0).

Pad widths are bucketed to powers of two so XLA recompiles O(log max_nnz)
times, not per batch shape.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

# (index, weight) pairs, already hashed. The canonical sparse vector type.
SparseVector = List[Tuple[int, float]]


def _bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class SparseBatch:
    """A batch of hashed sparse feature vectors as padded numpy arrays.

    Attributes:
      idx:  int32  [B, K] feature indices (0 for padding)
      val:  float32 [B, K] feature values (0.0 for padding)
    """

    __slots__ = ("idx", "val")

    def __init__(self, idx: np.ndarray, val: np.ndarray) -> None:
        assert idx.shape == val.shape and idx.ndim == 2
        self.idx = idx
        self.val = val

    @property
    def batch_size(self) -> int:
        return self.idx.shape[0]

    @property
    def width(self) -> int:
        return self.idx.shape[1]

    @classmethod
    def from_vectors(
        cls,
        vectors: Sequence[SparseVector],
        min_width: int = 8,
        batch_bucket: int = 1,
    ) -> "SparseBatch":
        """Pack hashed sparse vectors into padded arrays.

        Widths (and optionally batch sizes) are rounded up to power-of-two
        buckets to bound the number of distinct XLA compilations.
        """
        n = len(vectors)
        bsz = _bucket(max(n, 1), batch_bucket) if batch_bucket > 1 else max(n, 1)
        width = _bucket(max((len(v) for v in vectors), default=1), min_width)
        idx = np.zeros((bsz, width), dtype=np.int32)
        val = np.zeros((bsz, width), dtype=np.float32)
        for i, vec in enumerate(vectors):
            if not vec:
                continue
            k = len(vec)
            idx[i, :k] = [j for j, _ in vec]
            val[i, :k] = [w for _, w in vec]
        return cls(idx, val)

    def pad_aux(self, aux: Sequence, fill=0, dtype=None) -> np.ndarray:
        """Pad a per-example array (labels, targets) to this batch's row count.

        Required when batch_bucket > 1 added all-zero padding rows: training
        kernels gate updates on ||x||^2 > 0, so padded rows are no-ops for
        any in-range fill value.
        """
        out = np.full(self.batch_size, fill, dtype=dtype or np.asarray(aux).dtype)
        out[: len(aux)] = aux
        return out

    def squared_norms(self) -> np.ndarray:
        return (self.val.astype(np.float64) ** 2).sum(axis=1)

    def __len__(self) -> int:
        return self.batch_size
