"""Server framework: driver lifecycle, save/load, mixer scheduling, config.

Rebuild of jubatus/server/framework/ (SURVEY.md §2.3) minus what a static TPU
mesh makes unnecessary (ZooKeeper master election, CHT ring maintenance).
"""

from jubatus_tpu.framework.driver import DriverBase  # noqa: F401
from jubatus_tpu.framework.save_load import load_model, save_model  # noqa: F401
from jubatus_tpu.framework.sharded_checkpoint import (  # noqa: F401
    load_sharded,
    save_sharded,
)
from jubatus_tpu.framework.mixer import IntervalMixer  # noqa: F401
