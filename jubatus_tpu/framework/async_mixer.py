"""Asynchronous staleness-bounded mix — the round barrier off the
serving path (ISSUE 11 / ROADMAP item 3).

Every synchronous mix mode is a pulled round: the master fans out
``get_diff``, folds while every contributor's freshness decays, and a
below-quorum round aborts AFTER the gather is already paid. One slow or
dead member stalls the whole fleet ("Exploring the limits of
Concurrency in ML Training on Google TPUs": past a point you must
overlap communication with compute or scaling dies; "TensorFlow: A
system for large-scale machine learning" treats asynchronous,
staleness-tolerant updates as the baseline posture for a fleet of
unreliable workers).

Here rounds stream continuously in the background and nothing on the
serving path ever waits for one:

- **Members push, the master folds.** Each member's scheduler tick
  snapshots its local diff (the only model-lock hold — gauged as
  ``mix.snapshot_stall_ms``) and SUBMITS it to the current master over
  ``mix_submit_diff``, then returns. No member ever blocks inside a
  round: the wire transfer, the fold, and the broadcast all happen on
  other threads while train/classify keep running against the current
  model snapshot.
- **A diff inbox replaces the gather.** The master keeps the latest
  submitted payload per member (successive ``get_diff`` snapshots are
  cumulative — put_diff resets accumulation — so latest-wins is exact,
  not lossy). The fold tick consumes whatever has arrived; an empty
  inbox is an idle tick, not an abort.
- **Bounded-staleness weights replace quorum aborts.** Every payload
  carries the model version it was snapshot against. At fold time its
  staleness is ``base - version`` (one fold == one version bump, so
  this is rounds-stale); its fold weight decays as ``2**-staleness``
  and past ``--mix-staleness-bound`` the payload is dropped
  (``mix.async_dropped_stale``). A straggler therefore degrades its
  OWN contribution instead of stalling or aborting the round.
- **Double-buffered apply.** The fold's broadcast applies through the
  same ``local_put_obj`` every mode uses: unpack and version gating
  happen OFF the model lock, the lock is held only for the put_diff
  swaps, and the model version bumps INSIDE the lock — concurrent
  train/classify see a consistent (model, version) pair and a monotone
  ``mix.model_version`` gauge, never a torn intermediate.

The degradation ladder, in order: fresh (weight 1) → decayed
(``2**-s``) → dropped (``s > bound``, resubmits next tick) → obsolete
(missed applies; the existing full-model recovery pulls it back).
The convergence telemetry from ISSUE 7 (``mix.premix_divergence_*``,
``mix.staleness_max``, EF drift) is computed per fold exactly as the
sync master does, so the async plane's learning health is measured by
the same gauges — the drift-parity gate in the bench and tests holds
async divergence to the sync plane's.

Master discovery: the fold-tick winner of the coordinator master lock
publishes its node name at ``<actor>/async_master``; submitters read
it per tick (one coordinator read) and push there. A dead master's
hint goes stale harmlessly — submits fail fast through the breaker
board, and the next fold tick's lock winner republishes.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jubatus_tpu.coord import membership
from jubatus_tpu.coord.base import NodeInfo
from jubatus_tpu.framework.linear_mixer import (
    PROTOCOL_VERSION,
    RpcLinearMixer,
    _sum_names,
    mix_health,
    pack_mix,
    unpack_mix,
)
from jubatus_tpu.framework import model_guard
from jubatus_tpu.parallel.mix import tree_sum
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.utils import faults

log = logging.getLogger(__name__)

#: default rounds-stale bound (--mix-staleness-bound): weight has
#: decayed to 2**-8 ≈ 0.4% by the time a payload is dropped outright
DEFAULT_STALENESS_BOUND = 8


def fold_weight(staleness: int, bound: int) -> float:
    """Bounded-staleness fold weight: 1.0 when fresh, halved per round
    stale (the payload's information content decays geometrically as
    folds it missed land on top of its base), 0.0 past the bound —
    the drop that replaces the sync plane's quorum abort."""
    if staleness <= 0:
        return 1.0
    if staleness > bound:
        return 0.0
    return 2.0 ** -staleness


def _scale_leaf(x: Any, w: float) -> Any:
    """One diff leaf scaled by a fold weight, dtype-preserving: integer
    count leaves stay integral (truncation IS the down-weighting) so
    put_diff consumers never see a surprise float table."""
    y = x * w
    dt = getattr(x, "dtype", None)
    if dt is not None and getattr(y, "dtype", None) != dt:
        y = y.astype(dt)
    return y


def scale_tree(diff: Any, w: float) -> Any:
    """A diff pytree scaled by a staleness weight (identity at 1.0)."""
    if w == 1.0:
        return diff
    import jax

    return jax.tree_util.tree_map(lambda x: _scale_leaf(x, w), diff)


def _merge_delta_tree(a: Any, b: Any) -> Any:
    """Combine TWO DELTAS OF ONE MEMBER (an apply-time capture + a
    fresh snapshot) leaf-wise. Array leaves add (with tree_sum's
    trailing-row pad); EQUAL 0-d scalar leaves keep one copy — those
    are per-payload normalization markers (e.g. the classifier's
    replica-count leaf the cluster fold sums to average weights), and
    one member's two deltas are still ONE replica's contribution."""
    import jax

    def comb(x, y):
        xs = getattr(x, "shape", None)
        ys = getattr(y, "shape", None)
        if xs in ((), None) and ys in ((), None):
            try:
                if float(x) == float(y):
                    return x
            except (TypeError, ValueError):
                pass
        return tree_sum([x, y])  # no-guard — one member's own two deltas
        # (capture + fresh snapshot); admission screening happens when
        # the merged payload reaches an inbox or fold

    return jax.tree_util.tree_map(comb, a, b)


class DiffInbox:
    """Latest-diff-per-member store on the master — the async plane's
    replacement for the get_diff gather. ``submit`` keeps only the
    newest payload per member (cumulative snapshots make that exact);
    ``drain`` consumes everything for one fold."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, Any]] = {}
        self.submits = 0

    def submit(self, member: str, payload: Dict[str, Any]) -> None:
        entry = {"payload": payload,
                 "version": int(payload.get("version", 0)),
                 "ts": time.monotonic()}
        with self._lock:
            self._entries[member] = entry
            self.submits += 1

    def drain(self) -> Dict[str, Dict[str, Any]]:
        """Consume every pending entry (one fold's input). Entries are
        folded at most once — a silent member contributes nothing to
        later folds rather than replaying its last delta."""
        with self._lock:
            entries, self._entries = self._entries, {}
        return entries

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)


class AsyncLinearMixer(RpcLinearMixer):
    """RpcLinearMixer whose rounds stream in the background: members
    push diffs asynchronously, the master folds its inbox with
    bounded-staleness weights, and nothing blocks the serving path.
    Serves the whole linear-mixer RPC surface plus ``mix_submit_diff``
    / ``mix_async_status``, so recovery, health telemetry, and the
    flight recorder ride the existing machinery unchanged."""

    def __init__(self, driver: Any, comm: Any, *,
                 staleness_bound: int = DEFAULT_STALENESS_BOUND,
                 **kwargs) -> None:
        super().__init__(driver, comm, **kwargs)
        self.staleness_bound = int(staleness_bound)
        self.inbox = DiffInbox()
        #: fold ticks fire on the interval even when THIS node saw no
        #: local updates — other members' submissions may be pending
        self._scheduler.fire_idle = True
        self.async_rounds = 0
        self.async_dropped_stale = 0
        self.async_submit_errors = 0
        #: set by a fold whose every payload was schema-deferred (the
        #: fold tick retries once with a realigned self snapshot)
        self._fold_all_deferred = False
        #: member-side view of its own distance from the master's fold
        #: cadence, refreshed from every submit ack (base - my version)
        self.async_lag_rounds = 0
        #: master hint this member last submitted to (status/debugging)
        self.async_master = ""
        #: update_count at the last snapshot this member shipped: a
        #: tick with no new local updates submits nothing (a zero diff
        #: would only dilute the fold's contributor accounting)
        self._last_submitted_updates = -1
        #: pooled submit client, keyed by the master it points at
        self._submit_cli: Optional[RpcClient] = None
        self._submit_target = ""
        self._submit_lock = threading.Lock()
        #: accumulation captured just before a broadcast apply would
        #: have reset it unfolded (this member was not among the
        #: fold's contributors) — merged into the next submission
        self._captured: Optional[Dict[str, Any]] = None
        self._captured_lock = threading.Lock()
        #: update_count at the last successful apply (= accumulator
        #: reset): an accumulator with no training since the last
        #: reset is EMPTY — capturing it would only inject per-payload
        #: normalization markers (a count leaf) into a later merge
        self._updates_at_reset = getattr(driver, "update_count", 0) or 0

    # -- RPC surface ---------------------------------------------------------
    def register_api(self, rpc_server, name_check: str = "") -> None:
        super().register_api(rpc_server, name_check)
        rpc_server.register(
            "mix_submit_diff",
            lambda _n, member, packed: self.local_submit_diff(member, packed))
        rpc_server.register(
            "mix_async_status", lambda _n: self.async_status())

    def local_submit_diff(self, member: Any, packed: bytes) -> Dict[str, Any]:
        """Accept one member's pushed diff into the inbox and ack with
        my current base version (the submitter's lag gauge). Accepting
        while not (yet) master is deliberate: masterhood migrates
        tick-to-tick, and an inbox entry on a non-master is folded the
        moment this node wins the lock."""
        member = member.decode() if isinstance(member, bytes) \
            else str(member)
        # chaos site: drop = the submit is lost in transit (sender is
        # told, so the chaos ladder can distinguish drop from blackhole)
        if faults.is_armed() and faults.fire(f"mix.async.inbox.{member}"):
            return {"accepted": False, "base": int(self.model_version)}
        msg = unpack_mix(packed)
        if msg.get("protocol") != PROTOCOL_VERSION:
            return {"accepted": False, "base": int(self.model_version)}
        # inbox admission screen (ISSUE 15): the async plane has no
        # gather phase, so the finite screen runs the moment a payload
        # arrives — a poisoned submission never even occupies an inbox
        # slot (norm outliers are judged at fold time, where the peer
        # distribution exists). warn mode flags and admits.
        if self.guard.enabled:
            reason = self.guard.screen_payload(
                member, msg.get("diffs") or {},
                _sum_names(self.driver.get_mixables()))
            if reason is not None:
                if reason == "nonfinite":
                    self._count("mix.guard.nonfinite")
                if self.guard.mode == "quarantine":
                    self._count("mix.quarantined")
                    self.trace.events.emit(
                        "mix", "inbox_rejected", severity="warning",
                        member=member, reason=reason)
                    return {"accepted": False, "quarantined": True,
                            "base": int(self.model_version)}
        self.inbox.submit(member, msg)
        self._count("mix.async_submits")
        self.trace.gauge("mix.async_inbox_depth", float(self.inbox.depth()))
        return {"accepted": True, "base": int(self.model_version)}

    def async_status(self) -> Dict[str, Any]:
        return {
            "inbox_depth": self.inbox.depth(),
            "inbox_submits": self.inbox.submits,
            "rounds": self.async_rounds,
            "dropped_stale": self.async_dropped_stale,
            "submit_errors": self.async_submit_errors,
            "lag_rounds": self.async_lag_rounds,
            "master": self.async_master,
            "staleness_bound": self.staleness_bound,
            "model_version": self.model_version,
        }

    # -- apply-time capture (loss-window closure) ----------------------------
    def local_put_obj(self, msg) -> bool:
        self._capture_before_apply(msg)
        ok = super().local_put_obj(msg)
        if ok:
            # the apply reset the accumulators; training that lands in
            # the microseconds between the reset and this read may be
            # classed pre-reset (skipped by a later capture gate) —
            # the same loss window a sync apply always had
            self._updates_at_reset = getattr(
                self.driver, "update_count", 0) or 0
        return ok

    def _capture_before_apply(self, msg) -> None:
        """A broadcast apply resets local accumulation whether or not
        this member's diff made the fold (reference ``put_diff``
        semantics — the sync plane destroys a failed-gather member's
        accumulation identically). When this member is NOT among the
        fold's contributors, nothing of its accumulator was folded —
        capture it before the reset and merge it into the next
        submission, so a fold landing between this member's submits
        (bootstrap before the first master election, a master folding
        faster than a member ticks) destroys nothing. Contributors
        skip: their accumulators contain already-folded content and a
        capture would double-count — their loss window is exactly the
        sync plane's [get_diff, put_diff] window."""
        try:
            contributors = {c.decode() if isinstance(c, bytes) else str(c)
                            for c in (msg.get("contributors") or [])}
            me = self.self_node.name if self.self_node is not None \
                else "self"
            if not contributors or me in contributors:
                return  # pre-capture-era master, or my diff was folded
            updates = getattr(self.driver, "update_count", None)
            if updates is not None and updates == self._updates_at_reset:
                # nothing trained since the last reset: the
                # accumulator is empty — there is nothing to save
                return
            with self._captured_lock:
                have = self._captured is not None
            if updates is not None and \
                    updates == self._last_submitted_updates and not have:
                # everything trained is already submitted: the inbox's
                # latest-wins copy (or a past fold) covers it
                return
            snap = self.local_diff_obj(materialize=True,
                                       canonical_schema=True)
            self._count("mix.async_captures")
            with self._captured_lock:
                prev = self._captured
                # a second consecutive non-contributor apply: the new
                # accumulator holds only post-first-capture updates —
                # merging keeps the total
                self._captured = snap if prev is None \
                    else self._merge_payloads(prev, snap)
            if updates is not None:
                self._last_submitted_updates = updates
        except Exception:  # broad-ok — capture is best-effort protection
            log.warning("pre-apply capture failed", exc_info=True)

    def _merge_payloads(self, cap: Dict[str, Any],
                        fresh: Dict[str, Any]) -> Dict[str, Any]:
        """Merge a captured payload into a fresh snapshot (both are
        deltas; summable mixables add, custom-mix ones fold). Row
        alignment: the capture's schema must be a sorted PREFIX of the
        fresh schema (vocabularies grow; tree_sum pads trailing rows)
        — a rare non-prefix capture (novel early-sorting label in
        between) cannot be realigned and is dropped, counted."""
        cs = [s.decode() if isinstance(s, bytes) else s
              for s in (cap.get("schema") or [])]
        fs = [s.decode() if isinstance(s, bytes) else s
              for s in (fresh.get("schema") or [])]
        if cs != fs[:len(cs)]:
            self._count("mix.async_capture_dropped")
            return fresh
        mixables = self.driver.get_mixables()
        diffs = dict(fresh["diffs"])
        for name, d in (cap.get("diffs") or {}).items():
            if name not in diffs:
                diffs[name] = d
                continue
            m = mixables.get(name)
            custom = getattr(m, "mix", None) if m is not None else None
            if custom is not None and \
                    not getattr(m, "MIX_IS_SUM", False):
                diffs[name] = functools.reduce(custom, [d, diffs[name]])
            else:
                diffs[name] = _merge_delta_tree(d, diffs[name])
        return dict(fresh, diffs=diffs)

    def _with_captured(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Fold any apply-time capture into an outgoing snapshot (the
        capture rides the fresh stamp: additive deltas a sync round
        would have gathered at full weight one round later)."""
        with self._captured_lock:
            cap, self._captured = self._captured, None
        if cap is None:
            return payload
        return self._merge_payloads(cap, payload)

    # -- master discovery ----------------------------------------------------
    def _hint_path(self) -> str:
        actor = membership.actor_path(self.comm.engine, self.comm.name)
        return f"{actor}/async_master"

    def _publish_master_hint(self) -> None:
        if self.self_node is None:
            return
        try:
            if not self.comm.coord.set(
                    self._hint_path(), self.self_node.name.encode()):
                self.comm.coord.create(
                    self._hint_path(), self.self_node.name.encode())
        except Exception:  # broad-ok — next fold tick republishes
            log.debug("async master hint publish failed", exc_info=True)

    def _master_hint(self) -> Optional[NodeInfo]:
        try:
            raw = self.comm.coord.read(self._hint_path())
        except Exception:  # broad-ok — transient coordinator issue
            return None
        if not raw:
            return None
        try:
            return NodeInfo.from_name(raw.decode())
        except (ValueError, IndexError):
            return None

    # -- member side: the push ----------------------------------------------
    def submit_now(self) -> bool:
        """One submit tick, callable directly (tests, jubactl drills):
        snapshot my diff and push it at the current master."""
        members = self.comm.update_members()
        return self._submit_tick(members)

    def _submit_client(self, master: NodeInfo) -> RpcClient:
        with self._submit_lock:
            if self._submit_cli is None or \
                    self._submit_target != master.name:
                if self._submit_cli is not None:
                    try:
                        self._submit_cli.close()
                    except Exception:  # broad-ok — stale socket teardown
                        pass
                self._submit_cli = RpcClient(
                    master.host, master.port,
                    getattr(self.comm, "timeout", 10.0))
                self._submit_target = master.name
            return self._submit_cli

    def _drop_submit_client(self) -> None:
        with self._submit_lock:
            cli, self._submit_cli = self._submit_cli, None
            self._submit_target = ""
        if cli is not None:
            try:
                cli.close()
            except Exception:  # broad-ok
                pass

    def _submit_tick(self, members: Sequence[NodeInfo]) -> bool:
        if self.self_node is None:
            return False
        master = self._master_hint()
        self.async_master = master.name if master is not None else ""
        if master is None or master.name == self.self_node.name:
            # no master yet (first ticks of a fresh cluster) or I am
            # it: my own fold tick enqueues my diff in-process
            return False
        updates = getattr(self.driver, "update_count", None)
        with self._captured_lock:
            have_capture = self._captured is not None
        if not have_capture and updates is not None and \
                updates == self._last_submitted_updates:
            return False  # nothing new since the last shipped snapshot
        # brief model-lock hold (gauged); materialized so later train
        # steps cannot donate the snapshot's buffers mid-flight.
        # An apply-time capture merges in; on a FAILED submit the
        # capture is re-stashed (unlike the fresh snapshot, its
        # content no longer lives in the accumulator).
        with self._captured_lock:
            cap, self._captured = self._captured, None
        payload = self.local_diff_obj(materialize=True,
                                      canonical_schema=True)
        if cap is not None:
            payload = self._merge_payloads(cap, payload)

        def restore_capture() -> None:
            # a resubmit next tick must not be swallowed by the
            # update-count gate, and a popped capture must survive
            self._last_submitted_updates = -1
            if cap is None:
                return
            with self._captured_lock:
                self._captured = cap if self._captured is None \
                    else self._merge_payloads(cap, self._captured)

        if updates is not None:
            self._last_submitted_updates = updates
        # chaos site carries the SENDER's name so a straggler drill can
        # delay exactly one member's submissions
        if faults.is_armed() and \
                faults.fire(f"mix.async.submit.{self.self_node.name}"):
            restore_capture()  # the snapshot never left this process
            return False
        packed = pack_mix(payload)
        try:
            with self.trace.span("mix.phase.submit"):
                ack = self._submit_client(master).call(
                    "mix_submit_diff", self.comm.name,
                    self.self_node.name, packed)
        except Exception as e:  # broad-ok — submit is fire-and-forget
            self.async_submit_errors += 1
            self._count("mix.async_submit_errors")
            self._drop_submit_client()
            self.flight.record("async_submit", ok=False,
                               reason=f"{type(e).__name__}: {e}",
                               master=master.name)
            restore_capture()
            return False
        ack = {(k.decode() if isinstance(k, bytes) else str(k)): v
               for k, v in (ack or {}).items()}
        base = int(ack.get("base", 0))
        self.async_lag_rounds = max(0, base - int(payload["version"]))
        self.trace.gauge("mix.async_lag_rounds",
                         float(self.async_lag_rounds))
        self.bytes_sent += len(packed)
        accepted = bool(ack.get("accepted"))
        if not accepted:
            # refused (injected drop / protocol gate): the snapshot
            # never landed — next tick resubmits, the capture survives
            restore_capture()
        return accepted

    # -- the streaming round -------------------------------------------------
    def _mix_round(self) -> Optional[Dict[str, Any]]:
        if self._obsolete:
            self.maybe_recover()
        members = self.comm.update_members()
        if len(members) < 2 and self.self_node is not None:
            return None  # nothing to mix with
        self._submit_tick(members)
        if not self.comm.try_lock():
            return None  # submit-only tick; someone else folds
        try:
            self._publish_master_hint()
            if self.self_node is not None and \
                    self.async_master != self.self_node.name:
                # event plane (ISSUE 14): a new fold-lock winner is an
                # async-mix master election — emitted only on CHANGE
                # (the same master re-winning every tick is not news)
                self.trace.events.emit(
                    "mix", "async_master_elected",
                    master=self.self_node.name,
                    previous=self.async_master or None)
            if self.self_node is not None:
                self.async_master = self.self_node.name
            return self._fold_round(members)
        finally:
            self.comm.unlock()

    def _enqueue_own_diff(self) -> None:
        """The master's own contribution enters through the same inbox
        as everyone else's (freshest possible stamp, no special-cased
        fold path)."""
        updates = getattr(self.driver, "update_count", None)
        with self._captured_lock:
            have_capture = self._captured is not None
        if not have_capture and updates is not None and \
                updates == self._last_submitted_updates:
            return
        name = self.self_node.name if self.self_node is not None else "self"
        # materialized: unlike RPC-submitted payloads (wire copies),
        # the in-process snapshot would otherwise reference LIVE model
        # buffers a train step could donate out from under the fold
        self.inbox.submit(name, self._with_captured(self.local_diff_obj(
            materialize=True, canonical_schema=True)))
        if updates is not None:
            self._last_submitted_updates = updates

    def _fold_round(self, members: Sequence[NodeInfo]
                    ) -> Optional[Dict[str, Any]]:
        t0 = time.monotonic()
        phases: Dict[str, Any] = {}
        self._enqueue_own_diff()
        entries = self.inbox.drain()
        self.trace.gauge("mix.async_inbox_depth", 0.0)
        if not entries:
            return None  # idle tick — nothing arrived since last fold
        with self.trace.span("mix.phase.fold") as sp:
            self._fold_all_deferred = False
            folded = self._weighted_fold(entries)
            if folded is None and self._fold_all_deferred:
                # every payload was schema-deferred, but the union
                # sync just realigned OUR vocabulary too: retry once
                # with a fresh self snapshot so the tick still folds
                # (peers' deferred payloads return next tick aligned)
                self._last_submitted_updates = -1
                self._enqueue_own_diff()
                retry = self.inbox.drain()
                if retry:
                    folded = self._weighted_fold(retry)
        phases["fold_ms"] = round(sp.seconds * 1e3, 2)
        if folded is None:
            return None  # everything stale/deferred; next tick retries
        packed, meta = folded
        with self.trace.span("mix.phase.put_diff") as sp:
            # broadcast of a fold that _weighted_fold already screened
            acks = self.comm.put_diff(packed)  # no-guard — pre-screened
        phases["put_diff_ms"] = round(sp.seconds * 1e3, 2)
        for member in members:
            if not acks.get(member.name, False):
                self.comm.register_active(member, False)
        self.mix_count += 1
        self.async_rounds += 1
        self.bytes_sent += len(packed)
        self._count("mix.async_rounds")
        self._count("mix.bytes_shipped", len(packed))
        log.info("async mix round %d: %d/%d contributors (%d stale-"
                 "dropped), %d bytes, %.3fs", self.async_rounds,
                 meta["contributors"], len(entries), meta["dropped"],
                 len(packed), time.monotonic() - t0)
        epoch = self.comm.membership_epoch() \
            if hasattr(self.comm, "membership_epoch") else 0
        if epoch:
            self.trace.gauge("mix.epoch", float(epoch))
        return {"members": len(members), "bytes": len(packed),
                "mode": "async", "phases": phases,
                "contributors": meta["contributors"],
                "dropped_stale": meta["dropped"] or None,
                "deferred_schema": meta["deferred"] or None,
                "quarantined": meta.get("quarantined"),
                "weights": meta["weights"],
                "base_version": meta["base_version"],
                "epoch": epoch or None,
                "health": meta["health"] or None,
                "acked": sum(bool(v) for v in acks.values())}

    def _weighted_fold(self, entries: Dict[str, Dict[str, Any]]
                       ) -> Optional[Tuple[bytes, Dict[str, Any]]]:
        """Fold the inbox with bounded-staleness weights. Returns the
        packed put_diff broadcast + round metadata, or None when no
        payload survived the staleness/schema gates."""
        base_version = max(
            max(e["version"] for e in entries.values()),
            self.model_version)
        weights: Dict[str, float] = {}
        dropped = 0
        live: Dict[str, Dict[str, Any]] = {}
        for member, e in entries.items():
            staleness = max(0, base_version - e["version"])
            w = fold_weight(staleness, self.staleness_bound)
            if w == 0.0:
                dropped += 1
                continue
            weights[member] = round(w, 6)
            live[member] = e
        if dropped:
            self.async_dropped_stale += dropped
            self._count("mix.async_dropped_stale", dropped)
        if not live:
            return None
        # model-integrity admission screen (ISSUE 15): the inbox's
        # finite screen ran at submit time, but the NORM screen needs
        # this fold's peer distribution — and the master's own
        # in-process enqueue skipped the inbox screen entirely. Same
        # ladder as the sync master: warn counts, quarantine drops.
        quarantined_round: List[str] = []
        if self.guard.enabled:
            rep = self._guard_screen(
                {m: e["payload"]["diffs"] for m, e in live.items()},
                _sum_names(self.driver.get_mixables()))
            quarantined_round = sorted(rep.flagged)
            if self.guard.mode == "quarantine" and rep.flagged:
                for m in rep.flagged:
                    live.pop(m, None)
                    weights.pop(m, None)
                if not live:
                    self._count("mix.guard.all_quarantined")
                    return None
        # schema gate. The broadcast's schema must be the union of the
        # WHOLE cluster's vocabularies, not just this fold's
        # contributors — members apply it via sync_schema, and a
        # narrower union would shrink their label tables (drop rows).
        # So schema-bearing engines pay one failure-tolerant
        # get_schemas fan-out per fold (tiny lists; breakers skip dead
        # members — this is the sync round's phase 1, off the serving
        # path). Row alignment: diff rows sit in sorted-vocabulary
        # order (the snapshot self-canonicalizes), so a payload whose
        # schema is a sorted PREFIX of the union is foldable as-is
        # (absent trailing rows contribute zeros, exactly the pad
        # tree_sum applies); a non-prefix payload cannot be realigned
        # after the fact — it defers one tick while the union
        # broadcast realigns its owner's vocabulary.
        schemas = {m: [s.decode() if isinstance(s, bytes) else s
                       for s in (e["payload"].get("schema") or [])]
                   for m, e in live.items()}
        vocab = set().union(*(set(s) for s in schemas.values())) \
            if schemas else set()
        if self._has_schema():
            with self.driver.lock:
                vocab |= set(self.driver.get_schema())
            try:
                for s in self.comm.get_schemas():
                    vocab |= {x.decode() if isinstance(x, bytes) else x
                              for x in s}
            except Exception:  # broad-ok — degraded union this tick
                log.warning("async schema fan-out failed", exc_info=True)
        union = sorted(vocab)
        deferred = 0
        if union:
            misaligned = [m for m, s in schemas.items()
                          if s != union[:len(s)]]
            if misaligned:
                self.comm.sync_schema(union)
                self._count("mix.async_schema_deferred", len(misaligned))
                deferred = len(misaligned)
                for m in misaligned:
                    weights.pop(m, None)
                    live.pop(m, None)
                if not live:
                    # everything deferred this tick; the union sync
                    # above realigned vocabularies (ours included) —
                    # the caller may retry once with a fresh snapshot
                    self._fold_all_deferred = True
                    return None
        payloads = [(weights[m], e["payload"]) for m, e in live.items()]
        mixables = self.driver.get_mixables()
        totals: Dict[str, Any] = {}
        for name, mixable in mixables.items():
            pairs = [(w, p["diffs"][name]) for w, p in payloads
                     if name in p["diffs"]]
            if not pairs:
                continue
            custom_mix = getattr(mixable, "mix", None)
            if custom_mix is not None and \
                    not getattr(mixable, "MIX_IS_SUM", False):
                # dict-shaped custom folds (bandit, row stores) have no
                # meaningful scalar weighting — staleness still gates
                # them (dropped past the bound), freshness does not
                totals[name] = functools.reduce(
                    custom_mix, [d for _, d in pairs])
            else:
                totals[name] = tree_sum(
                    [scale_tree(d, w) for w, d in pairs])
        if weights:
            self.trace.gauge("mix.async_fold_weight_min",
                             min(weights.values()))
        # fold-total finite screen (ISSUE 15): same contract as the
        # sync master — a non-finite total is never broadcast in
        # quarantine mode (warn counts and proceeds)
        if self.guard.enabled and \
                model_guard.payload_nonfinite(totals,
                                              _sum_names(mixables)):
            self._count("mix.guard.nonfinite_total")
            self.trace.events.emit(
                "mix", "nonfinite_fold_total", severity="error",
                mode=self.guard.mode)
            if self.guard.mode == "quarantine":
                log.error("async fold aborted: total is non-finite")
                return None
        health = mix_health([p["diffs"] for _, p in payloads], totals,
                            _sum_names(mixables))
        members = self.comm._members if hasattr(self.comm, "_members") \
            else []
        health.update(self._staleness_update(members, set(live)))
        # the broadcast names its contributors: a member NOT listed
        # knows the apply is about to reset an accumulator nothing of
        # which was folded — it captures first (_capture_before_apply)
        packed = pack_mix(
            {"protocol": PROTOCOL_VERSION, "schema": union,
             "base_version": base_version, "diffs": totals,
             "contributors": sorted(live), "health": health})
        return packed, {"contributors": len(live), "dropped": dropped,
                        "deferred": deferred, "weights": weights,
                        "quarantined": quarantined_round or None,
                        "base_version": base_version, "health": health}

    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update({
            "async_mode": True,
            "async_rounds": self.async_rounds,
            "async_inbox_depth": self.inbox.depth(),
            "async_inbox_submits": self.inbox.submits,
            "async_dropped_stale": self.async_dropped_stale,
            "async_submit_errors": self.async_submit_errors,
            "async_lag_rounds": self.async_lag_rounds,
            "async_master": self.async_master,
            "staleness_bound": self.staleness_bound,
        })
        return st

    def stop(self) -> None:
        super().stop()
        self._drop_submit_client()
