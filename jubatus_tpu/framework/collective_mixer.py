"""Collective mixer — the production mix as a device collective.

``--mixer collective_mixer``: the control plane stays on the coordinator
and RPC (master election via the coordinator lock, schema sync, a
two-phase prepare/GO), but the DIFF payload — the reference's get_diff
fan-out, pairwise fold, and put_diff broadcast (linear_mixer.cpp:437-559)
— moves onto the accelerator interconnect as one psum across the
``jax.distributed`` world (parallel/collective.py). This is SURVEY.md §7
step 3's north-star component: the fold IS the AllReduce combiner, so a
Criteo-shaped round ships over ICI/DCN at interconnect bandwidth instead
of TCP through msgpack.

Round protocol (master = this round's lock holder):

1. ``mix_prepare(round, schema_union)`` (RPC): every member syncs the
   schema, STAGES its local diff under the model lock, starts a GO
   waiter, and answers (version, shape-signature). Nothing has entered a
   collective yet.
2. The master verifies every member staged with identical signatures and
   that the jax process world matches the member set — any mismatch
   aborts the round (members discard their staged diff; waiters exit)
   and the round falls back to the plain RPC mix, so the cluster always
   mixes.
3. The master writes a GO marker into the COORDINATOR (not an RPC): a
   member enters the collective only when it OBSERVES the marker, and
   every live member polling shared state eventually observes it — no
   single dropped message can leave part of the world inside the psum
   (the failure the commit-RPC design had). Each member then enters
   ``psum_pytree`` with its staged diff, applies the identical total
   with the same obsolete/active semantics as the RPC path, and writes
   an ack node the master folds into the actives transitions.

Failure model, closed loop: a member that never observes GO times out
and makes a FINAL verification read before discarding its stage — GO
present after all: enter late (peers are waiting in the psum); GO
verifiably absent: discard, nobody entered on this rid; coordinator
UNREADABLE (absence unverifiable — peers may be inside the collective
while this member cannot know): the member tears down its own
jax.distributed world, which the runtime's heartbeat turns into an error
on every peer's psum — bounded entry, never a silent wedge — and routes
its future rounds to the RPC fallback. A member that dies after entering
is detected the same way. A member that loses the coordinator stops via
its own session handling, which is the same death the runtime then
detects. Engines whose mixables are not plain-sum (dict-shaped diffs:
bandit, burst, row stores) are detected in prepare and served by the RPC
fallback path unchanged.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from jubatus_tpu.coord import membership
from jubatus_tpu.coord.base import NodeInfo
from jubatus_tpu.framework.linear_mixer import (
    PROTOCOL_VERSION,
    RpcLinearMixer,
)
from jubatus_tpu.utils.serialization import pack_obj, unpack_obj

log = logging.getLogger(__name__)

#: how long a prepared member waits to observe the GO marker before
#: discarding its staged diff (master write + coordinator poll latency;
#: generous because nobody is blocked in a collective while waiting)
GO_WAIT_SEC = 20.0
_GO_POLL_SEC = 0.05


def _summable(mixable: Any) -> bool:
    return getattr(mixable, "mix", None) is None or \
        getattr(mixable, "MIX_IS_SUM", False)


def elect_representatives(member_names, topo) -> Dict[int, str]:
    """host index -> the member fronting that host on the inter-host
    wire. Deterministic and derived ONLY from the full registered
    member list + topology (members sorted by name, grouped host-major
    — the same member↔process-order convention the world-size check
    already assumes; the group's first name represents it), NEVER from
    a round's contributor set — so a degraded / below-quorum round
    cannot reshuffle representatives, only a real membership or
    topology change can. Empty when the member count fits neither one
    process per (host, local) slot nor one per host (M local devices
    each) — the same fleets whose prepare signatures mismatch."""
    if topo is None:
        return {}
    names = sorted(member_names)
    if len(names) == topo.hosts * topo.locals:
        return {h: names[h * topo.locals] for h in range(topo.hosts)}
    if len(names) == topo.hosts:
        return {h: names[h] for h in range(topo.hosts)}
    return {}


def _signature(diffs: Dict[str, Any]) -> str:
    """Canonical shape/dtype signature; every member must match before
    anyone enters the collective (shape skew would wedge the psum).
    64-bit leaves report "unsupported": a psum in f32 would be LESS exact
    than the RPC fold, so those rounds take the fallback.

    Shapes/dtypes come from array attributes, never ``np.asarray`` — on
    a device-resident diff leaf that would be a full device→host copy of
    the payload just to read metadata (at the d24 bench shape, hundreds
    of MB per member per round)."""
    import jax
    import numpy as np

    parts: List[str] = []
    for name in sorted(diffs):
        leaves, treedef = jax.tree_util.tree_flatten(diffs[name])
        sigs = []
        for x in leaves:
            dtype = getattr(x, "dtype", None)
            shape = getattr(x, "shape", None)
            if dtype is None or shape is None:
                a = np.asarray(x)  # python scalar / list leaf
                dtype, shape = a.dtype, a.shape
            if np.dtype(dtype) in (np.dtype(np.float64), np.dtype(np.int64),
                                   np.dtype(np.uint64)):
                return "unsupported"
            sigs.append(f"{tuple(shape)}/{np.dtype(dtype)}")
        parts.append(f"{name}:{treedef}:{','.join(sigs)}")
    return "|".join(parts)


class CollectiveMixer(RpcLinearMixer):
    """RpcLinearMixer whose round rides the device collective when it can,
    and the RPC fan-out when it can't (non-sum mixables, world mismatch,
    prepare failures)."""

    def __init__(self, *args, compress: Any = False,
                 topology: str = "", **kwargs) -> None:
        super().__init__(*args, **kwargs)
        #: --mix-compress: wire mode for the psum — ``off`` ships native
        #: dtypes, ``bf16`` casts f32 diffs on device (half the
        #: interconnect bytes; additive diffs fold into an f32 master),
        #: ``int8`` rides the block-quantized collective (~4x fewer wire
        #: bytes) with this mixer's error-feedback residual keeping the
        #: averaged weights unbiased. The historical bool (True = bf16)
        #: still resolves. Folded into the prepare signature so a
        #: mixed-mode cluster falls back to the RPC mix instead of
        #: wedging the collective.
        self.compress = compress
        #: per-process chunk plan override (ISSUE 20): None rides the
        #: module default (collective.DEFAULT_CHUNK_MB). The mix-plane
        #: tuner retargets this via set_wire_plan(); because the chunk
        #: plan rides the prepare signature, a fleet mid-transition
        #: mismatches at prepare and the round falls back to the RPC
        #: mix — at most one fallback round per coherent plan change,
        #: never a wedged collective.
        self.chunk_mb: Optional[float] = None
        #: monotonically bumped by set_wire_plan (status/journal hook)
        self.plan_version = 0
        #: --mix-topology: the hierarchical-mix tier shape. ``""`` keeps
        #: the flat single-tier psum (and the legacy prepare-signature
        #: format — old peers interoperate); ``auto`` derives N hosts ×
        #: M local devices from the runtime and goes hierarchical when
        #: M > 1; an explicit ``HxM`` groups the process world (the
        #: co-located-processes deployment and the bench/test lever).
        #: The resolved ``NxM`` rides the prepare signature, so a fleet
        #: with heterogeneous topologies mismatches into the RPC
        #: fallback instead of wedging a skewed collective.
        self.topology = topology or ""
        #: resolved HostTopology for this process (lazy — resolution
        #: touches jax); None = flat
        self._topo: Optional[Any] = None
        self._topo_resolved = False
        #: last deterministic per-host representative election this
        #: member computed (master rounds refresh it; surfaced in
        #: get_status and stamped into master flight records)
        self._reps: Dict[int, str] = {}
        #: per-replica error-feedback residual pytree for int8 rounds
        #: (parallel/collective.ErrorFeedback): the quantization error of
        #: this member's shipped diff, added back into the NEXT round's
        #: diff so multi-round weight averages do not walk. Residuals are
        #: committed inside psum_pytree only when the whole entry
        #: succeeds — aborted/degraded/failed rounds leave the state of
        #: the last successful round intact. Device-resident: ~1.25x the
        #: chunked-diff payload in device memory while int8 is on.
        #: Created lazily so importing the mixer never drags jax in.
        self.ef: Optional[Any] = None
        self._staged_lock = threading.Lock()
        self._staged: Dict[str, Dict[str, Any]] = {}
        self._round_seq = 0
        self.collective_rounds = 0
        self.fallback_rounds = 0
        #: set after this member had to tear the jax world down (GO-window
        #: timeout with the coordinator unreadable): the collective plane
        #: is gone for this process; every later round mixes over RPC
        self.collective_dead = False
        #: model-integrity plane (ISSUE 15): rounds this member will
        #: answer "unsupported" at prepare after a chunk integrity
        #: failure (CRC mismatch / non-finite totals) — the next round
        #: mixes over RPC instead of re-entering a collective that just
        #: shipped or produced garbage; decremented per prepare
        self._force_rpc_rounds = 0
        self.integrity_failures = 0
        #: per-phase wall times of the last collective entry this member
        #: ran (cast/ship/reduce/readback ms + payload/wire MB) — the
        #: per-round log the reference keeps (linear_mixer.cpp:553-558)
        self.last_phases: Dict[str, Any] = {}
        #: error-feedback residual norms cached at round end (ISSUE 7):
        #: get_status and the drift-rate gauge read these instead of
        #: paying device reductions per scrape
        self._ef_norms: Dict[str, float] = {}

    def set_wire_plan(self, chunk_mb: Optional[float] = None,
                      compress: Any = None) -> Dict[str, Any]:
        """Retarget this member's wire plan (ISSUE 20 mix-plane tuner
        actuator). Only the NEXT prepare signs the new plan — a round
        already staged runs the plan it signed — and because the plan
        rides the prepare signature, a fleet applying a change
        non-simultaneously mismatches at prepare and mixes that round
        over RPC: at most one fallback round per coherent transition,
        never a wedged collective. Returns the applied plan."""
        from jubatus_tpu.parallel.collective import _norm_compress

        if chunk_mb is not None:
            self.chunk_mb = max(0.25, float(chunk_mb))
        if compress is not None:
            self.compress = _norm_compress(compress)
        self.plan_version += 1
        return {"chunk_mb": self.chunk_mb,
                "compress": _norm_compress(self.compress),
                "plan_version": self.plan_version}

    def _resolve_topology(self) -> Optional[Any]:
        """The hierarchical tier shape this member will sign and enter
        with, resolved once per process (membership does not change a
        process's device layout; a failed resolution logs and degrades
        to flat — the signature mismatch against correctly-resolved
        peers then routes the round to the RPC mix)."""
        if self._topo_resolved:
            return self._topo
        topo = None
        if self.topology:
            try:
                from jubatus_tpu.parallel.mesh import host_topology

                if self.topology == "auto":
                    t = host_topology()
                    # auto only goes hierarchical when there is an
                    # intra-host tier to exploit; Nx1 stays flat (and
                    # keeps the legacy signature format)
                    topo = t if t.locals > 1 else None
                else:
                    topo = host_topology(override=self.topology)
            except Exception:  # broad-ok — degrade to flat, peers mismatch
                log.warning("cannot resolve mix topology %r; staying flat",
                            self.topology, exc_info=True)
                topo = None
        self._topo = topo
        self._topo_resolved = True
        return topo

    # -- coordinator paths ----------------------------------------------------
    def _go_path(self) -> str:
        actor = membership.actor_path(self.comm.engine, self.comm.name)
        return f"{actor}/collective_go"

    def _ack_dir(self) -> str:
        # ONE fixed parent for every round (a per-round directory would
        # leak a durable node per round into the store/journal); leaves
        # are EPHEMERAL and carry the rid in their name
        actor = membership.actor_path(self.comm.engine, self.comm.name)
        return f"{actor}/collective_acks"

    def _ack_leaf(self, rid: str, node_name: str) -> str:
        return f"{rid.replace('/', '_')}__{node_name}"

    def _go_wait(self) -> float:
        """Member GO deadline. Must exceed the prepare fan-out's RPC
        timeout: GO is written at most one RPC timeout after the FIRST
        member staged, so every staged member's deadline safely covers
        the skew — no member can discard while another enters."""
        return max(GO_WAIT_SEC, 3.0 * getattr(self.comm, "timeout", 10.0))

    # -- RPC surface ---------------------------------------------------------
    def register_api(self, rpc_server, name_check: str = "") -> None:
        super().register_api(rpc_server, name_check)
        rpc_server.register(
            "mix_prepare", lambda _n, rid, union: self.local_prepare(rid, union))
        rpc_server.register(
            "mix_abort", lambda _n, rid: self.local_abort(rid))

    # -- member-side phases --------------------------------------------------
    def local_prepare(self, rid, union) -> List[Any]:
        rid = rid.decode() if isinstance(rid, bytes) else rid
        union = [u.decode() if isinstance(u, bytes) else u for u in union]
        with self.driver.lock:
            if union and hasattr(self.driver, "sync_schema"):
                self.driver.sync_schema(union)
            mixables = self.driver.get_mixables()
            if self._force_rpc_rounds > 0:
                # a chunk integrity failure last round (ISSUE 15):
                # route this round to the RPC mix — its fold-time
                # guard screens payloads on the host — instead of
                # re-entering the collective that shipped garbage
                self._force_rpc_rounds -= 1
                return [int(self.model_version), "unsupported"]
            if self.collective_dead or \
                    not all(_summable(m) for m in mixables.values()):
                # a dead world would fail the psum and demote this member;
                # "unsupported" routes the whole round to the RPC mix
                return [int(self.model_version), "unsupported"]
            diffs = {name: m.get_diff() for name, m in mixables.items()}
        sig = _signature(diffs)
        plan: Optional[Dict[str, Any]] = None
        if sig != "unsupported":
            # the compress mode AND the chunk plan ride the signature so
            # a mixed-mode or mixed-chunk-size cluster mismatches at
            # prepare (the chunked psum is a SEQUENCE of collectives — a
            # member chunking differently would wedge the world); the
            # "unsupported" SENTINEL must stay bare — the master's
            # fallback check matches it exactly, and a suffixed sentinel
            # would send a 64-bit round into the collective it cannot
            # ride. Old peers emit exactly "|bf16=N|chunk=M": off/bf16
            # keep that format verbatim, and int8 inserts a "|quant="
            # component an old peer never produces — so a mixed-era
            # cluster mismatches into the RPC fallback instead of
            # wedging half the world inside a quantized collective.
            from jubatus_tpu.parallel.collective import (
                DEFAULT_CHUNK_MB, QUANT_BLOCK, _norm_compress)

            # snapshot the live plan ONCE: the signed plan and the plan
            # the staged entry will enter the collective with must be
            # the same object even if the tuner retargets mid-round
            # (set_wire_plan between prepare and GO) — the entry runs
            # the OLD signed plan, the NEW plan signs from next round
            mode = _norm_compress(self.compress)
            chunk = DEFAULT_CHUNK_MB if self.chunk_mb is None \
                else float(self.chunk_mb)
            plan = {"mode": mode, "chunk_mb": chunk}
            sig += f"|bf16={int(mode == 'bf16')}"
            if mode == "int8":
                sig += f"|quant=int8:{QUANT_BLOCK}"
            sig += f"|chunk={chunk}"
            topo = self._resolve_topology()
            if topo is not None:
                # hierarchical rounds sign their tier shape: a member
                # resolving a DIFFERENT NxM (heterogeneous fleet, stale
                # flag, failed resolution) mismatches here and the
                # round falls back to the RPC mix — a skewed two-tier
                # collective would wedge the world. Flat members append
                # nothing, so pre-topology peers interoperate verbatim.
                sig += f"|topo={topo.signature}"
        with self._staged_lock:
            # one staged round at a time: a newer prepare supersedes any
            # stale round a dead master left behind (its waiter sees the
            # stage gone and exits). The SIGNED wire plan rides the stage:
            # _enter_collective runs exactly what prepare signed, even if
            # the tuner retargets the live plan between prepare and GO.
            self._staged = {rid: {"diffs": diffs, "union": union,
                                  "plan": plan}}
        threading.Thread(target=self._wait_for_go, args=(rid,), daemon=True,
                         name="mix-go-wait").start()
        return [int(self.model_version), sig]

    def local_abort(self, rid) -> bool:
        rid = rid.decode() if isinstance(rid, bytes) else rid
        with self._staged_lock:
            return self._staged.pop(rid, None) is not None

    def _wait_for_go(self, rid: str) -> None:
        """Observe the GO marker, then enter the collective. Every live
        prepared member runs this; entering only on OBSERVED shared state
        is what makes partial entry impossible for live members."""
        deadline = time.monotonic() + self._go_wait()
        base: Optional[int] = None
        world_n = 0
        while time.monotonic() < deadline:
            with self._staged_lock:
                if rid not in self._staged:
                    return  # aborted or superseded
            try:
                raw = self.comm.coord.read(self._go_path())
            except Exception:  # broad-ok — transient coordinator issue
                raw = None
            if raw:
                try:
                    msg = unpack_obj(raw)
                except Exception:  # broad-ok
                    msg = None
                if msg:
                    got = msg.get("rid")
                    got = got.decode() if isinstance(got, bytes) else got
                    if got == rid:
                        base = int(msg.get("base", 0))
                        world_n = int(msg.get("n", 0))
                        break
            time.sleep(_GO_POLL_SEC)
        if base is None:
            # deadline passed without observing GO. Before discarding,
            # VERIFY its absence — every poll above may have failed while
            # peers observed GO and entered the psum; discarding blind
            # would wedge them forever (the runtime detects process death,
            # not non-participation).
            with self._staged_lock:
                still_staged = rid in self._staged
            if not still_staged:
                return  # aborted or superseded meanwhile
            try:
                raw = self.comm.coord.read(self._go_path())
            except Exception:  # broad-ok — coordinator unreadable
                raw = False  # sentinel: absence NOT verified
            if raw not in (None, False, b""):
                try:
                    msg = unpack_obj(raw)
                    got = msg.get("rid")
                    got = got.decode() if isinstance(got, bytes) else got
                    if got == rid:  # GO was there all along: enter late,
                        base = int(msg.get("base", 0))  # peers are waiting
                        world_n = int(msg.get("n", 0))
                except Exception:  # broad-ok
                    pass
            if base is None:
                with self._staged_lock:
                    dropped = self._staged.pop(rid, None)
                if dropped is None:
                    return
                if raw is False:
                    # unverifiable: peers may be inside the collective.
                    # Bound their wait by killing this member's jax world —
                    # the runtime errors the psum out on everyone (the
                    # documented 'world torn down' model); this process
                    # mixes over RPC from now on.
                    log.error("round %s: no GO within %.0fs and the "
                              "coordinator is unreadable; tearing down the "
                              "jax distributed world to unblock any "
                              "entered peers", rid, self._go_wait())
                    self.flight.record(
                        "collective", ok=False, round_id=rid,
                        reason="go_timeout_unverifiable_world_torn_down")
                    self._kill_world()
                else:
                    log.warning("round %s: no GO within %.0fs (verified "
                                "absent); staged diff discarded", rid,
                                self._go_wait())
                    self.flight.record(
                        "collective", ok=False, round_id=rid,
                        reason="go_timeout_verified_absent")
                return
        ok = False
        try:
            ok = self._enter_collective(rid, base, world_n)
        except Exception as e:  # broad-ok — world torn down mid-psum
            log.exception("collective entry failed for round %s", rid)
            self.flight.record("collective", ok=False, round_id=rid,
                               reason=f"entry_failed: {type(e).__name__}: "
                                      f"{e}")
        if self.self_node is not None:
            # ephemeral (dies with this session; never journaled) and
            # retried: a dropped ack demotes a healthy member
            leaf = f"{self._ack_dir()}/{self._ack_leaf(rid, self.self_node.name)}"
            payload = b"1" if ok else b"0"
            for attempt in range(3):
                try:
                    if self.comm.coord.create(leaf, payload, ephemeral=True):
                        break
                    self.comm.coord.remove(leaf)  # stale same-name leaf
                except Exception:  # broad-ok
                    if attempt == 2:
                        log.warning("ack write failed for round %s", rid,
                                    exc_info=True)
                    time.sleep(0.1)

    def _kill_world(self) -> None:
        self.collective_dead = True
        self.trace.events.emit("mix", "collective_dead", severity="error")
        try:
            import jax

            jax.distributed.shutdown()
        except Exception:  # broad-ok — already down is fine
            log.debug("jax.distributed.shutdown raised", exc_info=True)

    def _enter_collective(self, rid: str, base_version: int,
                          world_n: int = 0) -> bool:
        with self._staged_lock:
            entry = self._staged.pop(rid, None)
        if entry is None:
            return False
        from jubatus_tpu.parallel.collective import (
            ErrorFeedback, psum_pytree_start)

        if self.ef is None:
            self.ef = ErrorFeedback()
        # per-phase wall times for the round just run, exposed for
        # status/bench (the reference logs time+bytes per mix round,
        # linear_mixer.cpp:553-558; here per phase + pipeline overlap +
        # the resolved quant mode and wire bytes the flight recorder
        # stamps per round). prefer_device: device-resident diff leaves
        # (the JAX models) enter with zero staging and the totals come
        # back as device arrays, which the jitted put_diff consumes
        # directly — no device→host→device round trip on the apply.
        # The reduce runs as a STREAMING round (psum_pytree_start):
        # each GO waiter is its own thread, so when rounds come back to
        # back the next round's early chunk ship/reduce overlaps this
        # round's readback drain — the dispatch gate in
        # parallel/collective.py keeps the collective order total
        # across the overlap (phases stamp the wait as
        # dispatch_gate_ms).
        from jubatus_tpu.parallel.collective import ChunkIntegrityError

        self.last_phases = {}
        # enter with the plan prepare SIGNED, not the live attributes: a
        # set_wire_plan() between prepare and GO must not change what
        # this round runs (the peers verified the signed plan; a skewed
        # chunk sequence would wedge the world). Legacy stages without a
        # plan ride the live attributes, matching what they signed.
        plan = entry.get("plan") or {}
        try:
            totals = psum_pytree_start(
                entry["diffs"], compress=plan.get("mode", self.compress),
                chunk_mb=plan.get("chunk_mb", self.chunk_mb),
                phases=self.last_phases, prefer_device=True,
                feedback=self.ef, guard=self.guard.mode,
                topology=self._resolve_topology()).result()
        except ChunkIntegrityError as e:
            # model-integrity plane (ISSUE 15): a corrupted staged
            # chunk (CRC) or a non-finite reduced total — the round is
            # dead for this member (nothing applied), and the NEXT
            # round routes to the RPC mix whose fold-time guard screens
            # on the host
            self.integrity_failures += 1
            self._force_rpc_rounds = max(self._force_rpc_rounds, 1)
            self._count("mix.guard.chunk_crc_mismatch" if e.kind == "crc"
                        else "mix.guard.nonfinite_total")
            self.trace.events.emit(
                "mix", "chunk_integrity_failure", severity="error",
                kind=e.kind, round_id=rid)
            log.error("collective round %s: %s; next round falls back "
                      "to the RPC mix", rid, e)
            self.flight.record(
                "collective", ok=False, round_id=rid,
                reason=f"chunk_integrity_{e.kind}",
                phases=dict(self.last_phases) or None)
            return False
        # mix-convergence telemetry (ISSUE 7): every member measures the
        # distance of its OWN contribution from the folded average — the
        # per-member half of the divergence signal the RPC master
        # computes centrally. Device leaves reduce on device; only the
        # scalar norms come back to the host.
        health = self._entry_health(entry["diffs"], totals, world_n)
        ok = self.local_put_obj({
            "protocol": PROTOCOL_VERSION,
            "schema": entry["union"],
            "base_version": base_version,
            "diffs": totals,
            "health": health,
            # the collective already finite-screened these totals ON
            # DEVICE (psum_pytree guard); re-screening here would force
            # a full device→host copy of a prefer_device payload
            "guard_screened": True,
        })
        if ok:
            self._note_round_telemetry()
        # flight record for THIS member's collective entry: the per-phase
        # breakdown (ship/reduce/readback + chunks) is per-member, so
        # every participant logs one — the master additionally logs a
        # collective_master record with the ack fold
        self.flight.record("collective", ok=ok, round_id=rid,
                           phases=dict(self.last_phases),
                           health=health or None)
        return ok

    def _entry_health(self, own: Dict[str, Any], totals: Dict[str, Any],
                      world_n: int) -> Dict[str, Any]:
        """Convergence stats for one collective entry: relative L2 of
        (own contribution - totals/n). Empty when the GO marker came
        from a pre-ISSUE-7 master (no world size on the wire)."""
        if world_n <= 0:
            return {}
        from jubatus_tpu.framework.linear_mixer import (
            _leaf_sq, _flatten, _sum_names, divergence_sq)

        try:
            with self.driver.lock:
                names = _sum_names(self.driver.get_mixables())
            if not names:
                return {}
            avg_sq = sum(
                _leaf_sq(t) / (world_n * world_n)
                for name in names if name in totals
                for t in _flatten(totals[name]))
            denom = (avg_sq ** 0.5) + 1e-12
            rel = (divergence_sq(own, totals, world_n, names) ** 0.5) / denom
            return {"premix_divergence": round(rel, 6),
                    "update_norm": round((avg_sq ** 0.5) * world_n, 6),
                    "contributors": world_n}
        except Exception:  # broad-ok — telemetry must never fail a round
            log.debug("entry health computation failed", exc_info=True)
            return {}

    def _note_round_telemetry(self) -> None:
        """Round-end gauges for the wire and the error-feedback chains:
        wire MB shipped, residual norms, and the residual DRIFT RATE
        (norm change per round) the SLO engine can watch — a positive
        drift rate sustained over rounds means quantization error is
        accumulating faster than the telescoping cancels it."""
        wire_mb = self.last_phases.get("wire_mb")
        if isinstance(wire_mb, (int, float)):
            self.trace.gauge("mix.wire_mb", float(wire_mb))
        # per-tier round timings + the scaling plane's wire gauge: the
        # intra tier must stay cheap and flat as hosts grow, the inter
        # tier is the wire, and wire bytes per HOST is the quantity the
        # hierarchical reduce holds proportional to hosts (flat mode
        # reports intra 0 / inter == reduce: every byte is inter-host)
        for src, key in (("intra_ms", "mix.intra_ms"),
                         ("inter_ms", "mix.inter_ms"),
                         ("wire_bytes_per_host", "mix.wire_bytes_per_host")):
            v = self.last_phases.get(src)
            if isinstance(v, (int, float)):
                self.trace.gauge(key, float(v))
        if self.ef is None or self.ef.rounds == 0:
            return
        try:
            norms = self.ef.norms()
        except Exception:  # broad-ok — telemetry must never fail a round
            log.debug("ef norm computation failed", exc_info=True)
            return
        prev = self._ef_norms.get("contrib_residual_norm")
        self._ef_norms = norms
        self.trace.gauge("mix.ef_contrib_residual_norm",
                         norms["contrib_residual_norm"])
        self.trace.gauge("mix.ef_total_residual_norm",
                         norms["total_residual_norm"])
        if prev is not None:
            self.trace.gauge("mix.ef_residual_drift_rate",
                             round(norms["contrib_residual_norm"] - prev, 9))

    def _note_fallback(self, reason: str) -> None:
        """One collective→RPC demotion: counter + timeline event
        (ISSUE 14) — the fallback cascade is exactly what an incident
        timeline must interleave with breaker/membership events."""
        self.fallback_rounds += 1
        self._count("mix.fallback_rounds")
        self.trace.events.emit("mix", "fallback", severity="warning",
                               reason=reason)

    # -- master round --------------------------------------------------------
    def _run_as_master(self, members: Sequence[NodeInfo]) -> Optional[Dict[str, Any]]:
        import jax

        if self.collective_dead or jax.process_count() != len(members):
            # world torn down by a bounded-entry timeout, or replicas are
            # not one jax world (not all joined yet): the collective
            # cannot span them — mix over RPC
            self._note_fallback("collective_dead" if self.collective_dead
                                else "world_mismatch")
            self.flight.record(
                "collective", ok=False,
                reason=("collective_dead" if self.collective_dead
                        else f"world_mismatch: {jax.process_count()} jax "
                             f"processes vs {len(members)} members"))
            return super()._run_as_master(members)
        breakers = getattr(self.comm, "breakers", None)
        if breakers is not None and any(
                not breakers.available((m.host, m.port)) for m in members):
            # a member with an OPEN breaker cannot be counted on to enter
            # the psum — the collective is all-or-wedge, so route the
            # round to the RPC mix, whose fan-out skips/degrades per host
            self._note_fallback("breaker_open_member")
            self.flight.record("collective", ok=False,
                               reason="breaker_open_member",
                               members=len(members))
            return super()._run_as_master(members)
        topo = self._resolve_topology()
        if topo is not None:
            # refresh the deterministic per-host representative election
            # from the FULL member list (degraded rounds keep it stable;
            # only membership/topology changes move it)
            self._reps = elect_representatives(
                [m.name for m in members], topo)
        t0 = time.monotonic()
        schemas = self.comm.get_schemas() if self._has_schema() else []
        union: List[str] = sorted(
            set().union(*(set(s) for s in schemas))) if schemas else []
        union = [s.decode() if isinstance(s, bytes) else s for s in union]

        self._round_seq += 1
        # globally unique rid: a restarted master reuses its name, seq,
        # and version, and a stale durable GO marker matching a reused rid
        # would trigger premature entry
        import os as _os

        rid = (f"{self.self_node.name if self.self_node else 'm'}"
               f"-{self._round_seq}-{_os.urandom(6).hex()}")
        results, errors = self.comm.collect("mix_prepare", rid, union)
        sigs = {r[1] if not isinstance(r[1], bytes) else r[1].decode()
                for _, r in results}
        if errors or len(results) != len(members) or len(sigs) != 1 \
                or "unsupported" in sigs:
            self.comm.collect("mix_abort", rid)
            self._note_fallback("prepare_not_viable")
            log.info("collective round %s not viable (%d errors, sigs %s); "
                     "falling back to rpc mix", rid, len(errors), len(sigs))
            self.flight.record(
                "collective", ok=False, round_id=rid,
                reason=f"prepare_not_viable: {len(errors)} errors, "
                       f"{len(sigs)} signatures",
                members=len(members))
            return super()._run_as_master(members)
        base_version = max(int(r[0]) for _, r in results)

        # GO rides the coordinator: every live prepared member observes it.
        # A failed write means nobody will enter — abort and mix over RPC.
        try:
            if not self.comm.coord.set(
                    self._go_path(),
                    pack_obj({"rid": rid, "base": base_version,
                              "n": len(members)})):
                raise RuntimeError("coordinator refused the GO write")
        except Exception:  # broad-ok
            self.comm.collect("mix_abort", rid)
            self._note_fallback("go_write_failed")
            log.warning("collective round %s: GO write failed; falling "
                        "back to rpc mix", rid, exc_info=True)
            self.flight.record("collective", ok=False, round_id=rid,
                               reason="go_write_failed",
                               members=len(members))
            return super()._run_as_master(members)

        # collect acks — the members' waiters (this process included)
        # enter, apply, and ack; psum completion is world-wide or nobody's.
        # One list() per poll (not N reads); once the FIRST ack appears the
        # psum provably completed everywhere, so stragglers get only a
        # short grace before a missing ack means a failed apply.
        acks: Dict[str, bool] = {}
        ack_dir = self._ack_dir()
        deadline = time.monotonic() + self._go_wait() + 10.0
        grace: Optional[float] = None
        prefix = f"{rid.replace('/', '_')}__"
        while time.monotonic() < deadline and len(acks) < len(members):
            try:
                leaves = [c for c in self.comm.coord.list(ack_dir)
                          if c.startswith(prefix)]
            except Exception:  # broad-ok
                leaves = []
            for leaf in leaves:
                name = leaf[len(prefix):]
                if name in acks:
                    continue
                raw = self.comm.coord.read(f"{ack_dir}/{leaf}")
                if raw is not None:
                    acks[name] = raw == b"1"
            if acks and grace is None:
                grace = time.monotonic() + 5.0
            if grace is not None and time.monotonic() > grace:
                break
            if len(acks) < len(members):
                time.sleep(_GO_POLL_SEC)
        for member in members:
            try:
                self.comm.coord.remove(
                    f"{ack_dir}/{self._ack_leaf(rid, member.name)}")
            except Exception:  # broad-ok
                pass
        if not acks:
            # indistinguishable between nobody-entered and everyone-stuck:
            # demoting the whole actives list would unroute the cluster,
            # so report the failed round and let the next one retry
            log.error("collective round %s: no member acked", rid)
            self.flight.record("collective_master", ok=False, round_id=rid,
                               reason="no_acks", members=len(members))
            return None
        for member in members:
            if not acks.get(member.name, False):
                self.comm.register_active(member, False)
        self.collective_rounds += 1
        self.mix_count += 1
        log.info("collective mix round %d: %d members (%d acked), %.3fs",
                 self.mix_count, len(members), sum(acks.values()),
                 time.monotonic() - t0)
        out = {"members": len(members), "collective": True,
               "acked": sum(acks.values()),
               "mode": "collective_master", "round_id": rid}
        if topo is not None:
            out["topology"] = topo.signature
            out["representatives"] = sorted(self._reps.values())
        return out

    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        from jubatus_tpu.parallel.collective import _norm_compress
        from jubatus_tpu.parallel.multihost import collective_capabilities

        topo = self._resolve_topology()
        st.update(collective_rounds=self.collective_rounds,
                  fallback_rounds=self.fallback_rounds,
                  integrity_failures=self.integrity_failures,
                  mix_compress=_norm_compress(self.compress),
                  mix_chunk_mb=self.chunk_mb,
                  mix_plan_version=self.plan_version,
                  mix_topology=topo.signature if topo is not None
                  else "flat")
        if self._reps:
            st["mix_representatives"] = sorted(self._reps.values())
        for k, v in collective_capabilities().items():
            st[f"mix_caps_{k}"] = v
        if self.ef is not None:
            for k, v in self.ef.stats().items():
                st[f"mix_ef_{k}"] = v
        for k, v in self._ef_norms.items():
            st[f"mix_ef_{k}"] = v
        for k, v in self.last_phases.items():
            st[f"last_mix_{k}"] = v
        return st
