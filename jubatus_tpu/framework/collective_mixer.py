"""Collective mixer — the production mix as a device collective.

``--mixer collective_mixer``: the control plane stays MessagePack-RPC
(master election via the coordinator lock, schema sync, a two-phase
prepare/commit), but the DIFF payload — the reference's get_diff fan-out,
pairwise fold, and put_diff broadcast (linear_mixer.cpp:437-559) — moves
onto the accelerator interconnect as one psum across the
``jax.distributed`` world (parallel/collective.py). This is SURVEY.md §7
step 3's north-star component: the fold IS the AllReduce combiner, so a
Criteo-shaped round ships over ICI/DCN at interconnect bandwidth instead
of TCP through msgpack.

Round protocol (master = this round's lock holder):

1. prepare(round, schema_union): every member syncs the schema, STAGES
   its local diff under the model lock, and answers (version,
   shape-signature). Nothing has entered a collective yet.
2. The master verifies every member staged with identical signatures and
   that the jax process world matches the member set — any mismatch
   aborts the round (members discard their staged diff) and the round
   falls back to the plain RPC mix, so the cluster always mixes.
3. commit(round, base_version): every member (master included, via its
   own RPC server) enters ``psum_pytree`` with its staged diff; all
   replicas receive the identical total and apply it locally with the
   same obsolete/active semantics as the RPC path.

Failure model: prepare/commit are RPCs with timeouts; once a member has
entered the collective it blocks until the world completes — a process
that dies mid-collective is detected by the jax distributed runtime's
heartbeat (which terminates the world), the same blast radius as losing
a chip mid-allreduce in any SPMD training step. Engines whose mixables
are not plain-sum (dict-shaped diffs: bandit, burst, row stores) are
detected in prepare and served by the RPC fallback path unchanged.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence

from jubatus_tpu.coord.base import NodeInfo
from jubatus_tpu.framework.linear_mixer import (
    PROTOCOL_VERSION,
    RpcLinearMixer,
)

log = logging.getLogger(__name__)


def _summable(mixable: Any) -> bool:
    return getattr(mixable, "mix", None) is None or \
        getattr(mixable, "MIX_IS_SUM", False)


def _signature(diffs: Dict[str, Any]) -> str:
    """Canonical shape/dtype signature; every member must match before
    anyone enters the collective (shape skew would wedge the psum).
    64-bit leaves report "unsupported": a psum in f32 would be LESS exact
    than the RPC fold, so those rounds take the fallback."""
    import jax
    import numpy as np

    parts: List[str] = []
    for name in sorted(diffs):
        leaves, treedef = jax.tree_util.tree_flatten(diffs[name])
        sigs = []
        for x in leaves:
            a = np.asarray(x)
            if a.dtype in (np.float64, np.int64, np.uint64):
                return "unsupported"
            sigs.append(f"{a.shape}/{a.dtype}")
        parts.append(f"{name}:{treedef}:{','.join(sigs)}")
    return "|".join(parts)


class CollectiveMixer(RpcLinearMixer):
    """RpcLinearMixer whose round rides the device collective when it can,
    and the RPC fan-out when it can't (non-sum mixables, world mismatch,
    prepare failures)."""

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._staged_lock = threading.Lock()
        self._staged: Dict[str, Dict[str, Any]] = {}
        self._round_seq = 0
        self.collective_rounds = 0
        self.fallback_rounds = 0

    # -- RPC surface ---------------------------------------------------------
    def register_api(self, rpc_server, name_check: str = "") -> None:
        super().register_api(rpc_server, name_check)
        rpc_server.register(
            "mix_prepare", lambda _n, rid, union: self.local_prepare(rid, union))
        rpc_server.register(
            "mix_commit", lambda _n, rid, base: self.local_commit(rid, base))
        rpc_server.register(
            "mix_abort", lambda _n, rid: self.local_abort(rid))

    # -- member-side phases --------------------------------------------------
    def local_prepare(self, rid, union) -> List[Any]:
        rid = rid.decode() if isinstance(rid, bytes) else rid
        union = [u.decode() if isinstance(u, bytes) else u for u in union]
        with self.driver.lock:
            if union and hasattr(self.driver, "sync_schema"):
                self.driver.sync_schema(union)
            mixables = self.driver.get_mixables()
            if not all(_summable(m) for m in mixables.values()):
                return [int(self.model_version), "unsupported"]
            diffs = {name: m.get_diff() for name, m in mixables.items()}
        with self._staged_lock:
            # one staged round at a time: a newer prepare supersedes any
            # stale round a dead master left behind
            self._staged = {rid: {"diffs": diffs, "union": union}}
        return [int(self.model_version), _signature(diffs)]

    def local_commit(self, rid, base_version) -> bool:
        rid = rid.decode() if isinstance(rid, bytes) else rid
        with self._staged_lock:
            entry = self._staged.pop(rid, None)
        if entry is None:
            log.warning("commit for unknown round %s", rid)
            return False
        from jubatus_tpu.parallel.collective import psum_pytree

        totals = psum_pytree(entry["diffs"])
        return self.local_put_obj({
            "protocol": PROTOCOL_VERSION,
            "schema": entry["union"],
            "base_version": int(base_version),
            "diffs": totals,
        })

    def local_abort(self, rid) -> bool:
        rid = rid.decode() if isinstance(rid, bytes) else rid
        with self._staged_lock:
            return self._staged.pop(rid, None) is not None

    # -- master round --------------------------------------------------------
    def _run_as_master(self, members: Sequence[NodeInfo]) -> Optional[Dict[str, Any]]:
        import time

        import jax

        if jax.process_count() != len(members):
            # replicas are not one jax world (or not all joined yet):
            # the collective cannot span them — mix over RPC
            self.fallback_rounds += 1
            return super()._run_as_master(members)
        t0 = time.monotonic()
        schemas = self.comm.get_schemas() if self._has_schema() else []
        union: List[str] = sorted(
            set().union(*(set(s) for s in schemas))) if schemas else []
        union = [s.decode() if isinstance(s, bytes) else s for s in union]

        self._round_seq += 1
        rid = f"{self.self_node.name if self.self_node else 'm'}:{self._round_seq}"
        results, errors = self.comm.collect("mix_prepare", rid, union)
        sigs = {r[1] if not isinstance(r[1], bytes) else r[1].decode()
                for _, r in results}
        if errors or len(results) != len(members) or len(sigs) != 1 \
                or "unsupported" in sigs:
            self.comm.collect("mix_abort", rid)
            self.fallback_rounds += 1
            log.info("collective round %s not viable (%d errors, sigs %s); "
                     "falling back to rpc mix", rid, len(errors), len(sigs))
            return super()._run_as_master(members)
        base_version = max(int(r[0]) for _, r in results)

        acks_raw, commit_errors = self.comm.collect("mix_commit", rid,
                                                    base_version)
        acks = {f"{h}_{p}": bool(r) for (h, p), r in acks_raw}
        for e in commit_errors:
            acks[f"{e.host}_{e.port}"] = False
        for member in members:
            if not acks.get(member.name, False):
                self.comm.register_active(member, False)
        self.collective_rounds += 1
        self.mix_count += 1
        log.info("collective mix round %d: %d members, %.3fs",
                 self.mix_count, len(members), time.monotonic() - t0)
        return {"members": len(members), "collective": True}

    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(collective_rounds=self.collective_rounds,
                  fallback_rounds=self.fallback_rounds)
        return st
