"""Driver base class (≙ core::driver::driver_base, SURVEY.md §2.9).

A driver owns one engine's model state + fv_converter and exposes:
- the engine's business API (train/classify/... defined by subclasses),
- the mixable protocol for the mix engine (get_mixables),
- pack/unpack for checkpointing (framework/save_load.py),
- clear and schema sync.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Dict, List

from jubatus_tpu.parallel.mix import Mixable


def locked(fn):
    """Method decorator: hold the driver's model lock (the reference's
    JRLOCK_/JWLOCK_ decorators collapsed to one reentrant lock — snapshot
    reads of JAX arrays make a reader/writer split unnecessary for now)."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self.lock:
            return fn(self, *args, **kwargs)

    return wrapper


class DriverBase:
    #: engine type name, e.g. "classifier" — matches the reference's server
    #: type strings used in model filenames and RPC registration.
    TYPE: str = "base"

    #: bumped when a driver's pack() layout changes (reference
    #: user_data_version, server_base.hpp:41-109)
    USER_DATA_VERSION: int = 1

    def __init__(self) -> None:
        self.update_count = 0
        #: model lock (the reference's rw_mutex, server_base.hpp:70-72):
        #: drivers hold it in their public methods; the mix engine holds every
        #: participant's lock for the round (parallel/mix.py), so a background
        #: mix can never interleave with train/classify on the same model.
        self.lock = threading.RLock()

    # -- mix plane ----------------------------------------------------------
    def get_mixables(self) -> Dict[str, Mixable]:
        return {}

    def get_schema(self) -> List[str]:
        """Row-vocabulary schema for pre-mix alignment (default: none)."""
        return []

    def sync_schema(self, union_schema: List[str]) -> None:
        pass

    # -- persistence --------------------------------------------------------
    def pack(self) -> Any:
        raise NotImplementedError

    def unpack(self, obj: Any) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError

    # -- bookkeeping ---------------------------------------------------------
    def event_model_updated(self, n: int = 1) -> None:
        """Reference server_base::event_model_updated (server_base.cpp:214-219):
        bump the update counter; the mixer watches it."""
        self.update_count += n

    def get_status(self) -> Dict[str, Any]:
        return {"type": self.TYPE, "update_count": self.update_count}
