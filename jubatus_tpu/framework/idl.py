"""Engine service definitions (≙ jubatus/server/server/*.idl).

The reference generates server bindings, proxy routing, and clients from
msgpack-IDL files with three decorators per RPC — routing (#@random /
#@broadcast / #@cht(n) / #@internal), lock (#@update / #@analysis / #@nolock),
and aggregator (#@pass / #@all_and / #@all_or / #@merge / #@concat)
(tools/jenerator/src/syntax.ml:41-66). Here the same information is a data
table: one `Method` per RPC, transcribed from each engine's .idl (cited
per-service below). The table drives:

- `jubatus_tpu.server.service` — binding driver methods onto RpcServer,
- `jubatus_tpu.server.proxy`  — routing + aggregation per method,
- `jubatus_tpu.client`        — typed client stubs.

`jubatus_tpu.codegen` can regenerate this module from the .idl files; the
checked-in table keeps the framework free of a build-time codegen step.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Optional, Tuple

RANDOM, BROADCAST, CHT, INTERNAL = "random", "broadcast", "cht", "internal"


@dataclass(frozen=True)
class Method:
    name: str
    #: wire argument names AFTER the leading cluster-name string every
    #: jubatus call carries (client.hpp:30-87)
    args: Tuple[str, ...]
    routing: str = RANDOM
    #: CHT successor count for routing == "cht" (#@cht defaults to 2,
    #: recommender_proxy.cpp:21-45; #@cht(1) where the idl says so)
    cht_n: int = 2
    #: update → model write lock; analysis → read; nolock (server decides)
    lock: str = "nolock"
    #: broadcast/cht reducer (framework/aggregators.hpp)
    aggregator: str = "pass"
    #: retry-safety class (beyond the reference's IDL): True when
    #: re-issuing the call cannot change state (reads), False when a
    #: duplicate would double-apply (train/push/clear/...). None derives
    #: from the lock decorator — analysis → idempotent, update →
    #: effectful, nolock → effectful unless tagged here explicitly.
    idempotent: Optional[bool] = None

    @property
    def is_idempotent(self) -> bool:
        if self.idempotent is not None:
            return self.idempotent
        return self.lock == "analysis"


def _m(name, args=(), routing=RANDOM, cht_n=2, lock="nolock", agg="pass",
       idem: Optional[bool] = None):
    return Method(name, tuple(args), routing, cht_n, lock, agg, idem)


#: engine name → RPC surface. Source: the .idl file named per key.
SERVICES: Dict[str, Tuple[Method, ...]] = {
    # classifier.idl:40-81
    "classifier": (
        _m("train", ("data",), RANDOM, lock="update"),
        _m("classify", ("data",), RANDOM, lock="analysis"),
        _m("get_labels", (), RANDOM, lock="analysis"),
        _m("set_label", ("new_label",), BROADCAST, lock="update", agg="all_and"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
        _m("delete_label", ("target_label",), BROADCAST, lock="update", agg="all_or"),
    ),
    # regression.idl
    "regression": (
        _m("train", ("train_data",), RANDOM, lock="update"),
        _m("estimate", ("estimate_data",), RANDOM, lock="analysis"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
    ),
    # recommender.idl
    "recommender": (
        _m("clear_row", ("id",), CHT, 2, "update", "all_and"),
        _m("update_row", ("id", "row"), CHT, 2, "update", "all_and"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
        _m("complete_row_from_id", ("id",), CHT, 2, "analysis"),
        _m("complete_row_from_datum", ("row",), RANDOM, lock="analysis"),
        _m("similar_row_from_id", ("id", "size"), CHT, 2, "analysis"),
        _m("similar_row_from_datum", ("row", "size"), RANDOM, lock="analysis"),
        _m("decode_row", ("id",), CHT, 2, "analysis"),
        _m("get_all_rows", (), RANDOM, lock="analysis"),
        _m("calc_similarity", ("lhs", "rhs"), RANDOM, lock="analysis"),
        _m("calc_l2norm", ("row",), RANDOM, lock="analysis"),
    ),
    # nearest_neighbor.idl (queries are #@nolock reads: retry-safe)
    "nearest_neighbor": (
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
        _m("set_row", ("id", "d"), CHT, 1, "update"),
        _m("neighbor_row_from_id", ("id", "size"), RANDOM, idem=True),
        _m("neighbor_row_from_datum", ("query", "size"), RANDOM, idem=True),
        _m("similar_row_from_id", ("id", "ret_num"), RANDOM, idem=True),
        _m("similar_row_from_datum", ("query", "ret_num"), RANDOM, idem=True),
        _m("get_all_rows", (), RANDOM, idem=True),
    ),
    # anomaly.idl
    "anomaly": (
        _m("clear_row", ("id",), CHT, 2, "update", "all_and"),
        _m("add", ("row",), RANDOM),
        _m("update", ("id", "row"), CHT, 2, "update"),
        _m("overwrite", ("id", "row"), CHT, 2, "update"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
        _m("calc_score", ("row",), RANDOM, lock="analysis"),
        _m("get_all_rows", (), RANDOM, lock="analysis"),
    ),
    # graph.idl
    "graph": (
        _m("create_node", (), RANDOM),
        _m("remove_node", ("node_id",), CHT, 2),
        _m("update_node", ("node_id", "property"), CHT, 2, "update", "all_and"),
        _m("create_edge", ("node_id", "e"), CHT, 1),
        _m("update_edge", ("node_id", "edge_id", "e"), CHT, 2, "update", "all_and"),
        _m("remove_edge", ("node_id", "edge_id"), CHT, 2, "update", "all_and"),
        _m("get_centrality", ("node_id", "centrality_type", "query"), RANDOM, lock="analysis"),
        _m("add_centrality_query", ("query",), BROADCAST, lock="update", agg="all_and"),
        _m("add_shortest_path_query", ("query",), BROADCAST, lock="update", agg="all_and"),
        _m("remove_centrality_query", ("query",), BROADCAST, lock="update", agg="all_and"),
        _m("remove_shortest_path_query", ("query",), BROADCAST, lock="update", agg="all_and"),
        _m("get_shortest_path", ("query",), RANDOM, lock="analysis"),
        _m("update_index", (), BROADCAST, lock="update", agg="all_and"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
        _m("get_node", ("node_id",), CHT, 2, "analysis"),
        _m("get_edge", ("node_id", "edge_id"), CHT, 2, "analysis"),
        _m("create_node_here", ("node_id",), INTERNAL, lock="update"),
        _m("remove_global_node", ("node_id",), INTERNAL, lock="update"),
        _m("create_edge_here", ("edge_id", "e"), INTERNAL, lock="update"),
    ),
    # burst.idl
    "burst": (
        # broadcast to every node (each processes only its CHT-assigned
        # keywords); the reply is the first node's count — #@pass, NOT a sum
        # (burst.idl:40-41, burst_proxy.cpp:21-23)
        _m("add_documents", ("data",), BROADCAST, lock="update", agg="pass"),
        _m("get_result", ("keyword",), CHT, 2, "analysis"),
        _m("get_result_at", ("keyword", "pos"), CHT, 2, "analysis"),
        _m("get_all_bursted_results", (), BROADCAST, lock="analysis", agg="merge"),
        _m("get_all_bursted_results_at", ("pos",), BROADCAST, lock="analysis", agg="merge"),
        _m("get_all_keywords", (), RANDOM, lock="analysis"),
        _m("add_keyword", ("keyword",), BROADCAST, lock="update", agg="all_and"),
        _m("remove_keyword", ("keyword",), BROADCAST, lock="update", agg="all_and"),
        _m("remove_all_keywords", (), BROADCAST, lock="update", agg="all_and"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
    ),
    # clustering.idl
    "clustering": (
        _m("push", ("points",), RANDOM, lock="update"),
        _m("get_revision", (), RANDOM, lock="analysis"),
        _m("get_core_members", (), RANDOM, lock="analysis"),
        _m("get_core_members_light", (), RANDOM, lock="analysis"),
        _m("get_k_center", (), RANDOM, lock="analysis"),
        _m("get_nearest_center", ("point",), RANDOM, lock="analysis"),
        _m("get_nearest_members", ("point",), RANDOM, lock="analysis"),
        _m("get_nearest_members_light", ("point",), RANDOM, lock="analysis"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
    ),
    # stat.idl
    "stat": (
        _m("push", ("key", "value"), CHT, 1, "update", "all_and"),
        _m("sum", ("key",), CHT, 1, "analysis"),
        _m("stddev", ("key",), CHT, 1, "analysis"),
        _m("max", ("key",), CHT, 1, "analysis"),
        _m("min", ("key",), CHT, 1, "analysis"),
        _m("entropy", ("key",), CHT, 1, "analysis"),
        _m("moment", ("key", "degree", "center"), CHT, 1, "analysis"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
    ),
    # bandit.idl
    "bandit": (
        _m("register_arm", ("arm_id",), BROADCAST, lock="update", agg="all_and"),
        _m("delete_arm", ("arm_id",), BROADCAST, lock="update", agg="all_and"),
        _m("select_arm", ("player_id",), CHT, 1, "update"),
        _m("register_reward", ("player_id", "arm_id", "reward"), CHT, 1, "update", "all_and"),
        _m("get_arm_info", ("player_id",), CHT, 1, "analysis"),
        _m("reset", ("player_id",), BROADCAST, lock="update", agg="all_or"),
        _m("clear", (), BROADCAST, lock="update", agg="all_and"),
    ),
    # weight.idl (calc_weight is a pure read; update mutates df tables)
    "weight": (
        _m("update", ("d",), RANDOM),
        _m("calc_weight", ("d",), RANDOM, idem=True),
        _m("clear", (), BROADCAST, agg="all_and"),
    ),
}

#: engines whose proxies route by CHT (use_cht=true in *_impl.cpp)
USES_CHT = frozenset(
    e
    for e, methods in SERVICES.items()
    if any(m.routing == CHT for m in methods)
)

ENGINES: Tuple[str, ...] = tuple(sorted(SERVICES))


def get_service(engine: str) -> Tuple[Method, ...]:
    try:
        return SERVICES[engine]
    except KeyError:
        raise KeyError(f"unknown engine {engine!r}; known: {', '.join(ENGINES)}")


# -- idempotency classes (rpc/retry.py consumers) -----------------------------

#: built-ins + mixer internals that are pure reads — safe to retry on a
#: transport failure (the mix_* reads matter: a mixer master retrying a
#: get_diff against a flaky member beats skipping its contribution)
IDEMPOTENT_BUILTINS: FrozenSet[str] = frozenset({
    "get_config", "get_status", "get_metrics", "get_mix_history",
    "get_spans", "get_slow_log",
    "get_timeseries", "get_alerts",
    # continuous profiling plane (ISSUE 8): profile reads are pure;
    # profile_device only re-captures into the same capped artifacts
    # dir on a retry — safe to re-issue after a transport failure
    "get_profile", "profile_device", "get_proxy_profile",
    "get_proxy_status", "get_proxy_metrics", "get_proxy_spans",
    "get_proxy_slow_log", "get_proxy_timeseries", "get_proxy_alerts",
    "get_breakers",
    "mix_get_schema", "mix_get_diff", "mix_get_model",
    # elastic membership (ISSUE 10): epoch/drain/migration READS.
    # migrate_range is a pure read on the SOURCE (the puller owns the
    # cursor, so re-issuing a chunk fetch just re-reads the same rows)
    "get_epoch", "drain_status", "migrate_range", "get_row_count",
    # async mix (ISSUE 11): the inbox/fold status read is pure
    "mix_async_status",
    # autoscaling control plane (ISSUE 12): journal/status read is pure
    "get_autoscale_status",
    # event plane + incident bundles (ISSUE 14): journal/bundle reads
    # are pure (get_events is cursor-driven; a replayed read re-serves
    # the same events)
    "get_events", "get_incidents", "get_proxy_events",
    "get_proxy_incidents",
    # data-quality plane (ISSUE 17): the sketch/drift doc read is pure
    "get_quality", "get_proxy_quality",
    # usage-attribution plane (ISSUE 19): the ledger doc read is pure —
    # a retried get_usage re-serves the same mergeable snapshot
    "get_usage", "get_proxy_usage",
    # durable model plane (ISSUE 18): the store/warm-boot status read
    # is pure
    "get_store_status",
    # self-tuning performance plane (ISSUE 20): tuner state/journal
    # read is pure
    "get_tune",
})

#: effectful built-ins, listed for the docs' idempotency matrix (anything
#: not in either set is treated as effectful — the safe default)
EFFECTFUL_BUILTINS: FrozenSet[str] = frozenset({
    "save", "load", "clear", "do_mix", "mix_put_diff", "mix_sync_schema",
    "mix_prepare", "mix_abort",
    # elastic membership (ISSUE 10): drain flips routing state,
    # rebalance pulls rows in, put_rows writes rows
    "drain", "rebalance", "put_rows",
    # async mix (ISSUE 11): a replayed submit is mostly-safe
    # (latest-wins inbox) but a retry racing a fold can double-count a
    # delta — classed effectful; the submitter resubmits next tick
    # instead of retrying
    "mix_submit_diff",
    # model-integrity plane (ISSUE 15): rollback rewrites the live
    # model from the snapshot ring — effectful by definition
    "rollback",
    # durable model plane (ISSUE 18): point-in-time restore rewrites
    # the live model from the shared store — effectful by definition
    "store_restore",
})


def idempotent_methods(engine: str) -> FrozenSet[str]:
    """Wire-method names safe to retry for ``engine`` (IDL reads +
    idempotent built-ins)."""
    return frozenset(
        m.name for m in get_service(engine) if m.is_idempotent
    ) | IDEMPOTENT_BUILTINS


def _client_safe() -> FrozenSet[str]:
    """Method names idempotent in EVERY engine that defines them — the
    conservative table for clients that don't know which engine they talk
    to (a name like ``update`` that is effectful anywhere stays
    effectful everywhere)."""
    verdict: Dict[str, bool] = {}
    for methods in SERVICES.values():
        for m in methods:
            verdict[m.name] = verdict.get(m.name, True) and m.is_idempotent
    return frozenset(n for n, ok in verdict.items() if ok) \
        | IDEMPOTENT_BUILTINS


#: engine-agnostic retry-safety table (rpc/client.py's default gate)
CLIENT_SAFE_RETRY: FrozenSet[str] = _client_safe()
