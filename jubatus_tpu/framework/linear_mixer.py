"""Distributed linear mixer over RPC (≙ mixer/linear_mixer.{hpp,cpp}).

The multi-host control-plane mix loop, for deployments that are N independent
server processes rather than one SPMD pod program. (Within a pod, mix is the
collective in parallel/mix.py — no master, no RPC. A multi-host TPU fleet
composes the two: each host mixes its local replicas via collective, hosts
mix with each other through this loop over DCN.)

Round semantics mirror the reference exactly (linear_mixer.cpp:437-559):

  1. elect a per-round master (coordinator master_lock try_lock, :386);
  2. schema sync — engines whose diff arrays are row-keyed by a dynamic
     vocabulary (classifier labels, stat keys) first agree on the sorted
     union schema (fan-out get_schema → union → fan-out sync_schema), so
     per-replica diff arrays are row-aligned before any fold. Engines with
     no schema skip this (two cheap no-op fan-outs);
  3. master fans out ``get_diff`` to every member — including itself, through
     the same path, so all diffs are wire-canonical;
  4. folds diffs pairwise per mixable (custom ``mix`` or elementwise add);
  5. broadcasts ``put_diff``; each member applies it under its model lock;
  6. put_diff success drives the actives list (:658-681): valid → register
     active, obsolete → unregister + full-model recovery via ``get_model``
     from a random peer (:598-632).

The ``LinearCommunication`` seam makes rounds testable without sockets
(reference linear_communication_stub, linear_mixer_test.cpp:65-112).
"""

from __future__ import annotations

import functools
import logging
import math
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jubatus_tpu.coord import membership
from jubatus_tpu.coord.base import Coordinator, NodeInfo
from jubatus_tpu.framework.mixer import IntervalMixer, MixFlightRecorder
from jubatus_tpu.framework.model_guard import MixGuard, payload_nonfinite
from jubatus_tpu.parallel.mix import tree_sum
from jubatus_tpu.rpc.breaker import BreakerBoard
from jubatus_tpu.rpc.client import RpcClient, RpcMClient
from jubatus_tpu.utils import events, faults
from jubatus_tpu.utils.serialization import pack_obj, unpack_obj

log = logging.getLogger(__name__)

#: mixer protocol version — mismatch forces shutdown (linear_mixer.cpp:618-624).
#: v2: payloads carry the R/Z compression tag (pack_mix). A v1 peer cannot
#: decode v2 payloads at all; v2 decodes v1 via the unpack_mix fallback and
#: the version gate then rejects it cleanly.
PROTOCOL_VERSION = 2

#: payloads above this compress with zlib before hitting the wire — mix
#: rounds cross hosts (DCN), where sparse/periodic diffs compress well;
#: below it the header+cpu cost isn't worth it
COMPRESS_THRESHOLD = 2048


def pack_mix(obj) -> bytes:
    """Pack a mix payload, zlib-compressed when large (1-byte tag)."""
    raw = pack_obj(obj)
    if len(raw) > COMPRESS_THRESHOLD:
        import zlib

        z = zlib.compress(raw, 1)
        if len(z) + 1 < len(raw):
            return b"Z" + z
    return b"R" + raw


def unpack_mix(data: bytes):
    """Inverse of pack_mix; unprefixed payloads (older peers) pass through."""
    tag = data[:1]
    if tag == b"Z":
        import zlib

        return unpack_obj(zlib.decompress(data[1:]))
    if tag == b"R":
        return unpack_obj(data[1:])
    return unpack_obj(data)


# -- mix-convergence telemetry (ISSUE 7) --------------------------------------
# The health plane answers "is the LEARNING healthy?": how far apart the
# replicas' contributions are before the fold (divergence), how big the
# applied step is (update norm), and which members keep missing rounds
# (staleness). Computed once per round from data the round already holds
# — no extra RPCs, one vector pass over the payloads.

def _leaf_sq(x: Any) -> float:
    """Sum of squares of one diff leaf. Multiplying by 1.0 promotes int
    leaves without forcing a host copy of device arrays (jnp and numpy
    both dispatch through the operators); scalar leaves fall through."""
    d = x * 1.0
    s = getattr(d, "sum", None)
    if s is None:
        return float(d * d)
    return float((d * d).sum())


def _pair_sq(a: Any, b: Any, b_scale: float) -> float:
    """Sum of squares of ``a - b * b_scale`` (0.0 on a leaf-shape
    mismatch: row-trimmed label diffs may differ by a row — tree_sum
    pads them for the fold, the health stats just skip them)."""
    if getattr(a, "shape", None) != getattr(b, "shape", None):
        return 0.0
    d = a * 1.0 - b * b_scale
    s = getattr(d, "sum", None)
    if s is None:
        return float(d * d)
    return float((d * d).sum())


def _sum_names(mixables: Dict[str, Any]) -> List[str]:
    """Mixables whose fold is elementwise addition — the only ones for
    which "contribution vs folded average" is meaningful."""
    return [name for name, m in mixables.items()
            if getattr(m, "mix", None) is None
            or getattr(m, "MIX_IS_SUM", False)]


def _flatten(tree: Any) -> List[Any]:
    import jax

    return jax.tree_util.tree_flatten(tree)[0]


def divergence_sq(diffs: Dict[str, Any], totals: Dict[str, Any],
                  n: int, names: List[str]) -> float:
    """Squared L2 distance of one member's contribution from the folded
    average (``totals / n``), summed over the summable mixables."""
    s = 0.0
    for name in names:
        if name not in diffs or name not in totals:
            continue
        own = _flatten(diffs[name])
        tot = _flatten(totals[name])
        if len(own) != len(tot):
            continue
        for a, t in zip(own, tot):
            s += _pair_sq(a, t, 1.0 / n)
    return s


def mix_health(contribs: List[Dict[str, Any]], totals: Dict[str, Any],
               names: List[str]) -> Dict[str, Any]:
    """Per-round convergence stats: relative pre-mix divergence of each
    contribution vs the folded average, and the applied step's norm.
    Divergences are normalized by the average's own norm so the signal
    is scale-free — 0.0 means the replicas agree, ~1.0 means they are
    as far apart as the update is big (learning divergence or a sick
    replica)."""
    n = len(contribs)
    if n == 0 or not names:
        return {}
    avg_sq = sum(
        _leaf_sq(t) / (n * n)
        for name in names if name in totals
        for t in _flatten(totals[name]))
    denom = math.sqrt(avg_sq) + 1e-12
    rel = [math.sqrt(divergence_sq(d, totals, n, names)) / denom
           for d in contribs]
    update_norm = math.sqrt(avg_sq) * n
    return {
        "premix_divergence_mean": round(sum(rel) / n, 6),
        "premix_divergence_max": round(max(rel), 6),
        "update_norm": round(update_norm, 6),
        "contributors": n,
    }


class LinearCommunication:
    """Communication seam (≙ linear_communication, linear_mixer.hpp:35-72)."""

    def update_members(self) -> List[NodeInfo]:
        raise NotImplementedError

    def try_lock(self) -> bool:
        raise NotImplementedError

    def unlock(self) -> None:
        raise NotImplementedError

    def get_schemas(self) -> List[List[str]]:
        """Fan out get_schema; per-host row vocabularies (default: none)."""
        return []

    def sync_schema(self, union: List[str]) -> None:
        """Broadcast the union schema for pre-diff row alignment."""

    def get_diff(self) -> List[Tuple[NodeInfo, bytes]]:
        """Fan out get_diff; per-host packed diffs (failures skipped)."""
        raise NotImplementedError

    def put_diff(self, packed: bytes) -> Dict[str, bool]:
        """Broadcast the reduced diff; host name → accepted."""
        raise NotImplementedError

    def get_model(self, member: NodeInfo) -> bytes:
        raise NotImplementedError

    def register_active(self, node: NodeInfo, active: bool) -> None:
        pass


class RpcLinearCommunication(LinearCommunication):
    def __init__(
        self,
        coord: Coordinator,
        engine: str,
        name: str,
        timeout: float = 10.0,
    ) -> None:
        self.coord = coord
        self.engine = engine
        self.name = name
        self.timeout = timeout
        self._members: List[NodeInfo] = []
        #: per-member circuit breakers (rpc/breaker.py): a member that
        #: has been failing its mix RPCs for a while is skipped by the
        #: fan-out (instant BreakerOpen host error instead of a timeout
        #: burned EVERY round) and re-admitted via half-open probes. The
        #: registry is installed by the owning mixer's
        #: set_trace_registry, so trips count as mix.breaker_open there.
        self.breakers = BreakerBoard(counter_prefix="mix.breaker")
        self._mc: Optional[RpcMClient] = None  # persistent session pool

    def update_members(self) -> List[NodeInfo]:
        self._members = membership.get_all_nodes(self.coord, self.engine, self.name)
        # elastic membership (ISSUE 10): draining members are mid-exit —
        # they stopped accepting effectful work and will unregister, so
        # they must not count against the round's quorum denominator
        # (the EPOCH's member set, not the booted-process set)
        try:
            draining = {m.name for m in membership.get_draining(
                self.coord, self.engine, self.name)}
        except Exception:  # broad-ok — a coord hiccup must not stop mix
            draining = set()
        if draining:
            self._members = [m for m in self._members
                             if m.name not in draining]
        if self._members:
            hosts = self._hosts()
            if self._mc is None:
                self._mc = RpcMClient(hosts, self.timeout,
                                      breakers=self.breakers)
            else:
                self._mc.set_hosts(hosts)
        return self._members

    def membership_epoch(self) -> int:
        """The ring version this round's member set was read under."""
        try:
            return membership.get_epoch(self.coord, self.engine, self.name)
        except Exception:  # broad-ok
            return 0

    def _lock_path(self) -> str:
        return f"{membership.actor_path(self.engine, self.name)}/master_lock"

    def try_lock(self) -> bool:
        return self.coord.try_lock(self._lock_path())

    def unlock(self) -> None:
        self.coord.unlock(self._lock_path())

    def _hosts(self) -> List[Tuple[str, int]]:
        return [(m.host, m.port) for m in self._members]

    def get_schemas(self) -> List[List[str]]:
        results, errors = self._mc.call_collect("mix_get_schema", self.name)
        for e in errors:
            # a host missing schema sync would contribute row-misaligned
            # diffs; surface it loudly (its get_diff may still succeed)
            log.warning("get_schema failed: %s", e)
        return [r for _, r in results]

    def sync_schema(self, union: List[str]) -> None:
        _results, errors = self._mc.call_collect("mix_sync_schema", self.name, union)
        for e in errors:
            log.warning("sync_schema failed: %s", e)

    def get_diff(self) -> List[Tuple[NodeInfo, bytes]]:
        # chaos site (utils/faults.py): drop = the whole gather vanishes
        # on the wire, error/delay model a sick master-side fan-out
        if faults.is_armed() and faults.fire("mix.comm.get_diff"):
            return []
        results, errors = self._mc.call_collect("mix_get_diff", self.name)
        for e in errors:
            log.warning("get_diff failed: %s", e)
        return [(NodeInfo(h, p), r) for (h, p), r in results]

    def put_diff(self, packed: bytes) -> Dict[str, bool]:
        # chaos site: drop = the broadcast is lost (no member acks)
        if faults.is_armed() and faults.fire("mix.comm.put_diff"):
            return {}
        results, errors = self._mc.call_collect("mix_put_diff", self.name, packed)
        for e in errors:
            log.warning("put_diff failed: %s", e)
        out = {f"{h}_{p}": bool(r) for (h, p), r in results}
        for e in errors:
            out[f"{e.host}_{e.port}"] = False
        return out

    def get_model(self, member: NodeInfo) -> bytes:
        with RpcClient(member.host, member.port, self.timeout) as c:
            return c.call("mix_get_model", self.name)

    def collect(self, method: str, *args):
        """Generic parallel fan-out to all members (collective mixer's
        prepare/commit/abort control RPCs); returns (results, errors)."""
        return self._mc.call_collect(method, self.name, *args)

    def close(self) -> None:
        if self._mc is not None:
            self._mc.close()
            self._mc = None

    def register_active(self, node: NodeInfo, active: bool) -> None:
        # The master only DEMOTES failed members (removal is session-less).
        # Promotion happens on the member itself via on_active — an actives
        # entry must be an ephemeral owned by the member's own session, or it
        # dies with the master instead of with the member.
        if not active:
            membership.unregister_active(
                self.coord, self.engine, self.name, node.host, node.port
            )


class RpcLinearMixer:
    """Drives one driver's participation in the cluster mix."""

    def __init__(
        self,
        driver: Any,
        comm: LinearCommunication,
        *,
        self_node: Optional[NodeInfo] = None,
        interval_sec: float = 16.0,
        interval_count: int = 512,
        quorum_fraction: float = 0.5,
        guard: Optional[MixGuard] = None,
    ) -> None:
        self.driver = driver
        self.comm = comm
        self.self_node = self_node
        #: model-integrity admission guard (ISSUE 15,
        #: framework/model_guard.py): screens every contribution before
        #: it enters a fold and every folded total before it applies —
        #: --mix-guard {off,warn,quarantine} + --mix-norm-bound
        self.guard = guard if guard is not None else MixGuard()
        #: set by the owning server: called when put_diff refuses a
        #: non-finite folded total, so the server can auto-roll back to
        #: its last-good model snapshot (+ incident bundle)
        self.on_poisoned_total: Optional[Any] = None
        #: minimum fraction of members whose diffs must arrive for the
        #: round to proceed (--mix-quorum). The reference aborts only
        #: when ALL get_diffs fail — a round folding 1 of 50 diffs then
        #: broadcasting it as everyone's new base is technically a mix
        #: but practically a rollback. Rounds that proceed with missing
        #: members are DEGRADED: counted (mix.quorum_degraded) and
        #: stamped in the flight recorder.
        self.quorum_fraction = float(quorum_fraction)
        #: per-round flight recorder (framework/mixer.py): master rounds
        #: land via the scheduler, member-side collective entries and
        #: failure reasons are recorded by the mixers directly
        self.flight = MixFlightRecorder()
        if self_node is not None:
            self.flight.node = self_node.name
        self._scheduler = IntervalMixer(
            self._mix_round,
            interval_sec=interval_sec,
            interval_count=interval_count,
            flight=self.flight,
        )
        self.mix_count = 0
        self.bytes_sent = 0
        self._obsolete = False
        #: mix epoch (≙ core::storage::version, linear_mixer.cpp:48,222-233):
        #: bumped on every applied round. A node whose version is behind the
        #: round's base missed history its peers hold only in their MASTER
        #: arrays (diffs are deltas!), so applying the fold cannot catch it
        #: up — it must pull a full model (the restart/joining case).
        self.model_version = 0
        #: the round base that declared us obsolete: recovery must pull a
        #: model at least this current or keep trying
        self._required_version = 0
        #: set by the owning server: called with True/False after each
        #: locally-applied put_diff so the member (re)registers ITSELF in the
        #: actives list through its own coordinator session
        self.on_active: Optional[Any] = None
        # -- model-health plane (ISSUE 7) --------------------------------
        #: master-side staleness bookkeeping: rounds THIS node led, and
        #: per-member (round index of last contribution, round index
        #: first seen) — staleness = rounds since a member's diff last
        #: made it into a fold this master ran
        self._rounds_led = 0
        self._member_last_contrib: Dict[str, int] = {}
        self._member_first_seen: Dict[str, int] = {}
        #: membership epoch the ledger entries were accumulated under
        #: (ISSUE 11 fix): when the CHT epoch bumps, entries for names
        #: no longer in the member set are dropped — a drained node that
        #: later rejoins under the same name is re-seeded fresh instead
        #: of inheriting rounds of bogus staleness from its past life
        self._ledger_epoch = 0
        #: did the last master round this node led proceed without every
        #: member's diff? (/healthz degraded-reason "mix_quorum_degraded")
        self.last_round_degraded = False
        #: member-side staleness: consecutive put_diffs this member
        #: failed to apply (0 = healthy; grows while obsolete/recovering)
        self.self_staleness = 0
        #: last round's convergence stats, as received in the put_diff
        #: payload (every member holds the master's computed view)
        self.last_health: Dict[str, Any] = {}

    # -- RPC surface served by the owning server (linear_mixer.cpp:270-290) --
    def register_api(self, rpc_server, name_check: str = "") -> None:
        # binary=True: these responses ship packed model/diff bytes between
        # our own servers and must keep the modern bin type even under
        # --legacy-wire (legacy clients never call mixer internals)
        rpc_server.register("mix_get_schema", lambda _name: self.local_get_schema())
        rpc_server.register(
            "mix_sync_schema", lambda _name, union: self.local_sync_schema(union)
        )
        rpc_server.register("mix_get_diff", lambda _name: self.local_get_diff(),
                            binary=True)
        rpc_server.register(
            "mix_put_diff", lambda _name, packed: self.local_put_diff(packed)
        )
        rpc_server.register("mix_get_model", lambda _name: self.local_get_model(),
                            binary=True)
        # flight recorder: structured per-round history (ISSUE 2) — the
        # same records jubadump --mix-history dumps
        rpc_server.register(
            "get_mix_history", lambda _name: self.flight.snapshot())
        # do_mix itself is served by the engine server (it delegates here)

    def local_get_schema(self) -> List[str]:
        with self.driver.lock:
            return (
                self.driver.get_schema() if hasattr(self.driver, "get_schema") else []
            )

    def local_sync_schema(self, union) -> bool:
        with self.driver.lock:
            if hasattr(self.driver, "sync_schema"):
                self.driver.sync_schema([
                    s.decode() if isinstance(s, bytes) else s for s in union
                ])
        return True

    def local_diff_obj(self, materialize: bool = False,
                       canonical_schema: bool = False) -> Dict[str, Any]:
        """My diff as a payload dict (model read lock;
        linear_mixer.cpp:562-579) — in-process consumers (push exchange)
        use this directly, skipping the wire compress/decompress.

        ``materialize=True`` copies device leaves to host numpy INSIDE
        the lock: a snapshot that outlives the lock (the RPC pack runs
        after release; async submissions outlive it by whole
        submit/fold latencies) races train steps that DONATE the very
        buffers it references (jitted train paths reuse state buffers)
        — under write load that race aborted whole sync rounds.

        ``canonical_schema=True`` is the ASYNC plane's extra contract:
        ``get_schema`` is sorted but diff ROWS sit in slot (training)
        order, and the async fold has no pre-round schema phase to
        align contributors — so the snapshot first aligns its own rows
        to its sorted vocabulary (a no-op in steady state). The sync
        round must NOT do this: its schema phase already aligned slots
        to the union, and re-sorting around a just-trained novel label
        would break the trailing-row pad tree_sum relies on.

        The lock-held time is the snapshot's ENTIRE train-path cost —
        gauged as ``mix.snapshot_stall_ms`` so the async plane's
        "train never waits on a round" claim is a measured quantity,
        not a design assertion."""
        with self.trace.span("mix.stall.snapshot") as sp:
            with self.driver.lock:
                if canonical_schema and self._has_schema() and \
                        hasattr(self.driver, "sync_schema"):
                    self.driver.sync_schema(self.driver.get_schema())
                diffs = {
                    name: m.get_diff()
                    for name, m in self.driver.get_mixables().items()
                }
                if materialize:
                    import jax
                    import numpy as np

                    diffs = jax.tree_util.tree_map(np.asarray, diffs)
                schema = (
                    self.driver.get_schema()
                    if hasattr(self.driver, "get_schema") else []
                )
        self.trace.gauge("mix.snapshot_stall_ms", round(sp.seconds * 1e3, 3))
        # chaos site (ISSUE 15): nan patches one element of a float
        # leaf (a single bad datum), scale:F multiplies the whole
        # contribution (a runaway learner) — the poisons the admission
        # guard must catch. The site carries this NODE's name (like
        # mix.async.submit.<node>) so a drill can poison exactly one
        # member of an in-process cluster; arm `mix.diff.poison*` to
        # hit any member. Mutates only the outgoing snapshot (leaves
        # copy), never the model.
        if faults.is_armed():
            site = "mix.diff.poison" + (
                f".{self.self_node.name}" if self.self_node is not None
                else "")
            mut = faults.fire_mutate(site)
            if mut is not None:
                diffs = faults.poison_tree(diffs, mut)
        return {"protocol": PROTOCOL_VERSION, "schema": schema,
                "version": self.model_version, "diffs": diffs}

    def local_get_diff(self) -> bytes:
        # materialize: the pack below runs OUTSIDE the model lock, and
        # a train step in between may donate the snapshot's buffers —
        # under write load that race aborted whole rounds ("Array has
        # been deleted" at pack time, get_diff error at the master)
        return pack_mix(self.local_diff_obj(materialize=True))

    def local_put_diff(self, packed: bytes) -> bool:
        # chaos site: drop = this member silently loses the broadcast
        # (it goes stale and recovers via the existing ladder)
        if faults.is_armed() and faults.fire("mix.put_diff"):
            return False
        return self.local_put_obj(unpack_mix(packed))

    def local_put_obj(self, msg) -> bool:
        """Apply a reduced-diff message already in object form (the
        collective mixer lands its psum result here without a wire
        pack/unpack round-trip).

        Diff leaves may be host numpy OR device ``jax.Array``s — the
        collective plane hands totals over device-resident
        (``psum_pytree(prefer_device=True)``) so a jitted ``put_diff``
        consumes them without a device→host→device bounce; mixables that
        fold into host numpy masters convert with ``np.asarray`` exactly
        as they would have paid at readback."""
        if msg.get("protocol") != PROTOCOL_VERSION:
            log.error("mix protocol mismatch: %s", msg.get("protocol"))
            return False
        health = msg.get("health")
        if isinstance(health, dict):
            self._note_health(health)
        # model-integrity plane (ISSUE 15): the last line of defense —
        # a non-finite folded total must NEVER reach the weights (NaN
        # is absorbing under the apply's adds; one poisoned broadcast
        # resets every member to garbage). quarantine mode refuses the
        # apply and asks the owning server to roll back to last-good
        # (an unguarded/old master may have applied it locally — our
        # own snapshot is the only provably-clean state); warn mode
        # counts and proceeds. The obsolete/recovery ladder is skipped
        # on refusal: the model we HOLD is good, and a peer pull could
        # import the very poison we just refused.
        # guard_screened: the collective entry already screened these
        # totals on device — a host re-screen would force a full
        # device→host copy of a prefer_device payload
        if self.guard.enabled and not msg.get("guard_screened") and \
                self._total_poisoned(msg):
            if self.guard.mode != "quarantine":
                log.warning("mix guard (warn): non-finite folded total "
                            "applied anyway")
            else:
                self._poisoned_total_rollback()
                return False
        base_version = int(msg.get("base_version", 0))
        if self.model_version < base_version:
            # I missed rounds (fresh boot / restart): the fold is deltas
            # only — reject it and pull a full model instead
            # (linear_mixer.cpp:644-652 put_diff → not_obsolete=false)
            log.warning("model obsolete (mine v%d < round base v%d); "
                        "recovering", self.model_version, base_version)
            self._required_version = base_version
            ok = False
        else:
            # everything above this lock (unpack, version gate, health
            # adoption) ran without the model lock: the apply holds it
            # only for the put_diff swaps — that lock-held time is the
            # round's whole train-path stall, gauged per apply
            with self.trace.span("mix.stall.apply") as sp:
                with self.driver.lock:
                    if msg.get("schema") and \
                            hasattr(self.driver, "sync_schema"):
                        self.driver.sync_schema(list(msg["schema"]))
                    ok = True
                    mixables = self.driver.get_mixables()
                    for name, diff in msg["diffs"].items():
                        m = mixables.get(name)
                        if m is not None:
                            ok = bool(m.put_diff(diff)) and ok
                    if ok:
                        # version bump INSIDE the lock: a reader holding
                        # the model lock sees (model, version) move
                        # together — no torn snapshot/version pairs
                        self.model_version = base_version + 1
            self.trace.gauge("mix.apply_stall_ms",
                             round(sp.seconds * 1e3, 3))
        self.trace.gauge("mix.model_version", float(self.model_version))
        self._obsolete = not ok
        # member-side staleness: every member gauges its OWN distance
        # from the cluster's round cadence (applied rounds reset it)
        self.self_staleness = 0 if ok else self.self_staleness + 1
        self.trace.gauge("mix.self_staleness", self.self_staleness)
        if self.on_active is not None:
            try:
                self.on_active(ok)
            except Exception:  # broad-ok
                log.exception("active-list transition failed")
        if not ok:
            # pull a full model from a peer once the round settles
            # (linear_mixer.cpp:404-424 runs this from the stabilizer loop)
            threading.Thread(
                target=self._recover_soon, daemon=True, name="mix-recover"
            ).start()
        return ok

    def _note_health(self, health: Dict[str, Any]) -> None:
        """Adopt one round's convergence stats (master-computed for the
        RPC mix, self-computed for the collective): remember the dict
        for get_status and publish the scalar gauges every member's
        /metrics must carry (ISSUE 7 acceptance)."""
        norm = {k.decode() if isinstance(k, bytes) else str(k): v
                for k, v in health.items()}
        self.last_health = norm
        # HLC causality (ISSUE 14): adopting a round's health payload is
        # receiving a message from the master — merge its clock so this
        # member's subsequent events sort after the fold that drove them
        if norm.get("hlc"):
            events.observe(norm["hlc"])
        for key in ("premix_divergence_mean", "premix_divergence_max",
                    "premix_divergence", "update_norm", "staleness_max",
                    "contributors"):
            v = norm.get(key)
            if isinstance(v, (int, float)):
                self.trace.gauge(f"mix.{key}", float(v))

    def _total_poisoned(self, msg) -> bool:
        """Finite screen of an incoming folded total over the summable
        mixables (model-integrity plane, ISSUE 15)."""
        try:
            names = _sum_names(self.driver.get_mixables())
            return payload_nonfinite(msg.get("diffs") or {}, names)
        except Exception:  # broad-ok — the screen must never fail an apply
            log.warning("guard total screen failed", exc_info=True)
            return False

    def _poisoned_total_rollback(self) -> None:
        """One refused non-finite total: count, emit, and hand the
        server the auto-rollback trigger (mix.rollbacks is counted by
        the server where the snapshot ring lives)."""
        log.error("mix guard: refusing non-finite folded total "
                  "(rolling back to last-good)")
        self._count("mix.guard.nonfinite_total")
        self.trace.events.emit(
            "mix", "poisoned_total_refused", severity="error")
        if self.on_poisoned_total is not None:
            try:
                self.on_poisoned_total()
            except Exception:  # broad-ok — rollback failure must not
                log.exception("auto-rollback failed")  # kill the apply path

    def _guard_round(self, entries, mixables):
        """Master-side admission screen (ISSUE 15): screen every
        contribution's summable mixables for non-finite leaves and
        update-norm outliers before anything enters the fold. Returns
        the surviving (node, payload) list + the GuardReport (None when
        the guard is off). Counting/events happen here so the keys land
        in the owning server's registry; a quarantined member's entry
        is dropped, which also keeps it out of the round's contributed
        set — its staleness ledger entry grows exactly like a member
        whose get_diff failed."""
        guard = self.guard
        if not guard.enabled or not entries:
            return entries, None
        rep = self._guard_screen(
            {node.name: p["diffs"] for node, p in entries},
            _sum_names(mixables))
        if guard.mode != "quarantine":
            return entries, rep
        return [(n, p) for n, p in entries
                if n.name in rep.admitted], rep

    def _guard_screen(self, by_member, names):
        """Run one full guard screen over member -> diffs and turn the
        report into counters/events/gauges in the owning registry (the
        sync master and the async fold share this)."""
        guard = self.guard
        rep = guard.screen(by_member, names)
        for member, reason in rep.flagged.items():
            if reason in ("nonfinite", "norm_outlier"):
                self._count(f"mix.guard.{reason}")
        if rep.flagged:
            if guard.mode == "quarantine":
                self._count("mix.quarantined", len(rep.flagged))
            self.trace.events.emit(
                "mix", "guard_flagged", severity="warning",
                mode=guard.mode, flagged=dict(rep.flagged))
        for member in rep.quarantined_now:
            log.error("mix guard: member %s quarantined", member)
            self.trace.events.emit("mix", "member_quarantined",
                                   severity="error", member=member)
        for member in rep.released:
            log.info("mix guard: member %s released from quarantine",
                     member)
            self.trace.events.emit("mix", "member_released",
                                   member=member)
        self.trace.gauge("mix.guard.quarantined_members",
                         float(len(guard.quarantined())))
        return rep

    def _recover_soon(self) -> None:
        time.sleep(0.2)  # let the master finish broadcasting this round
        try:
            self.maybe_recover()
        except Exception:  # broad-ok — retried on the next round
            log.exception("model recovery failed")

    def local_get_model(self) -> bytes:
        with self.driver.lock:
            return pack_mix(
                {"protocol": PROTOCOL_VERSION, "model": self.driver.pack(),
                 "version": self.model_version}
            )

    def set_trace_registry(self, registry) -> None:
        """Route mix.round spans into the owning server's registry (and
        the comm seam's breaker transitions with them)."""
        self._scheduler.trace = registry
        if hasattr(self.comm, "breakers"):
            self.comm.breakers.registry = registry

    @property
    def trace(self):
        """The owning server's tracing registry (mix.phase.* spans)."""
        return self._scheduler.trace

    def _count(self, name: str, n: int = 1) -> None:
        """Bump a counter in the owning server's registry."""
        self._scheduler.trace.count(name, n)

    # -- scheduling (≙ stabilizer_loop) --------------------------------------
    def start(self) -> None:
        self._scheduler.start()

    def stop(self) -> None:
        self._scheduler.stop()
        if hasattr(self.comm, "close"):
            self.comm.close()

    def updated(self, n: int = 1) -> None:
        self._scheduler.updated(n)

    def mix_now(self) -> Optional[Dict[str, Any]]:
        return self._scheduler.mix_now()

    def _has_schema(self) -> bool:
        """True iff the driver class overrides DriverBase.get_schema — only
        those engines pay the two schema fan-outs per round."""
        from jubatus_tpu.framework.driver import DriverBase

        cls_fn = getattr(type(self.driver), "get_schema", None)
        return cls_fn is not None and cls_fn is not DriverBase.get_schema

    # -- the round (≙ linear_mixer::mix) -------------------------------------
    def _mix_round(self) -> Optional[Dict[str, Any]]:
        if self._obsolete:
            self.maybe_recover()
        members = self.comm.update_members()
        if len(members) < 2 and self.self_node is not None:
            return None  # nothing to mix with
        if not self.comm.try_lock():
            return None  # someone else is master this round
        try:
            return self._run_as_master(members)
        finally:
            self.comm.unlock()

    def _run_as_master(self, members: Sequence[NodeInfo]) -> Optional[Dict[str, Any]]:
        t0 = time.monotonic()
        phases: Dict[str, Any] = {}
        # Each phase is a registry span (mix.phase.*): the flight record
        # keeps its per-round ms, the histograms accumulate the quantile
        # view, and — because the scheduler roots a trace per round — the
        # spans assemble under the round's trace_id in jubactl -c trace.
        # phase 1: schema alignment (classifier label vocab, stat keys) —
        # skipped entirely for engines that don't define a row schema
        with self.trace.span("mix.phase.schema") as sp:
            schemas = self.comm.get_schemas() if self._has_schema() else []
            schema_union: List[str] = sorted(
                set().union(*(set(s) for s in schemas))
            ) if schemas else []
            schema_union = [
                s.decode() if isinstance(s, bytes) else s
                for s in schema_union
            ]
            if schema_union:
                self.comm.sync_schema(schema_union)
        phases["schema_ms"] = round(sp.seconds * 1e3, 2)
        # phase 2: pull row-aligned diffs
        with self.trace.span("mix.phase.get_diff") as sp:
            replies = self.comm.get_diff()
            if not replies:
                log.error("mix aborted: all get_diffs failed")
                self.flight.record("rpc", ok=False,
                                   reason="all_get_diffs_failed",
                                   members=len(members))
                return None
            entries = [(node, unpack_mix(p)) for node, p in replies]
            entries = [(node, p) for node, p in entries
                       if p.get("protocol") == PROTOCOL_VERSION]
            payloads = [p for _, p in entries]
            if not payloads:
                self.flight.record("rpc", ok=False,
                                   reason="no_protocol_payloads",
                                   members=len(members))
                return None
            # quorum gate: proceeding on a sliver of the cluster would
            # broadcast a near-empty fold as everyone's new base version
            if len(payloads) < self.quorum_fraction * len(members):
                log.error("mix aborted: quorum not met (%d/%d diffs, quorum "
                          "%.0f%%)", len(payloads), len(members),
                          self.quorum_fraction * 100)
                self._count("mix.quorum_aborted")
                self.trace.events.emit(
                    "mix", "quorum_abort", severity="error",
                    contributors=len(payloads), members=len(members))
                self.flight.record(
                    "rpc", ok=False,
                    reason=f"quorum_not_met: {len(payloads)}/{len(members)}",
                    members=len(members))
                return None
            degraded = len(payloads) < len(members)
            if degraded:
                self._count("mix.quorum_degraded")
                self.trace.events.emit(
                    "mix", "quorum_degraded", severity="warning",
                    contributors=len(payloads), members=len(members))
        phases["get_diff_ms"] = round(sp.seconds * 1e3, 2)
        # phase 3: pairwise fold per mixable (linear_mixer.cpp:481-499)
        with self.trace.span("mix.phase.fold") as sp:
            mixables = self.driver.get_mixables()
            # model-integrity admission screen (ISSUE 15): quarantine a
            # poisoned contribution BEFORE it enters the fold — NaN is
            # absorbing under tree_sum, and the broadcast would poison
            # every member in one round
            entries, guard_rep = self._guard_round(entries, mixables)
            if not entries:
                log.error("mix aborted: every contribution quarantined")
                self._count("mix.guard.all_quarantined")
                self.flight.record("rpc", ok=False,
                                   reason="all_quarantined",
                                   members=len(members))
                return None
            payloads = [p for _, p in entries]
            totals: Dict[str, Any] = {}
            for name, mixable in mixables.items():
                diffs = [p["diffs"][name] for p in payloads
                         if name in p["diffs"]]
                if not diffs:
                    continue
                custom_mix = getattr(mixable, "mix", None)
                if custom_mix is not None:
                    totals[name] = functools.reduce(custom_mix, diffs)
                else:
                    totals[name] = tree_sum(diffs)
            # the round's base = the most advanced contributor; anyone
            # behind it cannot be caught up by deltas and must recover a
            # full model
            base_version = max(
                (int(p.get("version", 0)) for p in payloads), default=0
            )
            # mix-convergence telemetry (ISSUE 7): divergence of each
            # contribution vs the folded average + per-member staleness,
            # shipped INSIDE the put_diff payload so every member (not
            # just the master) gauges the round's health. Old peers
            # ignore the extra key — the protocol version is unchanged.
            health = mix_health([p["diffs"] for p in payloads], totals,
                                _sum_names(mixables))
            health.update(self._staleness_update(
                members, {node.name for node, _ in entries}))
            # master-side total screen (ISSUE 15): even with every
            # contribution admitted, the FOLD can overflow to inf —
            # never broadcast a non-finite total (quarantine mode
            # aborts the round; warn counts and proceeds)
            if self.guard.enabled and \
                    payload_nonfinite(totals, _sum_names(mixables)):
                self._count("mix.guard.nonfinite_total")
                self.trace.events.emit(
                    "mix", "nonfinite_fold_total", severity="error",
                    mode=self.guard.mode)
                if self.guard.mode == "quarantine":
                    log.error("mix aborted: folded total is non-finite")
                    self.flight.record("rpc", ok=False,
                                       reason="nonfinite_fold_total",
                                       members=len(members))
                    return None
            # event plane (ISSUE 14): the master's HLC rides the
            # broadcast; receivers observe() it in _note_health, so a
            # member's post-apply events sort after the round that
            # caused them even under skewed wall clocks
            health["hlc"] = events.hlc_now()
            packed = pack_mix(
                {"protocol": PROTOCOL_VERSION, "schema": schema_union,
                 "base_version": base_version, "diffs": totals,
                 "health": health}
            )
        phases["fold_ms"] = round(sp.seconds * 1e3, 2)
        with self.trace.span("mix.phase.put_diff") as sp:
            acks = self.comm.put_diff(packed)
        phases["put_diff_ms"] = round(sp.seconds * 1e3, 2)
        # active-list transitions (linear_mixer.cpp:658-681): master demotes
        # failures; successes promote themselves via on_active
        for member in members:
            if not acks.get(member.name, False):
                self.comm.register_active(member, False)
        self.mix_count += 1
        self.bytes_sent += len(packed)
        self._count("mix.bytes_shipped", len(packed))
        log.info(
            "mix round %d: %d members, %d bytes, %.3fs",
            self.mix_count, len(members), len(packed), time.monotonic() - t0,
        )
        self.last_round_degraded = bool(degraded)
        # elastic membership (ISSUE 10): stamp the ring version the
        # round's member set (and therefore its quorum denominator) was
        # read under — churn forensics read it off the flight record
        epoch = self.comm.membership_epoch() \
            if hasattr(self.comm, "membership_epoch") else 0
        if epoch:
            self.trace.gauge("mix.epoch", float(epoch))
        return {"members": len(members), "bytes": len(packed),
                "mode": "rpc", "phases": phases,
                "contributors": len(payloads),
                "degraded": True if degraded else None,
                "epoch": epoch or None,
                "health": health or None,
                "quarantined": sorted(guard_rep.flagged)
                if guard_rep is not None and guard_rep.flagged else None,
                "acked": sum(bool(v) for v in acks.values())}

    def _staleness_update(self, members: Sequence[NodeInfo],
                          contributed: set) -> Dict[str, Any]:
        """Advance the master-side staleness ledger for one led round
        and return the health fields: per-member rounds since last
        contribution (0 = contributed this round) and the max.

        The ledger is keyed by node name and survives membership epoch
        changes by REBASING (ISSUE 11 fix): when the CHT epoch bumps,
        entries for names no longer registered are dropped, so a node
        that drained away and later rejoined under the same name is
        seeded fresh by the setdefault below instead of inheriting the
        staleness its past incarnation accrued while gone."""
        epoch = self.comm.membership_epoch() \
            if hasattr(self.comm, "membership_epoch") else 0
        if epoch != self._ledger_epoch:
            current = {m.name for m in members}
            for ledger in (self._member_last_contrib,
                           self._member_first_seen):
                for name in [n for n in ledger if n not in current]:
                    del ledger[name]
            self._ledger_epoch = epoch
        self._rounds_led += 1
        idx = self._rounds_led
        staleness: Dict[str, int] = {}
        for m in members:
            self._member_first_seen.setdefault(m.name, idx - 1)
            if m.name in contributed:
                self._member_last_contrib[m.name] = idx
            base = self._member_last_contrib.get(
                m.name, self._member_first_seen[m.name])
            staleness[m.name] = idx - base
        if not staleness:
            return {}
        return {"staleness": staleness,
                "staleness_max": max(staleness.values())}

    # -- obsolete-model recovery (linear_mixer.cpp:404-424,598-632) ----------
    def maybe_recover(self) -> bool:
        if not self._obsolete:
            return False
        members = [
            m for m in self.comm.update_members()
            if self.self_node is None or m.name != self.self_node.name
        ]
        if not members:
            return False
        # a random member may be another stale joiner mid-recovery; try a
        # few and accept only a model at least as current as the round base
        # that declared us obsolete (the reference re-tries each stabilizer
        # tick until current, linear_mixer.cpp:404-424)
        random.shuffle(members)
        for peer in members[:3]:
            try:
                packed = self.comm.get_model(peer)
            except Exception as e:  # broad-ok — dead peer, try another
                log.warning("recovery pull from %s failed: %s", peer.name, e)
                continue
            msg = unpack_mix(packed)
            if msg.get("protocol") != PROTOCOL_VERSION:
                raise RuntimeError(
                    "protocol version mismatch on recovery — restart")
            version = int(msg.get("version", 0))
            if version < self._required_version:
                log.info("peer %s model v%d < required v%d; trying another",
                         peer.name, version, self._required_version)
                continue
            with self.driver.lock:
                self.driver.unpack(msg["model"])
            self.model_version = version
            self._obsolete = False
            log.info("recovered full model (v%d) from %s",
                     version, peer.name)
            return True
        return False  # retried next stabilizer tick / round

    def get_status(self) -> Dict[str, Any]:
        st = self._scheduler.get_status()
        st.update({"bytes_sent": self.bytes_sent, "obsolete": self._obsolete,
                   "model_version": self.model_version,
                   "quorum_fraction": self.quorum_fraction,
                   "self_staleness": self.self_staleness,
                   "last_round_degraded": self.last_round_degraded})
        # model-integrity plane (ISSUE 15): guard mode + quarantine set
        st.update(self.guard.status())
        for k, v in self.last_health.items():
            if isinstance(v, (int, float, dict)):
                st[f"health_{k}"] = v
        breakers = getattr(self.comm, "breakers", None)
        if breakers is not None:
            snap = breakers.snapshot()
            st["breaker_backends"] = len(snap)
            st["breaker_open"] = sum(
                1 for b in snap.values() if b["state"] == "open")
        return st
