"""State-migration data plane (elastic membership, ISSUE 10).

CHT-routed engines (recommender / nearest_neighbor / …) place rows on
ring successors; a membership change moves ranges between owners. This
module is the machinery that moves the ROWS with them:

- ``serve_range`` — the SOURCE side of the ``migrate_range`` RPC: walk my
  row store in sorted-id order from a cursor and return the rows the
  requesting member owns under the CURRENT ring, bounded by a byte
  budget per chunk. Pure read — the puller owns the cursor, so a
  re-issued chunk fetch re-reads the same rows (idempotent).
- ``RangePuller`` — the DESTINATION side: a chunked, double-buffered
  pull. The next chunk's ``migrate_range`` RPC is in flight on a reader
  thread while the current chunk applies through ``put_rows`` — the same
  ship/apply overlap as the mix plane's transfer engine
  (parallel/collective.py), over RPC instead of the device interconnect,
  and it borrows that engine's chunk budget (``DEFAULT_CHUNK_MB``).
  Sources that die mid-stream fail over to the remaining sources: with
  CHT replication >= 2 every row the dead source held exclusively for us
  is also on its ring successor, which is in the source list.
- ``DrainController`` — the departing member's state machine:
  ``active → draining → handoff → drained``. Draining flips the RPC
  dispatch gate (new EFFECTFUL calls are rejected with the retryable
  ``NodeDraining`` BEFORE any state change, so proxies re-route;
  in-flight work finishes), handoff pushes every local row to its new
  ring owners in byte-bounded ``put_rows`` chunks, drained unregisters.

Epoch protocol: every ``migrate_range`` carries the caller's membership
epoch; the source rejects a mismatch with the retryable
``EpochMismatch`` — the puller refreshes its ring/epoch view and
resumes from its cursor. No chunk is ever applied under a ring the two
sides disagree about.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from jubatus_tpu.coord.base import NodeInfo
from jubatus_tpu.coord.cht import CHT
from jubatus_tpu.rpc.errors import EpochMismatch, RpcError
from jubatus_tpu.utils import faults

log = logging.getLogger(__name__)

#: chunk byte budget: ride the mix data plane's chunk plan (the transfer
#: shapes are the same order of magnitude and the same wire)
from jubatus_tpu.parallel.collective import DEFAULT_CHUNK_MB  # noqa: E402

DEFAULT_CHUNK_BYTES = max(1 << 16, int(DEFAULT_CHUNK_MB * 2 ** 20))

#: CHT successor count rows are replicated onto (the engines' #@cht(2))
REPLICATION = 2


def row_owned_by(ring: CHT, row_id: str, member_name: str,
                 n: int = REPLICATION) -> bool:
    """Is ``member_name`` one of the ``n`` ring successors of the row?"""
    return any(m.name == member_name for m in ring.find(row_id, n))


def _row_bytes(row: Sequence[Any]) -> int:
    """Cheap size estimate for the chunk budget: id + 12 B per (idx,
    val) pair + the stored datum blob when present."""
    rid, ii, _vv = row[0], row[1], row[2]
    datum = row[3] if len(row) > 3 else None
    size = len(rid) + 12 * len(ii) + 16
    if isinstance(datum, (bytes, bytearray, str)):
        size += len(datum)
    elif datum is not None:
        size += 64
    return size


def serve_range(driver: Any, ring: CHT, target: str, cursor: str,
                limit_bytes: int = DEFAULT_CHUNK_BYTES,
                n: int = REPLICATION) -> Dict[str, Any]:
    """One source-side chunk: rows after ``cursor`` (sorted id order)
    that ``target`` owns under ``ring``, up to ``limit_bytes``. Returns
    ``{"rows": [...], "cursor": next, "done": bool}``; ``cursor`` is the
    LAST id included, so resume is exact even if ids are inserted
    concurrently (sorted-order walk).

    The walk is host-metadata only: ``row_ids``/``get_rows`` read the
    store's per-shard host arenas (parallel/row_store.py), so serving a
    range from a device-sharded 10⁸-row store never materializes the
    device table (tests/test_row_store_sharded.py pins this)."""
    if not hasattr(driver, "get_rows") or not hasattr(driver, "row_ids"):
        return {"rows": [], "cursor": "", "done": True}
    limit_bytes = max(1, int(limit_bytes))
    ids = sorted(driver.row_ids())
    out: List[Any] = []
    size = 0
    last = str(cursor or "")
    for rid in ids:
        if last and rid <= last:
            continue
        if not row_owned_by(ring, rid, target, n):
            continue
        rows = driver.get_rows([rid])
        if not rows:
            continue  # raced a concurrent remove
        row = rows[0]
        out.append(row)
        last = rid
        size += _row_bytes(row)
        if size >= limit_bytes:
            return {"rows": out, "cursor": last, "done": False}
    return {"rows": out, "cursor": "", "done": True}


class MigrationStats:
    """Counters for one node's migration plane, mirrored into the
    tracing registry (``migration.rows_moved`` / ``migration.bytes``
    counters, ``migration.active`` gauge)."""

    def __init__(self, registry: Any = None) -> None:
        self.registry = registry
        self._lock = threading.Lock()
        self.rows_moved = 0
        self.bytes_moved = 0
        self.chunks = 0
        self.failovers = 0
        self.pulls = 0
        self.active = 0
        self.last_error = ""

    def note_chunk(self, rows: int, nbytes: int) -> None:
        with self._lock:
            self.rows_moved += rows
            self.bytes_moved += nbytes
            self.chunks += 1
        if self.registry is not None:
            if rows:
                self.registry.count("migration.rows_moved", rows)
            if nbytes:
                self.registry.count("migration.bytes", nbytes)

    def note_failover(self) -> None:
        with self._lock:
            self.failovers += 1
        if self.registry is not None:
            self.registry.count("migration.failovers")

    def set_active(self, active: bool) -> None:
        with self._lock:
            self.active += 1 if active else -1
            self.active = max(0, self.active)
            if active:
                self.pulls += 1
            val = self.active
        if self.registry is not None:
            self.registry.gauge("migration.active", float(val))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"rows_moved": self.rows_moved,
                    "bytes": self.bytes_moved,
                    "chunks": self.chunks,
                    "failovers": self.failovers,
                    "pulls": self.pulls,
                    "active": self.active,
                    "last_error": self.last_error}


class RangePuller:
    """Destination side of a migration: pull my owned ranges from a list
    of source members, chunked and double-buffered (chunk N+1's RPC is
    in flight while chunk N applies locally).

    ``client_factory(node)`` must return an object with
    ``call(method, *args)`` (an rpc.client.RpcClient works); the puller
    closes nothing — callers own connection lifecycle."""

    def __init__(self, cluster: str, target: str,
                 apply_rows: Callable[[List[Any]], int],
                 client_factory: Callable[[NodeInfo], Any],
                 stats: Optional[MigrationStats] = None,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 epoch_of: Optional[Callable[[], int]] = None) -> None:
        self.cluster = cluster
        self.target = target
        self.apply_rows = apply_rows
        self.client_factory = client_factory
        self.stats = stats or MigrationStats()
        self.chunk_bytes = int(chunk_bytes)
        #: current-epoch reader: re-queried after an EpochMismatch so the
        #: pull resumes under the refreshed ring
        self.epoch_of = epoch_of or (lambda: 0)

    def _fetch(self, cli: Any, epoch: int, cursor: str) -> Dict[str, Any]:
        # chaos site (utils/faults.py): delay models a slow source,
        # error a mid-stream death — both exercise the puller's
        # failover/resume ladder deterministically
        if faults.is_armed():
            faults.fire("migration.pull")
        doc = cli.call("migrate_range", self.cluster, int(epoch),
                       self.target, cursor, self.chunk_bytes)
        if not isinstance(doc, dict):
            raise RpcError(f"malformed migrate_range reply: {type(doc)}")
        return {(k.decode() if isinstance(k, bytes) else k): v
                for k, v in doc.items()}

    def _pull_source(self, node: NodeInfo) -> Tuple[int, int]:
        """Drain one source; returns (rows, bytes). Double-buffered: the
        next chunk is fetched on the reader executor while the current
        one applies."""
        cli = self.client_factory(node)
        rows_total = bytes_total = 0
        cursor = ""
        epoch = int(self.epoch_of())
        with ThreadPoolExecutor(max_workers=1,
                                thread_name_prefix="migrate-read") as ex:
            try:
                nxt = self._fetch(cli, epoch, cursor)
            except EpochMismatch:
                epoch = int(self.epoch_of())
                nxt = self._fetch(cli, epoch, cursor)
            while True:
                rows = nxt.get("rows") or []
                cursor = nxt.get("cursor") or ""
                done = bool(nxt.get("done"))
                fut = None
                if not done:
                    # ship/apply overlap: next chunk crosses the wire
                    # while this one lands in the row store
                    fut = ex.submit(self._fetch, cli, epoch, cursor)
                if rows:
                    applied = int(self.apply_rows(rows))
                    nbytes = sum(_row_bytes(r) for r in rows)
                    rows_total += applied
                    bytes_total += nbytes
                    self.stats.note_chunk(applied, nbytes)
                if done:
                    return rows_total, bytes_total
                try:
                    nxt = fut.result()
                except EpochMismatch:
                    # ring moved under us: adopt the new epoch and
                    # resume from the cursor (rows are overwrite-
                    # idempotent, so a replayed boundary row is safe)
                    epoch = int(self.epoch_of())
                    log.info("migrate_range epoch refresh (now %d), "
                             "resuming from %r", epoch, cursor)
                    nxt = self._fetch(cli, epoch, cursor)

    def pull(self, sources: Sequence[NodeInfo]) -> Dict[str, Any]:
        """Pull my owned ranges from every source (skipping myself).
        A source that dies mid-stream is abandoned and counted as a
        failover — its rows are also on its ring successor, which is in
        the source list (replication >= 2), so coverage holds."""
        t0 = time.monotonic()
        self.stats.set_active(True)
        rows = nbytes = 0
        failed: List[str] = []
        try:
            for node in sources:
                if node.name == self.target:
                    continue
                try:
                    r, b = self._pull_source(node)
                except Exception as e:  # broad-ok — failover is the plan
                    log.warning("migration pull from %s failed: %s",
                                node.name, e)
                    self.stats.note_failover()
                    self.stats.last_error = f"{node.name}: {e}"
                    failed.append(node.name)
                    continue
                rows += r
                nbytes += b
        finally:
            self.stats.set_active(False)
        secs = max(time.monotonic() - t0, 1e-9)
        return {"rows": rows, "bytes": nbytes, "seconds": round(secs, 3),
                "mb_per_sec": round(nbytes / 2 ** 20 / secs, 3),
                "sources_failed": failed}


class DrainController:
    """Departing-member state machine. One instance per EngineServer;
    ``run`` drives ``active → draining → handoff → drained`` on a
    background thread (the ``drain`` RPC returns immediately with the
    current state).

    - **draining**: unregister from actives + mark the coordinator's
      draining/ node (quorum stops counting us, proxies stop routing new
      CHT/random traffic our way), flip the dispatch gate so new
      effectful calls are rejected with ``NodeDraining`` (retryable —
      they re-route), wait for in-flight work (RPC workers + coalescer
      queues) to finish.
    - **handoff**: push every local row to its new ring owners
      (byte-bounded ``put_rows`` chunks).
    - **drained**: clear the draining marker; optionally remove the
      nodes/ registration, which fires the suicide watcher and stops
      the server (``stop_after``).
    """

    STATES = ("active", "draining", "handoff", "drained")

    def __init__(self, server: Any, grace_sec: float = 1.0,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> None:
        self.server = server
        self.grace_sec = float(grace_sec)
        self.chunk_bytes = int(chunk_bytes)
        self.state = "active"  # no-event — initial state, not a transition
        self.rows_handed_off = 0
        self.bytes_handed_off = 0
        self.error = ""
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # -- gate -----------------------------------------------------------------
    def _install_gate(self) -> None:
        from jubatus_tpu.framework.idl import idempotent_methods
        from jubatus_tpu.rpc.errors import NodeDraining

        allowed = set(idempotent_methods(self.server.engine))
        # drain's own control surface must keep answering
        allowed.update({"drain", "get_status", "get_metrics"})
        trace = self.server.rpc.trace

        def gate(method: str) -> None:
            if method in allowed:
                return
            trace.count("rpc.drain_rejected")
            raise NodeDraining(f"{method}: node draining")

        self.server.rpc.dispatch_gate = gate

    def _set_state(self, state: str) -> None:
        with self._lock:
            self.state = state
        trace = self.server.rpc.trace
        trace.gauge("drain.state", float(self.STATES.index(state)))
        # event plane (ISSUE 14): every drain phase edge on the timeline
        trace.events.emit("drain", state,
                          rows_handed_off=self.rows_handed_off or None)
        log.info("drain: %s", state)

    def _wait_inflight(self) -> None:
        """In-flight work finishes on its own (the gate only rejects NEW
        dispatches); wait for the coalescer queues to empty, bounded by
        the grace period."""
        deadline = time.monotonic() + max(self.grace_sec, 0.0)
        while time.monotonic() < deadline:
            busy = False
            for co in self.server.coalescers.values():
                if getattr(co, "_pending_items", None):
                    busy = True
                    break
            if not busy:
                # one short beat for dispatches between gate and queue
                time.sleep(min(0.1, self.grace_sec))
                return
            time.sleep(0.05)

    # -- handoff --------------------------------------------------------------
    def _handoff(self) -> None:
        srv = self.server
        driver = srv.driver
        if not (hasattr(driver, "get_rows") and hasattr(driver, "row_ids")):
            return  # replicated engines carry no CHT-owned rows
        from jubatus_tpu.coord import membership

        me = srv.self_nodeinfo()
        actives = [m for m in membership.get_all_actives(
            srv.coord, srv.engine, srv.args.name) if m.name != me.name]
        if not actives:
            log.warning("drain: no remaining actives — rows stay local")
            return
        ring = CHT(actives,
                   epoch=membership.get_epoch(srv.coord, srv.engine,
                                              srv.args.name))
        with driver.lock:
            ids = sorted(driver.row_ids())
        # group rows by new owner, ship in byte-bounded chunks
        by_owner: Dict[str, Tuple[NodeInfo, List[Any], int]] = {}
        stats = srv.migration

        def flush(owner_key: str) -> None:
            node, rows, size = by_owner.pop(owner_key)
            if not rows:
                return
            try:
                srv.peer_client(node).call("put_rows", srv.args.name, rows)
                self.rows_handed_off += len(rows)
                self.bytes_handed_off += size
                stats.note_chunk(len(rows), size)
            except Exception as e:  # broad-ok — best-effort per owner
                log.warning("drain handoff to %s failed: %s", node.name, e)
                srv.drop_peer_client(node)
                stats.note_failover()
                self.error = f"{node.name}: {e}"

        for rid in ids:
            with driver.lock:
                rows = driver.get_rows([rid])
            if not rows:
                continue
            row = rows[0]
            size = _row_bytes(row)
            for owner in ring.find(rid, REPLICATION):
                entry = by_owner.get(owner.name)
                if entry is None:
                    entry = by_owner[owner.name] = (owner, [], 0)
                node, rows_acc, acc = entry
                rows_acc.append(row)
                by_owner[owner.name] = (node, rows_acc, acc + size)
                if acc + size >= self.chunk_bytes:
                    flush(owner.name)
        for key in list(by_owner):
            flush(key)

    # -- the state machine ----------------------------------------------------
    def start(self, stop_after: bool = False) -> str:
        """Kick the drain off (idempotent — a second call reports the
        current state)."""
        with self._lock:
            if self._thread is not None:
                return self.state
            self._thread = threading.Thread(
                target=self._run, args=(bool(stop_after),),
                daemon=True, name="drain")
        self._thread.start()
        return "draining"

    def _run(self, stop_after: bool) -> None:
        srv = self.server
        from jubatus_tpu.coord import membership

        try:
            self._set_state("draining")
            me = srv.self_nodeinfo()
            if srv.coord is not None:
                try:
                    membership.mark_draining(
                        srv.coord, srv.engine, srv.args.name,
                        me.host, me.port)
                    membership.unregister_active(
                        srv.coord, srv.engine, srv.args.name,
                        me.host, me.port)
                except Exception:  # broad-ok — drain must proceed
                    log.warning("drain: coordinator update failed",
                                exc_info=True)
            # a draining member must not re-promote itself on the next
            # healthy put_diff
            if srv.mixer is not None:
                srv.mixer.on_active = None
            self._install_gate()
            self._wait_inflight()
            self._set_state("handoff")
            self._handoff()
            self._set_state("drained")
            if srv.coord is not None:
                try:
                    membership.clear_draining(
                        srv.coord, srv.engine, srv.args.name,
                        me.host, me.port)
                except Exception:  # broad-ok
                    log.debug("drain: clear marker failed", exc_info=True)
                if stop_after:
                    # removing our nodes/ entry fires the suicide
                    # watcher — the clean unregister-then-exit path
                    try:
                        srv.coord.remove(
                            f"{membership.actor_path(srv.engine, srv.args.name)}"
                            f"/nodes/{me.name}")
                    except Exception:  # broad-ok
                        log.debug("drain: node unregister failed",
                                  exc_info=True)
        except Exception as e:  # broad-ok — surface via drain_status
            self.error = str(e)
            log.exception("drain failed")

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self.state,
                    "rows_handed_off": self.rows_handed_off,
                    "bytes_handed_off": self.bytes_handed_off,
                    "error": self.error}
