"""Interval-driven mix scheduling (≙ linear_mixer's stabilizer_loop).

Reference behavior (mixer/linear_mixer.cpp:362-435, defaults from
server_util.cpp:223-228): a background thread wakes at most every 0.5 s and
fires a mix when update_count >= interval_count (512) OR elapsed >=
interval_sec (16 s) with at least one update. Here the mix itself is a
collective (parallel/mix.py) executed by a supplied callable, so the same
scheduler drives LocalMixGroup in tests and the pod collective in production.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from jubatus_tpu.utils import events, tracing
from jubatus_tpu.utils.tracing import Registry, default_registry


class MixFlightRecorder:
    """Bounded ring of structured per-round mix records (the flight
    recorder ISSUE 2 calls for): round id, mode (rpc / collective /
    push strategy / master), success + failure reason, duration, per-phase
    wall times (the ``ship_ms``/``reduce_ms``/``readback_ms``/``chunks``
    dict the collective plane stamps), peers/bytes. Owned by each mixer,
    queryable over the ``get_mix_history`` RPC and dumped by ``jubadump
    --mix-history`` — the post-mortem the reference's per-round log lines
    scroll away."""

    def __init__(self, capacity: int = 128) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        #: owner's node name (set by the server once the port is known)
        self.node = ""

    def record(self, mode: str, *, ok: bool = True, round_id: str = "",
               reason: str = "", duration_ms: Optional[float] = None,
               phases: Optional[Dict[str, Any]] = None,
               **fields: Any) -> Dict[str, Any]:
        # ISSUE 14 satellite: flight records ride the event plane's HLC
        # helper instead of an ad-hoc wall-clock stamp, so `jubactl -c
        # timeline` and the mix history agree on ordering; ``ts`` stays
        # the human-readable wall seconds derived from the same tick
        h = events.hlc_now()
        rec: Dict[str, Any] = {
            "mode": mode, "ok": bool(ok),
            "hlc": h,
            "ts": round(events.hlc_wall_s(h), 3),
            "node": self.node,
        }
        if round_id:
            rec["round_id"] = round_id
        if reason:
            rec["reason"] = reason
        if duration_ms is not None:
            rec["duration_ms"] = round(duration_ms, 3)
        if phases:
            rec["phases"] = dict(phases)
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
        return rec

    def snapshot(self, last: int = 0) -> List[Dict[str, Any]]:
        """Oldest-first copy of the ring (the newest ``last`` when > 0)."""
        with self._lock:
            out = list(self._ring)
        return out[-last:] if last > 0 else out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            recs = list(self._ring)
            total = self._seq
        return {"recorded": total,
                "retained": len(recs),
                "failed_retained": sum(1 for r in recs if not r["ok"])}


class IntervalMixer:
    POLL_SEC = 0.5  # linear_mixer.cpp:372-374

    def __init__(
        self,
        mix_fn: Callable[[], Any],
        *,
        interval_sec: float = 16.0,
        interval_count: int = 512,
        flight: Optional[MixFlightRecorder] = None,
    ) -> None:
        self._mix_fn = mix_fn
        self.interval_sec = interval_sec
        self.interval_count = interval_count
        #: fire the interval tick even with zero local updates — the
        #: async mix plane (framework/async_mixer.py) sets this: a fold
        #: tick must consume OTHER members' submitted diffs whether or
        #: not this process trained anything since the last round
        self.fire_idle = False
        #: set by the owning server so mix spans land in ITS registry
        self.trace: Registry = default_registry()
        #: per-round flight records; an owning mixer passes its own so
        #: scheduler-level and mixer-level records share one ring
        self.flight = flight if flight is not None else MixFlightRecorder()
        self._counter = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._mix_serialize = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # status counters (reference linear_mixer.cpp:349-360)
        self.mix_count = 0
        self.last_mix_duration = 0.0
        self._last_mix_time = time.monotonic()

    # -- server integration --------------------------------------------------
    def updated(self, n: int = 1) -> None:
        """Called on every model update (server_base::event_model_updated)."""
        with self._cond:
            self._counter += n
            if self._counter >= self.interval_count:
                self._cond.notify()

    def mix_now(self) -> Any:
        """Synchronous mix (the reference's do_mix RPC)."""
        return self._run_mix()

    def set_interval(self, sec: float) -> float:
        """Retarget the cadence (ISSUE 20 async-mix cadence tuner). The
        loop polls, so a new interval takes effect within POLL_SEC; the
        caller (the tuner) owns the floor/ceiling policy — here we only
        refuse non-positive values. Returns the applied interval."""
        with self._cond:
            self.interval_sec = max(0.001, float(sec))
            self._cond.notify()
            return self.interval_sec

    def _run_mix(self) -> Any:
        """Execute one mix round WITHOUT holding the condition lock: updated()
        callers (the train hot path) must never block behind a collective.
        _mix_serialize keeps concurrent mix_now/loop rounds from overlapping.

        Every round roots a FRESH trace context: the ``mix.round`` span
        (and the master's phase spans + the members' mix_* dispatch spans,
        which inherit the context through the RPC fan-out) land in the
        span store under one trace_id, stamped into the flight record —
        ``jubactl -c trace <id>`` then shows a mix round's cross-node
        anatomy next to the RPC traffic it contended with."""
        ctx = tracing.new_root()
        with self._mix_serialize, tracing.use_trace(ctx):
            with self._cond:
                self._counter = 0
            # event plane (ISSUE 14): round start/end bracket the
            # timeline; the end event's hlc cross-links into the flight
            # record (event_hlc) so -c timeline and --mix-history agree
            self.trace.events.emit("mix", "round_start", severity="debug")
            try:
                with self.trace.span("mix.round") as sp:
                    result = self._mix_fn()
            except Exception as e:  # broad-ok — mix_fn is arbitrary
                self.trace.count("mix.round.errors")
                evt = self.trace.events.emit(
                    "mix", "round_error", severity="error",
                    reason=f"{type(e).__name__}: {e}")
                self.flight.record(
                    "error", ok=False,
                    reason=f"{type(e).__name__}: {e}",
                    duration_ms=sp.seconds * 1e3,
                    trace_id=ctx.trace_id,
                    event_hlc=evt["hlc"] if evt else None)
                raise
            with self._cond:
                self.last_mix_duration = sp.seconds
                self.mix_count += 1
                self._last_mix_time = time.monotonic()
            if isinstance(result, dict):
                # mixers annotate their round result (mode, members,
                # bytes, phases, round_id); record it as one flight entry
                extra = dict(result)
                mode = extra.pop("mode", "mix")
                phases = extra.pop("phases", None)
                rid = extra.pop("round_id", "")
                for k in ("ok", "reason", "duration_ms", "ts", "node",
                          "seq", "trace_id", "hlc", "event_hlc"):
                    extra.pop(k, None)  # reserved record fields
                evt = self.trace.events.emit(
                    "mix", "round", mode=mode, round_id=rid or None,
                    duration_ms=round(self.last_mix_duration * 1e3, 1),
                    degraded=extra.get("degraded"),
                    contributors=extra.get("contributors"))
                self.flight.record(
                    mode, ok=True, round_id=rid, phases=phases,
                    duration_ms=self.last_mix_duration * 1e3,
                    trace_id=ctx.trace_id,
                    event_hlc=evt["hlc"] if evt else None, **extra)
            return result

    # -- background loop ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True, name="mixer")
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    return
                self._cond.wait(timeout=self.POLL_SEC)
                if not self._running:
                    return
                elapsed = time.monotonic() - self._last_mix_time
                due = self._counter >= self.interval_count or (
                    (self._counter > 0 or self.fire_idle)
                    and elapsed >= self.interval_sec
                )
            if due:
                try:
                    self._run_mix()  # outside the cond lock
                except Exception:  # broad-ok — must not kill the loop
                    import logging

                    logging.getLogger(__name__).exception("mix round failed")

    def get_status(self) -> Dict[str, Any]:
        st = {
            "mix_count": self.mix_count,
            "counter": self._counter,
            "interval_sec": self.interval_sec,
            "interval_count": self.interval_count,
            "last_mix_duration": self.last_mix_duration,
        }
        for k, v in self.flight.stats().items():
            st[f"flight_{k}"] = v
        return st
