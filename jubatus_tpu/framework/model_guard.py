"""Model-integrity plane: poisoned-update quarantine + last-good
snapshots (ISSUE 15).

Every robustness plane before this one protects *availability* —
retries, breakers, elastic membership, async staleness, autoscaling.
None protects the *model*: mix is model averaging, so one replica gone
sick (a NaN from a bad datum, a norm-exploded diff from a runaway
learner, a bit-flipped wire chunk) poisons every peer's weights in a
single round, and deltas give no path back. This module is the
admission control and the way back:

- **MixGuard** — fold-time admission screen shared by the sync master
  (``linear_mixer._run_as_master``), the async fold
  (``async_mixer._weighted_fold``), and the async inbox
  (``local_submit_diff``). Two screens, in order:

  * **finite screen** — any non-finite element in a summable mixable's
    diff rejects the contribution outright. NaN is absorbing under
    addition: one admitted NaN leaf makes the folded total NaN and the
    broadcast resets EVERY member's weights to garbage.
  * **norm screen** — a contribution whose update norm exceeds
    ``--mix-norm-bound`` × the median of its PEERS' norms this round is
    an outlier (leave-one-out median: robust with as few as two
    contributors, and a 1e6-scaled diff cannot drag its own baseline
    up). An all-quiet baseline (peer median 0) judges nothing — the
    norm screen needs evidence of what "normal" is; the finite screen
    is the absolute one.

  Verdicts feed a per-member **quarantine breaker**: ``quarantine_after``
  consecutive offenses exclude the member's contributions from every
  fold until it screens clean ``release_after`` consecutive rounds.
  Mode ladder (``--mix-guard``): ``off`` — no screening (and no cost);
  ``warn`` — screen, count, emit, fold everything anyway;
  ``quarantine`` — screened-out contributions are dropped from the fold
  and repeat offenders trip the breaker. The guard is pure decision
  machinery: counting/events stay in the owning mixer so the keys land
  in the server's registry.

- **ModelSnapshotRing** — a bounded ring of periodic in-process model
  snapshots in the save_load envelope format (48-byte header + CRC32),
  so a restore revalidates integrity exactly like a checkpoint load.
  ``put_diff`` refusing a non-finite folded total auto-rolls back to
  the newest snapshot (server/base.py wires the callback); operators
  roll back explicitly with ``jubactl -c rollback --target``.

The collective path cannot screen payloads on the host (diffs stay
device-resident); its finite screen and per-chunk CRC live in
``parallel/collective.py`` and surface through the same counters.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

GUARD_MODES = ("off", "warn", "quarantine")

#: consecutive screened offenses that trip the per-member quarantine
#: breaker, and consecutive clean screens that release it
DEFAULT_QUARANTINE_AFTER = 2
DEFAULT_RELEASE_AFTER = 3


def norm_mode(mode: Any) -> str:
    m = (mode or "off").lower() if isinstance(mode, str) else \
        ("quarantine" if mode else "off")
    if m not in GUARD_MODES:
        raise ValueError(f"unknown mix guard mode {mode!r}; "
                         f"expected one of {GUARD_MODES}")
    return m


def _leaves(tree: Any) -> List[Any]:
    import jax

    return jax.tree_util.tree_flatten(tree)[0]


def tree_nonfinite(tree: Any) -> bool:
    """True when any element of any leaf is NaN/Inf. Leaves are host
    numpy on every screened path (mix payloads materialize before the
    wire); a stray device leaf round-trips through np.asarray."""
    for leaf in _leaves(tree):
        a = np.asarray(leaf)
        if a.dtype == object:
            continue  # non-numeric custom leaf: not summable anyway
        if not np.isfinite(a).all():
            return True
    return False


def payload_nonfinite(diffs: Dict[str, Any], names: List[str]) -> bool:
    """Finite screen over one contribution's SUMMABLE mixables (the
    ones whose fold is addition, where NaN is absorbing)."""
    return any(name in diffs and tree_nonfinite(diffs[name])
               for name in names)


def payload_norm(diffs: Dict[str, Any], names: List[str]) -> float:
    """L2 norm of one contribution over the summable mixables — the
    quantity the norm screen compares across the round's peers."""
    s = 0.0
    for name in names:
        if name not in diffs:
            continue
        for leaf in _leaves(diffs[name]):
            a = np.asarray(leaf)
            if a.dtype == object:
                continue
            d = a * 1.0
            s += float((d * d).sum())
    return math.sqrt(s)


def norm_outliers(norms: Dict[str, float], bound: float) -> Dict[str, float]:
    """member -> peer-median baseline, for every member whose norm
    exceeds ``bound`` × the median of the OTHER members' norms.
    Leave-one-out keeps the screen honest at small N (with two
    contributors, a 1e6-scaled diff is judged against its healthy peer,
    not a median it dominates). A non-positive peer baseline judges
    nothing: on a quiet fleet there is no evidence of normal scale."""
    out: Dict[str, float] = {}
    if bound <= 0 or len(norms) < 2:
        return out
    for member, n in norms.items():
        others = [v for m, v in norms.items() if m != member]
        base = float(np.median(others))
        if base > 0.0 and n > bound * base:
            out[member] = base
    return out


class GuardReport:
    """One round's screening outcome: what folds, what was flagged and
    why, and the breaker transitions the mixer turns into counters and
    timeline events."""

    __slots__ = ("admitted", "flagged", "norms", "quarantined_now",
                 "released")

    def __init__(self) -> None:
        self.admitted: Dict[str, Dict[str, Any]] = {}
        #: member -> reason in {"nonfinite", "norm_outlier", "quarantined"}
        self.flagged: Dict[str, str] = {}
        self.norms: Dict[str, float] = {}
        self.quarantined_now: List[str] = []
        self.released: List[str] = []


class MixGuard:
    """Fold-time admission guard + per-member quarantine breaker.

    Thread-safe: the async inbox screens from RPC worker threads while
    the fold tick screens from the mixer thread."""

    def __init__(self, mode: Any = "off", norm_bound: float = 10.0,
                 quarantine_after: int = DEFAULT_QUARANTINE_AFTER,
                 release_after: int = DEFAULT_RELEASE_AFTER) -> None:
        self.mode = norm_mode(mode)
        self.norm_bound = float(norm_bound)
        self.quarantine_after = int(quarantine_after)
        self.release_after = int(release_after)
        self._lock = threading.Lock()
        self._offenses: Dict[str, int] = {}
        self._clean: Dict[str, int] = {}
        self._quarantined: Dict[str, float] = {}  # member -> since ts
        #: lifetime totals (mirrored into counters by the owning mixer;
        #: kept here too so get_status works without registry plumbing)
        self.screened = 0
        self.rejected = 0

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def is_quarantined(self, member: str) -> bool:
        with self._lock:
            return member in self._quarantined

    def _note_offense(self, member: str) -> bool:
        """Record one screened offense; True when it TRIPS the breaker
        (caller emits the quarantine event exactly once)."""
        with self._lock:
            self._clean.pop(member, None)
            if member in self._quarantined:
                return False
            n = self._offenses.get(member, 0) + 1
            self._offenses[member] = n
            if self.mode == "quarantine" and n >= self.quarantine_after:
                self._quarantined[member] = time.monotonic()
                self._offenses.pop(member, None)
                return True
        return False

    def _note_clean(self, member: str) -> bool:
        """Record one clean screen; True when it RELEASES the member
        from quarantine (K consecutive clean rounds)."""
        with self._lock:
            self._offenses.pop(member, None)
            if member not in self._quarantined:
                return False
            n = self._clean.get(member, 0) + 1
            self._clean[member] = n
            if n >= self.release_after:
                del self._quarantined[member]
                del self._clean[member]
                return True
        return False

    def screen(self, entries: Dict[str, Dict[str, Any]],
               names: List[str]) -> GuardReport:
        """Screen one round's contributions (member -> diffs). In
        quarantine mode, ``admitted`` excludes flagged members and
        members already behind the breaker; warn mode admits everything
        and only reports. ``off`` short-circuits (no screening cost)."""
        rep = GuardReport()
        if not self.enabled or not entries:
            rep.admitted = dict(entries)
            return rep
        self.screened += len(entries)
        verdicts: Dict[str, Optional[str]] = {}
        finite_members: Dict[str, Dict[str, Any]] = {}
        for member, diffs in entries.items():
            if payload_nonfinite(diffs, names):
                verdicts[member] = "nonfinite"
            else:
                finite_members[member] = diffs
                rep.norms[member] = payload_norm(diffs, names)
        for member, base in norm_outliers(rep.norms,
                                          self.norm_bound).items():
            verdicts[member] = "norm_outlier"
        for member, diffs in entries.items():
            reason = verdicts.get(member)
            quarantined = self.is_quarantined(member)
            if reason is None:
                if self._note_clean(member):
                    rep.released.append(member)
                    quarantined = False
            else:
                if self._note_offense(member):
                    rep.quarantined_now.append(member)
                    quarantined = True
                rep.flagged[member] = reason
            if self.mode == "quarantine" and quarantined and \
                    member not in rep.flagged:
                # behind the breaker: clean rounds count toward release
                # but the contribution stays out of the fold until K
                rep.flagged[member] = "quarantined"
            if self.mode == "quarantine" and member in rep.flagged:
                self.rejected += 1
                continue
            rep.admitted[member] = diffs
        return rep

    def screen_payload(self, member: str, diffs: Dict[str, Any],
                       names: List[str]) -> Optional[str]:
        """Single-payload admission screen (the async inbox): the
        finite screen plus the breaker — no peer distribution exists
        yet, so norm outliers are judged at fold time. Returns the flag
        reason ("nonfinite" / "quarantined") or None; the caller
        REJECTS only in quarantine mode (warn flags and admits). A
        quarantined member's clean payload still counts toward its
        K-clean release."""
        if not self.enabled:
            return None
        self.screened += 1
        if payload_nonfinite(diffs, names):
            self._note_offense(member)
            if self.mode == "quarantine":
                self.rejected += 1
            return "nonfinite"
        if self.mode == "quarantine" and self.is_quarantined(member):
            self._note_clean(member)
            if self.is_quarantined(member):
                self.rejected += 1
                return "quarantined"
        return None

    def quarantined(self) -> Dict[str, float]:
        """member -> seconds in quarantine (status/watch view)."""
        now = time.monotonic()
        with self._lock:
            return {m: round(now - t, 1)
                    for m, t in self._quarantined.items()}

    def status(self) -> Dict[str, Any]:
        with self._lock:
            q = sorted(self._quarantined)
            offenses = dict(self._offenses)
        return {
            "guard_mode": self.mode,
            "guard_norm_bound": self.norm_bound,
            "guard_screened": self.screened,
            "guard_rejected": self.rejected,
            "guard_quarantined": q,
            "guard_offense_streaks": offenses,
        }


class ModelSnapshotRing:
    """Bounded ring of in-process model snapshots — the rollback
    plane's "last good". Entries are full save_load envelopes (header +
    CRC32 + system + user sections), so ``restore`` revalidates exactly
    like a checkpoint load: a snapshot that rotted in RAM refuses to
    apply instead of substituting one corruption for another."""

    def __init__(self, capacity: int = 4) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._ring: List[Dict[str, Any]] = []
        self.taken = 0
        self.restored = 0

    def snapshot(self, driver, model_version: int) -> Dict[str, Any]:
        """Capture one snapshot. The caller holds the driver's model
        lock — pack() must see a quiescent model."""
        from jubatus_tpu.utils.serialization import pack_obj

        system = {"version": 1, "timestamp": int(time.time()),  # wall-clock
                  "type": driver.TYPE, "id": "snapshot",
                  "model_version": int(model_version), "config": ""}
        from jubatus_tpu.framework.save_load import pack_envelope

        blob = pack_envelope(
            pack_obj(system),
            pack_obj([driver.USER_DATA_VERSION, driver.pack()]))
        entry = {"model_version": int(model_version),
                 "ts": time.time(),  # wall-clock
                 "bytes": len(blob), "blob": blob}
        with self._lock:
            self._ring.append(entry)
            if len(self._ring) > self.capacity:
                self._ring.pop(0)
            self.taken += 1
        return entry

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def restore(self, driver, entry: Optional[Dict[str, Any]] = None) -> int:
        """Apply a snapshot (default: newest) back into the driver —
        CRC-validated through the same read_envelope every checkpoint
        load uses. The caller holds the driver's model lock. Returns
        the snapshot's model_version."""
        from jubatus_tpu.framework.save_load import read_envelope
        from jubatus_tpu.utils.serialization import unpack_obj

        if entry is None:
            entry = self.latest()
        if entry is None:
            raise RuntimeError("no model snapshot to roll back to "
                               "(--model-snapshot-interval off?)")
        system_b, user_b = read_envelope(entry["blob"], "snapshot-ring")
        system = unpack_obj(system_b)
        user_version, user_data = unpack_obj(user_b)
        if user_version != driver.USER_DATA_VERSION:
            raise RuntimeError(
                f"snapshot user data version {user_version} != "
                f"{driver.USER_DATA_VERSION}")
        driver.unpack(user_data)
        with self._lock:
            self.restored += 1
        return int(system.get("model_version", 0))

    def list(self) -> List[Dict[str, Any]]:
        """Metadata view (no blobs) for status / jubactl."""
        with self._lock:
            return [{k: v for k, v in e.items() if k != "blob"}
                    for e in self._ring]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            newest = self._ring[-1] if self._ring else None
            return {
                "count": len(self._ring),
                "capacity": self.capacity,
                "taken": self.taken,
                "restored": self.restored,
                "bytes": sum(e["bytes"] for e in self._ring),
                "last_model_version": (newest or {}).get(
                    "model_version", -1),
                "last_age_s": round(
                    time.time() - newest["ts"], 1)  # wall-clock
                if newest else -1.0,
            }
