"""Durable model plane (ISSUE 18): a shared snapshot store the fleet
can die and come back from.

Every durability primitive before this PR was node-local: the ISSUE 15
``ModelSnapshotRing`` lives in the server process, ``save``/``load``
write per-node files under ``--datadir``, and a spawned replica boots
empty — a fleet-wide crash loses the model entirely and an autoscaler
scale-out pays full re-learn/migration. This module is the durable
plane those paths hang off: a blob store (pluggable backend; local
directory now, the API shaped like an object store — put/get/list/
delete on flat keys) holding CRC'd checkpoint envelopes plus
**incremental diff-chains**, with store-side compaction so restore
cost stays bounded.

Store layout (flat keys under the backend root)::

    <cluster>/<engine>/full/<hlc:020d>.<version:012d>.<node>.jub
    <cluster>/<engine>/diff/<hlc:020d>.<version:012d>.<node>.jub

Record metadata (HLC stamp, mix ``model_version``, uploading node)
lives in the key so listing is cheap; record BYTES are always a
48-byte-header CRC envelope (framework/save_load.py):

- **full** records are byte-identical to a ``save_model`` envelope
  (system container + ``[user_data_version, driver.pack()]``), so the
  per-node ``load`` RPC and ``jubadump`` consume them unchanged.
- **diff** records carry ``kind: "diff"`` in the system container and a
  structural delta document in the user section: unchanged subtrees are
  skipped, changed non-float leaves ship as raw replacements, and float
  ndarray deltas optionally ride the same blockwise-int8 scheme as the
  mix wire plane (``compress="int8"``), with the uploader holding the
  error-feedback residual in its *belief* state so the chain's
  cumulative quantization error telescopes to ONLY the last diff's —
  the "bounded diff-chain tail" the kill-everything drill measures.

Chain semantics: each diff's ``base_hlc`` names the record it applies
on top of (the previous diff or the anchoring full). ``materialize``
replays full + contiguous chain and REFUSES to cross a gap (a dropped
upload), falling back to the longest valid prefix. ``compact`` replays
the chain store-side into a new full record and deletes the folded
diffs — by construction chain replay == compacted full, which
tests/test_model_store.py pins.

Fault sites (chaos drills arm these; docs/ROBUSTNESS.md §11):
``store.put`` / ``store.get`` (error + delay + drop + bitflip corrupt)
in the backend, ``store.compact`` around compaction. A corrupt record
is REFUSED by the CRC check (counted ``store.crc_refused``), never
loaded — a flaky store degrades warm-boot to cold-boot + migration,
never a wrong or partial model.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from jubatus_tpu.framework.save_load import (
    FORMAT_VERSION,
    SaveLoadError,
    pack_envelope,
    read_envelope,
)
from jubatus_tpu.utils import events, faults
from jubatus_tpu.utils.serialization import pack_obj, unpack_obj

__all__ = ["BlobBackend", "LocalDirBackend", "ModelStore", "StoreRecord",
           "StoreUploader", "diff_tree", "apply_diff"]

#: blockwise-int8 quantization block, matching the mix wire plane's
#: granularity so the store's lossy mode shares its error model
QUANT_BLOCK = 256

#: version tag inside every diff record's user section
DIFF_DOC_VERSION = 1


class BlobBackend:
    """Object-store-shaped blob API: flat string keys, whole-value
    put/get, prefix list, delete. Implementations must be atomic per
    put (a reader never sees a half-written value)."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError


class LocalDirBackend(BlobBackend):
    """Local-directory backend (one file per key, tmp + rename atomic
    put). The fault sites ``store.put`` / ``store.get`` live HERE so a
    chaos rule exercises every consumer — uploads, warm-boots, fleet
    restores — through one choke point. ``bitflip`` rules corrupt the
    bytes (put: before write; get: after read) so the envelope CRC
    refusal path is what the drills prove."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        if ".." in key or key.startswith("/"):
            raise ValueError(f"bad store key {key!r}")
        return os.path.join(self.root, key)

    def put(self, key: str, data: bytes) -> None:
        if faults.fire("store.put"):
            return  # drop rule: the upload is silently lost
        mutation = faults.fire_mutate("store.put")
        if mutation and mutation[0] == "bitflip":
            data = faults.flip_byte(data)
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, key: str) -> bytes:
        faults.fire("store.get")
        with open(self._path(key), "rb") as f:
            raw = f.read()
        mutation = faults.fire_mutate("store.get")
        if mutation and mutation[0] == "bitflip":
            raw = faults.flip_byte(raw)
        return raw

    def list(self, prefix: str = "") -> List[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            rel = "" if rel == "." else rel + "/"
            for name in files:
                if name.endswith(".tmp"):
                    continue
                key = rel + name
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass


class StoreRecord:
    """One parsed store key: kind ("full"/"diff"), HLC stamp, mix
    model_version, uploading node."""

    __slots__ = ("key", "kind", "hlc", "version", "node")

    def __init__(self, key: str, kind: str, hlc: int, version: int,
                 node: str) -> None:
        self.key = key
        self.kind = kind
        self.hlc = hlc
        self.version = version
        self.node = node

    def __repr__(self) -> str:
        return (f"StoreRecord({self.kind} hlc={self.hlc} "
                f"v={self.version} node={self.node})")


def _tree_children(node: Any):
    """(key, child) pairs for container nodes, None for leaves. Only
    dicts and lists recurse — everything else (ndarray, bytes, scalars)
    is a leaf."""
    if isinstance(node, dict):
        return list(node.items())
    if isinstance(node, list):
        return list(enumerate(node))
    return None


def _leaf_equal(a: Any, b: Any) -> bool:
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
                and a.dtype == b.dtype and a.shape == b.shape
                and bool(np.array_equal(a, b)))
    return type(a) is type(b) and a == b


def _quant_int8(delta: np.ndarray) -> Tuple[bytes, bytes]:
    """Blockwise-int8 quantization of a float delta (block=QUANT_BLOCK,
    per-block absmax scale): returns (int8 bytes, f32 scale bytes)."""
    flat = np.ascontiguousarray(delta, dtype=np.float32).reshape(-1)
    pad = (-flat.size) % QUANT_BLOCK
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, dtype=np.float32)])
    blocks = flat.reshape(-1, QUANT_BLOCK)
    scales = np.abs(blocks).max(axis=1) / 127.0
    safe = np.where(scales > 0.0, scales, 1.0).astype(np.float32)
    q = np.clip(np.round(blocks / safe[:, None]), -127, 127).astype(np.int8)
    return q.tobytes(), scales.astype(np.float32).tobytes()


def _dequant_int8(qbytes: bytes, sbytes: bytes, shape, dtype) -> np.ndarray:
    q = np.frombuffer(qbytes, dtype=np.int8).reshape(-1, QUANT_BLOCK)
    scales = np.frombuffer(sbytes, dtype=np.float32)
    flat = (q.astype(np.float32) * scales[:, None]).reshape(-1)
    size = int(np.prod(shape)) if shape else 1
    return flat[:size].reshape(shape).astype(dtype)


def diff_tree(base: Any, new: Any, *, compress: str = "off"):
    """Structural delta from ``base`` to ``new`` (both normalized trees,
    i.e. already round-tripped through pack_obj/unpack_obj so dicts/
    lists/ndarrays are canonical).

    Returns ``(doc, belief)`` where ``doc`` is the diff document and
    ``belief`` is the tree a replayer ends up with after applying
    ``doc`` to ``base`` — identical to ``new`` in lossless mode
    (``compress="off"``), and ``new`` minus the current quantization
    residual in ``int8`` mode (the caller keeps ``belief`` as the next
    diff's base so the residual feeds forward — error feedback).

    Rules: unchanged subtrees are skipped; a container whose child-key
    set changed is replaced whole (raw); changed float ndarray leaves
    with matching shape/dtype ship bit-exact leaf bytes (``compress=
    "off"``) or int8-quantized additive deltas (``compress="int8"``);
    every other changed leaf ships raw."""
    changed: List[list] = []
    belief = _copy_tree(base)

    def walk(b: Any, n: Any, path: List) -> Any:
        bc, nc = _tree_children(b), _tree_children(n)
        if bc is not None and nc is not None and type(b) is type(n) \
                and [k for k, _ in bc] == [k for k, _ in nc]:
            out = b if isinstance(b, dict) else list(b)
            for key, nchild in nc:
                sub = walk(b[key], nchild, path + [key])
                if isinstance(b, dict):
                    b[key] = sub
                else:
                    out[key] = sub
            if isinstance(b, dict):
                return b
            return out
        if bc is None and nc is None:
            if isinstance(b, np.ndarray) and isinstance(n, np.ndarray) \
                    and b.shape == n.shape and b.dtype == n.dtype \
                    and np.issubdtype(n.dtype, np.floating):
                if np.array_equal(b, n):
                    return b
                delta = n.astype(np.float32) - b.astype(np.float32)
                if compress == "int8":
                    qb, sb = _quant_int8(delta)
                    changed.append([path, {"m": "i8", "q": qb, "s": sb,
                                           "sh": list(n.shape),
                                           "dt": n.dtype.str}])
                    approx = (b.astype(np.float32) + _dequant_int8(
                        qb, sb, n.shape, np.float32)).astype(n.dtype)
                    return approx
                # lossless mode ships the changed leaf's own bytes, not a
                # delta: base + (new - base) in f32 does NOT reconstruct
                # new exactly (rounding), and a delta is the same size as
                # the leaf anyway — deltas only pay off under quantization.
                changed.append([path, {"m": "b", "d": n.tobytes(),
                                       "sh": list(n.shape),
                                       "dt": n.dtype.str}])
                return n
            if _leaf_equal(b, n):
                return b
        # structure changed, non-float leaf, or leaf/container swap:
        # ship the whole new subtree raw
        changed.append([path, {"m": "raw", "b": pack_obj(n)}])
        return _copy_tree(n)

    belief = walk(belief, new, [])
    return {"v": DIFF_DOC_VERSION, "changed": changed}, belief


def apply_diff(base: Any, doc: dict) -> Any:
    """Replay one diff document onto ``base`` (mutates and returns it).
    Inverse of ``diff_tree``: raises SaveLoadError on version or path
    mismatch instead of guessing — a broken chain must refuse, not
    approximate."""
    if doc.get("v") != DIFF_DOC_VERSION:
        raise SaveLoadError(f"diff doc version {doc.get('v')!r} unsupported")
    for path, spec in doc["changed"]:
        if not path:
            base = _apply_leaf(base, spec)
            continue
        parent = base
        try:
            for part in path[:-1]:
                parent = parent[part]
            old = parent[path[-1]]
            parent[path[-1]] = _apply_leaf(old, spec)
        except (KeyError, IndexError, TypeError) as e:
            raise SaveLoadError(f"diff path {path!r} missing in base: {e}")
    return base


def _apply_leaf(old: Any, spec: dict) -> Any:
    mode = spec["m"]
    if mode == "raw":
        return unpack_obj(spec["b"])
    shape = tuple(spec["sh"])
    dtype = np.dtype(spec["dt"])
    if not isinstance(old, np.ndarray) or old.shape != shape:
        raise SaveLoadError("additive diff leaf has no matching base array")
    if mode == "b":
        # bit-exact leaf replacement (lossless mode)
        return np.frombuffer(spec["d"], dtype=dtype).reshape(shape).copy()
    if mode == "i8":
        delta = _dequant_int8(spec["q"], spec["s"], shape, np.float32)
        return (old.astype(np.float32) + delta).astype(dtype)
    raise SaveLoadError(f"unknown diff leaf mode {mode!r}")


def _copy_tree(node: Any) -> Any:
    if isinstance(node, dict):
        return {k: _copy_tree(v) for k, v in node.items()}
    if isinstance(node, list):
        return [_copy_tree(v) for v in node]
    return node


class ModelStore:
    """The durable model plane over a blob backend: CRC'd full
    snapshots + diff chains per uploading node, namespaced by
    ``<cluster>/<engine>``. Thread-safe for the server's use (one
    uploader thread + restore RPCs): the backend is the serialization
    point; this class keeps no mutable state beyond counters."""

    def __init__(self, backend: BlobBackend, *, cluster: str, engine: str,
                 counter: Optional[Callable[..., Any]] = None) -> None:
        self.backend = backend
        self.cluster = cluster or "standalone"
        self.engine = engine
        self._counter = counter

    # -- counters ---------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        if self._counter is not None:
            self._counter(key, n)

    # -- keys -------------------------------------------------------
    def _prefix(self, kind: str = "") -> str:
        base = f"{self.cluster}/{self.engine}/"
        return base + (kind + "/" if kind else "")

    def _key(self, kind: str, hlc: int, version: int, node: str) -> str:
        safe_node = node.replace("/", "_") or "local"
        return (f"{self._prefix(kind)}{hlc:020d}.{version:012d}"
                f".{safe_node}.jub")

    def _parse(self, key: str) -> Optional[StoreRecord]:
        rest = key[len(self._prefix()):]
        kind, _, name = rest.partition("/")
        if kind not in ("full", "diff") or not name.endswith(".jub"):
            return None
        try:
            hlc_s, ver_s, node = name[:-len(".jub")].split(".", 2)
            return StoreRecord(key, kind, int(hlc_s), int(ver_s), node)
        except ValueError:
            return None

    # -- writes (every path CRC-stamps via pack_envelope) -----------
    def put_full(self, system: dict, user_payload: bytes, *, node: str,
                 model_version: int, hlc: Optional[int] = None) -> str:
        """Upload a full snapshot. ``user_payload`` is the already
        msgpack'd ``[user_data_version, state]`` section; the record is
        byte-identical to a save_model envelope of the same content."""
        blob = pack_envelope(pack_obj(system), user_payload)
        return self.put_blob(blob, kind="full", node=node,
                             model_version=model_version, hlc=hlc)

    def put_blob(self, blob: bytes, *, kind: str, node: str,
                 model_version: int, hlc: Optional[int] = None) -> str:
        """Upload pre-packed envelope bytes (the save RPC's own file
        bytes ride through here unchanged). Refuses a blob that does
        not parse as a CRC-valid envelope — the store never holds an
        unstamped record."""
        read_envelope(blob, f"store:{kind}")  # CRC stamp precondition
        key = self._key(kind, hlc if hlc is not None else events.hlc_now(),
                        model_version, node)
        try:
            self.backend.put(key, blob)
        except Exception as e:  # broad-ok — any backend failure counts
            self._count("store.put_errors")
            events.emit("store", "put_failed", severity="error",
                        key=key, error=str(e)[:200])
            raise
        self._count("store.puts")
        self._count("store.bytes_uploaded", len(blob))
        self._count("store.fulls" if kind == "full" else "store.diffs")
        return key

    def put_diff(self, doc: dict, *, node: str, model_version: int,
                 base_hlc: int, model_id: str = "", config: str = "",
                 hlc: Optional[int] = None) -> str:
        """Append one diff record to ``node``'s chain: CRC envelope
        whose system container names the base record's HLC."""
        system = {
            "version": FORMAT_VERSION,
            "timestamp": int(time.time()),  # wall-clock
            "type": self.engine,
            "id": model_id,
            "kind": "diff",
            "base_hlc": int(base_hlc),
            "config": config,
        }
        blob = pack_envelope(pack_obj(system),
                             pack_obj([DIFF_DOC_VERSION, doc]))
        return self.put_blob(blob, kind="diff", node=node,
                             model_version=model_version, hlc=hlc)

    # -- reads ------------------------------------------------------
    def fetch(self, key: str) -> bytes:
        """CRC-validated read: returns raw envelope bytes or raises.
        A CRC/format refusal is counted separately from transport
        errors — the drills assert corrupt records are refused, never
        loaded."""
        try:
            raw = self.backend.get(key)
        except SaveLoadError:
            raise
        except Exception as e:  # broad-ok — any backend failure counts
            self._count("store.get_errors")
            events.emit("store", "get_failed", severity="warning",
                        key=key, error=str(e)[:200])
            raise
        self._count("store.gets")
        try:
            read_envelope(raw, key)
        except SaveLoadError:
            self._count("store.crc_refused")
            events.emit("store", "crc_refused", severity="error", key=key)
            raise
        self._count("store.bytes_fetched", len(raw))
        return raw

    def records(self, *, kind: str = "", node: str = "") -> List[StoreRecord]:
        """Parsed records sorted by (hlc, version), optionally filtered
        by kind and uploading node."""
        out = []
        for key in self.backend.list(self._prefix(kind)):
            rec = self._parse(key)
            if rec is None:
                continue
            if node and rec.node != node:
                continue
            out.append(rec)
        out.sort(key=lambda r: (r.hlc, r.version, r.kind))
        return out

    def nodes(self) -> List[str]:
        return sorted({r.node for r in self.records(kind="full")})

    def resolve(self, *, at: Optional[int] = None, node: str = "",
                ) -> Tuple[Optional[StoreRecord], List[StoreRecord]]:
        """The restore plan at ``at`` (HLC; None = latest): newest full
        record ≤ at, plus the longest CONTIGUOUS diff chain on top of
        it (each diff's base_hlc naming its predecessor is validated by
        materialize; here contiguity means hlc-ordered diffs newer than
        the full, up to ``at``)."""
        fulls = [r for r in self.records(kind="full", node=node)
                 if at is None or r.hlc <= at]
        if not fulls:
            return None, []
        full = fulls[-1]
        chain = [r for r in self.records(kind="diff", node=full.node)
                 if r.hlc > full.hlc and (at is None or r.hlc <= at)]
        return full, chain

    def materialize(self, *, at: Optional[int] = None, node: str = "",
                    ) -> Tuple[bytes, Dict[str, Any]]:
        """Replay full + diff chain into full envelope bytes. Walks the
        chain in HLC order, REFUSING to cross a gap (base_hlc mismatch
        — a dropped or corrupt upload truncates replay at the longest
        valid prefix rather than skipping records). Raises SaveLoadError
        when no full snapshot resolves."""
        full, chain = self.resolve(at=at, node=node)
        if full is None:
            raise SaveLoadError(
                f"store {self._prefix()}: no full snapshot"
                + (f" at hlc<={at}" if at is not None else ""))
        raw = self.fetch(full.key)
        system_bytes, user_bytes = read_envelope(raw, full.key)
        if not chain:
            return raw, {"key": full.key, "hlc": full.hlc,
                         "model_version": full.version, "chain_len": 0,
                         "node": full.node}
        user_version, state = unpack_obj(user_bytes)
        applied = 0
        cur_hlc = full.hlc
        cur_version = full.version
        for rec in chain:
            try:
                diff_raw = self.fetch(rec.key)
                dsys, duser = read_envelope(diff_raw, rec.key)
                dsystem = unpack_obj(dsys)
                if dsystem.get("kind") != "diff" \
                        or dsystem.get("base_hlc") != cur_hlc:
                    break  # gap: a record in between was lost
                doc_version, doc = unpack_obj(duser)
                if doc_version != DIFF_DOC_VERSION:
                    break
                state = apply_diff(state, doc)
            except (SaveLoadError, OSError):
                break  # corrupt/missing link truncates the chain here
            applied += 1
            cur_hlc = rec.hlc
            cur_version = rec.version
        blob = pack_envelope(system_bytes,
                             pack_obj([user_version, state]))
        return blob, {"key": full.key, "hlc": cur_hlc,
                      "model_version": cur_version, "chain_len": applied,
                      "node": full.node}

    def materialize_all(self, *, at: Optional[int] = None,
                        ) -> Dict[str, Tuple[bytes, Dict[str, Any]]]:
        """Per-node materialized snapshots at ``at`` — the fleet
        restore's input (each restoring member unions the rows it owns
        from every node's snapshot). Nodes whose records fail to
        materialize are skipped (counted via fetch), never guessed."""
        out = {}
        for node in self.nodes():
            try:
                out[node] = self.materialize(at=at, node=node)
            except (SaveLoadError, OSError):
                continue
        return out

    def latest(self, *, at: Optional[int] = None,
               ) -> Optional[Tuple[bytes, Dict[str, Any]]]:
        """The single freshest materializable snapshot across nodes
        (warm-boot's pick): max by replayed (hlc, model_version)."""
        best = None
        for node in self.nodes():
            try:
                blob, meta = self.materialize(at=at, node=node)
            except (SaveLoadError, OSError):
                continue
            rank = (meta["hlc"], meta["model_version"])
            if best is None or rank > best[0]:
                best = (rank, blob, meta)
        if best is None:
            return None
        return best[1], best[2]

    # -- compaction -------------------------------------------------
    def compact(self, *, node: str, at: Optional[int] = None) -> Optional[str]:
        """Fold ``node``'s diff chain into a new full record and delete
        the folded diffs (store-side; chain replay == the compacted
        full by construction). Returns the new full's key, or None when
        there is nothing to fold. Fault site ``store.compact``."""
        faults.fire("store.compact")
        blob, meta = self.materialize(at=at, node=node)
        key = None
        if meta["chain_len"]:
            key = self.put_blob(  # no-crc — materialize() stamped blob
                blob, kind="full", node=node,
                model_version=meta["model_version"], hlc=meta["hlc"])
        # prune every diff the newest full supersedes — including the
        # orphans left behind when the uploader re-anchors with a fresh
        # full (chain_len 0 here, but older diffs are now unreachable)
        for rec in self.records(kind="diff", node=node):
            if rec.hlc <= meta["hlc"]:
                self.backend.delete(rec.key)
        if key is None:
            return None
        self._count("store.compactions")
        events.emit("store", "compacted", node=node, key=key,
                    folded=meta["chain_len"])
        return key

    def stats(self) -> Dict[str, Any]:
        recs = self.records()
        fulls = [r for r in recs if r.kind == "full"]
        diffs = [r for r in recs if r.kind == "diff"]
        return {
            "store.records_full": len(fulls),
            "store.records_diff": len(diffs),
            "store.head_hlc": max((r.hlc for r in recs), default=0),
            "store.nodes": len({r.node for r in fulls}),
        }


class StoreUploader:
    """The background upload half of the durable plane: periodically
    snapshots the driver (under its lock), diffs against the *belief*
    (what a replayer reconstructs from the chain — NOT the last true
    state, so int8 quantization error feeds back), and uploads a diff
    record; every ``compact_every`` diffs it re-anchors with a fresh
    full (and asks the store to fold the old chain), bounding both
    restore cost and the lossy tail. One instance per server; the
    server's telemetry thread drives ``tick``."""

    def __init__(self, store: ModelStore, node: str, *,
                 model_id: str = "", config: str = "",
                 compress: str = "off", compact_every: int = 8) -> None:
        self.store = store
        self.node = node
        self.model_id = model_id
        self.config = config
        self.compress = compress
        self.compact_every = max(int(compact_every), 1)
        self._belief: Any = None
        self._belief_hlc = 0
        self._chain_len = 0
        self._last_version = -1
        self._tick_lock = threading.Lock()

    def tick(self, driver, model_version: int, *,
             force_full: bool = False) -> Optional[str]:
        """One upload cycle. Packs under the driver lock, encodes and
        uploads OUTSIDE it (the serving path never waits on the blob
        store). No-op when the model hasn't advanced since the last
        upload. Returns the uploaded key (None = skipped). Upload
        errors propagate — the caller counts and keeps serving.

        Serialized: two concurrent ticks would each diff against the
        same belief and upload two diffs naming the same base_hlc —
        the replayer's gap check would refuse the second and truncate
        the chain there."""
        with self._tick_lock:
            return self._tick_locked(driver, model_version,
                                     force_full=force_full)

    def _tick_locked(self, driver, model_version: int, *,
                     force_full: bool = False) -> Optional[str]:
        if model_version == self._last_version and not force_full:
            return None
        with driver.lock:
            version = model_version
            user_payload = pack_obj([driver.USER_DATA_VERSION,
                                     driver.pack()])
            driver_type = driver.TYPE
        hlc = events.hlc_now()
        full_due = (force_full or self._belief is None
                    or self._chain_len >= self.compact_every)
        if full_due:
            system = {
                "version": FORMAT_VERSION,
                "timestamp": int(time.time()),  # wall-clock
                "type": driver_type,
                "id": self.model_id,
                "config": self.config,
            }
            blob = pack_envelope(pack_obj(system), user_payload)
            key = self.store.put_blob(blob, kind="full", node=self.node,
                                      model_version=version, hlc=hlc)
            # belief = exactly what a replayer unpacks from the record
            _, state = unpack_obj(user_payload)
            self._belief = state
            self._belief_hlc = hlc
            if self._chain_len:
                try:
                    self.store.compact(node=self.node)
                except (SaveLoadError, OSError, faults.FaultInjected):
                    pass  # compaction is advisory; the chain still replays
            self._chain_len = 0
        else:
            _, state = unpack_obj(user_payload)
            doc, belief = diff_tree(self._belief, state,
                                    compress=self.compress)
            if not doc["changed"]:
                self._last_version = version
                return None
            key = self.store.put_diff(
                doc, node=self.node, model_version=version,
                base_hlc=self._belief_hlc, model_id=self.model_id,
                config=self.config, hlc=hlc)
            self._belief = belief
            self._belief_hlc = hlc
            self._chain_len += 1
        self._last_version = version
        return key
