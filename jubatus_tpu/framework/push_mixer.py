"""Gossip (push) mixers + candidate strategies (≙ mixer/push_mixer.{hpp,cpp}
+ broadcast_mixer / random_mixer / skip_mixer headers).

The reference's push mixers skip master election: each node, on its own
interval, picks candidate peers via a strategy and exchanges model state
pairwise (push_mixer.cpp:342-429). Strategies (mixer_factory.cpp:41-97):

- broadcast: every other member               (broadcast_mixer.hpp:46-55)
- random:    one uniformly random member      (random_mixer.hpp:45-58)
- skip:      Chord-style finger peers at offsets +1, +2, +4, ... around
             the name-sorted member ring      (skip_mixer.hpp:46-57)

Round semantics here: for each candidate, pull her packed diff
(``mix_get_diff`` — the same RPC surface the linear mixer serves, so push
and linear nodes interoperate), fold it with my own diff per mixable, and
apply the fold on BOTH sides (``mix_put_diff``). Each exchange is exactly
a 2-party linear mix; repeated gossip rounds converge the cluster without
any per-round master, trading the linear mixer's O(N) master fan-out for
elastic, leaderless propagation. Schema-bearing engines piggyback the
vocabulary union inside the packed diff (local_put_diff syncs schema
before applying).
"""

from __future__ import annotations

import functools
import logging
import random
import time
from typing import Any, Dict, List, Optional, Sequence

from jubatus_tpu.coord.base import NodeInfo
from jubatus_tpu.framework.linear_mixer import (
    PROTOCOL_VERSION,
    pack_mix,
    unpack_mix,
    LinearCommunication,
    RpcLinearCommunication,
    RpcLinearMixer,
)
from jubatus_tpu.parallel.mix import tree_sum
from jubatus_tpu.rpc.client import RpcClient

log = logging.getLogger(__name__)


# -- candidate strategies -----------------------------------------------------


def broadcast_candidates(members: Sequence[NodeInfo],
                         self_node: Optional[NodeInfo]) -> List[NodeInfo]:
    return [m for m in members
            if self_node is None or m.name != self_node.name]


def random_candidates(members: Sequence[NodeInfo],
                      self_node: Optional[NodeInfo]) -> List[NodeInfo]:
    others = broadcast_candidates(members, self_node)
    return [random.choice(others)] if others else []


def skip_candidates(members: Sequence[NodeInfo],
                    self_node: Optional[NodeInfo]) -> List[NodeInfo]:
    """Finger peers on the name-sorted ring: offsets 1, 2, 4, ... from my
    position (skip_mixer.hpp:46-57)."""
    ring = sorted(members, key=lambda m: m.name)
    if self_node is None:
        return list(ring)
    try:
        me = next(i for i, m in enumerate(ring) if m.name == self_node.name)
    except StopIteration:
        return broadcast_candidates(members, self_node)
    n = len(ring)
    out, offset = [], 1
    while offset < n:
        peer = ring[(me + offset) % n]
        if peer.name != self_node.name and peer.name not in {p.name for p in out}:
            out.append(peer)
        offset <<= 1
    return out


STRATEGIES = {
    "broadcast_mixer": broadcast_candidates,
    "random_mixer": random_candidates,
    "skip_mixer": skip_candidates,
}


# -- per-peer communication ---------------------------------------------------


class PeerSession:
    """One connection for a whole pairwise exchange (up to 4 calls), instead
    of a TCP setup per call."""

    def __init__(self, client: RpcClient, name: str) -> None:
        self._c = client
        self._name = name

    def get_schema(self) -> List[str]:
        return self._c.call("mix_get_schema", self._name)

    def sync_schema(self, union: List[str]) -> bool:
        return bool(self._c.call("mix_sync_schema", self._name, union))

    def get_diff(self) -> bytes:
        return self._c.call("mix_get_diff", self._name)

    def put_diff(self, packed: bytes) -> bool:
        return bool(self._c.call("mix_put_diff", self._name, packed))

    def close(self) -> None:
        self._c.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class PushCommunication(RpcLinearCommunication):
    """Adds the single-peer exchange session to the membership/session
    plumbing (≙ push_communication, push_mixer.hpp)."""

    def peer_session(self, member: NodeInfo) -> PeerSession:
        return PeerSession(
            RpcClient(member.host, member.port, self.timeout), self.name)


class RpcPushMixer(RpcLinearMixer):
    """Leaderless gossip rounds; serves the same mix_* RPC surface as the
    linear mixer (register_api inherited)."""

    def __init__(self, driver: Any, comm: LinearCommunication, *,
                 strategy: str = "random_mixer", **kwargs) -> None:
        super().__init__(driver, comm, **kwargs)
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown push strategy {strategy!r}")
        self.strategy = strategy
        self._select = STRATEGIES[strategy]

    # -- the round (≙ push_mixer::mix, push_mixer.cpp:342-429) ---------------
    def _mix_round(self) -> Optional[Dict[str, Any]]:
        if self._obsolete:
            self.maybe_recover()
        members = self.comm.update_members()
        candidates = self._select(members, self.self_node)
        if not candidates:
            return None
        t0 = time.monotonic()
        exchanged = 0
        total_bytes = 0
        skipped_open = 0
        failures: List[str] = []
        breakers = getattr(self.comm, "breakers", None)
        for peer in candidates:
            key = (peer.host, peer.port)
            if breakers is not None and not breakers.allow(key):
                # open circuit: don't burn a timeout gossiping at a peer
                # that has been failing for a while — half-open probes
                # re-admit it once its cooldown passes
                skipped_open += 1
                continue
            try:
                total_bytes += self._exchange(peer)
                exchanged += 1
                if breakers is not None:
                    breakers.record(key, True)
            except Exception as e:  # broad-ok — gossip shrugs off a peer
                log.warning("push exchange with %s failed: %s", peer.name, e)
                failures.append(f"{peer.name}: {type(e).__name__}")
                if breakers is not None:
                    from jubatus_tpu.rpc.errors import is_retryable

                    breakers.record(key, not is_retryable(e))
        if not exchanged:
            # candidates existed but every exchange failed (or every
            # circuit is open): that's a failed round, not an idle tick
            self.flight.record(self.strategy, ok=False,
                               reason="; ".join(failures) or (
                                   "all_breakers_open" if skipped_open
                                   else "no_exchange"),
                               candidates=len(candidates))
            return None
        self.mix_count += 1
        self.bytes_sent += total_bytes
        log.info("push mix round %d (%s): %d/%d peers, %d bytes, %.3fs",
                 self.mix_count, self.strategy, exchanged, len(candidates),
                 total_bytes, time.monotonic() - t0)
        return {"members": exchanged, "bytes": total_bytes,
                "mode": self.strategy, "candidates": len(candidates),
                "skipped_open": skipped_open or None,
                "failed_peers": failures or None}

    def _exchange(self, peer: NodeInfo) -> int:
        """One pairwise linear mix over a single peer connection: align
        schemas, fold my diff with the peer's, apply the fold on both
        sides."""
        with self.comm.peer_session(peer) as sess:
            return self._exchange_on(sess, peer.name, peer=peer)

    def _exchange_on(self, sess, peer_name: str = "?",
                     peer: Optional[NodeInfo] = None) -> int:
        # phase 1: schema alignment — row-keyed diffs (classifier labels,
        # stat keys) must agree on the row vocabulary BEFORE diffing, same
        # as the linear round's phase 1
        schema: List[str] = []
        if self._has_schema():
            mine_schema = self.local_get_schema()
            hers_schema = sess.get_schema()
            schema = sorted(
                {s.decode() if isinstance(s, bytes) else s
                 for s in list(mine_schema) + list(hers_schema)}
            )
            if schema:
                self.local_sync_schema(schema)
                sess.sync_schema(schema)
        # phase 2: row-aligned diffs (mine stays in-process — no wire codec)
        mine = self.local_diff_obj()
        hers = unpack_mix(sess.get_diff())
        if hers.get("protocol") != PROTOCOL_VERSION:
            raise RuntimeError(f"protocol mismatch from {peer_name}")
        # phase 2.5: version asymmetry. A node behind the pair's base has
        # history its peer absorbed into MASTER arrays — deltas can't carry
        # it. If I'M behind, adopt her full model now (my diff snapshot
        # `mine` is folded back in below, so nothing local is lost). If
        # SHE'S behind, apply the fold only on MY side: she catches up when
        # her own round initiates — never demoted, no recovery storm.
        mv = int(mine.get("version", 0))
        hv = int(hers.get("version", 0))
        if mv < hv and peer is not None:
            model = unpack_mix(self.comm.get_model(peer))
            if model.get("protocol") != PROTOCOL_VERSION:
                raise RuntimeError(f"protocol mismatch from {peer_name}")
            with self.driver.lock:
                self.driver.unpack(model["model"])
            self.model_version = mv = int(model.get("version", hv))
            log.info("adopted full model v%d from %s before exchange",
                     mv, peer_name)
        mixables = self.driver.get_mixables()
        # model-integrity admission screen (ISSUE 15): a 2-party
        # exchange has no peer distribution for the norm screen, but
        # the finite screen + quarantine breaker still gate the fold —
        # a poisoned peer fails the exchange instead of poisoning us
        # (warn mode flags and folds; the apply-side total screen in
        # local_put_obj is the backstop either way)
        if self.guard.enabled:
            from jubatus_tpu.framework.linear_mixer import _sum_names

            reason = self.guard.screen_payload(
                peer_name, hers.get("diffs") or {}, _sum_names(mixables))
            if reason is not None:
                if reason == "nonfinite":
                    self._count("mix.guard.nonfinite")
                if self.guard.mode == "quarantine":
                    self._count("mix.quarantined")
                    raise RuntimeError(
                        f"peer diff rejected by mix guard: {reason}")
        totals: Dict[str, Any] = {}
        for name, mixable in mixables.items():
            diffs = [p["diffs"][name] for p in (mine, hers)
                     if name in p["diffs"]]
            if not diffs:
                continue
            custom_mix = getattr(mixable, "mix", None)
            totals[name] = (functools.reduce(custom_mix, diffs)
                            if custom_mix is not None else tree_sum(diffs))
        base_version = max(mv, hv)
        packed = pack_mix({"protocol": PROTOCOL_VERSION, "schema": schema,
                           "base_version": base_version, "diffs": totals})
        self.local_put_diff(packed)  # mv == base here (adopted above if not)
        if hv == base_version:
            sess.put_diff(packed)
        # else: she's behind — skipping her keeps the version gate from
        # demoting a merely gossip-lagged member; her next initiated round
        # adopts a full model (phase 2.5 on her side)
        return len(packed)


class DummyMixer:
    """Standalone no-op (≙ dummy_mixer when built without ZK,
    mixer_factory.cpp:24-31)."""

    def __init__(self, *_a, **_k) -> None:
        from jubatus_tpu.framework.mixer import MixFlightRecorder

        self.mix_count = 0
        self.flight = MixFlightRecorder()

    def register_api(self, rpc_server, name_check: str = "") -> None:
        # history stays queryable (empty) so tooling needn't special-case
        rpc_server.register(
            "get_mix_history", lambda _name: self.flight.snapshot())

    def set_trace_registry(self, registry) -> None:
        pass

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def updated(self, n: int = 1) -> None:
        pass

    def mix_now(self) -> None:
        return None

    def get_status(self) -> Dict[str, Any]:
        return {"mix_count": 0, "counter": 0, "mixer": "dummy"}


def create_mixer(name: str, driver: Any, comm: LinearCommunication, *,
                 self_node: Optional[NodeInfo] = None,
                 interval_sec: float = 16.0, interval_count: int = 512,
                 mix_bf16: bool = False, quorum_fraction: float = 0.5,
                 mix_compress: str = "off", mix_topology: str = "",
                 mix_async: bool = False, mix_staleness_bound: int = 8,
                 mix_guard: str = "warn", mix_norm_bound: float = 10.0):
    """Mixer factory (≙ create_mixer, mixer_factory.cpp:41-97): selects by
    the --mixer flag. ``mix_compress`` is the collective wire mode
    (off|bf16|int8); the deprecated ``mix_bf16`` bool still resolves to
    bf16 when no explicit mode is given. ``mix_topology`` is the
    collective mixer's hierarchical tier shape (``""``/``auto``/``HxM``,
    see --mix-topology). ``mix_async`` swaps the linear mixer for the
    asynchronous staleness-bounded plane (framework/async_mixer.py):
    members push diffs in the background and the master folds them with
    per-member weights decayed by ``mix_staleness_bound`` instead of
    gathering behind a round barrier. ``mix_guard``/``mix_norm_bound``
    configure the model-integrity admission guard
    (framework/model_guard.py, ISSUE 15) every strategy carries."""
    from jubatus_tpu.framework.model_guard import MixGuard

    kwargs = dict(self_node=self_node, interval_sec=interval_sec,
                  interval_count=interval_count,
                  quorum_fraction=quorum_fraction,
                  guard=MixGuard(mode=mix_guard,
                                 norm_bound=mix_norm_bound))
    if mix_async and name != "linear_mixer":
        raise ValueError(
            f"--mix-async rides the linear mix plane; --mixer {name} "
            "cannot stream rounds asynchronously (push mixers are "
            "already leaderless, the collective is a barrier by "
            "construction)")
    if name == "linear_mixer":
        if mix_async:
            from jubatus_tpu.framework.async_mixer import AsyncLinearMixer

            return AsyncLinearMixer(
                driver, comm, staleness_bound=mix_staleness_bound,
                **kwargs)
        return RpcLinearMixer(driver, comm, **kwargs)
    if name == "collective_mixer":
        from jubatus_tpu.framework.collective_mixer import CollectiveMixer

        mode = mix_compress if mix_compress != "off" else \
            ("bf16" if mix_bf16 else "off")
        return CollectiveMixer(driver, comm, compress=mode,
                               topology=mix_topology, **kwargs)
    if name in STRATEGIES:
        return RpcPushMixer(driver, comm, strategy=name, **kwargs)
    if name == "dummy_mixer":
        return DummyMixer()
    raise ValueError(f"unknown mixer {name!r}; known: linear_mixer, "
                     f"collective_mixer, {', '.join(sorted(STRATEGIES))}, "
                     "dummy_mixer")
