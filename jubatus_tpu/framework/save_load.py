"""Model checkpoint envelope — format parity with the reference.

Reference format (framework/save_load.cpp:45-158, SURVEY.md §5
checkpoint/resume): a fixed 48-byte header — magic "jubatus", format version,
framework version, CRC32, section sizes — followed by a msgpack'd system data
container {version, timestamp, type, id, config} and the versioned user data
[user_data_version, driver.pack()]. Load validates magic, format version,
CRC32, engine type, and semantic config equality (save_load.cpp:160-286,
compare_config at 104-109).

Header layout (big-endian, 48 bytes):
  0  : 8  magic "jubatus\\0"
  8  : 4  format_version (u32) = 1
  12 : 4x3 version major/minor/maintenance (u32 each)
  24 : 4  crc32 of (system_data + user_data)
  28 : 8  system_data_size (u64)
  36 : 8  user_data_size (u64)
  44 : 4  reserved (zeros)
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Any, Optional, Tuple

from jubatus_tpu.utils.serialization import pack_obj, unpack_obj
from jubatus_tpu.version import COMPAT_JUBATUS_VERSION

MAGIC = b"jubatus\x00"
FORMAT_VERSION = 1
_HEADER = struct.Struct(">8sI3IIQQ4x")
assert _HEADER.size == 48


class SaveLoadError(RuntimeError):
    pass


def _semantic_config_equal(a: str, b: str) -> bool:
    """Reference compare_config: configs match if their parsed JSON is equal,
    not their raw text (save_load.cpp:104-109)."""
    try:
        return json.loads(a) == json.loads(b)
    except Exception:  # broad-ok — unparseable json: compare raw
        return a == b


def read_envelope(raw: bytes, where: str):
    """Validate the 48-byte header + CRC and split the body. Returns
    (system_bytes, user_bytes). One implementation for every strict
    consumer (load_model, sharded_checkpoint); jubadump keeps its own
    non-throwing walk because it reports damage instead of refusing."""
    if len(raw) < _HEADER.size:
        raise SaveLoadError(f"{where}: truncated header")
    magic, fmt, _, _, _, crc, ssize, usize = _HEADER.unpack_from(raw)
    if magic != MAGIC:
        raise SaveLoadError(f"{where}: bad magic {magic!r}")
    if fmt != FORMAT_VERSION:
        raise SaveLoadError(f"{where}: unsupported format version {fmt}")
    body = raw[_HEADER.size:]
    if len(body) != ssize + usize:
        raise SaveLoadError(
            f"{where}: size mismatch (header says {ssize}+{usize}, "
            f"got {len(body)})")
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
        raise SaveLoadError(f"{where}: CRC32 mismatch")
    return body[:ssize], body[ssize:ssize + usize]


def pack_envelope(system_data: bytes, user_data: bytes = b"") -> bytes:
    """Header + CRC + body as one in-memory blob — the same bytes
    write_envelope persists. The model-integrity plane's in-process
    snapshot ring (framework/model_guard.ModelSnapshotRing) stores
    these so every rollback restore revalidates the CRC exactly like a
    checkpoint load would."""
    crc = zlib.crc32(system_data + user_data) & 0xFFFFFFFF
    header = _HEADER.pack(
        MAGIC,
        FORMAT_VERSION,
        *COMPAT_JUBATUS_VERSION,
        crc,
        len(system_data),
        len(user_data),
    )
    return header + system_data + user_data


def write_envelope(path: str, system_data: bytes,
                   user_data: bytes = b"") -> None:
    """Atomic envelope write: header + CRC, tmp + fsync + rename. Shared
    by save_model and the sharded-checkpoint sidecar (the reference
    additionally flocks against concurrent saves, server_base.cpp:152-159)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(pack_envelope(system_data, user_data))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def save_model(
    path: str,
    driver,
    *,
    model_id: str = "",
    config: str = "",
) -> None:
    system = {
        "version": FORMAT_VERSION,
        "timestamp": int(time.time()),  # wall-clock
        "type": driver.TYPE,
        "id": model_id,
        "config": config,
    }
    write_envelope(
        path,
        pack_obj(system),
        pack_obj([driver.USER_DATA_VERSION, driver.pack()]),
    )


def load_model(
    path: str,
    driver,
    *,
    expected_config: Optional[str] = None,
) -> Tuple[dict, Any]:
    """Validate + load a checkpoint file into the driver.

    Returns (system_data, user_data_version). Raises SaveLoadError on any
    validation failure, mirroring the reference's checks."""
    with open(path, "rb") as f:
        raw = f.read()
    return load_model_bytes(raw, driver, where=path,
                            expected_config=expected_config)


def load_model_bytes(
    raw: bytes,
    driver,
    *,
    where: str = "<bytes>",
    expected_config: Optional[str] = None,
) -> Tuple[dict, Any]:
    """The byte-level half of load_model: same validation ladder (magic,
    CRC, type, semantic config, user-data version) over an in-memory
    envelope — what the durable model plane (framework/model_store.py)
    feeds from store records during warm-boot and fleet restore."""
    system_bytes, user_bytes = read_envelope(raw, where)
    path = where
    system = unpack_obj(system_bytes)
    if system["type"] != driver.TYPE:
        raise SaveLoadError(
            f"{path}: model type {system['type']!r} != server type {driver.TYPE!r}"
        )
    if expected_config is not None and not _semantic_config_equal(
        system.get("config", ""), expected_config
    ):
        raise SaveLoadError(f"{path}: saved config does not match server config")
    user_version, user_data = unpack_obj(user_bytes)
    if user_version != driver.USER_DATA_VERSION:
        raise SaveLoadError(
            f"{path}: user data version {user_version} != {driver.USER_DATA_VERSION}"
        )
    driver.unpack(user_data)
    return system, user_version
