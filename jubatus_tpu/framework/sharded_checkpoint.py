"""Sharded checkpoint/resume for pod-scale state (orbax/tensorstore-backed).

The envelope path (framework/save_load.py, ≙ reference save_load.cpp)
serializes through a host gather — right for single-chip models and
byte-format parity, impossible for a sharded Criteo-scale table that
exceeds any single host. Here every process writes only its addressable
shards through orbax, and restore re-places arrays according to the
template's NamedShardings — on a multi-host pod each host touches only
its slice (jax.distributed must be initialized first;
parallel/multihost.py does that).

The reference envelope's system container (type, id, config, versions,
CRC) is preserved as a ``system.jubatus`` sidecar written in the SAME
48-byte-header format with an empty user-data section, so ``jubadump``
and the semantic-config-match validation (save_load.cpp:104-109) work
unchanged on checkpoint directories.

Layout:

    <dir>/system.jubatus   envelope header + system container, 0 user bytes
    <dir>/state/           orbax checkpoint of the state pytree
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax

from jubatus_tpu.framework.save_load import (
    FORMAT_VERSION,
    SaveLoadError,
    _semantic_config_equal,
    read_envelope,
    write_envelope,
)
from jubatus_tpu.utils.serialization import pack_obj, unpack_obj

SYSTEM_FILE = "system.jubatus"
STATE_DIR = "state"
#: pairing-token sidecar next to the state dir, used when the installed
#: orbax cannot carry custom_metadata in the checkpoint itself (the
#: kwarg appeared after 0.7; see _save_state/_state_token)
TOKEN_FILE = "state.token"


def _write_system(path: str, system: dict) -> None:
    write_envelope(path, pack_obj(system))


def _read_system(path: str) -> dict:
    with open(path, "rb") as f:
        raw = f.read()
    system_bytes, _ = read_envelope(raw, path)
    return unpack_obj(system_bytes)


def _save_state(ckptr, dir_path: str, state: Any, token: str) -> None:
    """Commit the state checkpoint with its pairing token. Newer orbax
    carries the token in the checkpoint's own custom_metadata; on
    releases whose ``StandardCheckpointer.save`` lacks the kwarg (0.7.x,
    the installed toolchain) the token commits to a ``state.token``
    sidecar AFTER the state and BEFORE ``system.jubatus`` — a crash
    between any two commits still leaves a detectable mismatch, never a
    silent mispairing."""
    state_path = os.path.join(dir_path, STATE_DIR)
    try:
        ckptr.save(state_path, state, force=True,
                   custom_metadata={"pairing_token": token})
        ckptr.wait_until_finished()
        return
    except TypeError:
        pass  # pre-custom_metadata orbax: token sidecar below
    ckptr.save(state_path, state, force=True)
    ckptr.wait_until_finished()
    if jax.process_index() == 0:
        tmp = os.path.join(dir_path, TOKEN_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write(token)
        os.replace(tmp, os.path.join(dir_path, TOKEN_FILE))


def _state_token(ckptr, dir_path: str) -> Optional[str]:
    """The pairing token committed WITH the state: orbax custom_metadata
    when the installed release returns it, else the state.token sidecar;
    None when neither exists (a checkpoint from before pairing)."""
    meta = ckptr.metadata(os.path.join(dir_path, STATE_DIR))
    custom = getattr(meta, "custom_metadata", None)
    if isinstance(custom, dict) and custom.get("pairing_token"):
        return str(custom["pairing_token"])
    try:
        with open(os.path.join(dir_path, TOKEN_FILE)) as f:
            return f.read().strip() or None
    except OSError:
        return None


def _metadata_tree(meta: Any):
    """Per-leaf ArrayMetadata pytree across orbax metadata shapes: newer
    releases wrap it (``meta.item_metadata.tree``), 0.7.x returns the
    tree directly."""
    item = getattr(meta, "item_metadata", None)
    if item is not None and hasattr(item, "tree"):
        return item.tree
    return meta


def abstract_like(state: Any):
    """Pytree of ShapeDtypeStructs carrying the template's shardings —
    what restore needs to re-place arrays on the mesh."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state,
    )


def shard_layout(state: Any) -> dict:
    """{axis: size} of the first sharded leaf's mesh — recorded in the
    system container so operators (jubadump, jubactl) can read what
    layout wrote a checkpoint without opening the orbax metadata.
    Informational only: restore re-places by the TEMPLATE's shardings,
    so a checkpoint written at N shards restores bit-exact at M
    (reshard-on-restore — orbax reads each host's needed byte ranges)."""
    for leaf in jax.tree_util.tree_leaves(state):
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        shape = getattr(mesh, "shape", None)
        if shape:
            return {str(k): int(v) for k, v in dict(shape).items()}
    return {}


def save_sharded(
    dir_path: str,
    state: Any,
    *,
    engine_type: str,
    model_id: str = "",
    config: str = "",
    user_data_version: int = 0,
) -> None:
    """Checkpoint a (possibly sharded) state pytree into ``dir_path``.

    Each file commits atomically (orbax finalization / tmp+rename), and a
    pairing token written into BOTH the orbax metadata and the sidecar
    makes a torn overwrite (crash between the two commits) detectable at
    load instead of silently pairing new state with stale metadata. On a
    multi-host pod the orbax save is collectively coordinated; the
    sidecar is written by process 0 only."""
    import binascii
    import time

    import orbax.checkpoint as ocp

    dir_path = os.path.abspath(dir_path)
    token = binascii.hexlify(os.urandom(8)).decode()
    if jax.process_count() > 1:
        # all hosts must agree on the token process 0 writes
        from jax.experimental import multihost_utils

        token = multihost_utils.broadcast_one_to_all(
            jax.numpy.frombuffer(bytes.fromhex(token), dtype=jax.numpy.uint8)
        ).tobytes().hex()
    os.makedirs(dir_path, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    _save_state(ckptr, dir_path, state, token)
    if jax.process_index() == 0:
        _write_system(os.path.join(dir_path, SYSTEM_FILE), {
            "version": FORMAT_VERSION,
            "timestamp": int(time.time()),  # wall-clock
            "type": engine_type,
            "id": model_id,
            "config": config,
            "user_data_version": user_data_version,
            "sharded": True,
            "shard_layout": shard_layout(state),
            "pairing_token": token,
        })
    if jax.process_count() > 1:
        # barrier: no host may report the save complete (and let a reader
        # observe state/ without its sidecar) before process 0 has written
        # system.jubatus
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("jubatus_tpu:sharded_save")
    # event plane (ISSUE 14): checkpoint saves land on the timeline
    # (default journal — this plane has no registry in reach)
    from jubatus_tpu.utils import events

    events.emit("checkpoint", "save", dir=dir_path, model_id=model_id,
                shard_layout=shard_layout(state) or None)


def load_sharded(
    dir_path: str,
    template: Any,
    *,
    expected_type: Optional[str] = None,
    expected_config: Optional[str] = None,
) -> Tuple[dict, Any]:
    """Restore a checkpoint into the template's shapes/dtypes/shardings.

    ``template`` is a live state pytree or the result of
    ``abstract_like``. Returns (system container, restored state); raises
    SaveLoadError on metadata mismatch (same checks as the envelope
    loader: engine type and semantic config equality).

    Reshard-on-restore (ISSUE 13): the template's shardings govern the
    restored placement, independent of the layout that WROTE the
    checkpoint — a save at N shards restores bit-exact onto an M-shard
    template (N→1, 1→M, N→M; tests/test_sharded_checkpoint.py), which
    is how a fleet reshape or a single-device debug session opens a
    pod-scale checkpoint."""
    import orbax.checkpoint as ocp

    dir_path = os.path.abspath(dir_path)
    system = _read_system(os.path.join(dir_path, SYSTEM_FILE))
    if expected_type is not None and system.get("type") != expected_type:
        raise SaveLoadError(
            f"{dir_path}: model type {system.get('type')!r} != "
            f"{expected_type!r}")
    if expected_config is not None and not _semantic_config_equal(
            system.get("config", ""), expected_config):
        raise SaveLoadError(
            f"{dir_path}: saved config does not match server config")
    abstract = jax.tree_util.tree_map(
        lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        template,
    )
    ckptr = ocp.StandardCheckpointer()
    state_path = os.path.join(dir_path, STATE_DIR)
    want_token = system.get("pairing_token")
    if want_token is not None:
        have_token = _state_token(ckptr, dir_path)
        if have_token != want_token:
            raise SaveLoadError(
                f"{dir_path}: state/metadata pairing mismatch "
                "(interrupted overwrite?) — the sidecar describes a "
                "different checkpoint than the state directory holds")
    state = ckptr.restore(state_path, abstract)
    # event plane (ISSUE 14): restores — and RESHAPES (the template's
    # layout differing from the one that wrote the checkpoint, i.e.
    # reshard-on-restore actually engaging) — land on the timeline
    from jubatus_tpu.utils import events

    saved_layout = system.get("shard_layout") or {}
    restored_layout = shard_layout(state) or {}
    resharded = bool(saved_layout) != bool(restored_layout) or \
        saved_layout != restored_layout
    events.emit("checkpoint", "reshard" if resharded else "restore",
                dir=dir_path,
                saved_layout=saved_layout or None,
                restored_layout=restored_layout or None)
    return system, state


def checkpoint_metadata(dir_path: str) -> dict:
    """System container + per-array shape/dtype metadata without reading
    array bytes (jubadump uses this for directory inputs)."""
    import orbax.checkpoint as ocp

    dir_path = os.path.abspath(dir_path)
    out = {"system": _read_system(os.path.join(dir_path, SYSTEM_FILE))}
    ckptr = ocp.StandardCheckpointer()
    meta = ckptr.metadata(os.path.join(dir_path, STATE_DIR))
    tree = _metadata_tree(meta)  # {leaf name: ArrayMetadata}
    arrays = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        entry = {
            "shape": list(getattr(leaf, "shape", ()) or ()),
            "dtype": str(getattr(leaf, "dtype", "")),
        }
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "partition_spec", None)
        if spec is not None:
            entry["partition_spec"] = [str(s) for s in spec]
        arrays[key] = entry
    out["arrays"] = arrays
    return out
