"""Per-engine console entry points (≙ the reference's juba* binaries).

The reference installs one binary per engine (``jubaclassifier``,
``jubarecommender_proxy``, ... — jubatus/server/server/wscript:13-34);
pip-installing this package provides the same command names via the
entry points declared in pyproject.toml, all thin wrappers over the
generic server/proxy mains with the engine pre-bound.
"""

from __future__ import annotations

import sys
from typing import List, Optional


def _server(engine: str, argv: Optional[List[str]]) -> int:
    from jubatus_tpu.server.__main__ import main

    return main([engine] + list(sys.argv[1:] if argv is None else argv)) or 0


def _proxy(engine: str, argv: Optional[List[str]]) -> int:
    from jubatus_tpu.server.proxy import main

    return main([engine] + list(sys.argv[1:] if argv is None else argv)) or 0


def jubaanomaly(argv=None): return _server("anomaly", argv)
def jubabandit(argv=None): return _server("bandit", argv)
def jubaburst(argv=None): return _server("burst", argv)
def jubaclassifier(argv=None): return _server("classifier", argv)
def jubaclustering(argv=None): return _server("clustering", argv)
def jubagraph(argv=None): return _server("graph", argv)
def jubanearest_neighbor(argv=None): return _server("nearest_neighbor", argv)
def jubarecommender(argv=None): return _server("recommender", argv)
def jubaregression(argv=None): return _server("regression", argv)
def jubastat(argv=None): return _server("stat", argv)
def jubaweight(argv=None): return _server("weight", argv)


def jubaanomaly_proxy(argv=None): return _proxy("anomaly", argv)
def jubabandit_proxy(argv=None): return _proxy("bandit", argv)
def jubaburst_proxy(argv=None): return _proxy("burst", argv)
def jubaclassifier_proxy(argv=None): return _proxy("classifier", argv)
def jubaclustering_proxy(argv=None): return _proxy("clustering", argv)
def jubagraph_proxy(argv=None): return _proxy("graph", argv)
def jubanearest_neighbor_proxy(argv=None): return _proxy("nearest_neighbor", argv)
def jubarecommender_proxy(argv=None): return _proxy("recommender", argv)
def jubaregression_proxy(argv=None): return _proxy("regression", argv)
def jubastat_proxy(argv=None): return _proxy("stat", argv)
def jubaweight_proxy(argv=None): return _proxy("weight", argv)
