"""Engine drivers — one per reference engine (SURVEY.md §2.4).

Each driver binds an fv_converter + XLA kernels (ops/) into the engine's
business API, implements the mixable protocol for the mix plane, and
pack/unpack for checkpoints. The RPC layer (rpc/) exposes them over the
reference's wire protocol.
"""

from jubatus_tpu.models.anomaly import AnomalyDriver  # noqa: F401
from jubatus_tpu.models.bandit import BanditDriver  # noqa: F401
from jubatus_tpu.models.burst import BurstDriver  # noqa: F401
from jubatus_tpu.models.classifier import ClassifierDriver  # noqa: F401
from jubatus_tpu.models.classifier_nn import ClassifierNNDriver  # noqa: F401
from jubatus_tpu.models.clustering import ClusteringDriver  # noqa: F401
from jubatus_tpu.models.graph import GraphDriver  # noqa: F401
from jubatus_tpu.models.nearest_neighbor import NearestNeighborDriver  # noqa: F401
from jubatus_tpu.models.recommender import RecommenderDriver  # noqa: F401
from jubatus_tpu.models.regression import RegressionDriver  # noqa: F401
from jubatus_tpu.models.stat import StatDriver  # noqa: F401
from jubatus_tpu.models.weight import WeightDriver  # noqa: F401
