"""Shared similarity-search backend for the instance-based engines.

Composes a RowStore (core/row_store.py) with one of the ops/knn.py methods
and keeps the per-row signature/projection tables aligned with the store.
Used by nearest_neighbor, recommender, and anomaly (the reference layers the
same way: recommender/anomaly sit on core nearest-neighbor backends,
/root/reference/config/anomaly/lof.json nests a NN method config).

Methods and their distance/similarity conventions:

  lsh          Hamming distance in [0,1] over sign-random-projection bits;
               similarity = 1 - distance.
  minhash      1 - (weighted-Jaccard estimate); similarity = 1 - distance.
  euclid_lsh   JL-estimated euclidean distance; similarity = -distance
               (the reference scores euclidean similarity as the negated
               distance, so "bigger is more similar" holds).
  inverted_index  exact cosine similarity; distance = 1 - similarity.
  euclid          exact euclidean distance; similarity = -distance.

Write path is buffered: set_row queues the vector and signatures are
computed for ALL pending rows in one batched kernel call at the next query
(amortizes jit dispatch; the reference instead pays a per-update index
write). Everything device-side is cached per store version.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from jubatus_tpu.core.row_store import RowStore
from jubatus_tpu.core.sparse import SparseBatch, SparseVector
from jubatus_tpu.ops import knn
from jubatus_tpu.parallel.row_store import ShardedRowStore

HASH_METHODS = ("lsh", "minhash", "euclid_lsh")
EXACT_METHODS = ("inverted_index", "euclid")
METHODS = HASH_METHODS + EXACT_METHODS


class NNBackend:
    def __init__(self, method: str, *, dim: int, hash_num: int = 64,
                 seed: int = 0, max_size: Optional[int] = None,
                 keep_datum: bool = False):
        if method not in METHODS:
            raise ValueError(f"unknown nearest-neighbor method {method!r}")
        self.method = method
        self.dim = dim
        self.hash_num = int(hash_num)
        self.seed = int(seed)
        self.store = RowStore(max_size=max_size, keep_datum=keep_datum)
        self._pending: Dict[str, SparseVector] = {}
        self._mesh = None
        self._mesh_axis = "shard"
        self._mesh_dev = None
        #: wall ms of the last sharded top-k (device scan + log-depth
        #: merge + readback) — the shard.topk_merge_ms gauge
        self.last_topk_ms: Optional[float] = None
        # -- ANN (IVF) tier, ISSUE 16: OFF by default — exact scans stay
        # the baseline until configure_ann("ivf") arms the lazy build
        self.ann_mode = "off"
        self.ann_cells = 0        # 0 = auto (pow2 ≈ √rows)
        self.ann_nprobe = 8
        self.ann_min_rows = 128   # lazy build once this many rows live
        self.ann_split_width = 0  # 0 = auto; cells past it re-split
        self._ann_reset()
        self._init_sigs()

    def _ann_reset(self) -> None:
        self._ann_centroids: Optional[np.ndarray] = None
        self._ann_arenas: Optional[Any] = None
        self._ann_degraded = False
        self._ann_counters = {"builds": 0, "resplits": 0,
                              "rebuild_failed": 0}
        self._ann_last = {"probed_cells": 0, "rescore_candidates": 0}
        self._ann_recall_probe: Optional[float] = None
        self._ann_queries = 0
        self._ann_dev: Optional[Tuple[Any, ...]] = None

    def _init_sigs(self) -> None:
        c = self.store.capacity
        if self.method == "lsh":
            self._sigs = np.zeros((c, knn.packed_words(self.hash_num)), np.uint32)
        elif self.method == "minhash":
            self._sigs = np.zeros((c, self.hash_num), np.uint32)
        elif self.method == "euclid_lsh":
            self._sigs = np.zeros((c, self.hash_num), np.float32)
        elif getattr(self, "ann_mode", "off") == "ivf":
            # exact methods hold no signatures — unless the IVF tier is
            # on, which PROBES by the same JL projection euclid_lsh
            # stores (the rescore stays the exact cosine/euclid math)
            self._sigs = np.zeros((c, self.hash_num), np.float32)
        else:
            self._sigs = None
        self._sig_dev: Optional[Tuple[int, Any]] = None
        self._mesh_dev = None

    # -- writes ---------------------------------------------------------------
    def set_row(self, row_id: str, vec: SparseVector, datum: Any = None) -> None:
        self.store.set_row(row_id, vec, datum=datum)
        if self._sigs is not None:
            self._pending[row_id] = vec

    def remove_row(self, row_id: str) -> bool:
        self._pending.pop(row_id, None)
        if self._ann_arenas is not None:
            self._ann_arenas.remove(row_id)
        return self.store.remove_row(row_id)

    def clear(self) -> None:
        self.store.clear()
        self._pending.clear()
        self._ann_reset()
        self._init_sigs()

    # -- signature maintenance -----------------------------------------------
    def _flush(self) -> None:
        if self._sigs is None:
            return
        # keep the signature table sized to the store even when nothing is
        # pending — capacity can grow via set_row and then drain via removes
        if self._sigs.shape[0] != self.store.capacity:
            pad = self.store.capacity - self._sigs.shape[0]
            self._sigs = np.pad(self._sigs, ((0, pad), (0, 0)))
            self._sig_dev = None
        if not self._pending:
            return
        items = [(rid, vec) for rid, vec in self._pending.items()
                 if rid in self.store.slots]
        self._pending.clear()
        if not items:
            return
        # bucketed rows: pending-set sizes vary per flush; extra signature
        # rows beyond len(items) are simply not written back
        sb = SparseBatch.from_vectors([vec for _, vec in items],
                                      batch_bucket=16)
        idx, val = jnp.asarray(sb.idx), jnp.asarray(sb.val)
        if self.method == "lsh":
            sigs = knn.lsh_signature(idx, val, hash_num=self.hash_num,
                                     seed=self.seed)
        elif self.method == "minhash":
            sigs = knn.minhash_signature(idx, val, hash_num=self.hash_num,
                                         seed=self.seed)
        else:
            sigs = knn.euclid_projection(idx, val, hash_num=self.hash_num,
                                         seed=self.seed)
        sigs = np.asarray(sigs)
        for row, (rid, _) in enumerate(items):
            self._sigs[self.store.slots[rid]] = sigs[row]
        self._sig_dev = None
        self._mesh_dev = None
        if self._ann_arenas is not None:
            # online insertion: append each new row to its owning cell,
            # then re-split any cell that overflowed its width
            self._ann_assign(sigs[: len(items)], [rid for rid, _ in items])
            self._ann_maintain()

    def _sig_view(self):
        if self._sig_dev is None or self._sig_dev[0] != self.store.version:
            self._sig_dev = (self.store.version, jnp.asarray(self._sigs))
        return self._sig_dev[1]

    # -- ANN (IVF) tier (ISSUE 16) ---------------------------------------------
    def configure_ann(self, mode: str, *, cells: int = 0, nprobe: int = 8,
                      min_rows: int = 128) -> None:
        """Arm or disarm the IVF tier. ``mode="ivf"`` schedules a lazy
        index build (first query past ``min_rows`` live rows trains the
        coarse partitioner); ``"off"`` restores pure exact scans —
        bit-identical to a backend that never had ANN. Reconfiguring
        drops any existing index (and clears a degraded latch); exact
        methods additionally allocate the JL probe-projection table and
        re-pend every row to fill it."""
        if mode not in ("off", "ivf"):
            raise ValueError(f"unknown ann mode {mode!r} "
                             "(expected 'off' or 'ivf')")
        self.ann_mode = mode
        self.ann_cells = max(0, int(cells))
        self.ann_nprobe = max(1, int(nprobe))
        self.ann_min_rows = max(1, int(min_rows))
        self._ann_reset()
        had_sigs = self._sigs is not None
        if self.method in EXACT_METHODS and (mode == "ivf") != had_sigs:
            self._init_sigs()
            self._pending = {rid: self.store.get_row(rid)
                             for rid in self.store.all_ids()}

    def _ann_ready(self) -> bool:
        """True when queries should ride the IVF path: armed, not
        degraded, and the index is live (or lazily buildable now)."""
        if self.ann_mode != "ivf" or self._ann_degraded:
            return False
        if self._ann_arenas is not None:
            return True
        if len(self.store) < self.ann_min_rows:
            return False
        return self._ann_rebuild()

    def _ann_embed(self, sig_rows):
        from jubatus_tpu.ops import ivf

        return ivf.embed_signatures(jnp.asarray(sig_rows),
                                    method=self.method,
                                    hash_num=self.hash_num)

    def _ann_rebuild(self) -> bool:
        """(Re)train centroids from a row sample (``kmeans_fit`` as the
        coarse partitioner) and cell-assign every live row. The
        ``ann.rebuild`` fault site degrades the tier to the exact scan
        — sticky until reconfigured — instead of ever wrong-answering."""
        from jubatus_tpu.ops import ivf
        from jubatus_tpu.parallel.row_store import CellArenas
        from jubatus_tpu.utils import faults

        self._flush()
        try:
            faults.fire("ann.rebuild")
        except faults.FaultInjected:
            self._ann_counters["rebuild_failed"] += 1
            self._ann_degrade("rebuild_fault")
            return False
        ids = self.store.all_ids()
        if not ids:
            return False
        slots = np.fromiter((self.store.slots[r] for r in ids),
                            np.int64, len(ids))
        n_cells = self.ann_cells or ivf.auto_cells(len(ids))
        n_cells = max(1, min(n_cells, len(ids)))
        if len(slots) > 65536:
            rng = np.random.default_rng(self.seed)
            sample = np.sort(rng.choice(slots, 65536, replace=False))
        else:
            sample = slots
        emb_s = self._ann_embed(self._sigs[sample])
        self._ann_centroids = ivf.train_centroids(emb_s, n_cells,
                                                  seed=self.seed)
        arenas = CellArenas(self.store, n_cells)
        cen = jnp.asarray(self._ann_centroids)
        for lo in range(0, len(ids), 65536):
            chunk = slots[lo: lo + 65536]
            asg = np.asarray(ivf.assign_cells(
                self._ann_embed(self._sigs[chunk]), cen))
            for i, cell in enumerate(asg):
                arenas.assign(ids[lo + i], int(cell))
        self._ann_arenas = arenas
        self._ann_dev = None
        self._ann_counters["builds"] += 1
        # cells can come out of training already past the width budget
        # (skewed data); give them the same re-split pass inserts get
        self._ann_maintain()
        return not self._ann_degraded

    def _ann_assign(self, sig_rows, rids) -> None:
        """Cell-assign freshly flushed rows against the live centroids
        (one [B, K]×[K, E] matmul)."""
        from jubatus_tpu.ops import ivf

        if not rids:
            return
        cells = np.asarray(ivf.assign_cells(
            self._ann_embed(sig_rows), jnp.asarray(self._ann_centroids)))
        arenas = self._ann_arenas
        for rid, cell in zip(rids, cells):
            arenas.assign(rid, int(cell))

    def _ann_split_width(self) -> int:
        return self.ann_split_width or max(
            64, 4 * max(1, len(self.store) // self._ann_arenas.n_cells))

    def _ann_maintain(self) -> None:
        """Background re-split: any cell past its width budget splits
        2-means into itself + a fresh cell (one rare recompile per cell
        count change). A fault at ``ann.rebuild`` degrades to exact."""
        from jubatus_tpu.utils import faults

        arenas = self._ann_arenas
        if arenas is None:
            return
        width = self._ann_split_width()
        over = [c for c, n in enumerate(arenas.sizes()) if n > width]
        try:
            for cell in over:
                faults.fire("ann.rebuild")
                self._ann_split_cell(cell)
                self._ann_counters["resplits"] += 1
        except faults.FaultInjected:
            self._ann_counters["rebuild_failed"] += 1
            self._ann_degrade("resplit_fault")

    def _ann_split_cell(self, cell: int) -> None:
        from jubatus_tpu.ops import ivf
        from jubatus_tpu.utils import events

        arenas = self._ann_arenas
        members = [rid for rid in arenas.members(cell)
                   if rid in self.store.slots]
        if len(members) < 2:
            return
        slots = np.fromiter((self.store.slots[r] for r in members),
                            np.int64, len(members))
        emb = self._ann_embed(self._sigs[slots])
        cents = ivf.train_centroids(emb, 2, seed=len(members))
        asg = np.asarray(ivf.assign_cells(emb, jnp.asarray(cents)))
        new_cell = arenas.add_cell()
        cen = np.array(self._ann_centroids, np.float32)
        cen[cell] = cents[0]
        self._ann_centroids = np.vstack([cen, cents[1:2]])
        for rid, side in zip(members, asg):
            arenas.assign(rid, new_cell if side else cell)
        self._ann_dev = None
        events.emit("ann", "resplit", cell=int(cell),
                    new_cell=int(new_cell), rows=len(members))

    def _ann_degrade(self, reason: str) -> None:
        """Drop the index and latch the tier off: every later query
        takes the exact path (approximate answers are never served from
        a half-built index)."""
        from jubatus_tpu.utils import events

        self._ann_degraded = True
        self._ann_arenas = None
        self._ann_dev = None
        events.emit("ann", "degraded", severity="warning", reason=reason)

    def _ann_restore(self, centroids: np.ndarray) -> None:
        """Adopt checkpointed centroids over the CURRENT store shape:
        arenas start empty and every (re-pended) row re-partitions via
        the stored centroids at the next flush — reshard-on-restore."""
        from jubatus_tpu.parallel.row_store import CellArenas

        self._ann_centroids = np.array(centroids, np.float32)
        self._ann_arenas = CellArenas(self.store,
                                      self._ann_centroids.shape[0])
        self._ann_degraded = False
        self._ann_dev = None

    def _ann_device_state(self):
        """(centroids, cell tables, cell_cap) device views — sharded
        over the mesh when attached; cached per (store, arena) version."""
        arenas = self._ann_arenas
        key = (self.store.version, arenas.version)
        if self._ann_dev is not None and self._ann_dev[0] == key:
            return self._ann_dev[1:]
        tab, cap = arenas.device_tables()
        cen = jnp.asarray(self._ann_centroids)
        if self._mesh is not None:
            from jubatus_tpu.parallel.sharded_knn import (replicate,
                                                          shard_table)
            tab = shard_table(self._mesh, tab, self._mesh_axis)
            cen = replicate(self._mesh, cen)
        self._ann_dev = (key, cen, tab, cap)
        return cen, tab, cap

    def ann_stats(self) -> Dict[str, Any]:
        """ANN index gauges (ann.* — OBSERVABILITY.md §7); {} when the
        tier is off."""
        if self.ann_mode == "off":
            return {}
        arenas = self._ann_arenas
        st: Dict[str, Any] = {
            "mode": self.ann_mode,
            "built": arenas is not None,
            "degraded": self._ann_degraded,
            "nprobe": self.ann_nprobe,
            "cells": arenas.n_cells if arenas is not None else 0,
            "rows_indexed": len(arenas) if arenas is not None else 0,
        }
        st.update(self._ann_counters)
        st.update(self._ann_last)
        if self._ann_recall_probe is not None:
            st["recall_probe"] = self._ann_recall_probe
        return st

    # -- mesh-sharded serving (≙ CHT row sharding, SURVEY.md §5) -------------
    def attach_mesh(self, mesh, axis: str = "shard") -> None:
        """Serve hash-method queries from a row-sharded signature table on
        a device mesh (parallel/sharded_knn.py) instead of one device —
        the capacity-scaling move the reference makes with CHT row
        placement. Exact methods (inverted_index/euclid) keep the dense
        path. Pass mesh=None to detach.

        Attaching swaps the flat RowStore for the sharded row arena
        (parallel/row_store.ShardedRowStore): rows land in their
        CHT-owned shard's slot range, so the [S*C, W] signature table is
        shard-contiguous by construction and migration-plane rows
        (NNRowMigration wire format) arrive directly in the owning
        shard."""
        if mesh is not None and self.method not in HASH_METHODS:
            raise ValueError(
                f"mesh-sharded serving supports hash methods {HASH_METHODS}, "
                f"not {self.method!r}")
        self._mesh = mesh
        self._mesh_axis = axis
        self._mesh_dev = None
        n = mesh.shape[axis] if mesh is not None else 1
        self._reshape_store(n if n > 1 else 1)

    def _reshape_store(self, n_shards: int) -> None:
        """Swap the row store's arena layout (flat <-> N shards),
        re-placing every live row by ``shard_for`` and re-pending all
        signatures (slots move). Update/mix trackers carry over."""
        old = self.store
        sharded = isinstance(old, ShardedRowStore)
        if n_shards <= 1 and not sharded:
            return
        if sharded and old.n_shards == n_shards:
            return
        if n_shards > 1:
            new: Any = ShardedRowStore(
                n_shards=n_shards, max_size=old.max_size,
                keep_datum=old.keep_datum)
        else:
            new = RowStore(max_size=old.max_size, keep_datum=old.keep_datum)
        pending_mix = dict(old.updated_since_mix)
        for rid in old.all_ids():
            new.set_row(rid, old.get_row(rid), datum=old.datums.get(rid))
        if not old.keep_datum:
            new.datums.update(old.datums)
        new.updated_since_mix = pending_mix
        self.store = new
        self._init_sigs()
        # every slot moved: recompute every signature at the next flush
        self._pending = {rid: new.get_row(rid) for rid in new.all_ids()}
        if self._ann_centroids is not None and not self._ann_degraded:
            # reshard re-partitions via the STORED centroids: fresh
            # arenas over the new shard shape; the re-pended rows above
            # re-assign cells at the next flush
            self._ann_restore(self._ann_centroids)

    def shard_stats(self) -> Dict[str, Any]:
        """Shard-layout gauges (shard.{count,rows,bytes_in_use,
        topk_merge_ms} — OBSERVABILITY.md §7): arena shape + last
        sharded-query merge wall time."""
        if isinstance(self.store, ShardedRowStore):
            st = self.store.shard_stats()
        else:
            st = {"count": 1, "rows": len(self.store),
                  "rows_per_shard": [len(self.store)],
                  "capacity_per_shard": self.store.capacity,
                  "bytes_in_use":
                      int(self.store.idx.nbytes + self.store.val.nbytes)}
        if self.last_topk_ms is not None:
            st["topk_merge_ms"] = round(self.last_topk_ms, 3)
        return st

    def _mesh_view(self):
        """(sharded sigs, sharded valid mask) — row count padded up to a
        multiple of the shard axis, padding slots masked invalid."""
        from jubatus_tpu.parallel.sharded_knn import shard_table

        if self._mesh_dev is not None and \
                self._mesh_dev[0] == self.store.version:
            return self._mesh_dev[1:]
        s = self._mesh.shape[self._mesh_axis]
        c = self.store.capacity
        pad = (-c) % s
        sigs = np.pad(self._sigs, ((0, pad), (0, 0)))
        valid = np.pad(self.store.live_mask(), (0, pad))
        sigs = shard_table(self._mesh, jnp.asarray(sigs), self._mesh_axis)
        valid = shard_table(self._mesh, jnp.asarray(valid), self._mesh_axis)
        self._mesh_dev = (self.store.version, sigs, valid)
        return sigs, valid

    def _query_sigs_batch(self, vecs):
        """[B, W/H] query signatures (hash methods) or JL projections
        (exact methods' ANN probe space) in one batched kernel call."""
        sb = SparseBatch.from_vectors(list(vecs))
        idx, val = jnp.asarray(sb.idx), jnp.asarray(sb.val)
        if self.method == "lsh":
            return knn.lsh_signature(idx, val, hash_num=self.hash_num,
                                     seed=self.seed)
        if self.method == "minhash":
            return knn.minhash_signature(idx, val, hash_num=self.hash_num,
                                         seed=self.seed)
        return knn.euclid_projection(idx, val, hash_num=self.hash_num,
                                     seed=self.seed)

    def _mesh_exact_topk(self, q, sigs, valid, k: int):
        """Exact sharded top-k dispatch for pre-computed query sigs."""
        from jubatus_tpu.parallel import sharded_knn

        if self.method == "lsh":
            return sharded_knn.sharded_hamming_topk(
                self._mesh, q, sigs, hash_num=self.hash_num, k=k,
                axis=self._mesh_axis, valid=valid)
        if self.method == "minhash":
            return sharded_knn.sharded_minhash_topk(
                self._mesh, q, sigs, k=k, axis=self._mesh_axis, valid=valid)
        return sharded_knn.sharded_euclid_lsh_topk(
            self._mesh, q, sigs, hash_num=self.hash_num, k=k,
            axis=self._mesh_axis, valid=valid)

    def _mesh_neighbors(self, vecs, k: int) -> List[List[Tuple[str, float]]]:
        import time

        self._flush()
        k = min(k, len(self.store))
        if k <= 0 or not vecs:
            return [[] for _ in vecs]
        sigs, valid = self._mesh_view()
        t0 = time.perf_counter()
        q = self._query_sigs_batch(vecs)
        ann_used = self._ann_ready()
        if ann_used:
            from jubatus_tpu.ops import ivf
            from jubatus_tpu.parallel import sharded_ivf

            emb = ivf.embed_signatures(q, method=self.method,
                                       hash_num=self.hash_num)
            cen, tab, cap = self._ann_device_state()
            nprobe = min(self.ann_nprobe, self._ann_arenas.n_cells)
            d, gidx = sharded_ivf.sharded_ivf_topk(
                self._mesh, q, emb, sigs, cen, tab, method=self.method,
                hash_num=self.hash_num, k=k, nprobe=nprobe,
                axis=self._mesh_axis)
            self._ann_last = {"probed_cells": nprobe,
                              "rescore_candidates": nprobe * cap}
        else:
            d, gidx = self._mesh_exact_topk(q, sigs, valid, k)
        d, gidx = np.asarray(d), np.asarray(gidx)
        self.last_topk_ms = (time.perf_counter() - t0) * 1e3
        out = []
        for b in range(len(vecs)):
            row = [(self.store.ids[int(s)], float(d[b, j]))
                   for j, s in enumerate(gidx[b]) if np.isfinite(d[b, j])]
            out.append(row)
        if ann_used:
            self._ann_queries += 1
            if self._ann_queries % 64 == 1:
                # shadow one query down the exact path: ann.recall_probe
                de, ge = self._mesh_exact_topk(q[:1], sigs, valid, k)
                de, ge = np.asarray(de), np.asarray(ge)
                exact_ids = {self.store.ids[int(s)]
                             for j, s in enumerate(ge[0])
                             if np.isfinite(de[0, j])}
                got = {rid for rid, _ in out[0]}
                if exact_ids:
                    self._ann_recall_probe = round(
                        len(exact_ids & got) / len(exact_ids), 4)
        return out

    def _ann_neighbors_flat(self, vecs, k: int) -> \
            List[List[Tuple[str, float]]]:
        """Single-device two-phase IVF query (ops/ivf.py): probe +
        exact rescore over the probed cells only."""
        from jubatus_tpu.ops import ivf

        q = self._query_sigs_batch(vecs)
        emb = ivf.embed_signatures(q, method=self.method,
                                   hash_num=self.hash_num)
        cen, tab, cap = self._ann_device_state()
        nprobe = min(self.ann_nprobe, self._ann_arenas.n_cells)
        if self.method in HASH_METHODS:
            d, slots = ivf.ivf_topk(
                q, emb, self._sig_view(), cen, tab, method=self.method,
                hash_num=self.hash_num, k=k, nprobe=nprobe)
        else:
            idx, val, _ = self.store.device_view()
            qd = np.zeros((len(vecs), self.dim), np.float32)
            for b, vec in enumerate(vecs):
                for i, v in vec:
                    qd[b, i] += v
            d, slots = ivf.ivf_topk_exact(
                jnp.asarray(qd), emb, idx, val, cen, tab,
                method=self.method, k=k, nprobe=nprobe)
        self._ann_last = {"probed_cells": nprobe,
                          "rescore_candidates": nprobe * cap}
        d, slots = np.asarray(d), np.asarray(slots)
        out = []
        for b in range(len(vecs)):
            out.append([(self.store.ids[int(s)], float(d[b, j]))
                        for j, s in enumerate(slots[b])
                        if np.isfinite(d[b, j])])
        return out

    def _ann_query(self, vecs, k: int) -> List[List[Tuple[str, float]]]:
        self._flush()
        k = min(k, len(self.store))
        if k <= 0 or not vecs:
            return [[] for _ in vecs]
        out = self._ann_neighbors_flat(vecs, k)
        self._ann_queries += 1
        if self._ann_queries % 64 == 1:
            self._ann_probe_recall(vecs[0], out[0], k)
        return out

    def _ann_probe_recall(self, vec, approx, k: int) -> None:
        """Shadow one query down the exact path and record overlap@k —
        the ann.recall_probe gauge (every 64th ANN batch, flat path)."""
        d = self.distances(vec)
        kk = min(k, len(self.store))
        if kk <= 0:
            return
        order = np.argpartition(d, kk - 1)[:kk]
        exact_ids = {self.store.ids[int(s)] for s in order
                     if np.isfinite(d[s])}
        if not exact_ids:
            return
        got = {rid for rid, _ in approx}
        self._ann_recall_probe = round(
            len(exact_ids & got) / len(exact_ids), 4)

    # -- queries ---------------------------------------------------------------
    def _query_sig(self, vec: SparseVector):
        sb = SparseBatch.from_vectors([vec])
        idx, val = jnp.asarray(sb.idx), jnp.asarray(sb.val)
        if self.method == "lsh":
            return knn.lsh_signature(idx, val, hash_num=self.hash_num,
                                     seed=self.seed)[0]
        if self.method == "minhash":
            return knn.minhash_signature(idx, val, hash_num=self.hash_num,
                                         seed=self.seed)[0]
        return knn.euclid_projection(idx, val, hash_num=self.hash_num,
                                     seed=self.seed)[0]

    def _mesh_distances(self, q_batch) -> np.ndarray:
        """[B, C] full distances from the row-sharded table (truncated to
        the unpadded capacity)."""
        from jubatus_tpu.parallel import sharded_knn

        sigs, _valid = self._mesh_view()
        d = sharded_knn.sharded_distances(
            self._mesh, q_batch, sigs, method=self.method,
            hash_num=self.hash_num, axis=self._mesh_axis)
        return np.asarray(d)[:, : self.store.capacity]

    def distances(self, vec: SparseVector) -> np.ndarray:
        """Distance of every live slot to the query; dead slots +inf. [C]."""
        self._flush()
        live = self.store.live_mask()
        if not live.any():
            return np.full(self.store.capacity, np.inf, np.float32)
        if self.method in HASH_METHODS and self._mesh is not None:
            q = self._query_sig(vec)
            d = self._mesh_distances(q[None])[0]
        elif self.method in HASH_METHODS:
            q = self._query_sig(vec)
            sigs = self._sig_view()
            if self.method == "lsh":
                d = knn.hamming_distances(q, sigs, hash_num=self.hash_num)
            elif self.method == "minhash":
                d = knn.minhash_distances(q, sigs)
            else:
                d = knn.euclid_lsh_distances(q, sigs, hash_num=self.hash_num)
        else:
            idx, val, _ = self.store.device_view()
            qd = knn.densify(jnp.asarray(np.array([i for i, _ in vec] or [0],
                                                  np.int32)),
                             jnp.asarray(np.array([v for _, v in vec] or [0.0],
                                                  np.float32)),
                             dim=self.dim)
            if self.method == "inverted_index":
                d = 1.0 - knn.cosine_scores(idx, val, qd)
            else:
                d = knn.euclid_distances(idx, val, qd)
        d = np.asarray(d, np.float32).copy()
        d[~live] = np.inf
        return d

    def similarity_from_distance(self, d: np.ndarray) -> np.ndarray:
        if self.method in ("euclid_lsh", "euclid"):
            return -d
        return 1.0 - d

    def neighbors(self, vec: SparseVector, k: int) -> List[Tuple[str, float]]:
        """k nearest as (id, distance), ascending."""
        if self._mesh is not None:
            return self._mesh_neighbors([vec], k)[0]
        self._flush()
        if self._ann_ready():
            return self._ann_query([vec], k)[0]
        d = self.distances(vec)
        k = min(k, len(self.store))
        if k <= 0:
            return []
        order = np.argpartition(d, k - 1)[:k]
        order = order[np.argsort(d[order])]
        return [(self.store.ids[s], float(d[s])) for s in order]

    def neighbors_batch(self, vecs: List[SparseVector],
                        k: int) -> List[List[Tuple[str, float]]]:
        """Batched k-nearest: one sharded scan for the whole batch when a
        mesh is attached, else per-query dense scans (one batched IVF
        probe when the ANN tier is live)."""
        if self._mesh is not None:
            return self._mesh_neighbors(list(vecs), k)
        self._flush()
        if self._ann_ready():
            return self._ann_query(list(vecs), k)
        return [self.neighbors(v, k) for v in vecs]

    def similar(self, vec: SparseVector, k: int) -> List[Tuple[str, float]]:
        """k most similar as (id, similarity), descending."""
        return [(rid, float(self.similarity_from_distance(np.float32(dist))))
                for rid, dist in self.neighbors(vec, k)]

    # -- batch distances (LOF lrd cache) ---------------------------------------
    def distances_from_slots(self, slots: np.ndarray,
                             chunk: int = 256) -> np.ndarray:
        """Distances from each of the given row slots to every slot:
        [len(slots), C]; dead columns +inf. Hash methods run the batched
        signature kernels (one [B, C] pass per chunk); exact methods fall
        back to a per-row loop over the single-query kernel."""
        self._flush()
        live = self.store.live_mask()
        c = self.store.capacity
        out = np.full((len(slots), c), np.inf, np.float32)
        if not live.any():
            return out
        if self.method in HASH_METHODS and self._mesh is not None:
            for lo in range(0, len(slots), chunk):
                sel = np.asarray(slots[lo:lo + chunk])
                q = jnp.asarray(self._sigs[sel])
                out[lo:lo + chunk] = self._mesh_distances(q)
        elif self.method in HASH_METHODS:
            sigs = self._sig_view()
            for lo in range(0, len(slots), chunk):
                sel = np.asarray(slots[lo:lo + chunk])
                q = sigs[jnp.asarray(sel)]
                if self.method == "lsh":
                    d = knn.hamming_distances_batch(q, sigs,
                                                    hash_num=self.hash_num)
                elif self.method == "minhash":
                    d = knn.minhash_distances_batch(q, sigs)
                else:
                    d = knn.euclid_lsh_distances_batch(q, sigs,
                                                       hash_num=self.hash_num)
                out[lo:lo + chunk] = np.asarray(d)
        else:
            for row, s in enumerate(slots):
                rid = self.store.ids[int(s)]
                vec = self.store.get_row(rid) or []
                out[row] = self.distances(vec)
        out[:, ~live] = np.inf
        return out

    # -- persistence / mix -----------------------------------------------------
    def pack(self) -> Any:
        self._flush()
        out: Dict[str, Any] = {"store": self.store.pack()}
        if self.ann_mode == "ivf" and self._ann_centroids is not None:
            cen = np.ascontiguousarray(self._ann_centroids, np.float32)
            # centroid tables ride the save_load envelope (CRC'd like
            # any other mixable payload) as raw bytes + shape
            out["ann"] = {"cells": int(cen.shape[0]),
                          "dim": int(cen.shape[1]),
                          "centroids": cen.tobytes()}
        return out

    def unpack(self, obj: Any, datum_decoder=None) -> None:
        self.clear()
        self.store.unpack(obj["store"], datum_decoder=datum_decoder)
        for rid in self.store.all_ids():
            vec = self.store.get_row(rid)
            if self._sigs is not None:
                self._pending[rid] = vec
        ann = obj.get("ann") if isinstance(obj, dict) else None
        if ann is not None and self.ann_mode == "ivf":
            cen = np.frombuffer(ann["centroids"], np.float32)
            self._ann_restore(cen.reshape(ann["cells"], ann["dim"]))

    def pop_update_diff(self):
        return self.store.pop_update_diff()

    def apply_update_diff(self, diff, datum_decoder=None) -> None:
        for rid, (ii, vv, datum) in diff.items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            if datum is not None and datum_decoder is not None:
                datum = datum_decoder(datum)
            vec = [(int(i), float(v)) for i, v in zip(ii, vv)]
            self.set_row(rid, vec, datum=datum)
        self.store.updated_since_mix = {}


class NNRowMigration:
    """Row-migration driver hooks (elastic membership, ISSUE 10) shared
    by the NNBackend-based engines (nearest_neighbor, recommender).

    Wire row shape: ``[id, idx_list, val_list, datum_msgpack_or_None]``
    — the ALREADY-HASHED vector, so the destination applies without
    re-converting (and without the source's converter state). Migrated
    rows do NOT re-enter the next mix diff (they already live on their
    owners); ``put_rows`` clears the update tracker for them.

    Mixed into drivers that define ``self.backend`` (an NNBackend);
    callers (server/base.py migrate_range / put_rows, the drain
    handoff) hold the driver lock — the RLock makes the decorated
    methods safe either way.
    """

    def row_ids(self) -> List[str]:
        return self.backend.store.all_ids()

    def get_rows(self, ids: Optional[List[str]] = None) -> List[list]:
        store = self.backend.store
        out: List[list] = []
        for rid in (ids if ids is not None else store.all_ids()):
            rid = rid.decode() if isinstance(rid, bytes) else rid
            vec = store.get_row(rid)
            if vec is None:
                continue  # raced a concurrent remove/evict
            datum = store.datums.get(rid)
            out.append([rid, [i for i, _ in vec], [v for _, v in vec],
                        datum.to_msgpack()
                        if hasattr(datum, "to_msgpack") else None])
        return out

    def put_rows(self, rows: List[list]) -> int:
        from jubatus_tpu.core.datum import Datum

        n = 0
        for row in rows or []:
            rid = row[0]
            rid = rid.decode() if isinstance(rid, bytes) else str(rid)
            ii, vv = row[1], row[2]
            datum = row[3] if len(row) > 3 else None
            if datum is not None:
                datum = Datum.from_msgpack(datum)
            self.backend.set_row(
                rid, [(int(i), float(v)) for i, v in zip(ii, vv)],
                datum=datum)
            # migrated rows are not "local updates" for the next mix
            self.backend.store.updated_since_mix.pop(rid, None)
            n += 1
        return n
