"""Anomaly engine driver (LOF / light_lof over a nearest-neighbor backend).

API parity with the reference's anomaly service
(jubatus/server/server/anomaly.idl: clear_row / add / update / overwrite /
clear / calc_score / get_all_rows). Config shape from
/root/reference/config/anomaly/lof.json: method lof|light_lof, parameter
{nearest_neighbor_num, reverse_nearest_neighbor_num, method: <nn method>,
parameter: {...}}, optional lru unlearner
(config/anomaly/light_lof_unlearn_lru.json).

Local Outlier Factor with k = nearest_neighbor_num:

  kdist(o)       distance from o to its k-th nearest stored neighbor
  reach(q, o)  = max(kdist(o), d(q, o))
  lrd(q)       = k / Σ_{o ∈ kNN(q)} reach(q, o)
  LOF(q)       = mean_{o ∈ kNN(q)} lrd(o) / lrd(q)

``add`` scores the point against the store *before* inserting it (so the
point doesn't dilute its own score) and returns (generated_id, score) —
the reference does the ZK-id + CHT dance (anomaly_serv.cpp:155-211); here
ids come from the driver's monotonic counter (the id_service seam).

TPU design: the per-row kdist/lrd tables are rebuilt lazily per store
version with the batched [B, C] distance kernels (ops/knn.py) — the whole
store's LOF support structure is a few vectorized passes, not per-point
index maintenance. light_lof and lof share this design (light_lof's whole
point in the reference was to cache instead of recompute — here both do).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.core.sparse import SparseVector
from jubatus_tpu.framework.driver import DriverBase, locked
from jubatus_tpu.models._nn_backend import NNBackend

METHODS = ("lof", "light_lof")


class AnomalyConfigError(ValueError):
    pass


class AnomalyDriver(DriverBase):
    TYPE = "anomaly"

    def __init__(self, config: dict, dim_bits: int = 18):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        method = config.get("method")
        if method not in METHODS:
            raise AnomalyConfigError(f"unknown anomaly method {method!r}")
        self.method = method
        param = dict(config.get("parameter") or {})
        self.k = int(param.get("nearest_neighbor_num", 10))
        nn_method = param.get("method", "euclid_lsh")
        nn_param = dict(param.get("parameter") or {})
        if nn_method == "inverted_index_euclid":
            nn_method = "euclid"
        self.converter = make_fv_converter(config.get("converter"),
                                           dim_bits=dim_bits)
        unl_param = param.get("unlearner_parameter") or {}
        self.backend = NNBackend(
            nn_method,
            dim=self.converter.dim,
            hash_num=int(nn_param.get("hash_num", 64)),
            seed=int(nn_param.get("seed", 0)),
            max_size=(int(unl_param["max_size"])
                      if param.get("unlearner") == "lru" else None),
        )
        self._next_id = 0
        #: cluster-wide id minting (≙ ZK global_id_generator, anomaly_serv
        #: .cpp:160) — set by the server in distributed mode so ids minted on
        #: different nodes never collide when row diffs merge in a mix round
        self.idgen = None
        self._lrd_cache: Optional[Tuple[int, np.ndarray, np.ndarray]] = None

    def set_id_generator(self, gen) -> None:
        self.idgen = gen

    # -- lrd support structure -------------------------------------------------
    def _support(self) -> Tuple[np.ndarray, np.ndarray]:
        """(kdist [C], lrd [C]) over store slots, rebuilt per store version."""
        v = self.backend.store.version
        if self._lrd_cache is not None and self._lrd_cache[0] == v:
            return self._lrd_cache[1], self._lrd_cache[2]
        store = self.backend.store
        c = store.capacity
        kdist = np.full(c, np.inf, np.float32)
        lrd = np.zeros(c, np.float32)
        slots = np.asarray(sorted(store.slots.values()), np.int64)
        n = len(slots)
        if n >= 2:
            k = min(self.k, n - 1)
            d = self.backend.distances_from_slots(slots)     # [n, C]
            d[np.arange(n), slots] = np.inf                  # exclude self
            dl = d[:, slots]                                 # [n, n]
            part = np.sort(dl, axis=1)[:, :k]                # kNN distances
            kdist[slots] = part[:, -1]
            # lrd needs each row's neighbors' kdist
            nbr = np.argsort(dl, axis=1)[:, :k]              # local indices
            nbr_slots = slots[nbr]                           # [n, k]
            reach = np.maximum(kdist[nbr_slots], np.take_along_axis(dl, nbr, 1))
            denom = reach.sum(axis=1)
            lrd[slots] = np.where(denom > 0, k / np.maximum(denom, 1e-30),
                                  np.float32(np.inf))
        self._lrd_cache = (v, kdist, lrd)
        return kdist, lrd

    def _score(self, vec: SparseVector) -> float:
        """LOF of a query point against the current store."""
        store = self.backend.store
        n = len(store)
        if n < 2:
            return 1.0
        k = min(self.k, n)
        kdist, lrd = self._support()
        d = self.backend.distances(vec)                      # [C]
        order = np.argpartition(d, k - 1)[:k]
        order = order[np.argsort(d[order])]
        reach = np.maximum(kdist[order], d[order])
        denom = reach.sum()
        if denom <= 0:
            return 1.0  # exact duplicates of dense cluster points
        lrd_q = k / denom
        nbr_lrd = lrd[order]
        if np.isinf(nbr_lrd).any():
            return float("inf") if not np.isinf(lrd_q) else 1.0
        return float(nbr_lrd.mean() / lrd_q)

    # -- updates ---------------------------------------------------------------
    def add(self, row: Datum) -> Tuple[str, float]:
        # mint the cluster id BEFORE taking the model lock: the coordinator
        # round-trip must not stall other RPC threads or a mix round
        row_id = str(self.idgen.generate()) if self.idgen is not None else None
        with self.lock:
            if row_id is None:
                row_id = str(self._next_id)
                self._next_id += 1
            vec = self.converter.convert(row, update_weights=True)
            score = self._score(vec)
            self.backend.set_row(row_id, vec)
            self.event_model_updated()
        return row_id, score

    @locked
    def update(self, row_id: str, row: Datum) -> float:
        if row_id not in self.backend.store:
            raise KeyError(f"unknown row id {row_id!r}")
        return self._overwrite(row_id, row)

    @locked
    def overwrite(self, row_id: str, row: Datum) -> float:
        return self._overwrite(row_id, row)

    def _overwrite(self, row_id: str, row: Datum) -> float:
        vec = self.converter.convert(row, update_weights=True)
        self.backend.remove_row(row_id)
        score = self._score(vec)
        self.backend.set_row(row_id, vec)
        self.event_model_updated()
        return score

    @locked
    def clear_row(self, row_id: str) -> bool:
        ok = self.backend.remove_row(row_id)
        if ok:
            self.event_model_updated()
        return ok

    @locked
    def clear(self) -> None:
        self.backend.clear()
        self.converter.weights.clear()
        self._next_id = 0
        self._lrd_cache = None
        self.update_count = 0

    # -- queries ---------------------------------------------------------------
    @locked
    def calc_score(self, row: Datum) -> float:
        return self._score(self.converter.convert(row))

    @locked
    def get_all_rows(self) -> List[str]:
        return self.backend.store.all_ids()

    # -- mix plane -------------------------------------------------------------
    def get_mixables(self):
        from jubatus_tpu.models.nearest_neighbor import _RowUpdateMixable
        return {"rows": _RowUpdateMixable(self.backend),
                "weights": self.converter.weights}

    # -- persistence -----------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {"method": self.method, "backend": self.backend.pack(),
                "weights": self.converter.weights.pack(),
                "next_id": self._next_id}

    @locked
    def unpack(self, obj: Any) -> None:
        saved = obj.get("method")
        if isinstance(saved, bytes):
            saved = saved.decode()
        if saved != self.method:
            raise ValueError(
                f"checkpoint method {saved!r} != driver method {self.method!r}")
        self.backend.unpack(obj["backend"])
        self.converter.weights.unpack(obj["weights"])
        self._next_id = int(obj.get("next_id", 0))
        self._lrd_cache = None

    def shard_stats(self) -> Dict[str, Any]:
        """Row-shard layout gauges; empty when unsharded."""
        if self.backend._mesh is None:
            return {}
        return self.backend.shard_stats()

    def ann_stats(self) -> Dict[str, Any]:
        """IVF ANN-tier gauges (ann.* catalog rows); empty when --ann off."""
        return self.backend.ann_stats()

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(method=self.method, num_rows=len(self.backend.store), k=self.k)
        st.update({f"shard.{k}": v for k, v in self.shard_stats().items()})
        st.update({f"ann.{k}": v for k, v in self.ann_stats().items()})
        return st
